"""Per-rule jaxlint fixtures: each rule fires on a known-bad snippet
and stays silent on the known-good twin (ISSUE 2 acceptance)."""

import textwrap

from brainiak_tpu.analysis.core import analyze_file
from brainiak_tpu.analysis.rules import (
    Float64Leak,
    HostSyncInLoop,
    JitPerCall,
    MissingStatic,
    RngHazard,
    TracedBranch,
)


def lint(tmp_path, src, rule_cls):
    path = tmp_path / "snippet.py"
    path.write_text(textwrap.dedent(src))
    findings = analyze_file(str(path), str(tmp_path), [rule_cls()])
    assert not any(f.code == "CHK001" for f in findings), findings
    return findings


# -- JX001 jit-per-call ----------------------------------------------

def test_jx001_fires_on_jit_in_loop(tmp_path):
    findings = lint(tmp_path, """
        import jax
        def run(fns, x):
            out = []
            for fn in fns:
                jfn = jax.jit(fn)
                out.append(jfn(x))
            return out
        """, JitPerCall)
    assert [f.code for f in findings] == ["JX001"]
    assert "inside a loop" in findings[0].message


def test_jx001_fires_on_immediately_invoked_jit(tmp_path):
    findings = lint(tmp_path, """
        import jax
        def g(x):
            return x + 1
        y = jax.jit(g)(3.0)
        """, JitPerCall)
    assert [f.code for f in findings] == ["JX001"]
    assert "immediately" in findings[0].message


def test_jx001_fires_on_jit_inside_function(tmp_path):
    findings = lint(tmp_path, """
        import jax
        def make(fn):
            return jax.jit(fn)
        """, JitPerCall)
    assert [f.code for f in findings] == ["JX001"]
    assert "'make'" in findings[0].message


def test_jx001_silent_on_good_patterns(tmp_path):
    findings = lint(tmp_path, """
        import functools
        import jax

        @jax.jit
        def decorated(x):
            return x + 1

        def g(x):
            return x * 2

        g_jit = jax.jit(g, static_argnames=("n",))

        @functools.lru_cache(maxsize=None)
        def cached_builder(n):
            return jax.jit(lambda a: a + n)
        """, JitPerCall)
    assert findings == []


# -- JX002 host-sync-in-loop -----------------------------------------

def test_jx002_fires_in_epoch_loop(tmp_path):
    findings = lint(tmp_path, """
        import numpy as np
        def fit(step, state, n_iter):
            for epoch in range(n_iter):
                state = step(state)
                print(np.asarray(state).sum())
            return state
        """, HostSyncInLoop)
    assert [f.code for f in findings] == ["JX002"]
    assert "np.asarray" in findings[0].message


def test_jx002_fires_in_scan_body(tmp_path):
    findings = lint(tmp_path, """
        import jax
        import numpy as np
        def body(carry, x):
            host = np.asarray(x)
            return carry + host.sum(), x
        def run(xs):
            return jax.lax.scan(body, 0.0, xs)
        """, HostSyncInLoop)
    assert [f.code for f in findings] == ["JX002"]
    assert "lax.scan" in findings[0].message


def test_jx002_fires_in_resilient_chunk_body(tmp_path):
    findings = lint(tmp_path, """
        from brainiak_tpu.resilience import run_resilient_loop
        def fit(step, init):
            def run_chunk(state, i, n):
                done = float(step(state))
                return state, done > 0
            return run_resilient_loop(run_chunk, init, 10)
        """, HostSyncInLoop)
    assert [f.code for f in findings] == ["JX002"]
    assert "run_resilient_loop" in findings[0].message


def test_jx002_fires_on_fori_loop_lambda(tmp_path):
    findings = lint(tmp_path, """
        import jax
        def run(xs):
            return jax.lax.fori_loop(
                0, 10, lambda i, c: c + xs.item(), 0.0)
        """, HostSyncInLoop)
    assert [f.code for f in findings] == ["JX002"]
    assert ".item()" in findings[0].message


def test_jx002_fires_in_epoch_while_loop(tmp_path):
    """Former blind spot (ISSUE 10 satellite): host syncs inside
    ``while`` loops with epoch-style conditions were not visited."""
    findings = lint(tmp_path, """
        import numpy as np
        def fit(step, state, n_iter):
            epoch = 0
            while epoch < n_iter:
                state = step(state)
                print(np.asarray(state).sum())
                epoch += 1
            return state
        """, HostSyncInLoop)
    assert [f.code for f in findings] == ["JX002"]
    assert "while-loop" in findings[0].message


def test_jx002_fires_in_epoch_comprehension(tmp_path):
    """Former blind spot (ISSUE 10 satellite): comprehension bodies
    whose generators read as epoch/chunk loops were not visited."""
    findings = lint(tmp_path, """
        import numpy as np
        def fit(step, xs, n_steps):
            return [np.asarray(step(xs, s))
                    for s in range(n_steps)]
        """, HostSyncInLoop)
    assert [f.code for f in findings] == ["JX002"]
    assert "comprehension" in findings[0].message


def test_jx002_silent_on_non_epoch_while_and_comprehension(
        tmp_path):
    findings = lint(tmp_path, """
        import numpy as np
        def drain(queue):
            while queue:
                item = queue.pop()
                print(np.asarray(item))
        def collect(rows):
            return [np.asarray(r) for r in rows]
        """, HostSyncInLoop)
    assert findings == []


def test_jx002_silent_on_host_side_code(tmp_path):
    findings = lint(tmp_path, """
        import numpy as np
        def load(lines):
            rows = []
            for line in lines:
                rows.append(float(line))
            return np.asarray(rows)
        def fit(step, state, n_iter):
            for epoch in range(n_iter):
                state = step(state)
            return np.asarray(state)
        """, HostSyncInLoop)
    assert findings == []


# -- JX003 float64-leak ----------------------------------------------

def test_jx003_fires_on_jnp_float64(tmp_path):
    findings = lint(tmp_path, """
        import jax.numpy as jnp
        ZEROS = jnp.zeros((4,), dtype=jnp.float64)
        """, Float64Leak)
    assert [f.code for f in findings] == ["JX003"]


def test_jx003_fires_on_float64_string_in_jax_call(tmp_path):
    findings = lint(tmp_path, """
        import jax.numpy as jnp
        ONES = jnp.ones((4,), dtype="float64")
        """, Float64Leak)
    assert [f.code for f in findings] == ["JX003"]


def test_jx003_fires_on_astype_in_jit(tmp_path):
    findings = lint(tmp_path, """
        import jax
        @jax.jit
        def f(x):
            return x.astype("float64")
        """, Float64Leak)
    assert [f.code for f in findings] == ["JX003"]
    assert ".astype" in findings[0].message


def test_jx003_silent_when_guarded_or_host_side(tmp_path):
    findings = lint(tmp_path, """
        import jax
        import numpy as np
        dtype = np.float64 if jax.config.jax_enable_x64 \\
            else np.float32
        HOST = np.zeros((4,), dtype=np.float64)
        """, Float64Leak)
    assert findings == []


# -- JX004 rng-hazard ------------------------------------------------

def test_jx004_fires_on_np_random_in_jit(tmp_path):
    findings = lint(tmp_path, """
        import jax
        import numpy as np
        @jax.jit
        def f(x):
            return x + np.random.rand()
        """, RngHazard)
    assert [f.code for f in findings] == ["JX004"]
    assert "numpy.random" in findings[0].message


def test_jx004_fires_on_key_reuse(tmp_path):
    findings = lint(tmp_path, """
        import jax
        @jax.jit
        def f(key):
            a = jax.random.normal(key, (3,))
            b = jax.random.uniform(key, (3,))
            return a + b
        """, RngHazard)
    assert [f.code for f in findings] == ["JX004"]
    assert "split" in findings[0].message


def test_jx004_fires_on_key_created_inside_jit(tmp_path):
    """The canonical form: a PRNGKey minted in the function and fed
    to two samplers without a split."""
    findings = lint(tmp_path, """
        import jax
        @jax.jit
        def f(x):
            key = jax.random.PRNGKey(0)
            a = jax.random.normal(key, (3,))
            b = jax.random.uniform(key, (3,))
            return x + a + b
        """, RngHazard)
    assert [f.code for f in findings] == ["JX004"]


def test_jx004_silent_on_key_rotation(tmp_path):
    """A name rebound between sampler calls is rotation, not reuse."""
    findings = lint(tmp_path, """
        import jax
        @jax.jit
        def f(keys):
            k = keys[0]
            a = jax.random.normal(k, (3,))
            k = keys[1]
            b = jax.random.uniform(k, (3,))
            return a + b
        """, RngHazard)
    assert findings == []


def test_jx004_silent_on_split_keys_and_host_rng(tmp_path):
    findings = lint(tmp_path, """
        import jax
        import numpy as np
        @jax.jit
        def f(key):
            k1, k2 = jax.random.split(key)
            a = jax.random.normal(k1, (3,))
            b = jax.random.uniform(k2, (3,))
            return a + b
        def host_init(seed):
            return np.random.default_rng(seed).normal(size=3)
        """, RngHazard)
    assert findings == []


# -- JX005 traced-branch ---------------------------------------------

def test_jx005_fires_on_if_over_traced_param(tmp_path):
    findings = lint(tmp_path, """
        import jax
        @jax.jit
        def f(x):
            if x > 0:
                return x
            return -x
        """, TracedBranch)
    assert [f.code for f in findings] == ["JX005"]
    assert "`x`" in findings[0].message


def test_jx005_silent_on_static_and_metadata_branches(tmp_path):
    findings = lint(tmp_path, """
        from functools import partial
        import jax

        @partial(jax.jit, static_argnames=("flag",))
        def g(x, flag):
            if flag:
                return x
            return -x

        @jax.jit
        def h(x, y=None):
            if y is None:
                y = x
            if x.ndim == 2:
                return x + y
            return x - y

        def plain(x):
            if x > 0:
                return x
            return -x
        """, TracedBranch)
    assert findings == []


# -- JX006 missing-static --------------------------------------------

def test_jx006_fires_on_traced_reshape_arg(tmp_path):
    findings = lint(tmp_path, """
        import jax
        @jax.jit
        def f(x, n):
            return x.reshape(n, -1)
        """, MissingStatic)
    assert [f.code for f in findings] == ["JX006"]
    assert "static_argnums" in findings[0].message


def test_jx006_fires_on_traced_range_arg(tmp_path):
    findings = lint(tmp_path, """
        import jax
        @jax.jit
        def f(x, steps):
            for _ in range(steps):
                x = x + 1
            return x
        """, MissingStatic)
    assert [f.code for f in findings] == ["JX006"]


def test_jx006_silent_with_static_declaration(tmp_path):
    findings = lint(tmp_path, """
        import jax

        def _impl(x, n):
            return x.reshape(n, -1)

        _impl_jit = jax.jit(_impl, static_argnames=("n",))

        @jax.jit
        def g(x):
            return x.reshape(x.shape[0], -1)
        """, MissingStatic)
    assert findings == []

# -- JX001 x program_cache registry (ISSUE 17 regression) ------------
#
# program_cache moved from serve.engine to serve.batching; jaxlint's
# _CACHE_DECOS learned the new module spellings.  A builder decorated
# under ANY of them is a cached factory — jit construction inside it
# must stay silent, while the undecorated twin still fires.

def test_jx001_silent_on_batching_program_cache_spellings(tmp_path):
    findings = lint(tmp_path, """
        import jax
        from brainiak_tpu.serve import batching
        from brainiak_tpu.serve.batching import program_cache
        import brainiak_tpu.serve.batching as sb

        @batching.program_cache("fixture.attr")
        def _attr_builder(n, b):
            return jax.jit(lambda x: x + n)

        @program_cache("fixture.bare")
        def _bare_builder(n, b):
            return jax.jit(lambda x: x * n)

        @sb.program_cache("fixture.asname")
        def _asname_builder(n, b):
            return jax.jit(lambda x: x - n)
        """, JitPerCall)
    assert findings == [], [f.message for f in findings]


def test_jx001_silent_on_engine_reexport_spelling(tmp_path):
    # engine re-exports program_cache for back-compat; the old
    # spelling must keep working too
    findings = lint(tmp_path, """
        import jax
        from brainiak_tpu.serve import engine

        @engine.program_cache("fixture.legacy")
        def _legacy_builder(n, b):
            return jax.jit(lambda x: x + n)
        """, JitPerCall)
    assert findings == [], [f.message for f in findings]


def test_jx001_still_fires_on_uncached_twin(tmp_path):
    # control: the identical builder WITHOUT the cache decorator is
    # the real hazard and must keep firing
    findings = lint(tmp_path, """
        import jax

        def _uncached_builder(n, b):
            return jax.jit(lambda x: x + n)
        """, JitPerCall)
    assert [f.code for f in findings] == ["JX001"]
