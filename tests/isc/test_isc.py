import numpy as np
import pytest

from brainiak_tpu.isc import (
    bootstrap_isc,
    compute_summary_statistic,
    isc,
    isfc,
    permutation_isc,
    phaseshift_isc,
    squareform_isfc,
    timeshift_isc,
)


def simulated_timeseries(n_subjects, n_TRs, n_voxels=30, noise=1.0,
                         random_state=None):
    """Shared signal + independent noise per subject -> [T, V, S]."""
    prng = np.random.RandomState(random_state)
    signal = prng.randn(n_TRs, n_voxels)
    return np.dstack([signal + prng.randn(n_TRs, n_voxels) * noise
                      for _ in range(n_subjects)])


def correlated_timeseries(n_subjects, n_TRs, noise=0.0, random_state=None):
    """3 voxels: first two share a signal, third is independent noise."""
    prng = np.random.RandomState(random_state)
    signal = prng.randn(n_TRs)
    data = np.repeat(np.column_stack((signal, signal))[..., np.newaxis],
                     n_subjects, axis=2)
    uncorrelated = prng.randn(n_TRs, 1, n_subjects)
    data = np.concatenate((data, uncorrelated), axis=1)
    return data + prng.randn(n_TRs, 3, n_subjects) * noise


def test_isc_shapes_and_inputs():
    n_subjects, n_TRs, n_voxels = 8, 60, 5
    data = simulated_timeseries(n_subjects, n_TRs, n_voxels, random_state=0)
    iscs_loo = isc(data, pairwise=False)
    assert iscs_loo.shape == (n_subjects, n_voxels)
    iscs_pw = isc(data, pairwise=True)
    assert iscs_pw.shape == (n_subjects * (n_subjects - 1) // 2, n_voxels)
    assert isc(data, summary_statistic='mean').shape == (n_voxels,)
    assert isc(data, summary_statistic='median').shape == (n_voxels,)
    # list input == array input
    data_list = [data[:, :, s] for s in range(n_subjects)]
    assert np.allclose(isc(data_list), iscs_loo)
    # two subjects: plain correlation, 1-D output
    iscs2 = isc(data[..., :2])
    assert iscs2.shape == (n_voxels,)
    with pytest.raises(ValueError):
        isc(data, summary_statistic='std')


def test_isc_detects_correlation():
    data = correlated_timeseries(10, 120, noise=0.1, random_state=42)
    for pairwise in (False, True):
        iscs = isc(data, pairwise=pairwise)
        assert np.all(iscs[:, :2] > 0.8)
        assert np.all(np.abs(iscs[:, 2]) < 0.5)


def test_isc_matches_numpy_oracle():
    data = simulated_timeseries(5, 40, 3, random_state=1)
    iscs = isc(data, pairwise=False)
    # oracle: plain numpy loop
    for s in range(5):
        others = np.mean(np.delete(data, s, axis=2), axis=2)
        for v in range(3):
            r = np.corrcoef(data[:, v, s], others[:, v])[0, 1]
            assert np.isclose(iscs[s, v], r, atol=1e-10)
    iscs_pw = isc(data, pairwise=True)
    k = 0
    for i in range(5):
        for j in range(i + 1, 5):
            for v in range(3):
                r = np.corrcoef(data[:, v, i], data[:, v, j])[0, 1]
                assert np.isclose(iscs_pw[k, v], r, atol=1e-10)
            k += 1


def test_isc_nans():
    n_subjects, n_TRs, n_voxels = 6, 30, 4
    data = simulated_timeseries(n_subjects, n_TRs, n_voxels, random_state=2)
    data[0, 0, 0] = np.nan
    # tolerant: only the NaN subject's own voxel ISC is NaN
    iscs_t = isc(data, pairwise=False, tolerate_nans=True)
    assert np.sum(np.isnan(iscs_t)) == 1
    # intolerant: every subject's ISC at that voxel is NaN
    iscs_f = isc(data, pairwise=False, tolerate_nans=False)
    assert np.sum(np.isnan(iscs_f)) == n_subjects
    # threshold float below requirement excludes voxel entirely
    iscs_80 = isc(data, pairwise=False, tolerate_nans=0.9)
    assert np.all(np.isnan(iscs_80[:, 0]))
    with pytest.raises(ValueError):
        isc(data, tolerate_nans=1.5)


def test_isfc_shapes_and_symmetry():
    n_subjects, n_TRs, n_voxels = 6, 50, 4
    data = simulated_timeseries(n_subjects, n_TRs, n_voxels, random_state=3)
    isfcs, iscs = isfc(data, pairwise=False)
    n_pairs_vox = n_voxels * (n_voxels - 1) // 2
    assert isfcs.shape == (n_subjects, n_pairs_vox)
    assert iscs.shape == (n_subjects, n_voxels)
    # consistency with isc()
    assert np.allclose(iscs, isc(data, pairwise=False), atol=1e-10)
    # square form
    sq = isfc(data, pairwise=False, vectorize_isfcs=False)
    assert sq.shape == (n_subjects, n_voxels, n_voxels)
    assert np.allclose(sq, np.swapaxes(sq, 1, 2))
    # squareform round-trip
    isfcs2, iscs2 = squareform_isfc(sq)
    assert np.allclose(isfcs2, isfcs) and np.allclose(iscs2, iscs)
    back = squareform_isfc(isfcs2, iscs2)
    assert np.allclose(back, sq)
    # pairwise shape
    isfcs_pw, iscs_pw = isfc(data, pairwise=True)
    assert isfcs_pw.shape == (n_subjects * (n_subjects - 1) // 2,
                              n_pairs_vox)


def test_isfc_two_subjects_and_single_inputs():
    """Reference edge cases (isc.py:529-590, 847-872): exactly two
    subjects collapse to one symmetrized matrix; single-subject
    squareform inputs round-trip without the leading axis; pairwise
    stat input must be a valid condensed triangle."""
    data = simulated_timeseries(2, 40, 4, random_state=5)
    sq = isfc(data, pairwise=False, vectorize_isfcs=False)
    assert sq.shape == (4, 4)

    # single square matrix <-> condensed vector round-trip
    n_subjects, n_voxels = 5, 4
    many = isfc(simulated_timeseries(n_subjects, 40, n_voxels,
                                     random_state=6),
                pairwise=False, vectorize_isfcs=False)
    one = many[0]
    v, d = squareform_isfc(one)
    assert v.shape == (n_voxels * (n_voxels - 1) // 2,)
    assert d.shape == (n_voxels,)
    back = squareform_isfc(v, d)
    assert np.allclose(back, one)

    # list input to the stat tests takes the 1-D promotion path
    iscs_list = [0.2, 0.3, 0.25, 0.35, 0.3]
    observed, ci, p, dist = bootstrap_isc(iscs_list, n_bootstraps=50)
    assert np.isscalar(p) or np.asarray(p).size == 1

    # malformed pairwise input: not a condensed triangle
    with pytest.raises(ValueError, match="vectorized triangle"):
        bootstrap_isc(np.array([0.1, 0.2, 0.3, 0.4]), pairwise=True,
                      n_bootstraps=10)


def test_isc_api_parity_surfaces():
    """Reference API conveniences: a RandomState instance as
    random_state, the summary-collapsed ISFC return, and the
    summary-statistic validation (reference isc.py:529-700)."""
    data = simulated_timeseries(5, 40, 4, random_state=7)

    # summary-statistic collapse: one condensed vector + one isc diag
    v, d = isfc(data, pairwise=False, summary_statistic='mean')
    assert v.shape == (4 * 3 // 2,) and d.shape == (4,)
    many_v, many_d = isfc(data, pairwise=False)
    np.testing.assert_allclose(
        v, compute_summary_statistic(many_v, 'mean', axis=0),
        atol=1e-12)

    # RandomState instance accepted wherever a seed is (reference
    # _check_random_state analog)
    iscs = isc(data)
    rs = np.random.RandomState(11)
    observed, ci, p, dist = bootstrap_isc(iscs, n_bootstraps=20,
                                          random_state=rs)
    assert np.asarray(dist).shape[0] == 20

    with pytest.raises(ValueError, match="mean"):
        permutation_isc(iscs, summary_statistic='mode')


def test_isfc_mesh_matches_dense():
    """Ring-sharded leave-one-out ISFC equals the replicated einsum path."""
    from brainiak_tpu.parallel import make_mesh
    from tests.conftest import mesh_atol

    rng = np.random.RandomState(3)
    data = rng.randn(40, 16, 5)
    mesh = make_mesh(("voxel",), (8,))
    dense = isfc(data, vectorize_isfcs=False)
    ringed = isfc(data, vectorize_isfcs=False, mesh=mesh)
    assert ringed.shape == dense.shape
    assert np.allclose(ringed, dense, atol=mesh_atol())
    with pytest.raises(ValueError):
        isfc(data, pairwise=True, mesh=mesh)
    # a partially-NaN voxel (kept by tolerate_nans) must propagate NaN the
    # same way the dense path does, not fabricate finite correlations
    d = data.copy()
    d[:5, 2, 1] = np.nan
    dense_nan = isfc(d, vectorize_isfcs=False)
    ring_nan = isfc(d, vectorize_isfcs=False, mesh=mesh)
    assert np.array_equal(np.isnan(ring_nan), np.isnan(dense_nan))
    assert np.allclose(ring_nan, dense_nan, atol=mesh_atol(),
                       equal_nan=True)
    # 2 subjects + mesh: explicit error, not silent dense fallback
    with pytest.raises(ValueError):
        isfc(data[..., :2], mesh=mesh)


def test_isc_and_nulls_mesh_match_single():
    """mesh= shards the voxel axis (NaN-padded to the shard count) and
    must reproduce the unsharded results; the null distributions are
    seeded so mesh-vs-single is an exact comparison of the same
    resamples."""
    from brainiak_tpu.parallel import make_mesh
    from tests.conftest import mesh_atol

    mesh = make_mesh(("voxel",), (8,))
    # 13 voxels: deliberately NOT divisible by 8 to exercise padding
    data = simulated_timeseries(
        n_subjects=5, n_TRs=40, n_voxels=13, noise=1.0, random_state=42)

    for pairwise in (False, True):
        plain = isc(data, pairwise=pairwise)
        sharded = isc(data, pairwise=pairwise, mesh=mesh)
        assert sharded.shape == plain.shape
        assert np.allclose(sharded, plain, atol=mesh_atol())

    iscs = isc(data)
    for fn, kwargs in ((bootstrap_isc, dict(n_bootstraps=30)),
                       (permutation_isc, dict(n_permutations=30))):
        r_plain = fn(iscs, random_state=7, **kwargs)
        r_mesh = fn(iscs, random_state=7, mesh=mesh,
                    null_batch_size=8, **kwargs)
        for a, b in zip(r_plain, r_mesh):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       atol=mesh_atol())

    for fn in (timeshift_isc, phaseshift_isc):
        r_plain = fn(data, n_shifts=20, random_state=7)
        r_mesh = fn(data, n_shifts=20, random_state=7, mesh=mesh,
                    null_batch_size=4)
        for a, b in zip(r_plain, r_mesh):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       atol=mesh_atol())


def test_isfc_targets_asymmetric():
    data = simulated_timeseries(5, 40, 4, random_state=4)
    targets = simulated_timeseries(5, 40, 7, random_state=5)
    out = isfc(data, targets=targets)
    assert out.shape == (5, 4, 7)
    # summary statistic collapses subjects
    out_m = isfc(data, targets=targets, summary_statistic='mean')
    assert out_m.shape == (4, 7)
    with pytest.raises(ValueError):
        isfc(data, targets=targets[:-1])


def test_compute_summary_statistic():
    iscs = np.array([[0.2, 0.4], [0.6, 0.8]])
    m = compute_summary_statistic(iscs, 'mean', axis=0)
    assert np.allclose(m, np.tanh(np.mean(np.arctanh(iscs), axis=0)))
    med = compute_summary_statistic(iscs, 'median', axis=0)
    assert np.allclose(med, [0.4, 0.6])
    with pytest.raises(ValueError):
        compute_summary_statistic(iscs, 'mode')


def test_bootstrap_isc():
    n_bootstraps = 100
    data = correlated_timeseries(15, 80, noise=0.5, random_state=42)
    for pairwise in (False, True):
        iscs = isc(data, pairwise=pairwise)
        observed, ci, p, distribution = bootstrap_isc(
            iscs, pairwise=pairwise, summary_statistic='median',
            n_bootstraps=n_bootstraps, random_state=0)
        assert distribution.shape == (n_bootstraps, 3)
        assert len(ci) == 2
        # correlated voxels significant; noise voxel not
        assert p[0] < 0.05 and p[1] < 0.05
        assert p[2] > 0.01
    # reproducible with same seed
    iscs = isc(data, pairwise=False)
    _, _, _, d1 = bootstrap_isc(iscs, n_bootstraps=50, random_state=7)
    _, _, _, d2 = bootstrap_isc(iscs, n_bootstraps=50, random_state=7)
    _, _, _, d3 = bootstrap_isc(iscs, n_bootstraps=50, random_state=8)
    assert np.array_equal(d1, d2)
    assert not np.array_equal(d1, d3)
    with pytest.raises(ValueError):
        bootstrap_isc(iscs, summary_statistic='mode')


def test_permutation_isc_one_sample():
    data = correlated_timeseries(12, 80, noise=0.5, random_state=42)
    for pairwise in (False, True):
        iscs = isc(data, pairwise=pairwise)
        observed, p, distribution = permutation_isc(
            iscs, pairwise=pairwise, summary_statistic='median',
            n_permutations=200, random_state=0)
        assert distribution.shape == (200, 3)
        assert p[0] < 0.05 and p[1] < 0.05
        # the noise voxel is strictly less significant than the signal
        # voxels (a fixed cutoff is too grainy at 200 permutations)
        assert p[2] > max(p[0], p[1])


def test_permutation_isc_one_sample_exact():
    data = correlated_timeseries(5, 60, noise=0.5, random_state=1)
    iscs = isc(data, pairwise=False)
    observed, p, distribution = permutation_isc(
        iscs, pairwise=False, n_permutations=100)  # 2**5=32 <= 100 -> exact
    assert distribution.shape == (32, 3)


def test_permutation_isc_two_sample():
    # group 1 strongly correlated, group 2 noisy
    g1 = simulated_timeseries(8, 60, 4, noise=0.5, random_state=3)
    g2 = simulated_timeseries(8, 60, 4, noise=20.0, random_state=4)
    iscs = np.vstack([isc(g1, pairwise=False), isc(g2, pairwise=False)])
    group_assignment = [1] * 8 + [2] * 8
    observed, p, distribution = permutation_isc(
        iscs, group_assignment=group_assignment, pairwise=False,
        summary_statistic='mean', n_permutations=200, random_state=0)
    assert distribution.shape == (200, 4)
    # group difference should be significant
    assert np.all(np.asarray(p) < 0.05)
    # pairwise two-sample on combined data
    data = np.dstack([g1, g2])
    iscs_pw = isc(data, pairwise=True)
    observed2, p2, dist2 = permutation_isc(
        iscs_pw, group_assignment=group_assignment, pairwise=True,
        summary_statistic='mean', n_permutations=200, random_state=0)
    assert dist2.shape == (200, 4)
    assert np.all(np.asarray(p2) < 0.1)


def test_permutation_isc_two_sample_exact():
    g1 = simulated_timeseries(3, 40, 3, noise=0.5, random_state=3)
    g2 = simulated_timeseries(3, 40, 3, noise=10.0, random_state=4)
    iscs = np.vstack([isc(g1, pairwise=False), isc(g2, pairwise=False)])
    observed, p, distribution = permutation_isc(
        iscs, group_assignment=[1, 1, 1, 2, 2, 2], pairwise=False,
        summary_statistic='mean', n_permutations=1000)  # 6! = 720 -> exact
    assert distribution.shape == (720, 3)
    with pytest.raises(ValueError):
        permutation_isc(iscs, group_assignment=[1, 1, 2, 2, 3, 3])
    with pytest.raises(ValueError):
        permutation_isc(iscs, group_assignment=[1, 1, 2])


def test_timeshift_isc():
    data = correlated_timeseries(10, 80, noise=0.5, random_state=42)
    observed, p, distribution = timeshift_isc(
        data, pairwise=False, n_shifts=100, random_state=0)
    assert distribution.shape == (100, 3)
    assert p[0] < 0.05 and p[1] < 0.05 and p[2] > 0.01
    observed, p, distribution = timeshift_isc(
        data, pairwise=True, n_shifts=50, random_state=0)
    assert distribution.shape == (50, 3)


def test_phaseshift_isc():
    data = correlated_timeseries(10, 80, noise=0.5, random_state=42)
    observed, p, distribution = phaseshift_isc(
        data, pairwise=False, n_shifts=100, random_state=0)
    assert distribution.shape == (100, 3)
    assert p[0] < 0.05 and p[1] < 0.05 and p[2] > 0.01
    observed, p, distribution = phaseshift_isc(
        data, pairwise=True, n_shifts=50, random_state=0)
    assert distribution.shape == (50, 3)


def test_resampling_preserves_nan_voxel_columns():
    """Voxels excluded by the NaN threshold must come back as NaN columns,
    keeping outputs positionally aligned with the input voxel axis."""
    rng = np.random.RandomState(0)
    data = rng.randn(30, 4, 6)
    data[:, 1, :] = np.nan
    for fn in (timeshift_isc, phaseshift_isc):
        obs, p, dist = fn(data, n_shifts=10, random_state=0)
        assert obs.shape == (4,)
        assert dist.shape == (10, 4)
        assert np.isnan(obs[1]) and np.all(np.isnan(dist[:, 1]))
        assert np.all(np.isfinite(dist[:, [0, 2, 3]]))
