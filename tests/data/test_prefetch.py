"""ShardPrefetcher: batching parity, backpressure, failure and
obs-disabled contracts."""

import threading
import time

import numpy as np
import pytest

from brainiak_tpu.data import (ShardPrefetcher, subject_shards,
                               write_store)


def make_store(tmp_path, n=6, voxels=12, samples=10, ragged=True,
               seed=0, name="st"):
    rng = np.random.RandomState(seed)
    subjects = [rng.randn(voxels + (i if ragged else 0), samples)
                for i in range(n)]
    return write_store(str(tmp_path / name), subjects), subjects


def test_subject_shards():
    assert subject_shards(7, 3) == [(0, 3), (3, 6), (6, 7)]
    assert subject_shards(4, 8) == [(0, 4)]
    with pytest.raises(ValueError):
        subject_shards(4, 0)


def test_batches_match_stack_and_pad(tmp_path):
    """A full pass reassembles exactly what the in-memory stacker
    produces: padded data, counts, raw traces, demeaned rows."""
    from brainiak_tpu.funcalign.srm import _stack_and_pad

    store, subjects = make_store(tmp_path)
    stacked, counts, mu, trace = _stack_and_pad(subjects, np.float64)
    shards = subject_shards(6, 4)
    got = np.zeros_like(stacked)
    with ShardPrefetcher(store, shards, dtype=np.float64, lanes=4,
                         demean=True, want_means=True) as pf:
        for batch in pf:
            xb = np.asarray(batch.x)
            for j, subj in enumerate(range(batch.lo, batch.hi)):
                got[subj] = xb[j]
                np.testing.assert_allclose(batch.means[j], mu[subj])
                assert batch.counts[j] == counts[subj]
                assert batch.mask[j] == 1.0
                np.testing.assert_allclose(batch.trace_xtx[j],
                                           trace[subj])
            # pad lanes are fully masked zeros
            for j in range(batch.hi - batch.lo, 4):
                assert batch.mask[j] == 0.0
                assert np.all(xb[j] == 0.0)
    np.testing.assert_allclose(got, stacked)


def test_raw_mode_returns_ragged_subjects(tmp_path):
    store, subjects = make_store(tmp_path)
    with ShardPrefetcher(store, subject_shards(6, 4), raw=True,
                         dtype=np.float64) as pf:
        seen = []
        for batch in pf:
            assert batch.x is None
            seen.extend(batch.subjects)
    assert len(seen) == 6
    for got, want in zip(seen, subjects):
        np.testing.assert_array_equal(got, want)


def test_bounded_buffer_backpressure(tmp_path, monkeypatch):
    """depth=1: the loader must never run more than depth+1 shards
    ahead of the consumer (bounded working set is the contract)."""
    store, _ = make_store(tmp_path, n=8)
    reads = []
    orig = store.read

    def counting_read(i, verify=False):
        reads.append(i)
        return orig(i, verify=verify)

    monkeypatch.setattr(store, "read", counting_read)
    shards = subject_shards(8, 2)  # 4 shards of 2 subjects
    pf = ShardPrefetcher(store, shards, dtype=np.float64, depth=1)
    try:
        deadline = time.time() + 5.0
        # without consuming anything: at most (queued=1) + (in
        # flight=1) shards of reads may ever happen
        while len(reads) < 4 and time.time() < deadline:
            time.sleep(0.02)
        time.sleep(0.2)  # give an over-eager loader time to overrun
        assert len(reads) <= 4, reads
        consumed = sum(1 for _ in pf)
        assert consumed == 4
        assert sorted(reads) == list(range(8))
    finally:
        pf.close()


def test_loader_failure_propagates_original_error(tmp_path,
                                                  monkeypatch):
    """A failing subject read fails the consuming fit with the
    ORIGINAL exception — and never hangs."""
    store, _ = make_store(tmp_path, n=6)
    orig = store.read
    boom = ValueError("subject 3 unreadable")

    def failing_read(i, verify=False):
        if i == 3:
            raise boom
        return orig(i, verify=verify)

    monkeypatch.setattr(store, "read", failing_read)
    pf = ShardPrefetcher(store, subject_shards(6, 2),
                         dtype=np.float64, depth=1)
    with pytest.raises(ValueError) as err:
        for _ in pf:
            pass
    assert err.value is boom


def test_retry_absorbs_transient_io_error(tmp_path):
    from brainiak_tpu.resilience import faults

    store, subjects = make_store(tmp_path, n=4)
    with faults.inject("io_error", times=1) as fault:
        with ShardPrefetcher(store, subject_shards(4, 2),
                             dtype=np.float64) as pf:
            n = sum(1 for _ in pf)
    assert n == 2
    assert fault.fired == 1


def test_obs_disabled_adds_zero_syncs(tmp_path, monkeypatch):
    """With no sink configured the pipeline must never call
    block_until_ready — prefetch stays fully asynchronous."""
    import jax

    from brainiak_tpu.obs import sink

    assert not sink.enabled()
    calls = []
    orig = jax.block_until_ready

    def spying_block(x):
        calls.append(1)
        return orig(x)

    monkeypatch.setattr(jax, "block_until_ready", spying_block)
    store, _ = make_store(tmp_path)
    with ShardPrefetcher(store, subject_shards(6, 3),
                         dtype=np.float64) as pf:
        batches = list(pf)
    assert len(batches) == 2
    assert calls == []


def test_obs_enabled_times_the_copy_off_thread(tmp_path):
    """Enabled: the loader thread syncs the placed batch (charging
    H2D to the prefetch span) and the instrumentation lands —
    spans, h2d bytes, per-shard seconds."""
    from brainiak_tpu.obs import metrics as obs_metrics
    from brainiak_tpu.obs import sink

    mem = sink.add_sink(sink.MemorySink())
    try:
        store, _ = make_store(tmp_path)
        h2d0 = obs_metrics.counter("data_h2d_bytes_total").value()
        with ShardPrefetcher(store, subject_shards(6, 3),
                             dtype=np.float64) as pf:
            n = sum(1 for _ in pf)
        assert n == 2
        h2d = obs_metrics.counter("data_h2d_bytes_total").value() \
            - h2d0
        assert h2d == 2 * 3 * store.v_max * store.samples * 8
        spans = [r for r in mem.records if r.get("kind") == "span"
                 and r.get("name") == "data.prefetch_shard"]
        assert len(spans) == 2
        hist = obs_metrics.histogram("data_prefetch_seconds")
        assert hist.summary()["count"] >= 2
    finally:
        sink.remove_sink(mem)


def test_mesh_placement_lands_on_subject_axis(tmp_path):
    from brainiak_tpu.parallel import make_mesh

    store, _ = make_store(tmp_path, n=8, ragged=False)
    mesh = make_mesh(("subject",), (4,))
    with ShardPrefetcher(store, subject_shards(8, 4),
                         dtype=np.float64, lanes=4,
                         mesh=mesh) as pf:
        batch = next(iter(pf))
        sharding = batch.x.sharding
        assert sharding.spec[0] == "subject"
    # lane count must be a multiple of the axis
    with pytest.raises(ValueError, match="multiple"):
        ShardPrefetcher(store, subject_shards(8, 3),
                        dtype=np.float64, lanes=3, mesh=mesh)


def test_close_mid_pass_releases_loader(tmp_path):
    store, _ = make_store(tmp_path, n=8)
    pf = ShardPrefetcher(store, subject_shards(8, 2),
                         dtype=np.float64, depth=1)
    next(iter(pf))  # consume one shard, then abandon the pass
    pf.close()
    deadline = time.time() + 5.0
    while pf._thread.is_alive() and time.time() < deadline:
        time.sleep(0.02)
    assert not pf._thread.is_alive()
    assert threading.active_count() < 50  # no thread leak
