"""Streamed SRM/DetSRM fits: parity, resume, memory, incremental."""

import numpy as np
import pytest

from brainiak_tpu.data import IncrementalSRM, write_store
from brainiak_tpu.funcalign.srm import SRM, DetSRM


def make_synthetic(n_subjects=6, voxels=24, samples=30, features=3,
                   noise=0.1, seed=0, ragged=True):
    rng = np.random.RandomState(seed)
    shared = rng.randn(features, samples)
    X = []
    for i in range(n_subjects):
        v = voxels + (i if ragged else 0)
        q, _ = np.linalg.qr(rng.randn(v, features))
        X.append(q @ shared + noise * rng.randn(v, samples))
    return X


@pytest.fixture()
def store_and_data(tmp_path):
    X = make_synthetic()
    return write_store(str(tmp_path / "store"), X), X


def assert_model_parity(a, b, atol=1e-6):
    for w0, w1 in zip(a.w_, b.w_):
        np.testing.assert_allclose(w0, w1, atol=atol)
    np.testing.assert_allclose(a.s_, b.s_, atol=atol)


def test_streamed_srm_matches_in_memory(store_and_data):
    """The acceptance parity: a streamed fit over uneven subject
    shards reproduces the stacked fit at the same schedule."""
    store, X = store_and_data
    inmem = SRM(n_iter=6, features=3).fit(X)
    streamed = SRM(n_iter=6, features=3, shard_subjects=4).fit(store)
    assert_model_parity(inmem, streamed)
    np.testing.assert_allclose(inmem.rho2_, streamed.rho2_,
                               atol=1e-8)
    np.testing.assert_allclose(inmem.sigma_s_, streamed.sigma_s_,
                               atol=1e-6)
    assert abs(inmem.logprob_ - streamed.logprob_) < 1e-4
    for m0, m1 in zip(inmem.mu_, streamed.mu_):
        np.testing.assert_allclose(m0, m1, atol=1e-12)


def test_streamed_detsrm_matches_in_memory(store_and_data):
    store, X = store_and_data
    inmem = DetSRM(n_iter=6, features=3).fit(X)
    streamed = DetSRM(n_iter=6, features=3,
                      shard_subjects=4).fit(store)
    assert_model_parity(inmem, streamed)
    assert abs(inmem.objective_ - streamed.objective_) \
        / abs(inmem.objective_) < 1e-6


def test_streamed_srm_on_mesh_matches(tmp_path):
    from brainiak_tpu.parallel import make_mesh

    X = make_synthetic(n_subjects=8, ragged=False)
    store = write_store(str(tmp_path / "st"), X)
    inmem = SRM(n_iter=5, features=3).fit(X)
    mesh = make_mesh(("subject",), (4,))
    streamed = SRM(n_iter=5, features=3, mesh=mesh,
                   shard_subjects=4).fit(store)
    assert_model_parity(inmem, streamed, atol=1e-5)


def test_streamed_fit_never_stacks_and_stays_under_budget(
        tmp_path, monkeypatch):
    """ISSUE 13 acceptance: a streamed fit over a store whose stack
    exceeds the configured host budget completes WITHOUT ever
    materializing the [subjects, V, T] stack — asserted structurally
    (the stacker is poisoned) and via the PR-4 memory_watermark
    gauges (host peak-RSS growth stays well under the stack size)."""
    import brainiak_tpu.funcalign.srm as srm_mod
    from brainiak_tpu.obs import metrics as obs_metrics
    from brainiak_tpu.obs import profile as obs_profile
    from brainiak_tpu.obs import sink

    X = make_synthetic(n_subjects=24, voxels=3000, samples=150,
                       ragged=False, features=3)
    store = write_store(str(tmp_path / "st"), X,
                        dtype=np.float64)
    stack_bytes = store.stack_nbytes  # ~86 MB
    assert stack_bytes > 80 * 1024 * 1024
    del X
    # the configured host budget is SMALLER than the dataset: the
    # auto shard size must make the fit stream in small batches
    monkeypatch.setenv("BRAINIAK_TPU_DATA_BUDGET_BYTES",
                       str(16 * 1024 * 1024))

    def poisoned_stack(*a, **k):  # the in-memory path must not run
        raise AssertionError(
            "streamed fit materialized the stacked tensor")

    monkeypatch.setattr(srm_mod, "_stack_and_pad", poisoned_stack)
    mem = sink.add_sink(sink.MemorySink())
    try:
        before = obs_profile.memory_watermark()
        model = SRM(n_iter=2, features=3).fit(store)
        after = obs_profile.memory_watermark()
    finally:
        sink.remove_sink(mem)
    assert len(model.w_) == 24
    assert np.isfinite(model.logprob_)
    # watermark gauges were set per fit chunk under the stream name
    gauge = obs_metrics.gauge("host_peak_rss_bytes")
    assert gauge.value(estimator="SRM.fit_stream") is not None
    if before["host_rss"] and after["host_rss"]:
        # the in-memory path would grow peak RSS by >= stack_bytes
        # (host stack + device copy); the streamed fit's growth is
        # bounded by the shard working set + fixed jit overheads
        growth = after["host_rss"] - before["host_rss"]
        assert growth < 0.5 * stack_bytes, (
            f"host peak RSS grew {growth} bytes, vs a "
            f"{stack_bytes}-byte stack — did something stack?")


def test_streamed_resume_after_preemption(store_and_data, tmp_path):
    """ISSUE 13 acceptance: an injected preemption mid-stream, then
    a resume at the last completed shard round, reproducing the
    uninterrupted fit."""
    from brainiak_tpu.resilience import faults

    store, _ = store_and_data
    full = SRM(n_iter=8, features=3, shard_subjects=4).fit(store)
    ck = str(tmp_path / "ck")
    with pytest.raises(faults.PreemptionError):
        with faults.inject("preempt", at_step=4):
            SRM(n_iter=8, features=3, shard_subjects=4).fit(
                store, checkpoint_dir=ck, checkpoint_every=2)
    resumed = SRM(n_iter=8, features=3, shard_subjects=4).fit(
        store, checkpoint_dir=ck, checkpoint_every=2)
    assert_model_parity(full, resumed, atol=1e-10)
    assert abs(full.logprob_ - resumed.logprob_) < 1e-8


def test_streamed_resume_refuses_modified_store(store_and_data,
                                                tmp_path):
    """Digest-mismatch refusal: a checkpoint written against one
    store must not resume against rewritten contents."""
    from brainiak_tpu.resilience import faults

    store, X = store_and_data
    ck = str(tmp_path / "ck")
    with pytest.raises(faults.PreemptionError):
        with faults.inject("preempt", at_step=2):
            SRM(n_iter=6, features=3, shard_subjects=4).fit(
                store, checkpoint_dir=ck, checkpoint_every=2)
    modified = write_store(str(tmp_path / "store"),
                           [x + 1.0 for x in X])
    with pytest.raises(ValueError, match="different data"):
        SRM(n_iter=6, features=3, shard_subjects=4).fit(
            modified, checkpoint_dir=ck, checkpoint_every=2)


def test_repeat_rounds_rebuild_no_programs(store_and_data):
    """Retrace stability: a SECOND streamed fit (more shard rounds,
    same shapes) must hit every srm.stream_* builder cache."""
    from brainiak_tpu.data import streaming_fit as sf

    store, _ = store_and_data
    SRM(n_iter=2, features=3, shard_subjects=4).fit(store)
    builders = (sf._init_program, sf._prob_shard_program,
                sf._prob_global_program, sf._ll_program)
    misses = [b.cache_info().misses for b in builders]
    SRM(n_iter=3, features=3, shard_subjects=4).fit(store)
    assert [b.cache_info().misses for b in builders] == misses


def test_streamed_fit_validates_store(tmp_path):
    lone = write_store(str(tmp_path / "one"),
                       [np.random.randn(10, 8)])
    with pytest.raises(ValueError, match="not enough subjects"):
        SRM(n_iter=2, features=3).fit(lone)
    small = write_store(str(tmp_path / "small"),
                        make_synthetic(samples=4))
    with pytest.raises(ValueError, match="not enough samples"):
        SRM(n_iter=2, features=10).fit(small)


# -- incremental / minibatch variant ---------------------------------

def test_incremental_srm_recovers_shared_structure(tmp_path):
    X = make_synthetic(n_subjects=8, ragged=False)
    store = write_store(str(tmp_path / "st"), X)
    inc = IncrementalSRM(n_iter=3, features=3,
                         batch_subjects=3).fit(store)
    assert inc.s_.shape == (3, 30)
    assert inc.n_seen_ >= 8
    s = inc.transform(X)
    corrs = [np.corrcoef(s[i].ravel(), s[j].ravel())[0, 1]
             for i in range(8) for j in range(i + 1, 8)]
    assert np.mean(corrs) > 0.9
    basis = inc.subject_basis(X[0])
    np.testing.assert_allclose(basis.T @ basis, np.eye(3),
                               atol=1e-8)


def test_incremental_partial_fit_matches_fit_round(tmp_path):
    """One fit round over the store == partial_fit over the same
    minibatches in order."""
    X = make_synthetic(n_subjects=6, ragged=False)
    store = write_store(str(tmp_path / "st"), X)
    a = IncrementalSRM(n_iter=1, features=3, batch_subjects=2)
    a.fit(store)
    b = IncrementalSRM(n_iter=1, features=3, batch_subjects=2)
    for lo in range(0, 6, 2):
        b.partial_fit(X[lo:lo + 2])
    np.testing.assert_allclose(a.s_, b.s_, atol=1e-10)
    assert a.n_seen_ == b.n_seen_ == 6


def test_incremental_checkpoint_resume(tmp_path):
    from brainiak_tpu.resilience import faults

    X = make_synthetic(n_subjects=6, ragged=False)
    store = write_store(str(tmp_path / "st"), X)
    full = IncrementalSRM(n_iter=4, features=3,
                          batch_subjects=2).fit(store)
    ck = str(tmp_path / "ck")
    with pytest.raises(faults.PreemptionError):
        with faults.inject("preempt", at_step=2):
            IncrementalSRM(n_iter=4, features=3,
                           batch_subjects=2).fit(
                store, checkpoint_dir=ck)
    resumed = IncrementalSRM(n_iter=4, features=3,
                             batch_subjects=2).fit(
        store, checkpoint_dir=ck)
    np.testing.assert_allclose(full.s_, resumed.s_, atol=1e-10)


def test_incremental_errors(tmp_path):
    X = make_synthetic(n_subjects=4, ragged=False)
    with pytest.raises(ValueError, match="not enough subjects"):
        IncrementalSRM(features=3).fit([X[0]])
    with pytest.raises(ValueError, match="SubjectStore"):
        IncrementalSRM(features=3).fit(
            X, checkpoint_dir=str(tmp_path / "ck"))
    inc = IncrementalSRM(features=3)
    with pytest.raises(RuntimeError, match="has not been run"):
        inc.subject_basis(X[0])
    inc.partial_fit(X[:2])
    with pytest.raises(ValueError, match="samples"):
        inc.partial_fit([np.random.randn(10, 7)])


def test_streaming_fit_uses_budget_for_default_shard(tmp_path,
                                                     monkeypatch):
    """With no explicit shard_subjects the lane count follows the
    host budget: (depth+1) in-flight batches must fit."""
    from brainiak_tpu.data.prefetch import host_budget_bytes
    from brainiak_tpu.data.streaming_fit import _resolve_lanes

    X = make_synthetic(n_subjects=6, voxels=24, samples=30,
                       ragged=False)
    store = write_store(str(tmp_path / "st"), X)
    per_subject = store.v_max * store.samples * 8
    monkeypatch.setenv("BRAINIAK_TPU_DATA_BUDGET_BYTES",
                       str(per_subject * 6))
    assert host_budget_bytes() == per_subject * 6
    lanes = _resolve_lanes(store, None, None, np.float64, depth=2)
    assert lanes == 2  # budget / (per_subject * (2+1))
    # and the fit actually runs at that lane count
    model = SRM(n_iter=2, features=3).fit(store)
    assert len(model.w_) == 6


def test_fit_still_takes_lists_unchanged(store_and_data):
    """The in-memory default path is untouched: list input behaves
    exactly as before (guard: the store dispatch must not disturb
    it)."""
    _, X = store_and_data
    model = SRM(n_iter=4, features=3).fit(X)
    assert len(model.w_) == len(X)
    assert model.s_.shape == (3, 30)
