"""SubjectStore: layout round-trips, digests, lazy refs."""

import numpy as np
import pytest

from brainiak_tpu.data import open_store, write_store
from brainiak_tpu.data.store import STORE_FORMATS


def make_subjects(n=4, voxels=20, samples=15, ragged=True, seed=0,
                  dtype=np.float64):
    rng = np.random.RandomState(seed)
    return [rng.randn(voxels + (i if ragged else 0),
                      samples).astype(dtype)
            for i in range(n)]


@pytest.mark.parametrize("fmt", STORE_FORMATS)
def test_write_open_read_roundtrip(tmp_path, fmt):
    subjects = make_subjects(dtype=np.float32)
    store = write_store(str(tmp_path / "st"), subjects, fmt=fmt)
    reopened = open_store(str(tmp_path / "st"))
    assert reopened.n_subjects == 4
    assert reopened.samples == 15
    assert reopened.format == fmt
    assert list(reopened.voxel_counts) == [20, 21, 22, 23]
    for i, subj in enumerate(subjects):
        got = reopened.read(i)
        assert got.dtype == np.float32
        np.testing.assert_array_equal(got, subj)


def test_read_verify_catches_out_of_band_rewrite(tmp_path):
    subjects = make_subjects(ragged=False)
    store = write_store(str(tmp_path / "st"), subjects)
    assert store.read(1, verify=True).shape == (20, 15)
    # rewrite one subject file behind the manifest's back
    np.save(store.path(1), subjects[1] + 5.0)
    with pytest.raises(ValueError, match="digest"):
        store.read(1, verify=True)
    # unverified read still returns the (new) bytes
    assert store.read(1).shape == (20, 15)


def test_fingerprint_tracks_content_not_layout(tmp_path):
    subjects = make_subjects()
    a = write_store(str(tmp_path / "a"), subjects)
    b = write_store(str(tmp_path / "b"), subjects)
    np.testing.assert_allclose(a.fingerprint(), b.fingerprint())
    c = write_store(str(tmp_path / "c"),
                    [subjects[0] + 1e-3] + subjects[1:])
    assert not np.allclose(a.fingerprint(), c.fingerprint())


def test_open_store_errors(tmp_path):
    with pytest.raises(FileNotFoundError, match="not a subject store"):
        open_store(str(tmp_path / "missing"))


def test_write_store_validation(tmp_path):
    with pytest.raises(ValueError, match="format"):
        write_store(str(tmp_path / "x"), [np.zeros((3, 4))],
                    fmt="hdf5")
    with pytest.raises(ValueError, match="empty"):
        write_store(str(tmp_path / "x"), [])
    with pytest.raises(ValueError, match="2-D"):
        write_store(str(tmp_path / "x"), [np.zeros(3)])
    with pytest.raises(ValueError, match="samples"):
        write_store(str(tmp_path / "x"),
                    [np.zeros((3, 4)), np.zeros((3, 5))])


def test_read_shape_mismatch_refused(tmp_path):
    store = write_store(str(tmp_path / "st"),
                        make_subjects(ragged=False))
    np.save(store.path(0), np.zeros((7, 15), dtype=np.float64))
    with pytest.raises(ValueError, match="shape"):
        store.read(0)


def test_subject_ref_streams_voxel_chunks(tmp_path):
    subjects = make_subjects(dtype=np.float32)
    store = write_store(str(tmp_path / "st"), subjects)
    ref = store.ref(2)
    assert ref.shape == (22, 15)
    np.testing.assert_array_equal(ref.load(), subjects[2])
    seen = np.zeros_like(subjects[2])
    for start, block in ref.iter_voxel_chunks(chunk_voxels=5):
        assert block.shape[0] <= 5
        seen[start:start + block.shape[0]] = block
    np.testing.assert_array_equal(seen, subjects[2])


def test_nbytes_accounting(tmp_path):
    store = write_store(str(tmp_path / "st"),
                        make_subjects(dtype=np.float32))
    assert store.total_nbytes == sum(20 + i for i in range(4)) * 15 * 4
    assert store.stack_nbytes == 4 * 23 * 15 * 4
    assert store.stack_nbytes >= store.total_nbytes


def test_store_dtype_cast_is_digested(tmp_path):
    """float64 inputs stored as float32 must digest the CAST bytes,
    so read-back verification agrees with the manifest."""
    subjects = make_subjects(dtype=np.float64)
    store = write_store(str(tmp_path / "st"), subjects,
                        dtype=np.float32)
    for i in range(store.n_subjects):
        store.read(i, verify=True)


def test_store_read_retries_transient_io(tmp_path):
    from brainiak_tpu.resilience import faults

    store = write_store(str(tmp_path / "st"), make_subjects())
    with faults.inject("io_error", times=1) as fault:
        got = store.read(0)
    assert fault.fired == 1  # failed once, retried, succeeded
    np.testing.assert_array_equal(got, store.read(0))
