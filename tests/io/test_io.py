from pathlib import Path

import numpy as np
import pytest

from brainiak_tpu import io, nifti
from brainiak_tpu.image import (
    MaskedMultiSubjectData,
    SingleConditionSpec,
    mask_image,
    mask_images,
    multimask_images,
)

# Real NIfTI fixtures from the reference test data (read-only).
DATA_DIR = Path("/root/reference/tests/io/data")


def test_load_images_from_dir_shape():
    images = list(io.load_images_from_dir(DATA_DIR, "bet.nii.gz"))
    assert len(images) == 2
    for img in images:
        assert img.shape == (64, 64, 26, 10)
        data = img.get_fdata()
        assert np.all(np.isfinite(data))
        assert data.max() > 0


def test_load_images_explicit_paths():
    paths = [DATA_DIR / "subject1_bet.nii.gz",
             DATA_DIR / "subject2_bet.nii.gz"]
    images = list(io.load_images(paths))
    assert len(images) == 2
    assert images[0].shape == (64, 64, 26, 10)


def test_load_boolean_mask():
    mask = io.load_boolean_mask(DATA_DIR / "mask.nii.gz")
    assert mask.dtype == bool
    assert mask.shape == (64, 64, 26)
    assert 0 < mask.sum() < mask.size
    # predicate variant
    mask2 = io.load_boolean_mask(DATA_DIR / "mask.nii.gz", lambda x: x > 0)
    assert np.array_equal(mask, mask2)


def test_load_labels():
    specs = io.load_labels(DATA_DIR / "epoch_labels.npy")
    assert len(specs) == 2
    for spec in specs:
        assert isinstance(spec, SingleConditionSpec)
        assert spec.shape == (2, 2, 10)
        labels = spec.extract_labels()
        assert labels.shape == (2,)
        assert set(labels) <= {0, 1}


def test_mask_image_and_multisubject_stack():
    mask = io.load_boolean_mask(DATA_DIR / "mask.nii.gz")
    images = list(io.load_images_from_dir(DATA_DIR, "bet.nii.gz"))
    masked = [mask_image(img, mask) for img in images]
    n_vox = int(mask.sum())
    for m in masked:
        assert m.shape == (n_vox, 10)
    data = MaskedMultiSubjectData.from_masked_images(iter(masked), 2)
    assert data.shape == (10, n_vox, 2)
    assert np.allclose(data[:, :, 0], masked[0].T)
    with pytest.raises(ValueError):
        MaskedMultiSubjectData.from_masked_images(iter(masked), 3)
    with pytest.raises(ValueError):
        MaskedMultiSubjectData.from_masked_images(
            iter([masked[0], masked[1][:-1]]), 2)


def test_from_masked_images_empty_iterator():
    with pytest.raises(ValueError, match="!= 0"):
        MaskedMultiSubjectData.from_masked_images(iter([]), 2)


def test_mask_images_generators():
    mask = io.load_boolean_mask(DATA_DIR / "mask.nii.gz")
    images = io.load_images_from_dir(DATA_DIR, "bet.nii.gz")
    out = list(mask_images(images, mask, np.float32))
    assert len(out) == 2
    assert out[0].dtype == np.float32
    images = io.load_images_from_dir(DATA_DIR, "bet.nii.gz")
    multi = list(multimask_images(images, (mask, mask)))
    assert len(multi) == 2 and len(multi[0]) == 2
    with pytest.raises(ValueError):
        mask_image(np.zeros((2, 2, 2, 5)), np.ones((3, 3, 3), dtype=bool))


def test_nifti_roundtrip(tmp_path):
    rng = np.random.RandomState(0)
    data = rng.rand(7, 6, 5, 4).astype(np.float32)
    affine = np.array([[2.0, 0, 0, -10], [0, 2.0, 0, -20],
                       [0, 0, 3.0, 5], [0, 0, 0, 1]])
    for name in ["img.nii", "img.nii.gz"]:
        path = tmp_path / name
        io.save_as_nifti_file(data, affine, path)
        img = nifti.load(path)
        assert img.shape == data.shape
        assert np.allclose(img.get_fdata(), data, atol=1e-6)
        assert np.allclose(img.affine, affine)


def test_nifti_int_dtype_roundtrip(tmp_path):
    data = np.arange(24, dtype=np.int16).reshape(2, 3, 4)
    path = tmp_path / "int.nii.gz"
    nifti.save(nifti.NiftiImage(data, np.eye(4)), path)
    img = nifti.load(path)
    assert np.array_equal(img.dataobj, data)
    assert img.dataobj.dtype == np.int16


def test_nifti_rejects_garbage(tmp_path):
    p = tmp_path / "bad.nii"
    p.write_bytes(b"\x00" * 400)
    with pytest.raises(ValueError):
        nifti.load(p)


def test_nifti_malformed_headers(tmp_path):
    """Each header validation fires its own error: truncated file, bad
    magic, invalid ndim, unknown datatype (NIfTI-1 spec fields)."""
    import struct

    good = tmp_path / "good.nii"
    nifti.save(nifti.NiftiImage(np.zeros((2, 2, 2), np.float32),
                                np.eye(4)), good)
    raw = bytearray(good.read_bytes())

    short = tmp_path / "short.nii"
    short.write_bytes(raw[:100])
    with pytest.raises(ValueError, match="too short"):
        nifti.load(short)

    bad_magic = bytearray(raw)
    bad_magic[344:348] = b"xxx\x00"
    p = tmp_path / "magic.nii"
    p.write_bytes(bad_magic)
    with pytest.raises(ValueError, match="magic"):
        nifti.load(p)

    bad_ndim = bytearray(raw)
    bad_ndim[40:42] = struct.pack("<h", 0)
    p = tmp_path / "ndim.nii"
    p.write_bytes(bad_ndim)
    with pytest.raises(ValueError, match="ndim"):
        nifti.load(p)

    bad_dtype = bytearray(raw)
    bad_dtype[70:72] = struct.pack("<h", 9999)
    p = tmp_path / "dtype.nii"
    p.write_bytes(bad_dtype)
    with pytest.raises(ValueError, match="datatype"):
        nifti.load(p)


def test_nifti_scl_slope_and_save_coercion(tmp_path):
    """scl_slope/scl_inter rescale on read (the NIfTI-1 scaling
    contract), save() rejects non-NiftiImage input, and unsupported
    dtypes are coerced to float32."""
    import struct

    data = np.arange(8, dtype=np.int16).reshape(2, 2, 2)
    p = tmp_path / "scaled.nii"
    nifti.save(nifti.NiftiImage(data, np.eye(4)), p)
    raw = bytearray(p.read_bytes())
    # scl_slope at offset 112, scl_inter at 116 (NIfTI-1 layout)
    raw[112:116] = struct.pack("<f", 2.5)
    raw[116:120] = struct.pack("<f", 10.0)
    p.write_bytes(raw)
    img = nifti.load(p)
    np.testing.assert_allclose(img.get_fdata(), data * 2.5 + 10.0)

    with pytest.raises(TypeError):
        nifti.save(np.zeros((2, 2, 2)), tmp_path / "notimg.nii")

    halves = np.zeros((2, 2, 2), dtype=np.float16)  # not a NIfTI code
    p2 = tmp_path / "coerced.nii"
    nifti.save(nifti.NiftiImage(halves, np.eye(4)), p2)
    assert nifti.load(p2).dataobj.dtype == np.float32
