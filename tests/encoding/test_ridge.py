"""Encoding tier: eigendecomposition ridge vs sklearn, the
one-program lambda sweep (ISSUE 7 acceptance), banded grouping,
and the resilient checkpoint/resume contract."""

import numpy as np
import pytest

from brainiak_tpu.encoding import BandedRidgeEncoder, RidgeEncoder
from brainiak_tpu.obs import metrics

ENC_SITES = ("encoding.prepare", "encoding.sweep", "encoding.refit",
             "encoding.banded_prepare", "encoding.banded_sweep",
             "encoding.banded_refit")


def _retraces():
    c = metrics.counter("retrace_total")
    return sum(c.value(site=s) for s in ENC_SITES)


def _make_data(t, f, v, seed=0, noise=0.5):
    rng = np.random.RandomState(seed)
    x = rng.randn(t, f).astype(np.float32)
    w = (rng.randn(f, v) / np.sqrt(f)).astype(np.float32)
    y = (x @ w + noise * rng.randn(t, v)).astype(np.float32)
    return x, y


def _sklearn_predictions(enc, x, y):
    """Per-voxel sklearn Ridge predictions at the CV-selected
    lambdas (voxels grouped by selected lambda — sklearn fits one
    multi-output Ridge per group)."""
    from sklearn.linear_model import Ridge

    sk = np.empty((x.shape[0], y.shape[1]), dtype=np.float64)
    for lam in np.unique(enc.lambda_):
        cols = enc.lambda_ == lam
        model = Ridge(alpha=float(lam),
                      fit_intercept=enc.fit_intercept).fit(
                          x, y[:, cols])
        sk[:, cols] = model.predict(x).reshape(x.shape[0], -1)
    return sk


def test_acceptance_scale_matches_sklearn():
    """ISSUE 7 acceptance: (T=200, V=8192, F=512, 10 lambdas, 5
    folds) matches sklearn Ridge per-voxel predictions to rtol 1e-4
    at the CV-selected lambdas, with the whole fit compiling at most
    one program per family (the lambda sweep is ONE program, not one
    per lambda)."""
    x, y = _make_data(200, 512, 8192)
    lambdas = np.logspace(1, 3, 10)
    before = _retraces()
    enc = RidgeEncoder(lambdas=lambdas, n_folds=5).fit(x, y)
    compiles = _retraces() - before
    # prepare + sweep + refit — NOT one per lambda
    assert compiles <= 3, compiles
    assert enc.W_.shape == (512, 8192)
    assert enc.cv_scores_.shape == (10, 8192)
    np.testing.assert_allclose(enc.predict(x),
                               _sklearn_predictions(enc, x, y),
                               rtol=1e-4, atol=1e-4)


def test_small_parity_without_intercept():
    x, y = _make_data(48, 12, 20, seed=1)
    enc = RidgeEncoder(lambdas=(1.0, 10.0, 100.0), n_folds=3,
                       fit_intercept=False).fit(x, y)
    np.testing.assert_allclose(enc.predict(x),
                               _sklearn_predictions(enc, x, y),
                               rtol=1e-4, atol=1e-4)
    assert np.all(enc.x_mean_ == 0) and np.all(enc.y_mean_ == 0)


def test_standardize_stores_scale_and_roundtrips():
    rng = np.random.RandomState(2)
    x = (rng.randn(60, 8) * rng.gamma(2.0, 2.0, 8)).astype(
        np.float32)
    y = _make_data(60, 8, 10, seed=2)[1]
    enc = RidgeEncoder(lambdas=(1.0, 10.0), n_folds=3,
                       standardize=True).fit(x, y)
    assert enc.x_scale_.shape == (8,)
    assert not np.allclose(enc.x_scale_, 1.0)
    # predictions correlate with the targets (the affine map applies
    # the stored preprocessing consistently)
    assert enc.score(x, y).mean() > 0.5


def test_rank_deficient_design_is_stable():
    """F > T (the whole-brain encoding regime): the Gram is rank
    deficient and the clamped eigensolver must stay finite and match
    sklearn."""
    x, y = _make_data(30, 64, 12, seed=3)
    enc = RidgeEncoder(lambdas=(10.0, 100.0), n_folds=3).fit(x, y)
    assert np.all(np.isfinite(enc.W_))
    np.testing.assert_allclose(enc.predict(x),
                               _sklearn_predictions(enc, x, y),
                               rtol=1e-3, atol=1e-3)


def test_lambda_block_chunking_is_exact():
    """Block-chunked sweeps (the checkpointable path) score
    identically to the one-block sweep, including an uneven last
    block."""
    x, y = _make_data(40, 8, 12, seed=4)
    lams = (0.5, 5.0, 50.0, 500.0, 5000.0)
    ref = RidgeEncoder(lambdas=lams, n_folds=4).fit(x, y)
    blocked = RidgeEncoder(lambdas=lams, n_folds=4,
                           lambda_block=2).fit(x, y)
    np.testing.assert_allclose(blocked.cv_scores_, ref.cv_scores_,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(blocked.lambda_, ref.lambda_)
    # a repeat fit of already-seen shapes reuses every program
    before = _retraces()
    RidgeEncoder(lambdas=lams, n_folds=4, lambda_block=2).fit(x, y)
    assert _retraces() - before == 0


def test_checkpoint_preempt_resume_parity(tmp_path):
    """The resilient fit contract: a preemption mid-sweep resumes at
    the last completed lambda block and lands on the same scores and
    coefficients as an uninterrupted fit."""
    from brainiak_tpu.resilience import faults

    x, y = _make_data(40, 8, 12, seed=5)
    lams = (0.5, 5.0, 50.0, 500.0)
    ref = RidgeEncoder(lambdas=lams, n_folds=4,
                       lambda_block=1).fit(x, y)
    ckpt = str(tmp_path / "ck")
    with pytest.raises(BaseException):
        with faults.inject("preempt", at_step=2):
            RidgeEncoder(lambdas=lams, n_folds=4,
                         lambda_block=1).fit(
                             x, y, checkpoint_dir=ckpt)
    enc = RidgeEncoder(lambdas=lams, n_folds=4,
                       lambda_block=1).fit(x, y,
                                           checkpoint_dir=ckpt)
    np.testing.assert_allclose(enc.cv_scores_, ref.cv_scores_)
    np.testing.assert_allclose(enc.W_, ref.W_)


def test_checkpoint_rejects_different_grid_or_block(tmp_path):
    """A checkpoint written for one lambda grid must not resume a
    sweep over another (score rows would silently mix), and a
    changed block size must restart too — resilient-loop steps are
    counted in BLOCKS, so a resume at the old step count under a
    bigger block would silently skip unswept rows."""
    from brainiak_tpu.resilience import faults

    x, y = _make_data(40, 8, 12, seed=6)
    ckpt = str(tmp_path / "ck")
    with pytest.raises(BaseException):
        with faults.inject("preempt", at_step=1):
            RidgeEncoder(lambdas=(0.5, 5.0, 50.0, 500.0), n_folds=4,
                         lambda_block=1).fit(
                             x, y, checkpoint_dir=ckpt)
    with pytest.raises(ValueError, match="different data"):
        RidgeEncoder(lambdas=(1.0, 10.0, 100.0, 1000.0), n_folds=4,
                     lambda_block=1).fit(x, y, checkpoint_dir=ckpt)
    with pytest.raises(ValueError, match="different data"):
        RidgeEncoder(lambdas=(0.5, 5.0, 50.0, 500.0), n_folds=4,
                     lambda_block=2).fit(x, y, checkpoint_dir=ckpt)


def test_banded_single_band_matches_plain_ridge():
    """With one band, banded ridge (scaling trick, per-candidate
    eigh) must reproduce the plain eigendecomposition sweep."""
    x, y = _make_data(48, 10, 14, seed=7)
    lams = (1.0, 10.0, 100.0)
    plain = RidgeEncoder(lambdas=lams, n_folds=3).fit(x, y)
    banded = BandedRidgeEncoder(np.zeros(10, np.int32),
                                lambdas=lams, n_folds=3,
                                candidate_block=3).fit(x, y)
    np.testing.assert_allclose(banded.cv_scores_, plain.cv_scores_,
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(banded.W_, plain.W_, rtol=1e-3,
                               atol=1e-4)
    np.testing.assert_array_equal(banded.lambda_[:, 0],
                                  plain.lambda_)


def test_banded_selects_per_band_lambdas():
    """Two bands, the second pure noise: the banded CV should
    regularize the noise band at least as hard as the signal band
    for most voxels, and every selected row must be a candidate."""
    rng = np.random.RandomState(8)
    t, v = 80, 24
    x_sig = rng.randn(t, 6).astype(np.float32)
    x_noise = rng.randn(t, 6).astype(np.float32)
    x = np.concatenate([x_sig, x_noise], axis=1)
    w = (rng.randn(6, v) / np.sqrt(6)).astype(np.float32)
    y = (x_sig @ w + 0.3 * rng.randn(t, v)).astype(np.float32)
    bands = np.repeat(np.arange(2), 6)
    enc = BandedRidgeEncoder(bands, lambdas=(0.1, 10.0, 1000.0),
                             n_folds=4, candidate_block=4).fit(x, y)
    assert enc.lambda_.shape == (v, 2)
    cand_rows = {tuple(row) for row in enc.candidates_}
    assert all(tuple(row) in cand_rows for row in enc.lambda_)
    assert np.median(enc.lambda_[:, 1]) >= np.median(
        enc.lambda_[:, 0])


def test_banded_candidate_grid_validation():
    with pytest.raises(ValueError, match="candidates"):
        BandedRidgeEncoder(np.zeros(4, np.int32),
                           candidates=np.ones((3, 2))).fit(
            *_make_data(30, 4, 6))
    with pytest.raises(ValueError, match="max_candidates"):
        BandedRidgeEncoder(np.repeat(np.arange(4), 2),
                           lambdas=tuple(float(i + 1)
                                         for i in range(10)),
                           max_candidates=100).fit(
            *_make_data(30, 8, 6))
    with pytest.raises(ValueError, match="bands"):
        BandedRidgeEncoder(np.zeros(5, np.int32)).fit(
            *_make_data(30, 4, 6))
    # sparse band ids would silently inflate the Cartesian grid
    with pytest.raises(ValueError, match="dense"):
        BandedRidgeEncoder(np.array([0, 0, 5, 5]),
                           lambdas=(1.0, 10.0)).fit(
            *_make_data(30, 4, 6))


def test_input_validation():
    x, y = _make_data(30, 4, 6)
    with pytest.raises(ValueError, match="finite"):
        RidgeEncoder().fit(np.full_like(x, np.nan), y)
    with pytest.raises(ValueError, match="matching T"):
        RidgeEncoder().fit(x, y[:-1])
    with pytest.raises(ValueError, match="lambdas"):
        RidgeEncoder(lambdas=(1.0, -2.0)).fit(x, y)
    with pytest.raises(ValueError, match="folds"):
        RidgeEncoder(n_folds=40).fit(x, y)
    with pytest.raises(ValueError, match="not fitted"):
        RidgeEncoder().predict(x)
    enc = RidgeEncoder(lambdas=(1.0,), n_folds=3).fit(x, y)
    with pytest.raises(ValueError, match="expected X"):
        enc.predict(x[:, :-1])


def test_gram_goes_through_distla_mesh():
    """With a mesh, the Xᵀ X Gram runs through the distla dispatcher
    (SUMMA when forced over budget) and the fit still matches the
    meshless one."""
    from brainiak_tpu.ops import distla
    from brainiak_tpu.parallel import make_mesh, max_divisible_shards

    x, y = _make_data(40, 8, 12, seed=9)
    n = max_divisible_shards(8)
    mesh = make_mesh(("voxel",), (n,))
    # raw-product parity on the ring itself
    g = np.asarray(distla.gram(x, mesh=mesh, force="summa",
                               normalize=False))
    np.testing.assert_allclose(g, x.T @ x, rtol=1e-4, atol=1e-3)
    ref = RidgeEncoder(lambdas=(1.0, 10.0), n_folds=4).fit(x, y)
    enc = RidgeEncoder(lambdas=(1.0, 10.0), n_folds=4,
                       mesh=mesh).fit(x, y)
    np.testing.assert_allclose(enc.W_, ref.W_, rtol=1e-4,
                               atol=1e-5)
