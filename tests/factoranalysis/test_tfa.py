import numpy as np
import pytest

from brainiak_tpu.factoranalysis.tfa import TFA


def make_rbf_data(n_grid=8, K=2, n_tr=60, noise=0.05, seed=0):
    rng = np.random.RandomState(seed)
    grid = np.array(np.meshgrid(*[np.arange(n_grid)] * 3)) \
        .reshape(3, -1).T.astype(float)
    centers = np.array([[2.0, 2.0, 2.0], [6.0, 6.0, 5.0]])[:K]
    widths = np.array([[3.0], [4.0]])[:K]
    F = np.exp(-((grid[:, None, :] - centers[None]) ** 2).sum(-1)
               / widths.T)
    W = rng.randn(K, n_tr)
    X = F @ W + noise * rng.randn(grid.shape[0], n_tr)
    return X, grid, centers, widths


def test_tfa_recovers_centers_and_widths():
    X, R, true_centers, true_widths = make_rbf_data()
    tfa = TFA(K=2, max_iter=8, threshold=0.1,
              max_num_voxel=512, max_num_tr=60)
    tfa.fit(X, R)
    est_c = tfa.get_centers(tfa.local_posterior_)
    est_w = tfa.get_widths(tfa.local_posterior_)
    # match factors to truth by nearest center
    order = np.argsort(est_c[:, 0])
    true_order = np.argsort(true_centers[:, 0])
    assert np.allclose(est_c[order], true_centers[true_order], atol=0.5)
    assert np.allclose(est_w[order], true_widths[true_order], atol=1.5)
    assert tfa.F_.shape == (X.shape[0], 2)
    assert tfa.W_.shape == (2, X.shape[1])


def test_tfa_subsampled_fit():
    X, R, true_centers, _ = make_rbf_data(noise=0.02)
    tfa = TFA(K=2, max_iter=10, threshold=0.5,
              max_num_voxel=200, max_num_tr=30, seed=7)
    tfa.fit(X, R)
    est_c = tfa.get_centers(tfa.local_posterior_)
    order = np.argsort(est_c[:, 0])
    true_order = np.argsort(true_centers[:, 0])
    assert np.allclose(est_c[order], true_centers[true_order], atol=1.0)


def test_tfa_with_template_prior():
    X, R, _, _ = make_rbf_data()
    tfa = TFA(K=2, max_iter=3, threshold=0.5,
              max_num_voxel=256, max_num_tr=40)
    tfa.n_dim = 3
    tfa.cov_vec_size = 6
    tfa.get_map_offset()
    template_prior, _, _ = tfa.get_template(R)
    tfa2 = TFA(K=2, max_iter=3, threshold=0.5,
               max_num_voxel=256, max_num_tr=40, nlss_loss='soft_l1')
    tfa2.fit(X, R, template_prior=template_prior)
    assert tfa2.local_posterior_.shape == (2 * 4,)
    # template path does not set F_/W_ (matches reference tfa.py:1017-1023)
    assert not hasattr(tfa2, "F_")


def test_tfa_weight_methods():
    X, R, _, _ = make_rbf_data(noise=0.01)
    for method in ("rr", "ols"):
        tfa = TFA(K=2, max_iter=2, threshold=5.0, weight_method=method,
                  max_num_voxel=256, max_num_tr=40)
        tfa.fit(X, R)
        assert np.all(np.isfinite(tfa.W_))


def test_tfa_input_validation():
    X, R, _, _ = make_rbf_data()
    with pytest.raises(TypeError):
        TFA(K=2).fit(list(X), R)
    with pytest.raises(TypeError):
        TFA(K=2).fit(X, R[:, 0])
    with pytest.raises(TypeError):
        TFA(K=2).fit(X[:-5], R)
    with pytest.raises(ValueError):
        TFA(K=2, weight_method='lasso').fit(X, R)


def test_tfa_reference_calling_conventions(caplog):
    """API-parity surface: chained setters, the (unique_R, inds)
    factor-evaluation convention (reference tfa.py:525-567, 879-906),
    and the verbose convergence diagnostics."""
    import logging

    X, R, centers, widths = make_rbf_data()
    tfa = TFA(K=1).set_K(2).set_seed(7).set_prior(None)
    assert tfa.K == 2 and tfa.seed == 7 and tfa.local_prior is None

    tfa.n_dim = R.shape[1]  # set by fit(); needed standalone
    unique_R, inds = tfa.get_unique_R(R)
    assert len(unique_R) == 3 and len(inds) == 3
    recon = np.stack([u[i] for u, i in zip(unique_R, inds)], axis=1)
    np.testing.assert_array_equal(recon, R)
    F = tfa.get_factors(unique_R, inds, centers, widths)
    expected = np.exp(-((R[:, None, :] - centers[None]) ** 2).sum(-1)
                      / widths.T)
    np.testing.assert_allclose(F, expected, atol=1e-5)

    with caplog.at_level(logging.INFO,
                         logger="brainiak_tpu.factoranalysis.tfa"):
        TFA(K=2, max_iter=2, threshold=0.1, max_num_voxel=256,
            max_num_tr=40, verbose=True).fit(X, R)
    assert any("max diff" in r.message and "mse" in r.message
               for r in caplog.records)


def test_map_offset_and_packing():
    tfa = TFA(K=3)
    tfa.n_dim = 3
    tfa.cov_vec_size = 6
    offs = tfa.get_map_offset()
    assert list(offs) == [0, 9, 12, 30]
    est = np.zeros(3 * (3 + 2 + 6))
    centers = np.arange(9.0).reshape(3, 3)
    tfa.set_centers(est, centers)
    assert np.allclose(tfa.get_centers(est), centers)
    widths = np.array([[1.0], [2.0], [3.0]])
    tfa.set_widths(est, widths)
    assert np.allclose(tfa.get_widths(est), widths)


def test_fused_weight_solve_matches_materialized_factor_solve():
    """ISSUE 11: the MTTKRP-style fused weight solve (chunked
    FᵀF/FᵀX, no materialized F) reproduces the reference solve
    through a materialized factor matrix, for both weight
    methods."""
    import jax.numpy as jnp

    from brainiak_tpu.factoranalysis.tfa import (_solve_weights,
                                                 _solve_weights_fused)
    from brainiak_tpu.ops.rbf import rbf_factors

    X, R, centers, widths = make_rbf_data()
    for method in ("rr", "ols"):
        F = np.asarray(rbf_factors(jnp.asarray(R),
                                   jnp.asarray(centers),
                                   jnp.asarray(widths)))
        ref = np.asarray(_solve_weights(jnp.asarray(X),
                                        jnp.asarray(F), method))
        got = np.asarray(_solve_weights_fused(
            jnp.asarray(X), jnp.asarray(R), jnp.asarray(centers),
            jnp.asarray(widths), method))
        assert np.allclose(got, ref, atol=1e-6), method
