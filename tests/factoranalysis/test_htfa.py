import numpy as np
import pytest

from brainiak_tpu.factoranalysis.htfa import HTFA
from tests.factoranalysis.test_tfa import make_rbf_data


def make_multi_subject(n_subj=3, seed=0):
    X, R = [], []
    centers = None
    for s in range(n_subj):
        x, r, centers, widths = make_rbf_data(noise=0.05, seed=seed + s)
        X.append(x)
        R.append(r)
    return X, R, centers, widths


def test_htfa_fit_recovers_template():
    np.random.seed(0)
    X, R, true_centers, true_widths = make_multi_subject()
    htfa = HTFA(K=2, n_subj=3, max_global_iter=3, max_local_iter=3,
                threshold=0.5, voxel_ratio=1.0, tr_ratio=1.0,
                max_voxel=512, max_tr=60)
    htfa.fit(X, R)
    assert htfa.global_posterior_.shape[0] == 2 * (3 + 2 + 6)
    est_c = htfa.get_centers(htfa.global_posterior_)
    order = np.argsort(est_c[:, 0])
    true_order = np.argsort(true_centers[:, 0])
    assert np.allclose(est_c[order], true_centers[true_order], atol=1.0)
    # per-subject posteriors and weights populated
    assert htfa.local_posterior_.shape == (3 * 2 * 4,)
    n_tr = X[0].shape[1]
    assert htfa.local_weights_.shape == (3 * 2 * n_tr,)
    assert np.all(np.isfinite(htfa.local_weights_))


def test_htfa_mesh_matches_single_host():
    """Sharding the subject axis over a mesh must not change the fit —
    the analog of the reference's distributed-vs-serial HTFA equivalence
    (reference tests/factoranalysis/test_htfa.py MPI runs)."""
    from brainiak_tpu.parallel.mesh import make_mesh

    from tests.conftest import mesh_atol

    X, R, _, _ = make_multi_subject(n_subj=4)
    common = dict(K=2, n_subj=4, max_global_iter=2, max_local_iter=2,
                  threshold=0.5, voxel_ratio=1.0, tr_ratio=1.0,
                  max_voxel=512, max_tr=60)
    np.random.seed(0)
    single = HTFA(**common).fit(X, R)
    np.random.seed(0)
    mesh = make_mesh(("subject",), (4,))
    sharded = HTFA(mesh=mesh, **common).fit(X, R)
    np.testing.assert_allclose(sharded.global_posterior_,
                               single.global_posterior_,
                               atol=mesh_atol())
    np.testing.assert_allclose(sharded.local_posterior_,
                               single.local_posterior_,
                               atol=mesh_atol())


def test_htfa_ragged_subjects_mesh_padding():
    """Subjects with different voxel counts batch via masked padding, and
    a subject count that does not divide the mesh axis is padded by
    repetition and discarded."""
    from brainiak_tpu.parallel.mesh import make_mesh

    from tests.conftest import mesh_atol

    X, R, _, _ = make_multi_subject(n_subj=3)
    # make subject raggedness real: drop voxels from subjects 1 and 2
    X = [X[0], X[1][:-37], X[2][:-101]]
    R = [R[0], R[1][:-37], R[2][:-101]]
    common = dict(K=2, n_subj=3, max_global_iter=1, max_local_iter=2,
                  threshold=0.5, voxel_ratio=0.5, tr_ratio=1.0,
                  max_voxel=200, max_tr=60)
    np.random.seed(1)
    single = HTFA(**common).fit(X, R)
    np.random.seed(1)
    mesh = make_mesh(("subject",), (2,))  # 3 subjects on 2 shards -> pad
    sharded = HTFA(mesh=mesh, **common).fit(X, R)
    np.testing.assert_allclose(sharded.local_posterior_,
                               single.local_posterior_,
                               atol=mesh_atol())


def test_htfa_input_validation():
    X, R, _, _ = make_multi_subject(n_subj=2)
    htfa = HTFA(K=2, n_subj=2)
    with pytest.raises(TypeError):
        htfa.fit(X[0], R)
    with pytest.raises(TypeError):
        htfa.fit(X, R[:1])
    with pytest.raises(TypeError):
        htfa.fit([X[0], X[1][:-3]], R)
