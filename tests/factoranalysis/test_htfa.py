import numpy as np
import pytest

from brainiak_tpu.factoranalysis.htfa import HTFA
from tests.factoranalysis.test_tfa import make_rbf_data


def make_multi_subject(n_subj=3, seed=0):
    X, R = [], []
    centers = None
    for s in range(n_subj):
        x, r, centers, widths = make_rbf_data(noise=0.05, seed=seed + s)
        X.append(x)
        R.append(r)
    return X, R, centers, widths


def test_htfa_fit_recovers_template():
    np.random.seed(0)
    X, R, true_centers, true_widths = make_multi_subject()
    htfa = HTFA(K=2, n_subj=3, max_global_iter=3, max_local_iter=3,
                threshold=0.5, voxel_ratio=1.0, tr_ratio=1.0,
                max_voxel=512, max_tr=60)
    htfa.fit(X, R)
    assert htfa.global_posterior_.shape[0] == 2 * (3 + 2 + 6)
    est_c = htfa.get_centers(htfa.global_posterior_)
    order = np.argsort(est_c[:, 0])
    true_order = np.argsort(true_centers[:, 0])
    assert np.allclose(est_c[order], true_centers[true_order], atol=1.0)
    # per-subject posteriors and weights populated
    assert htfa.local_posterior_.shape == (3 * 2 * 4,)
    n_tr = X[0].shape[1]
    assert htfa.local_weights_.shape == (3 * 2 * n_tr,)
    assert np.all(np.isfinite(htfa.local_weights_))


def test_htfa_mesh_matches_single_host():
    """Sharding the subject axis over a mesh must not change the fit —
    the analog of the reference's distributed-vs-serial HTFA equivalence
    (reference tests/factoranalysis/test_htfa.py MPI runs)."""
    from brainiak_tpu.parallel.mesh import make_mesh

    from tests.conftest import mesh_atol

    X, R, _, _ = make_multi_subject(n_subj=4)
    common = dict(K=2, n_subj=4, max_global_iter=2, max_local_iter=2,
                  threshold=0.5, voxel_ratio=1.0, tr_ratio=1.0,
                  max_voxel=512, max_tr=60)
    np.random.seed(0)
    single = HTFA(**common).fit(X, R)
    np.random.seed(0)
    mesh = make_mesh(("subject",), (4,))
    sharded = HTFA(mesh=mesh, **common).fit(X, R)
    np.testing.assert_allclose(sharded.global_posterior_,
                               single.global_posterior_,
                               atol=mesh_atol())
    np.testing.assert_allclose(sharded.local_posterior_,
                               single.local_posterior_,
                               atol=mesh_atol())


def test_htfa_ragged_subjects_mesh_padding():
    """Subjects with different voxel counts batch via masked padding, and
    a subject count that does not divide the mesh axis is padded with
    ZERO-MASKED lanes (inert: objective identically 0) and discarded."""
    from brainiak_tpu.parallel.mesh import make_mesh

    from tests.conftest import mesh_atol

    X, R, _, _ = make_multi_subject(n_subj=3)
    # make subject raggedness real: drop voxels from subjects 1 and 2
    X = [X[0], X[1][:-37], X[2][:-101]]
    R = [R[0], R[1][:-37], R[2][:-101]]
    common = dict(K=2, n_subj=3, max_global_iter=1, max_local_iter=2,
                  threshold=0.5, voxel_ratio=0.5, tr_ratio=1.0,
                  max_voxel=200, max_tr=60)
    np.random.seed(1)
    single = HTFA(**common).fit(X, R)
    np.random.seed(1)
    mesh = make_mesh(("subject",), (2,))  # 3 subjects on 2 shards -> pad
    sharded = HTFA(mesh=mesh, **common).fit(X, R)
    np.testing.assert_allclose(sharded.local_posterior_,
                               single.local_posterior_,
                               atol=mesh_atol())


def test_htfa_zero_masked_pad_lane_is_inert():
    """A zero-masked pad lane (zero data/coords/masks/scaling, unit ridge
    coefficient) must contribute an identically-zero objective: its
    L-BFGS converges immediately and returns the init unchanged —
    the property the mesh padding in ``_dispatch_batched_step`` relies
    on so pad lanes never re-run a real subject's optimization."""
    import jax.numpy as jnp

    from brainiak_tpu.factoranalysis.htfa import _batched_subject_step

    K, n_dim, V, T = 2, 3, 50, 20
    rng = np.random.RandomState(0)
    # lane 0: a real subject; lane 1: the zero-masked pad
    data = np.stack([rng.randn(V, T), np.zeros((V, T))])
    R = np.stack([rng.randn(V, n_dim), np.zeros((V, n_dim))])
    vmask = np.stack([np.ones(V), np.zeros(V)])
    tmask = np.stack([np.ones(T), np.zeros(T)])
    centers = np.tile(rng.randn(K, n_dim), (2, 1, 1))
    widths = np.tile(np.full(K, 1.0), (2, 1))
    lower = np.tile(np.concatenate([-5 * np.ones(K * n_dim),
                                    0.1 * np.ones(K)]), (2, 1))
    upper = np.tile(np.concatenate([5 * np.ones(K * n_dim),
                                    10.0 * np.ones(K)]), (2, 1))
    beta = np.array([1.0, 1.0])
    sigma = np.array([1.0, 1.0])
    scaling = np.array([0.5, 0.0])
    tmpl_centers = rng.randn(K, n_dim)
    tmpl_cov_inv = np.tile(np.eye(n_dim), (K, 1, 1))
    tmpl_widths = np.full(K, 1.0)
    tmpl_reci = np.full(K, 1.0)
    x, cost = _batched_subject_step(
        *[jnp.asarray(a) for a in
          (data, R, vmask, tmask, centers, widths, lower, upper,
           beta, sigma, scaling, tmpl_centers, tmpl_cov_inv,
           tmpl_widths, tmpl_reci)],
        K=K, n_dim=n_dim, nlss_loss="soft_l1", max_iters=8)
    assert float(cost[1]) == 0.0
    init = np.concatenate([centers[1].ravel(), widths[1]])
    np.testing.assert_allclose(np.asarray(x)[1], init, atol=1e-6)
    # the real lane actually optimized
    assert float(cost[0]) > 0.0


def test_htfa_input_validation():
    X, R, _, _ = make_multi_subject(n_subj=2)
    htfa = HTFA(K=2, n_subj=2)
    with pytest.raises(TypeError):
        htfa.fit(X[0], R)
    with pytest.raises(TypeError):
        htfa.fit(X, R[:1])
    with pytest.raises(TypeError):
        htfa.fit([X[0], X[1][:-3]], R)
    with pytest.raises(TypeError):
        htfa.fit(X, [R[0], R[1].ravel()])
    with pytest.raises(ValueError, match="weight_method"):
        HTFA(K=2, n_subj=2, weight_method='bogus').fit(X, R)
    # a mesh without the subject axis is a config error, not a crash
    import jax
    from jax.sharding import Mesh
    with pytest.raises(ValueError, match="subject"):
        HTFA(K=2, n_subj=2,
             mesh=Mesh(np.array(jax.devices()[:1]), ("wrong",))
             ).fit(X, R)


def test_htfa_verbose_logging(caplog):
    """verbose=True routes global-iteration progress through the module
    logger (the reference prints per-iteration diagnostics,
    htfa.py:766-841)."""
    import logging

    X, R, _, _ = make_multi_subject(n_subj=2)
    with caplog.at_level(logging.INFO,
                         logger="brainiak_tpu.factoranalysis.htfa"):
        HTFA(K=2, n_subj=2, max_global_iter=2, max_local_iter=2,
             max_voxel=30, max_tr=20, verbose=True).fit(X, R)
    assert any("HTFA" in r.message or "global iter" in r.message
               for r in caplog.records)


# -- ISSUE 13: SubjectStore streaming ---------------------------------

def test_htfa_store_matches_in_memory(tmp_path):
    """A SubjectStore-backed fit pulls subject shards through the
    prefetcher (disk reads overlap the inner L-BFGS rounds) and
    reproduces the in-memory fit: per-subject RNG streams are seeded
    from the global iteration, so shard-wise processing draws the
    same subsamples."""
    from brainiak_tpu.data import write_store

    X, R, _, _ = make_multi_subject(n_subj=4)
    kw = dict(K=2, n_subj=4, max_global_iter=2, max_local_iter=2,
              max_voxel=64, max_tr=20, lbfgs_iters=10)
    np.random.seed(0)
    inmem = HTFA(**kw).fit(X, R)
    store = write_store(str(tmp_path / "st"), X, dtype=np.float64)
    np.random.seed(0)  # the template-init subject draw must match
    streamed = HTFA(**kw, shard_subjects=2).fit(store, R)
    np.testing.assert_allclose(streamed.local_posterior_,
                               inmem.local_posterior_, atol=1e-8)
    np.testing.assert_allclose(streamed.global_posterior_,
                               inmem.global_posterior_, atol=1e-8)
    np.testing.assert_allclose(streamed.local_weights_,
                               inmem.local_weights_, atol=1e-8)


def test_htfa_store_checkpoint_resume(tmp_path):
    """Store-backed HTFA keeps the resilient-loop resume contract,
    with the fingerprint built from the store's manifest digests."""
    from brainiak_tpu.data import write_store
    from brainiak_tpu.resilience import faults

    X, R, _, _ = make_multi_subject(n_subj=3)
    store = write_store(str(tmp_path / "st"), X, dtype=np.float64)
    kw = dict(K=2, n_subj=3, max_global_iter=2, max_local_iter=1,
              threshold=1e-6, max_voxel=64, max_tr=20,
              lbfgs_iters=10, shard_subjects=2)
    np.random.seed(0)
    full = HTFA(**kw).fit(store, R)
    ck = str(tmp_path / "ck")
    np.random.seed(0)
    with pytest.raises(faults.PreemptionError):
        with faults.inject("preempt", at_step=1):
            HTFA(**kw).fit(store, R, checkpoint_dir=ck,
                           checkpoint_every=1)
    np.random.seed(1)  # resume restores the template: init draw moot
    resumed = HTFA(**kw).fit(store, R, checkpoint_dir=ck,
                             checkpoint_every=1)
    np.testing.assert_allclose(resumed.global_posterior_,
                               full.global_posterior_, atol=1e-8)


def test_htfa_store_input_validation(tmp_path):
    from brainiak_tpu.data import write_store

    X, R, _, _ = make_multi_subject(n_subj=3)
    store = write_store(str(tmp_path / "st"), X)
    htfa = HTFA(K=2, n_subj=3)
    with pytest.raises(TypeError, match="equal length"):
        htfa.fit(store, R[:2])
    with pytest.raises(TypeError, match="voxels"):
        htfa.fit(store, [r[:-1] for r in R])
