import numpy as np
import pytest
import scipy.stats as st

from brainiak_tpu.hyperparamopt.hpo import (
    fmin,
    get_next_sample,
    get_sigma,
    gmm_1d_distribution,
)


def test_get_sigma():
    x = np.array([1.0, 2.0, 5.0])
    sigma = get_sigma(x, min_limit=0.0, max_limit=6.0)
    # farthest of the two nearest neighbors
    assert np.allclose(sigma, [1.0, 3.0, 3.0])
    # unbounded: infinities fall back to the nearer gap
    sigma_u = get_sigma(np.array([1.0]), min_limit=0.0)
    assert sigma_u[0] == 1.0


def test_gmm_pdf_and_samples():
    np.random.seed(0)
    x = np.array([0.2, 0.5, 0.8])
    gmm = gmm_1d_distribution(x, min_limit=0.0, max_limit=1.0)
    # pdf is positive inside, zero outside
    assert gmm(0.5) > 0
    assert gmm(-0.1) == 0 and gmm(1.1) == 0
    vals = gmm(np.array([0.1, 0.5, 2.0]))
    assert vals.shape == (3,) and vals[2] == 0
    # each truncation-corrected component integrates to 1, so the mixture
    # integrates to N / W_sum (the reference's normalization behaves
    # identically)
    grid = np.linspace(0, 1, 2000)
    integral = np.trapezoid(gmm(grid), grid)
    assert np.isclose(integral, gmm.N / gmm.W_sum, atol=0.01)
    samples = gmm.get_samples(500)
    assert samples.shape == (500,)
    assert np.all((samples >= 0) & (samples <= 1))


def test_get_next_sample_prefers_good_region():
    np.random.seed(1)
    # loss minimized near x=0.3
    x = np.random.rand(40)
    y = (x - 0.3) ** 2
    nxt = get_next_sample(x, y, min_limit=0.0, max_limit=1.0)
    assert 0.0 <= nxt <= 1.0
    assert abs(nxt - 0.3) < 0.25


def test_fmin_minimizes_quadratic():
    np.random.seed(2)

    def loss(params):
        return (params['x'] - 0.7) ** 2

    space = {'x': {'dist': st.uniform(0, 1), 'lo': 0, 'hi': 1}}
    trials = []
    best = fmin(loss, space, max_evals=60, trials=trials,
                init_random_evals=15)
    assert len(trials) == 60
    assert abs(best['x'] - 0.7) < 0.1
    assert best['loss'] < 0.01


def test_fmin_validation_and_seeding():
    def loss(params):
        return params['x'] ** 2

    with pytest.raises(ValueError):
        fmin(loss, {'x': {'dist': "not-a-dist"}}, 5, [])
    # pre-seeded trials skip random init
    np.random.seed(3)
    trials = [{'x': v, 'loss': v ** 2}
              for v in np.linspace(-1, 1, 40)]
    best = fmin(loss, {'x': {'dist': st.uniform(-1, 2), 'lo': -1,
                             'hi': 1}},
                max_evals=10, trials=trials)
    assert abs(best['x']) < 0.2
