"""Noise covariance strategy classes (functional JAX).

Re-design of /root/reference/src/brainiak/matnormal/covs.py.  The reference
stores TF Variables inside covariance objects; here each class is a
stateless description whose learnable parameters live in an explicit pytree
(dict) — ``init_params`` creates it, and ``logdet``/``solve``/``logp`` are
pure traceable functions of it, so whole-model losses jit and autodiff
cleanly.

API: ``init_params(seed) -> dict``; ``solve(params, X) -> Σ⁻¹X``;
``logdet(params)``; ``logp(params)`` (prior, default 0); ``prec/cov`` for
inspection.
"""

import numpy as np
import jax
import jax.numpy as jnp
from scipy.special import logit

from ..utils.kronecker_solvers import (
    solve_lower_triangular_kron,
    solve_lower_triangular_masked_kron,
    solve_upper_triangular_kron,
    solve_upper_triangular_masked_kron,
)
from .utils import flatten_cholesky_unique, tril_size, \
    unflatten_cholesky_unique

__all__ = [
    "CovBase",
    "CovIdentity",
    "CovAR1",
    "CovIsotropic",
    "CovDiagonal",
    "CovDiagonalGammaPrior",
    "CovUnconstrainedCholesky",
    "CovUnconstrainedCholeskyWishartReg",
    "CovUnconstrainedInvCholesky",
    "CovKroneckerFactored",
]


class CovBase:
    """Base covariance strategy (reference covs.py:35-87)."""

    def __init__(self, size):
        self.size = size

    def init_params(self, seed=0):
        return {}

    def logdet(self, params):
        raise NotImplementedError

    def solve(self, params, X):
        raise NotImplementedError

    def logp(self, params):
        """Log-prior over the covariance parameters (regularization)."""
        return 0.0

    def prec(self, params):
        return self.solve(params, jnp.eye(self.size))

    def cov(self, params):
        return jnp.linalg.inv(self.prec(params))


class CovIdentity(CovBase):
    """Identity covariance (reference covs.py:89-126)."""

    def logdet(self, params):
        return 0.0

    def solve(self, params, X):
        return X

    def prec(self, params):
        return jnp.eye(self.size)

    def cov(self, params):
        return jnp.eye(self.size)


class CovIsotropic(CovBase):
    """Scaled identity (reference covs.py:234-277)."""

    def __init__(self, size, var=None):
        super().__init__(size)
        self._var0 = var

    def init_params(self, seed=0):
        if self._var0 is None:
            rng = np.random.RandomState(seed)
            return {"log_var": jnp.asarray(rng.standard_normal(1))}
        return {"log_var": jnp.asarray([np.log(self._var0)])}

    def logdet(self, params):
        return self.size * params["log_var"][0]

    def solve(self, params, X):
        return X / jnp.exp(params["log_var"][0])


class CovDiagonal(CovBase):
    """Independent per-element variances (reference covs.py:279-325)."""

    def __init__(self, size, diag_var=None):
        super().__init__(size)
        self._diag_var0 = diag_var

    def init_params(self, seed=0):
        if self._diag_var0 is None:
            rng = np.random.RandomState(seed)
            return {"logprec": jnp.asarray(rng.standard_normal(self.size))}
        return {"logprec": jnp.asarray(np.log(1.0 / self._diag_var0))}

    def logdet(self, params):
        return -jnp.sum(params["logprec"])

    def solve(self, params, X):
        return jnp.exp(params["logprec"])[:, None] * X


class CovDiagonalGammaPrior(CovDiagonal):
    """Diagonal covariance with an inverse-gamma prior on the precisions
    (reference covs.py:327-341)."""

    def __init__(self, size, sigma=None, alpha=1.5, beta=1e-10):
        super().__init__(size, sigma)
        self.alpha = alpha
        self.beta = beta

    def logp(self, params):
        x = jnp.exp(params["logprec"])
        a, b = self.alpha, self.beta
        # InverseGamma(a, b) log-density summed over elements
        return jnp.sum(a * jnp.log(b) - jax.scipy.special.gammaln(a)
                       - (a + 1) * jnp.log(x) - b / x)


class CovAR1(CovBase):
    """AR(1) covariance with optional scan-onset blocks
    (reference covs.py:127-229): precision
    (I − ρD + ρ²F)/σ² built from Toeplitz templates."""

    def __init__(self, size, rho=None, sigma=None, scan_onsets=None):
        super().__init__(size)
        if scan_onsets is None:
            self.run_sizes = [size]
        else:
            self.run_sizes = list(np.ediff1d(np.r_[scan_onsets, size]))
        off = np.zeros((size, size))
        diag = np.zeros((size, size))
        start = 0
        for r in self.run_sizes:
            for i in range(r - 1):
                off[start + i, start + i + 1] = 1
                off[start + i + 1, start + i] = 1
            inner = np.zeros(r)
            if r > 2:
                inner[1:-1] = 1
            diag[start:start + r, start:start + r] = np.diag(inner)
            start += r
        self.offdiag_template = jnp.asarray(off)
        self.diag_template = jnp.asarray(diag)
        self._rho0 = rho
        self._sigma0 = sigma

    def init_params(self, seed=0):
        rng = np.random.RandomState(seed)
        log_sigma = (rng.standard_normal(1) if self._sigma0 is None
                     else np.log(np.atleast_1d(self._sigma0)))
        rho_unc = (rng.standard_normal(1) if self._rho0 is None
                   else np.atleast_1d(logit(self._rho0 / 2 + 0.5)))
        return {"log_sigma": jnp.asarray(log_sigma),
                "rho_unc": jnp.asarray(rho_unc)}

    def _rho_sigma(self, params):
        rho = 2 * jax.nn.sigmoid(params["rho_unc"][0]) - 1
        return rho, jnp.exp(params["log_sigma"][0])

    def logdet(self, params):
        rho, _ = self._rho_sigma(params)
        run_sizes = jnp.asarray(self.run_sizes,
                                dtype=params["log_sigma"].dtype)
        return jnp.sum(2 * run_sizes * params["log_sigma"][0]
                       - jnp.log(1 - rho ** 2))

    def prec(self, params):
        rho, sigma = self._rho_sigma(params)
        return (jnp.eye(self.size) - rho * self.offdiag_template
                + rho ** 2 * self.diag_template) / sigma ** 2

    def solve(self, params, X):
        return self.prec(params) @ X


class CovUnconstrainedCholesky(CovBase):
    """Unconstrained covariance via its Cholesky factor
    (reference covs.py:343-404)."""

    def __init__(self, size=None, Sigma=None):
        if (size is None) == (Sigma is None):
            raise RuntimeError("Must pass either Sigma or size but not "
                               "both")
        if Sigma is not None:
            size = Sigma.shape[0]
        super().__init__(size)
        self._Sigma0 = Sigma

    def init_params(self, seed=0):
        if self._Sigma0 is None:
            rng = np.random.RandomState(seed)
            flat = rng.standard_normal(tril_size(self.size))
        else:
            flat = flatten_cholesky_unique(np.linalg.cholesky(self._Sigma0))
        return {"L_flat": jnp.asarray(flat)}

    def L(self, params):
        return unflatten_cholesky_unique(params["L_flat"], self.size)

    def logdet(self, params):
        return 2 * jnp.sum(jnp.log(jnp.diag(self.L(params))))

    def solve(self, params, X):
        L = self.L(params)
        return jax.scipy.linalg.cho_solve((L, True), X)


class CovUnconstrainedCholeskyWishartReg(CovUnconstrainedCholesky):
    """Cholesky-parameterized covariance with the weakly-informative
    Wishart regularization of Chung et al. 2015
    (reference covs.py:406-429)."""

    def __init__(self, size, Sigma=None):
        super().__init__(size=size)
        self.df = size + 2
        self.scale_diag = 1e5

    def logp(self, params):
        # WishartTriL(df, scale=1e5 I).log_prob(Sigma) up to terms constant
        # in Sigma: 0.5*(df - p - 1)*log|Sigma| - 0.5*tr(scale^-2 Sigma)
        L = self.L(params)
        p = self.size
        logdet_sigma = 2 * jnp.sum(jnp.log(jnp.diag(L)))
        trace_term = jnp.sum(L ** 2) / (self.scale_diag ** 2)
        half_df = 0.5 * (self.df - p - 1)
        # normalizing constants (constant wrt params) included for parity
        # of magnitude with the reference's tfp WishartTriL
        return half_df * logdet_sigma - 0.5 * trace_term


class CovUnconstrainedInvCholesky(CovBase):
    """Unconstrained covariance via its precision Cholesky — saves a
    cho_solve per step (reference covs.py:431-497).

    Note (matching the reference): the precision is parameterized as
    LinvᵀLinv, so initializing from ``invSigma`` seeds the optimizer at a
    precision with the same determinant but not elementwise equal to
    ``invSigma`` (reference covs.py:461-466 has the same property)."""

    def __init__(self, size=None, invSigma=None):
        if (size is None) == (invSigma is None):
            raise RuntimeError("Must pass either invSigma or size but not "
                               "both")
        if invSigma is not None:
            size = invSigma.shape[0]
        super().__init__(size)
        self._invSigma0 = invSigma

    def init_params(self, seed=0):
        if self._invSigma0 is None:
            rng = np.random.RandomState(seed)
            flat = rng.standard_normal(tril_size(self.size))
        else:
            flat = flatten_cholesky_unique(
                np.linalg.cholesky(self._invSigma0))
        return {"Linv_flat": jnp.asarray(flat)}

    def Linv(self, params):
        return unflatten_cholesky_unique(params["Linv_flat"], self.size)

    def logdet(self, params):
        return -2 * jnp.sum(jnp.log(jnp.diag(self.Linv(params))))

    def solve(self, params, X):
        Linv = self.Linv(params)
        return Linv.T @ (Linv @ X)

    def prec(self, params):
        Linv = self.Linv(params)
        return Linv.T @ Linv


class CovKroneckerFactored(CovBase):
    """Kronecker-product covariance from per-factor Cholesky factors
    (reference covs.py:499-622); optional element mask."""

    def __init__(self, sizes, Sigmas=None, mask=None):
        if not isinstance(sizes, list):
            raise TypeError("sizes is not a list")
        self.sizes = sizes
        self.nfactors = len(sizes)
        size = int(np.prod(sizes))
        super().__init__(size)
        self._Sigmas0 = Sigmas
        self.mask = None if mask is None else np.asarray(mask)

    def init_params(self, seed=0):
        rng = np.random.RandomState(seed)
        flats = []
        for i, n in enumerate(self.sizes):
            if self._Sigmas0 is None:
                flats.append(jnp.asarray(
                    rng.standard_normal(tril_size(n))))
            else:
                flats.append(jnp.asarray(flatten_cholesky_unique(
                    np.linalg.cholesky(self._Sigmas0[i]))))
        return {"L_flats": flats}

    def L(self, params):
        return [unflatten_cholesky_unique(f, n)
                for f, n in zip(params["L_flats"], self.sizes)]

    def logdet(self, params):
        Ls = self.L(params)
        if self.mask is None:
            n_prod = float(np.prod(self.sizes))
            total = 0.0
            for L, n in zip(Ls, self.sizes):
                total = total + jnp.sum(jnp.log(jnp.diag(L))) * \
                    (n_prod / n)
            return 2.0 * total
        mask_reshaped = self.mask.reshape(self.sizes)
        total = 0.0
        for i, L in enumerate(Ls):
            axes = tuple(j for j in range(self.nfactors) if j != i)
            counts = jnp.asarray(mask_reshaped.sum(axes),
                                 dtype=jnp.diag(L).dtype)
            total = total + jnp.sum(jnp.log(jnp.diag(L)) * counts)
        return 2.0 * total

    def solve(self, params, X):
        Ls = self.L(params)
        if self.mask is None:
            z = solve_lower_triangular_kron(Ls, X)
            return solve_upper_triangular_kron(Ls, z)
        z = solve_lower_triangular_masked_kron(Ls, X, self.mask)
        return solve_upper_triangular_masked_kron(Ls, z, self.mask)
