"""Matrix-normal log-likelihoods.

Re-design of /root/reference/src/brainiak/matnormal/matnormal_likelihoods.py
in pure JAX.  Covariance arguments are (cov_object, params) pairs following
the :class:`~brainiak_tpu.matnormal.covs.CovBase` functional API.
"""

import jax.numpy as jnp
import numpy as np

__all__ = [
    "matnorm_logp",
    "matnorm_logp_conditional_col",
    "matnorm_logp_conditional_row",
    "matnorm_logp_marginal_col",
    "matnorm_logp_marginal_row",
    "solve_det_conditional",
    "solve_det_marginal",
]

_LOG2PI = np.log(2.0 * np.pi)


def solve_det_marginal(x, sigma, sigma_params, A, Q, Q_params):
    """(Σ + AQAᵀ)⁻¹x by the Woodbury identity and its log-determinant by
    the matrix determinant lemma (reference
    matnormal_likelihoods.py:27-109)."""
    lemma_factor = jnp.linalg.cholesky(
        Q.prec(Q_params) + A.T @ sigma.solve(sigma_params, A))
    logdet = (Q.logdet(Q_params) + sigma.logdet(sigma_params)
              + 2 * jnp.sum(jnp.log(jnp.diag(lemma_factor))))
    atrp_sinv = A.T @ sigma.prec(sigma_params)
    prod_term = jnp.linalg.solve(
        lemma_factor.T, jnp.linalg.solve(lemma_factor, atrp_sinv))
    solve = sigma.solve(sigma_params,
                        (jnp.eye(sigma.size) - A @ prod_term)) @ x
    return solve, logdet


def solve_det_conditional(x, sigma, sigma_params, A, Q, Q_params):
    """(Σ − AQ⁻¹Aᵀ)⁻¹x via the inversion lemma and its log-determinant via
    the determinant lemma (reference matnormal_likelihoods.py:112-160)."""
    # (Q − Aᵀ Σ⁻¹ A)
    lemma_factor = jnp.linalg.cholesky(
        Q.cov(Q_params) - A.T @ sigma.solve(sigma_params, A))
    logdet = (-Q.logdet(Q_params) + sigma.logdet(sigma_params)
              + 2 * jnp.sum(jnp.log(jnp.diag(lemma_factor))))
    atrp_sinv = A.T @ sigma.prec(sigma_params)
    prod_term = jnp.linalg.solve(
        lemma_factor.T, jnp.linalg.solve(lemma_factor, atrp_sinv))
    solve = sigma.solve(sigma_params,
                        (jnp.eye(sigma.size) + A @ prod_term)) @ x
    return solve, logdet


def _mnorm_logp_internal(colsize, rowsize, logdet_row, logdet_col,
                         solve_row, solve_col):
    denominator = (-rowsize * colsize * _LOG2PI
                   - colsize * logdet_row - rowsize * logdet_col)
    numerator = -jnp.trace(solve_col @ solve_row)
    return 0.5 * (numerator + denominator)


def matnorm_logp(x, row_cov, row_params, col_cov, col_params):
    """Centered matrix-normal log-density
    (reference matnormal_likelihoods.py:202-231)."""
    rowsize, colsize = x.shape
    solve_col = col_cov.solve(col_params, x.T)
    solve_row = row_cov.solve(row_params, x)
    return _mnorm_logp_internal(
        colsize, rowsize, row_cov.logdet(row_params),
        col_cov.logdet(col_params), solve_row, solve_col)


def matnorm_logp_marginal_row(x, row_cov, row_params, col_cov, col_params,
                              marg, marg_cov, marg_params):
    """logp of Y ~ MN(0, R + AQAᵀ, C)
    (reference matnormal_likelihoods.py:233-272)."""
    rowsize, colsize = x.shape
    solve_col = col_cov.solve(col_params, x.T)
    solve_row, logdet_row = solve_det_marginal(
        x, row_cov, row_params, marg, marg_cov, marg_params)
    return _mnorm_logp_internal(
        colsize, rowsize, logdet_row, col_cov.logdet(col_params),
        solve_row, solve_col)


def matnorm_logp_marginal_col(x, row_cov, row_params, col_cov, col_params,
                              marg, marg_cov, marg_params):
    """logp of Y ~ MN(0, R, C + AᵀQA)
    (reference matnormal_likelihoods.py:274-316)."""
    rowsize, colsize = x.shape
    solve_row = row_cov.solve(row_params, x)
    solve_col, logdet_col = solve_det_marginal(
        x.T, col_cov, col_params, marg, marg_cov, marg_params)
    return _mnorm_logp_internal(
        colsize, rowsize, row_cov.logdet(row_params), logdet_col,
        solve_row, solve_col)


def matnorm_logp_conditional_row(x, row_cov, row_params, col_cov,
                                 col_params, cond, cond_cov, cond_params):
    """logp with the row covariance conditioned on another variable
    (reference matnormal_likelihoods.py:318-372)."""
    rowsize, colsize = x.shape
    solve_col = col_cov.solve(col_params, x.T)
    solve_row, logdet_row = solve_det_conditional(
        x, row_cov, row_params, cond, cond_cov, cond_params)
    return _mnorm_logp_internal(
        colsize, rowsize, logdet_row, col_cov.logdet(col_params),
        solve_row, solve_col)


def matnorm_logp_conditional_col(x, row_cov, row_params, col_cov,
                                 col_params, cond, cond_cov, cond_params):
    """logp with the column covariance conditioned on another variable
    (reference matnormal_likelihoods.py:375-429)."""
    rowsize, colsize = x.shape
    solve_row = row_cov.solve(row_params, x)
    solve_col, logdet_col = solve_det_conditional(
        x.T, col_cov, col_params, cond, cond_cov, cond_params)
    return _mnorm_logp_internal(
        colsize, rowsize, row_cov.logdet(row_params), logdet_col,
        solve_row, solve_col)
