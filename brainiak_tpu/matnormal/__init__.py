"""Matrix-normal models, TPU-native.

Re-design of /root/reference/src/brainiak/matnormal/: the TensorFlow
covariance/likelihood stack becomes pure-JAX functional covariance classes
(parameters as pytrees) with autodiff L-BFGS replacing the
scipy.minimize <-> TF bridge."""

from .covs import (  # noqa: F401
    CovAR1,
    CovBase,
    CovDiagonal,
    CovDiagonalGammaPrior,
    CovIdentity,
    CovIsotropic,
    CovKroneckerFactored,
    CovUnconstrainedCholesky,
    CovUnconstrainedCholeskyWishartReg,
    CovUnconstrainedInvCholesky,
)
from .mnrsa import MNRSA  # noqa: F401
from .regression import MatnormalRegression  # noqa: F401
