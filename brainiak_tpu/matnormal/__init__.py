"""Matrix-normal models, TPU-native.

Re-design of /root/reference/src/brainiak/matnormal/: the TensorFlow
covariance/likelihood stack becomes pure-JAX functional covariance classes
(parameters as pytrees).  The built-in models fit with autodiff L-BFGS
on device; for custom losses driven by ``scipy.optimize.minimize``,
:func:`matnormal.utils.make_val_and_grad` provides the jac=True bridge
(the JAX analog of the reference's TF session bridge)."""

from .covs import (  # noqa: F401
    CovAR1,
    CovBase,
    CovDiagonal,
    CovDiagonalGammaPrior,
    CovIdentity,
    CovIsotropic,
    CovKroneckerFactored,
    CovUnconstrainedCholesky,
    CovUnconstrainedCholeskyWishartReg,
    CovUnconstrainedInvCholesky,
)
from .mnrsa import MNRSA  # noqa: F401
from .regression import MatnormalRegression  # noqa: F401
