"""Matrix-normal utilities.

Re-design of /root/reference/src/brainiak/matnormal/utils.py: the
TF-variable pack/unpack disappears (JAX pytrees + autodiff); what
remains are the Cholesky flattening with log-diagonal uniqueness, the
matrix-normal sampler, and a scipy val-and-grad bridge
(:func:`make_val_and_grad`, the analog of the reference's
utils.py:107-124 TF bridge) for users optimizing custom matnormal
losses with ``scipy.optimize.minimize``."""

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "flatten_cholesky_unique",
    "make_val_and_grad",
    "rmn",
    "scaled_I",
    "unflatten_cholesky_unique",
    "x_tx",
    "xx_t",
]


def make_val_and_grad(loss_fn, *, jit=True):
    """Bridge a JAX scalar loss to ``scipy.optimize.minimize``.

    Returns ``f(x, *args) -> (val, grad)`` with float64 NumPy outputs,
    suitable for ``minimize(..., jac=True)`` — the JAX analog of the
    reference's TF session bridge (matnormal/utils.py:107-124), with
    autodiff replacing the TF graph gradients.

    loss_fn : callable taking a flat parameter vector (plus optional
        fixed args) and returning a scalar.
    """
    # one jit per bridge construction is intentional (jaxlint
    # baseline): user loss closures are uncacheable without pinning
    # their captured data for process lifetime, and a bridge is
    # built once per fit then reused for every minimize iteration
    vg = jax.value_and_grad(loss_fn)
    if jit:
        vg = jax.jit(vg)

    def val_and_grad(x, *args):
        val, grad = vg(jnp.asarray(x), *args)
        return float(val), np.asarray(grad, dtype=np.float64)

    return val_and_grad


def xx_t(x):
    """x xᵀ (reference matnormal/utils.py:28-37)."""
    return x @ x.T


def x_tx(x):
    """xᵀ x (reference matnormal/utils.py:40-48)."""
    return x.T @ x


def scaled_I(scale, size):
    """scale · I (reference matnormal/utils.py:51-62)."""
    return jnp.eye(size) * scale


def tril_size(n):
    return (n * (n + 1)) // 2


def unflatten_cholesky_unique(flat, size):
    """Vector [n(n+1)/2] -> lower-triangular Cholesky factor with
    exponentiated diagonal (unique parameterization)."""
    L = jnp.zeros((size, size), dtype=flat.dtype)
    L = L.at[jnp.tril_indices(size)].set(flat)
    diag = jnp.exp(jnp.diag(L))
    return L - jnp.diag(jnp.diag(L)) + jnp.diag(diag)


def flatten_cholesky_unique(L):
    """Inverse of :func:`unflatten_cholesky_unique` (log diagonal)."""
    L = np.asarray(L)
    size = L.shape[0]
    Llog = L - np.diag(np.diag(L)) + np.diag(np.log(np.diag(L)))
    return Llog[np.tril_indices(size)]


def rmn(rowcov, colcov, random_state=None):
    """Draw from a zero-mean matrix-normal with the given row/column
    covariances (reference matnormal/utils.py:8-25)."""
    prng = np.random.RandomState(random_state)
    Z = prng.standard_normal((rowcov.shape[0], colcov.shape[0]))
    return np.linalg.cholesky(rowcov) @ Z @ np.linalg.cholesky(colcov).T
