"""Matrix-normal utilities.

Re-design of /root/reference/src/brainiak/matnormal/utils.py: the
TF-variable pack/unpack and scipy val-and-grad bridge disappear (JAX
pytrees + autodiff); what remains are the Cholesky flattening with
log-diagonal uniqueness and the matrix-normal sampler."""

import jax.numpy as jnp
import numpy as np

__all__ = [
    "flatten_cholesky_unique",
    "rmn",
    "scaled_I",
    "unflatten_cholesky_unique",
    "x_tx",
    "xx_t",
]


def xx_t(x):
    """x xᵀ (reference matnormal/utils.py:28-37)."""
    return x @ x.T


def x_tx(x):
    """xᵀ x (reference matnormal/utils.py:40-48)."""
    return x.T @ x


def scaled_I(scale, size):
    """scale · I (reference matnormal/utils.py:51-62)."""
    return jnp.eye(size) * scale


def tril_size(n):
    return (n * (n + 1)) // 2


def unflatten_cholesky_unique(flat, size):
    """Vector [n(n+1)/2] -> lower-triangular Cholesky factor with
    exponentiated diagonal (unique parameterization)."""
    L = jnp.zeros((size, size), dtype=flat.dtype)
    L = L.at[jnp.tril_indices(size)].set(flat)
    diag = jnp.exp(jnp.diag(L))
    return L - jnp.diag(jnp.diag(L)) + jnp.diag(diag)


def flatten_cholesky_unique(L):
    """Inverse of :func:`unflatten_cholesky_unique` (log diagonal)."""
    L = np.asarray(L)
    size = L.shape[0]
    Llog = L - np.diag(np.diag(L)) + np.diag(np.log(np.diag(L)))
    return Llog[np.tril_indices(size)]


def rmn(rowcov, colcov, random_state=None):
    """Draw from a zero-mean matrix-normal with the given row/column
    covariances (reference matnormal/utils.py:8-25)."""
    prng = np.random.RandomState(random_state)
    Z = prng.standard_normal((rowcov.shape[0], colcov.shape[0]))
    return np.linalg.cholesky(rowcov) @ Z @ np.linalg.cholesky(colcov).T
