"""Matrix-normal regression, TPU-native.

Re-design of /root/reference/src/brainiak/matnormal/regression.py:
Y ~ MN(Xβ, Σ_t, Σ_s), fit by maximum likelihood over β and the covariance
parameters — one jitted L-BFGS over a parameter pytree instead of the
TF-variable/scipy bridge.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree
from sklearn.base import BaseEstimator

from ..ops.optimize import minimize_lbfgs
from .matnormal_likelihoods import matnorm_logp

__all__ = ["MatnormalRegression"]


class MatnormalRegression(BaseEstimator):
    """MLE regression under matrix-normal noise
    (reference regression.py:15-146).

    Parameters
    ----------
    time_cov, space_cov : CovBase strategy objects
    optimizer / optCtrl : accepted for API compatibility (L-BFGS is used)
    """

    def __init__(self, time_cov, space_cov, optimizer="L-BFGS-B",
                 optCtrl=None, max_iters=300):
        self.time_cov = time_cov
        self.space_cov = space_cov
        self.optMethod = optimizer
        self.optCtrl = optCtrl or {}
        self.max_iters = max_iters
        self.n_t = time_cov.size
        self.n_v = space_cov.size

    def logp(self, X, Y, params):
        resid = Y - X @ params["beta"]
        return (matnorm_logp(resid, self.time_cov, params["time"],
                             self.space_cov, params["space"])
                + self.time_cov.logp(params["time"])
                + self.space_cov.logp(params["space"]))

    def fit(self, X, y, naive_init=True):
        """X: [TRs, conditions] design; y: [TRs, voxels] data."""
        X = jnp.asarray(X)
        y = jnp.asarray(y)
        self.n_c = X.shape[1]

        time_params = self.time_cov.init_params(seed=0)
        space_params = self.space_cov.init_params(seed=1)
        if naive_init:
            sigma_inv_x = self.time_cov.solve(time_params, X)
            sigma_inv_y = self.time_cov.solve(time_params, y)
            beta_init = jnp.linalg.solve(X.T @ sigma_inv_x,
                                         X.T @ sigma_inv_y)
        else:
            beta_init = jnp.asarray(
                np.random.randn(self.n_c, self.n_v))
        params0 = {"beta": beta_init, "time": time_params,
                   "space": space_params}
        flat0, unravel = ravel_pytree(params0)

        @jax.jit
        def run(flat0):
            def loss(flat):
                return -self.logp(X, y, unravel(flat))

            return minimize_lbfgs(loss, flat0, max_iters=self.max_iters)

        flat, value = run(flat0)
        params = unravel(flat)
        self.beta_ = np.asarray(params["beta"])
        self.time_params_ = params["time"]
        self.space_params_ = params["space"]
        self.final_loss_ = float(value)
        return self

    def predict(self, X):
        """Predict data given design (reference regression.py:95-113)."""
        return np.asarray(jnp.asarray(X) @ jnp.asarray(self.beta_))

    def calibrate(self, Y):
        """Decode the design from new data using the fitted model:
        X̂ = (βΣ_s⁻¹βᵀ)⁻¹ βΣ_s⁻¹Yᵀ (reference regression.py:115-146)."""
        beta = jnp.asarray(self.beta_)
        sinv_y = self.space_cov.solve(self.space_params_,
                                      jnp.asarray(Y).T)
        sinv_bt = self.space_cov.solve(self.space_params_, beta.T)
        out = jnp.linalg.solve(beta @ sinv_bt, beta @ sinv_y)
        return np.asarray(out.T)
