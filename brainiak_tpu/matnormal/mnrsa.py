"""Matrix-normal RSA (MNRSA), TPU-native.

Re-design of /root/reference/src/brainiak/matnormal/mnrsa.py: learn the RSA
covariance U = LLᵀ of the mapping from design to signal by marginalizing
over the mapping:

    Y ~ MN(0, Σ_t + [XL, X₀][XL, X₀]ᵀ, Σ_s)

The reference couples TF variables, pymanopt-free scipy L-BFGS and hand
bridging (mnrsa.py:21-175); here the marginal likelihood is a pure JAX
function of a parameter pytree and one jitted L-BFGS fits everything.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree
from sklearn.base import BaseEstimator
from sklearn.linear_model import LinearRegression

from ..ops.optimize import minimize_lbfgs
from ..utils.utils import cov2corr
from .covs import CovIdentity
from .matnormal_likelihoods import matnorm_logp_marginal_row
from .utils import flatten_cholesky_unique, tril_size, \
    unflatten_cholesky_unique

__all__ = ["MNRSA"]


class MNRSA(BaseEstimator):
    """Matrix-normal RSA (reference mnrsa.py:21-175).

    Parameters
    ----------
    time_cov, space_cov : CovBase strategy objects
    n_nureg : number of nuisance regressors X₀
    optimizer / optCtrl : accepted for API compatibility

    Attributes after fit: ``U_`` (RSA covariance), ``C_`` (correlation),
    ``L_`` (Cholesky factor).
    """

    def __init__(self, time_cov, space_cov, n_nureg=5,
                 optimizer="L-BFGS-B", optCtrl=None, max_iters=300):
        self.n_T = time_cov.size
        self.n_V = space_cov.size
        self.n_nureg = n_nureg
        self.optMethod = optimizer
        self.optCtrl = optCtrl or {}
        self.max_iters = max_iters
        self.time_cov = time_cov
        self.space_cov = space_cov

    def logp(self, X, Y, params):
        """Marginal MNRSA log-likelihood (reference mnrsa.py:158-175)."""
        n_c = X.shape[1]
        rsa_cov = CovIdentity(size=n_c + self.n_nureg)
        L = unflatten_cholesky_unique(params["L_flat"], n_c)
        x_stack = jnp.concatenate([X @ L, params["X_0"]], axis=1)
        return (self.time_cov.logp(params["time"])
                + self.space_cov.logp(params["space"])
                + matnorm_logp_marginal_row(
                    Y, self.time_cov, params["time"],
                    self.space_cov, params["space"],
                    x_stack, rsa_cov, {}))

    def fit(self, X, y, naive_init=True):
        """X: brain data [TRs, voxels]; y: design [TRs, conditions]
        (sklearn orientation, flipped internally — reference
        mnrsa.py:93-156)."""
        X, Y = y, X  # generative orientation
        X = np.asarray(X)
        Y = np.asarray(Y)
        self.n_c = X.shape[1]

        if naive_init:
            m = LinearRegression(fit_intercept=False)
            m.fit(X=X, y=Y)
            self.naive_U_ = np.cov(m.coef_.T)
            L_flat0 = flatten_cholesky_unique(
                np.linalg.cholesky(self.naive_U_
                                   + 1e-8 * np.eye(self.n_c)))
        else:
            rng = np.random.RandomState(0)
            L_flat0 = rng.standard_normal(tril_size(self.n_c))

        rng = np.random.RandomState(1)
        params0 = {
            "L_flat": jnp.asarray(L_flat0),
            "X_0": jnp.asarray(rng.standard_normal(
                (self.n_T, self.n_nureg))),
            "time": self.time_cov.init_params(seed=2),
            "space": self.space_cov.init_params(seed=3),
        }
        flat0, unravel = ravel_pytree(params0)
        X_j = jnp.asarray(X)
        Y_j = jnp.asarray(Y)

        @jax.jit
        def run(flat0):
            def loss(flat):
                return -self.logp(X_j, Y_j, unravel(flat))

            return minimize_lbfgs(loss, flat0, max_iters=self.max_iters)

        flat, value = run(flat0)
        params = unravel(flat)
        L = np.asarray(unflatten_cholesky_unique(params["L_flat"],
                                                 self.n_c))
        self.L_ = L
        self.U_ = L @ L.T
        self.C_ = cov2corr(self.U_)
        self.X_0_ = np.asarray(params["X_0"])
        self.time_params_ = params["time"]
        self.space_params_ = params["space"]
        self.final_loss_ = float(value)
        return self
