"""Bayesian RSA (BRSA/GBRSA), TPU-native.

Re-design of /root/reference/src/brainiak/reprsimil/brsa.py (Cai et al.,
NIPS 2016).  The model:

    Y = X·β + X₀·β₀ + ε,   β_v ~ N(0, (s_v σ_v)² U),   ε_v ~ AR(1)(ρ_v, σ_v)

estimates the shared covariance U of task response patterns while
marginalizing the per-voxel response amplitudes, yielding an RSA estimate
unbiased by the design correlation structure.

TPU-first architecture: the reference maintains ~1500 lines of hand-derived
gradients for L-BFGS over custom likelihoods (brsa.py:2213-2696) plus
AR(1) template matrices; here the per-voxel marginal log-likelihood is ONE
vmapped Woodbury computation (AR(1) precision is analytic tridiagonal; the
low-rank task+nuisance term enters through a (K+n₀)×(K+n₀) Cholesky), and
all gradients come from autodiff through a jitted L-BFGS.  The parameters
are the Cholesky factor of U (optionally low rank), per-voxel log-SNR,
log-σ, transformed ρ, and nuisance amplitudes.

Documented deviations from the reference's internals:
- nuisance regressors are marginalized with learned per-voxel amplitudes
  instead of the reference's alternating explicit β₀ updates;
- the state-space decoder in transform/score treats the first sample of
  each scan as stationary AR(1) noise rather than white, and the fitted
  (score-unused, as in the reference) X0_null_/beta0_null_ attributes
  come from a least-squares fit rather than an alternating update;
- the Gaussian-Process prior on log-SNR learns its length scales and
  profiles its variance at the MAP exactly as the reference's fitV step
  does (brsa.py:2425-2517), but jointly inside the single L-BFGS MAP
  program rather than in an alternating fitU/fitV loop.
"""

import logging
from functools import partial

import scipy.stats

import jax
import jax.numpy as jnp
import numpy as np
from sklearn.base import BaseEstimator, TransformerMixin
from sklearn.decomposition import PCA
from sklearn.exceptions import NotFittedError
from sklearn.utils import assert_all_finite
from sklearn.utils.validation import check_random_state

from ..ops.optimize import minimize_lbfgs
from ..resilience.guards import (array_digest, check_state,
                                 pack_rng_state, run_resilient_loop,
                                 unpack_rng_state)
from ..utils.utils import cov2corr

logger = logging.getLogger(__name__)

__all__ = ["BRSA", "GBRSA", "Ncomp_SVHT_MG_DLD_approx",
           "prior_GP_var_half_cauchy", "prior_GP_var_inv_gamma"]


def prior_GP_var_inv_gamma(y_invK_y, n_y, tau_range):
    """MAP estimate of a Gaussian-Process variance tau^2 under an
    inverse-Gamma(2, tau_range^2) prior, plus the log posterior density
    at the MAP (reference brsa.py:70-155).  y_invK_y = y K^{-1} yᵀ for
    n_y observations of the GP-distributed function (e.g. log-SNR)."""
    import scipy.stats

    alpha = 2
    tau2 = (y_invK_y + 2 * tau_range ** 2) / (alpha * 2 + 2 + n_y)
    log_ptau = scipy.stats.invgamma.logpdf(tau2, scale=tau_range ** 2,
                                           a=2)
    return tau2, log_ptau


def prior_GP_var_half_cauchy(y_invK_y, n_y, tau_range):
    """MAP estimate of a Gaussian-Process variance tau^2 under a
    half-Cauchy(tau_range) prior on tau, plus the log prior density at
    the MAP (reference brsa.py:120-155)."""
    import scipy.stats

    tau2 = (y_invK_y - n_y * tau_range ** 2
            + np.sqrt(n_y ** 2 * tau_range ** 4 + (2 * n_y + 8)
                      * tau_range ** 2 * y_invK_y + y_invK_y ** 2)) \
        / 2 / (n_y + 2)
    log_ptau = scipy.stats.halfcauchy.logpdf(tau2 ** 0.5,
                                             scale=tau_range)
    return tau2, log_ptau


def Ncomp_SVHT_MG_DLD_approx(X, zscore=True):
    """Optimal number of principal components by the Gavish & Donoho
    singular-value hard threshold ("the optimal hard threshold is
    4/sqrt(3)"), using their omega(beta) approximation
    (reference brsa.py:157-187).  Used to auto-select ``n_nureg``."""
    X = np.asarray(X, dtype=float)
    beta = X.shape[0] / X.shape[1]
    if beta > 1:
        beta = 1 / beta
    omega = 0.56 * beta ** 3 - 0.95 * beta ** 2 + 1.82 * beta + 1.43
    if zscore:
        std = X.std(axis=0)
        Xz = np.where(std > 0, (X - X.mean(axis=0)) / np.where(
            std > 0, std, 1.0), 0.0)
        sing = np.linalg.svd(Xz, compute_uv=False)
    else:
        sing = np.linalg.svd(X, compute_uv=False)
    thresh = omega * np.median(sing)
    return int(np.sum(np.logical_and(
        sing > thresh, np.logical_not(np.isclose(sing, thresh)))))


def _ar1_whiten(M, rho, scan_starts_mask):
    """Apply the AR(1) whitening transform row-wise to M [T, C]:
    W M where WᵀW = precision."""
    M_prev = jnp.concatenate([M[:1], M[:-1]], axis=0)
    return jnp.where(scan_starts_mask[:, None],
                     M * jnp.sqrt(1 - rho ** 2), M - rho * M_prev)


def _voxel_marginal_ll(y, rho, log_sigma2, snr, log_lam2, XL, X0,
                       scan_starts, n_runs):
    """Marginal log-likelihood of one voxel's time series.

    Σ_v = σ²·AR1(ρ) + (snr·σ)²·XL·XLᵀ + λ²·X₀X₀ᵀ, computed by Woodbury
    with the analytic AR(1) precision.
    """
    t = y.shape[0]
    sigma2 = jnp.exp(log_sigma2)
    lam2 = jnp.exp(log_lam2)
    # combined low-rank factor [T, K+n0]
    F = jnp.concatenate([XL * (snr * jnp.sqrt(sigma2)),
                         X0 * jnp.sqrt(lam2)], axis=1)
    k = F.shape[1]

    wy = _ar1_whiten(y[:, None], rho, scan_starts)[:, 0]
    wF = _ar1_whiten(F, rho, scan_starts)

    quad_yy = jnp.sum(wy ** 2) / sigma2
    Fty = wF.T @ wy / sigma2
    FtF = wF.T @ wF / sigma2

    cap = jnp.eye(k) + FtF
    chol = jnp.linalg.cholesky(cap)
    z = jax.scipy.linalg.solve_triangular(chol, Fty, lower=True)
    quad = quad_yy - jnp.sum(z ** 2)
    logdet_cap = 2 * jnp.sum(jnp.log(jnp.diag(chol)))
    logdet_ar = t * jnp.log(sigma2) - n_runs * jnp.log(1 - rho ** 2)
    return -0.5 * (t * jnp.log(2 * jnp.pi) + logdet_ar + logdet_cap
                   + quad)


def _grid_marginal_ll(y, XL, s, r, starts, n_runs):
    """Per-(voxel, grid-point) marginal log-likelihood with sigma^2 profiled
    analytically.  Returns (ll, sigma2_hat).  Shared by GBRSA's fitting
    objective and its grid posteriors."""
    rank = XL.shape[1]
    t = y.shape[0]
    F = XL * s
    wy = _ar1_whiten(y[:, None], r, starts)[:, 0]
    wF = _ar1_whiten(F, r, starts)
    cap = jnp.eye(rank) + wF.T @ wF
    chol = jnp.linalg.cholesky(cap)
    z = jax.scipy.linalg.solve_triangular(chol, wF.T @ wy, lower=True)
    quad = jnp.sum(wy ** 2) - jnp.sum(z ** 2)
    logdet = 2 * jnp.sum(jnp.log(jnp.diag(chol))) \
        - n_runs * jnp.log(1 - r ** 2)
    return -0.5 * (t * jnp.log(quad) + logdet), quad / t


def _ar1_yw(x, same_para=False):
    """Yule-Walker AR(1) estimates per column of x (reference
    brsa.py:1632-1660 via nitime AR_est_YW): rho from the lag-1/lag-0
    autocovariance ratio and the innovation variance from the residual.
    Raw (non-demeaned) autocovariances are used so constant regressors
    (e.g. per-run DC columns) get a high-rho, small-innovation prior
    rather than an undefined one.  With ``same_para`` all columns are
    treated as one concatenated process (the reference's treatment of
    the task design matrix)."""
    x = np.asarray(x, dtype=float)

    def one(v):
        c0 = float(np.dot(v, v)) / len(v)
        if c0 <= 1e-12:
            return 0.0, 1e-6
        c1 = float(np.dot(v[:-1], v[1:])) / len(v)
        rho = float(np.clip(c1 / c0, -0.99, 0.99))
        return rho, max(c0 - rho * c1, 1e-6 * c0)

    if same_para:
        rho, sig2 = one(x.reshape(-1, order='F'))
        return (np.full(x.shape[1], rho), np.full(x.shape[1], sig2))
    pairs = [one(x[:, c]) for c in range(x.shape[1])]
    return (np.array([p[0] for p in pairs]),
            np.array([p[1] for p in pairs]))


def _whiten_segment(M, rho_e):
    """AR(1)-whiten the rows of one within-scan segment: the first row is
    scaled to the stationary marginal, subsequent rows become innovations.
    M: [T, V]; rho_e: [V]."""
    head = jnp.sqrt(1.0 - rho_e ** 2)[None, :] * M[:1]
    return jnp.concatenate([head, M[1:] - rho_e[None, :] * M[:-1]], 0)


@jax.jit
def _lgssm_segment(Y, W, sigma2_e, rho_e, rho_x, sigma2_x):
    """Exact posterior of latent time courses for one scan segment of the
    linear-Gaussian model the reference decodes with a forward-backward
    pass (reference brsa.py:1530-1582, 1664-1818):

        z_t = diag(rho_x)·z_{t-1} + w_t,  w ~ N(0, diag(sigma2_x)),
        Y_t = zₜ·W + e_t,                 e ~ stationary AR(1)(rho_e,
                                               sigma2_e) per voxel,

    with z_1 at the stationary AR(1) marginal.  TPU-native design: instead
    of sequential Kalman recursions over Python lists, the joint posterior
    precision is block-tridiagonal with K×K blocks shared across time, so
    the smoother is a Cholesky block-Thomas solve as two ``lax.scan``s;
    the linear term comes from autodiff of the explicit quadratic energy,
    eliminating hand-derived cross terms.  Returns (mu [T, K],
    log p(Y)); the noise model deviates from the reference in treating
    the first sample of each segment as stationary AR(1).
    """
    t_n, v_n = Y.shape
    k_n = W.shape[0]

    def energy(Z):
        resid_w = _whiten_segment(Y - Z @ W, rho_e)
        e_term = 0.5 * jnp.sum(resid_w ** 2 / sigma2_e[None, :])
        p_head = 0.5 * jnp.sum(Z[0] ** 2 * (1 - rho_x ** 2) / sigma2_x)
        p_tail = 0.5 * jnp.sum(
            (Z[1:] - rho_x[None, :] * Z[:-1]) ** 2 / sigma2_x[None, :])
        return e_term + p_head + p_tail

    b = -jax.grad(energy)(jnp.zeros((t_n, k_n), dtype=Y.dtype))

    # shared K x K emission blocks (weighted Gram matrices of W)
    def gram(wt):
        return (W * wt[None, :]) @ W.T

    A = gram(1.0 / sigma2_e)
    B = gram(rho_e ** 2 / sigma2_e)
    C = gram(rho_e / sigma2_e)
    A1 = gram((1.0 - rho_e ** 2) / sigma2_e)

    Pd = jnp.diag(1.0 / sigma2_x)
    Pmid = jnp.diag((1.0 + rho_x ** 2) / sigma2_x)
    R = jnp.diag(rho_x / sigma2_x)

    if t_n == 1:
        # single-sample segment: only the stationary prior and the
        # stationary-noise emission enter (no transition terms)
        D = (jnp.diag((1.0 - rho_x ** 2) / sigma2_x) + A1)[None]
    else:
        D = jnp.tile((Pmid + A + B)[None], (t_n, 1, 1))
        D = D.at[0].set(Pd + A1 + B)
        D = D.at[-1].set(Pd + A)
    O = -(R + C)

    # forward block-Thomas elimination
    chol0 = jnp.linalg.cholesky(D[0])

    def fwd(carry, inp):
        chol_prev, m_prev = carry
        d_t, b_t = inp
        SO = jax.scipy.linalg.cho_solve((chol_prev, True), O)
        Sm = jax.scipy.linalg.cho_solve((chol_prev, True), m_prev)
        S_t = d_t - O.T @ SO
        m_t = b_t - O.T @ Sm
        chol_t = jnp.linalg.cholesky(S_t)
        return (chol_t, m_t), (chol_t, m_t)

    (_, _), (chols_tail, ms_tail) = jax.lax.scan(
        fwd, (chol0, b[0]), (D[1:], b[1:]))
    chols = jnp.concatenate([chol0[None], chols_tail], 0)
    ms = jnp.concatenate([b[:1], ms_tail], 0)
    logdet_h = 2.0 * jnp.sum(
        jnp.log(jnp.diagonal(chols, axis1=1, axis2=2)))

    # backward substitution
    mu_last = jax.scipy.linalg.cho_solve((chols[-1], True), ms[-1])

    def bwd(mu_next, inp):
        chol_t, m_t = inp
        mu_t = jax.scipy.linalg.cho_solve(
            (chol_t, True), m_t - O @ mu_next)
        return mu_t, mu_t

    _, mu_rev = jax.lax.scan(bwd, mu_last, (chols[:-1], ms[:-1]),
                             reverse=True)
    mu = jnp.concatenate([mu_rev, mu_last[None]], 0)

    # marginal log-likelihood: -E(mu) + Gaussian integral + normalizers
    noise_norm = -0.5 * t_n * jnp.sum(jnp.log(
        2 * jnp.pi * sigma2_e)) + 0.5 * jnp.sum(jnp.log1p(-rho_e ** 2))
    prior_norm = (
        -0.5 * jnp.sum(jnp.log(2 * jnp.pi * sigma2_x / (1 - rho_x ** 2)))
        - 0.5 * (t_n - 1) * jnp.sum(jnp.log(2 * jnp.pi * sigma2_x)))
    log_p = (-energy(mu) + noise_norm + prior_norm - 0.5 * logdet_h +
             0.5 * t_n * k_n * jnp.log(2 * jnp.pi))
    return mu, log_p


def _latent_ar1_params(design, X0):
    """AR(1) smoothness priors for the decoded task and nuisance time
    courses, estimated Yule-Walker from the training design matrix
    (shared parameters) and nuisance regressors (per column) — the
    reference estimates the same quantities at fit time
    (brsa.py:778-780)."""
    rho_d, sig2_d = _ar1_yw(design, same_para=True)
    rho_0, sig2_0 = _ar1_yw(X0)
    return rho_d, sig2_d, rho_0, sig2_0


def _decode_timecourses(Y, weight, sigma2_e, rho_e, rho_x, sigma2_x,
                        onsets):
    """Run the smoother per scan segment; returns (mu [T, K], log_p)."""
    n_t = Y.shape[0]
    bounds = list(onsets) + [n_t]
    mus, log_p = [], 0.0
    for a, b in zip(bounds[:-1], bounds[1:]):
        mu, lp = _lgssm_segment(
            jnp.asarray(Y[a:b]), jnp.asarray(weight),
            jnp.asarray(sigma2_e), jnp.asarray(rho_e),
            jnp.asarray(rho_x), jnp.asarray(sigma2_x))
        mus.append(np.asarray(mu))
        log_p += float(lp)
    return np.concatenate(mus, 0), log_p


def _make_L(l_flat, n_c, rank):
    L = jnp.zeros((n_c, rank))
    rows, cols = np.tril_indices(n_c, m=rank)
    keep = cols < rank
    return L.at[rows[keep], cols[keep]].set(l_flat)


def _gp_neg_log_prior(log_snr, c_space, c_inten, dist2, inten_d2,
                      tau_range, space_range, inten_range, eta,
                      gp_inten_on, tau2_prior):
    """Negative log of the GP prior over log-SNR with LEARNED length
    scales (reference brsa.py:2425-2517): squared-exponential kernel
    over voxel coordinates (optionally × intensity), the GP variance
    tau² profiled at its MAP under an inverse-gamma (or half-Cauchy)
    prior, and half-Cauchy priors on the length scales themselves.
    Constants independent of the parameters are dropped."""
    n_v = log_snr.shape[0]
    quad = dist2 / jnp.exp(c_space)
    if gp_inten_on:
        quad = quad + inten_d2 / jnp.exp(c_inten)
    K = jnp.exp(-0.5 * quad) + eta * jnp.eye(n_v, dtype=log_snr.dtype)
    cho = jnp.linalg.cholesky(K)
    invk_y = jax.scipy.linalg.cho_solve((cho, True), log_snr)
    # clamp: at the log_snr = 0 init the half-Cauchy MAP tau2 would be
    # exactly 0 and its log would poison the whole fit with NaN
    y_invk_y = jnp.maximum(log_snr @ invk_y, 1e-10)
    logdet_k = 2.0 * jnp.sum(jnp.log(jnp.diag(cho)))

    if tau2_prior == "halfcauchy":
        # MAP tau2 = (y - a + sqrt(a^2 + b)) / (2(n+2)) with a = n*tau_r^2,
        # b = (2n+8)*tau_r^2*y + y^2 — rationalized as y + b/(sqrt(a^2+b)+a)
        # because the direct form cancels catastrophically in fp32 when
        # y << a (it rounded to <= 0 and NaN'd the log below)
        a = n_v * tau_range ** 2
        b = (2 * n_v + 8) * tau_range ** 2 * y_invk_y + y_invk_y ** 2
        tau2 = (y_invk_y + b / (jnp.sqrt(a * a + b) + a)) / 2 / (n_v + 2)
        log_ptau = jnp.log(2.0 / (jnp.pi * tau_range)) \
            - jnp.log1p(tau2 / tau_range ** 2)
    else:  # inverse-gamma on tau^2, shape=2, scale=tau_range^2
        tau2 = (y_invk_y + 2 * tau_range ** 2) / (2 * 2 + 2 + n_v)
        log_ptau = 2 * jnp.log(tau_range ** 2) \
            - 3 * jnp.log(tau2) - tau_range ** 2 / tau2

    neg = 0.5 * logdet_k + 0.5 * n_v * jnp.log(tau2) \
        + 0.5 * y_invk_y / tau2 - log_ptau
    # half-Cauchy priors on the length scales (reference brsa.py:2493-2496)
    neg = neg - (jnp.log(2.0 / (jnp.pi * space_range))
                 - jnp.log1p(jnp.exp(c_space) / space_range ** 2))
    if gp_inten_on:
        neg = neg - (jnp.log(2.0 / (jnp.pi * inten_range))
                     - jnp.log1p(jnp.exp(c_inten) / inten_range ** 2))
    return neg


@partial(jax.jit, static_argnames=("n_c", "rank", "max_iters", "gp_on",
                                   "gp_inten_on", "tau2_prior", "tol"))
def _fit_brsa_params(flat0, y_all, X, X0, scan_starts, n_runs, dist2,
                     inten_d2, tau_range, space_range, inten_range, eta,
                     gp_lims, *, n_c, rank, max_iters, gp_on,
                     gp_inten_on, tau2_prior, tol=1e-8):
    """Joint MAP fit of (L, per-voxel snr/σ²/ρ/λ², GP length scales) by
    autodiff L-BFGS.  The GP length-scale hyperparameters (log l²) ride
    at the tail of the flat parameter vector and are learned jointly, as
    the reference does in its fitV step (brsa.py:2425-2517)."""
    n_v = y_all.shape[1]
    n_l = len(np.tril_indices(n_c, m=rank)[0])

    def unpack(flat):
        l_flat = flat[:n_l]
        log_snr = flat[n_l:n_l + n_v]
        log_sigma2 = flat[n_l + n_v:n_l + 2 * n_v]
        rho_unc = flat[n_l + 2 * n_v:n_l + 3 * n_v]
        log_lam2 = flat[n_l + 3 * n_v:n_l + 4 * n_v]
        return l_flat, log_snr, log_sigma2, rho_unc, log_lam2

    def loss(flat):
        l_flat, log_snr, log_sigma2, rho_unc, log_lam2 = unpack(flat)
        L = _make_L(l_flat, n_c, rank)
        XL = X @ L
        rho = jnp.tanh(rho_unc)
        snr = jnp.exp(log_snr)
        ll = jax.vmap(
            lambda y, r, ls, s, ll2: _voxel_marginal_ll(
                y, r, ls, s, ll2, XL, X0, scan_starts, n_runs),
            in_axes=(1, 0, 0, 0, 0))(y_all, rho, log_sigma2, snr,
                                     log_lam2)
        total = -jnp.sum(ll)
        # weak priors keep scales identified (snr geometric mean ~ 1,
        # reference normalizes SNR similarly after fitting)
        total = total + 0.5 * jnp.sum(log_snr) ** 2 / n_v
        if gp_on:
            base = n_l + 4 * n_v
            # The log length-scales are box-bounded (sigmoid transform)
            # to [voxel scale, ROI extent]: the profiled-tau2 objective
            # has a degenerate optimum at l -> inf (K -> rank-1, its
            # logdet rewards collapse) that a joint optimizer will find;
            # the reference sidesteps it by alternating fits from a
            # small-l init (brsa.py:1406-1413), the box is the honest
            # guard for the single-program fit.
            c_space = gp_lims[0] + (gp_lims[1] - gp_lims[0]) * \
                jax.nn.sigmoid(flat[base])
            c_inten = gp_lims[2] + (gp_lims[3] - gp_lims[2]) * \
                jax.nn.sigmoid(flat[base + 1])
            total = total + _gp_neg_log_prior(
                log_snr, c_space, c_inten, dist2, inten_d2,
                tau_range, space_range, inten_range, eta, gp_inten_on,
                tau2_prior)
        return total

    return minimize_lbfgs(loss, flat0, max_iters=max_iters, tol=tol)


class BRSA(BaseEstimator, TransformerMixin):
    """Bayesian RSA for one subject (reference brsa.py:220-2694).

    Parameters follow the reference where meaningful here: ``n_iter``
    (outer rounds of auto-nuisance refitting), ``rank`` (of U),
    ``auto_nuisance``/``n_nureg``, ``GP_space``/``GP_inten``,
    ``random_state``.

    Attributes after fit: ``U_``, ``L_``, ``C_`` (correlation),
    ``nSNR_`` (normalized pseudo-SNR), ``sigma_``, ``rho_``, ``beta_``,
    ``beta0_``, ``X0_``.
    """

    def __init__(self, n_iter=2, rank=None, auto_nuisance=True,
                 n_nureg=None, nureg_zscore=True, nureg_method='PCA',
                 baseline_single=False, GP_space=False, GP_inten=False,
                 space_smooth_range=None, inten_smooth_range=None,
                 tau_range=5.0, tau2_prior=prior_GP_var_inv_gamma,
                 eta=0.0001, random_state=None, anneal_speed=10,
                 lbfgs_iters=200, tol=1e-4):
        if nureg_method not in ('PCA', 'FA', 'ICA', 'SPCA'):
            raise ValueError('nureg_method can only be FA, PCA, '
                             'SPCA(for sparse PCA) or ICA')
        self.n_iter = n_iter
        self.rank = rank
        self.auto_nuisance = auto_nuisance
        self.n_nureg = n_nureg
        self.nureg_zscore = nureg_zscore
        self.nureg_method = nureg_method
        self.baseline_single = baseline_single
        self.GP_space = GP_space
        self.GP_inten = GP_inten
        self.space_smooth_range = space_smooth_range
        self.inten_smooth_range = inten_smooth_range
        self.tau_range = tau_range
        self.tau2_prior = tau2_prior
        self.eta = eta
        self.random_state = random_state
        self.anneal_speed = anneal_speed
        self.lbfgs_iters = lbfgs_iters
        self.tol = tol

    # -- helpers ----------------------------------------------------------
    @staticmethod
    def _check_onsets(scan_onsets, n_t):
        """Validate scan onsets: must include 0 and be within range
        (reference brsa.py:692, 912-914)."""
        if scan_onsets is None:
            return np.array([0], dtype=int)
        scan_onsets = np.asarray(scan_onsets, dtype=int)
        assert scan_onsets.ndim == 1 and 0 in scan_onsets, \
            'scan_onsets should either be None or a 1-D array of indices ' \
            'including 0'
        assert np.all((scan_onsets >= 0) & (scan_onsets < n_t)), \
            'scan_onsets out of range'
        return np.unique(scan_onsets)

    @classmethod
    def _subject_onsets(cls, scan_onsets, s, n_t):
        """Per-subject onsets from either a list of per-subject onset
        arrays or one shared onset vector (a plain list of ints is the
        latter); used consistently by GBRSA fit/transform/score."""
        if scan_onsets is None:
            return np.array([0], dtype=int)
        per_subject = isinstance(scan_onsets, list) and \
            len(scan_onsets) > 0 and not np.isscalar(scan_onsets[0])
        raw = scan_onsets[s] if per_subject else scan_onsets
        return cls._check_onsets(raw, n_t)

    @staticmethod
    def _dc_regressors(n_t, scan_onsets):
        """Per-run DC components (reference includes these always)."""
        onsets = list(scan_onsets) + [n_t]
        X_dc = np.zeros((n_t, len(onsets) - 1))
        for i in range(len(onsets) - 1):
            X_dc[onsets[i]:onsets[i + 1], i] = 1.0
        return X_dc

    def _gp_distances(self, coords, inten):
        """Squared voxel-pair distances (and intensity differences) with
        the smooth-range defaults and length-scale inits for the learned
        GP (reference brsa.py:1212-1255: default smooth range is half
        the ROI extent / intensity span)."""
        d2 = ((coords[:, None, :] - coords[None, :, :]) ** 2).sum(-1)
        space_range = self.space_smooth_range
        if space_range is None:
            space_range = np.sqrt(np.max(d2)) / 2.0 if np.any(d2 > 0) \
                else 1.0
        # log l^2 box: [voxel scale, ROI extent].  The reference starts
        # the length scale at the voxel scale (brsa.py:1406-1413); we
        # additionally bound it above by the ROI extent because the
        # joint fit can otherwise reach the degenerate l -> inf optimum.
        if np.any(d2 > 0):
            cs_lo = np.log(np.min(d2[d2 > 0]))
            cs_hi = np.log(np.max(d2))
        else:
            cs_lo, cs_hi = -1.0, 1.0
        if cs_hi - cs_lo < 1e-6:
            cs_hi = cs_lo + 1.0
        di2 = np.zeros((1, 1))
        inten_range = 1.0
        ci_lo, ci_hi = -1.0, 1.0
        if self.GP_inten and inten is not None:
            di2 = (inten[:, None] - inten[None, :]) ** 2
            inten_range = self.inten_smooth_range
            if inten_range is None:
                inten_range = np.sqrt(np.max(di2)) / 2.0 \
                    if np.any(di2 > 0) else 1.0
            if np.any(di2 > 0):
                # 2nd-percentile lower edge, floored at 0.5
                # (brsa.py:1416-1424)
                ci_lo = np.log(max(np.percentile(di2[di2 > 0], 2), 0.5))
                ci_hi = np.log(max(np.max(di2), np.exp(ci_lo)))
            if ci_hi - ci_lo < 1e-6:
                ci_hi = ci_lo + 1.0
        return d2, di2, float(space_range), float(inten_range), \
            (float(cs_lo), float(cs_hi), float(ci_lo), float(ci_hi))

    # -- API --------------------------------------------------------------
    def fit(self, X, design, nuisance=None, scan_onsets=None, coords=None,
            inten=None, checkpoint_dir=None, checkpoint_every=5):
        """Fit the shared covariance U and per-voxel parameters
        (reference brsa.py:581-793).  Note the reference's argument
        naming: X is the DATA [T, V]; design is [T, C].

        With ``checkpoint_dir``, each auto-nuisance outer round (the
        fitted parameters, the nuisance design, and the RNG stream) is
        checkpointed every ``checkpoint_every`` rounds under the
        resilience guard, and a later call with the same directory
        resumes after preemption.

        Example
        -------
        >>> brsa = BRSA(n_iter=4, rank=2)
        >>> brsa.fit(X, design, checkpoint_dir="/ckpts/brsa1")
        """
        logger.info('Running Bayesian RSA')
        self.random_state_ = check_random_state(self.random_state)
        assert not self.GP_inten or self.GP_space, \
            'You must specify GP_space to True if you want to use GP_inten'
        assert_all_finite(X)
        assert X.ndim == 2, 'The data should be 2-dimensional ndarray'
        assert np.all(np.std(X, axis=0) > 0), \
            'The time courses of some voxels do not change at all.' \
            ' Please make sure all voxels are within the brain'
        assert_all_finite(design)
        assert design.ndim == 2, \
            'The design matrix should be 2-dimensional ndarray'
        assert np.linalg.matrix_rank(design) == design.shape[1], \
            'Your design matrix has rank smaller than the number of' \
            ' columns.'
        assert design.shape[0] == X.shape[0], \
            'Design matrix and data do not have the same number of time ' \
            'points.'
        n_t, n_v = X.shape
        n_c = design.shape[1]
        rank = self.rank if self.rank is not None else n_c
        assert rank <= n_c, \
            'Rank cannot exceed the number of conditions'
        scan_onsets = self._check_onsets(scan_onsets, n_t)
        scan_starts = np.zeros(n_t, dtype=bool)
        scan_starts[scan_onsets] = True
        n_runs = len(scan_onsets)

        data = np.asarray(X, dtype=float)
        design = np.asarray(design, dtype=float)

        X0 = self._dc_regressors(n_t, scan_onsets)
        if nuisance is not None:
            X0 = np.column_stack([X0, nuisance])

        gp_on = bool(self.GP_space and coords is not None)
        gp = None
        if gp_on:
            d2, di2, space_range, inten_range, lims = \
                self._gp_distances(np.asarray(coords, float),
                                   None if inten is None
                                   else np.asarray(inten, float))
            gp = {"dist2": d2, "inten_d2": di2,
                  "space_range": space_range, "inten_range": inten_range,
                  "lims": lims,
                  "inten_on": bool(self.GP_inten and inten is not None)}

        n_rounds = max(self.n_iter, 1)
        res_keys = ("U", "L", "snr", "sigma2", "rho", "beta", "beta0")

        def pack(X0_c, result, done):
            keys, meta = pack_rng_state(self.random_state_)
            state = {"X0": np.asarray(X0_c, dtype=float),
                     "rng_keys": keys, "rng_meta": meta,
                     "done": np.array(float(done))}
            if result is not None:
                for key in res_keys:
                    state["res_" + key] = np.asarray(result[key], float)
                state["res_loss"] = np.array(float(result["loss"]))
                if gp_on:
                    state["res_gp"] = np.array(
                        [result["c_space"],
                         result.get("c_inten", 0.0),
                         result["tau2"]], dtype=float)
            return state

        def unpack(state):
            unpack_rng_state(self.random_state_, state["rng_keys"],
                             state["rng_meta"])
            X0_c = np.array(state["X0"], dtype=float)
            result = None
            if "res_U" in state:
                result = {key: np.array(state["res_" + key], float)
                          for key in res_keys}
                result["loss"] = float(np.asarray(state["res_loss"]))
                if "res_gp" in state:
                    gp_vals = np.asarray(state["res_gp"], float)
                    result["c_space"] = float(gp_vals[0])
                    result["c_inten"] = float(gp_vals[1])
                    result["tau2"] = float(gp_vals[2])
            return X0_c, result

        def run_chunk(state, step, n_steps):
            X0_c, result = unpack(state)
            done = False
            for i in range(n_steps):
                it = step + i
                result = self._fit_once(data, design, X0_c, scan_starts,
                                        n_runs, n_c, rank, gp)
                check_state({key: result[key] for key in res_keys},
                            iteration=it + 1, where="BRSA.fit")
                if not self.auto_nuisance or it == n_rounds - 1:
                    done = True
                    break
                # auto-nuisance: PCA of residuals after removing the
                # estimated task response and current nuisance fit
                resid = data - design @ result["beta"] - \
                    X0_c @ result["beta0"]
                X0_c = np.column_stack(
                    [self._dc_regressors(n_t, scan_onsets),
                     self._nuisance_components(resid)]
                    + ([nuisance] if nuisance is not None else []))
            return pack(X0_c, result, done), done

        # n_rounds is part of the fingerprint: the round count changes
        # the nuisance-design sequence, so a checkpoint from a
        # different n_iter is not resumable
        fingerprint = np.array(
            [array_digest(data), float(n_t), float(n_v), float(n_c),
             float(rank), array_digest(design), float(n_rounds)])
        state, _ = run_resilient_loop(
            run_chunk, pack(X0, None, False), n_rounds,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
            fingerprint=fingerprint, name="BRSA.fit",
            progress_objective="res_loss", progress_direction="min")
        X0, result = unpack(state)

        self.U_ = result["U"]
        self.L_ = result["L"]
        self.C_ = cov2corr(self.U_ + 1e-12 * np.eye(n_c))
        self.nSNR_ = result["snr"] / np.exp(
            np.mean(np.log(result["snr"])))
        self.sigma_ = np.sqrt(result["sigma2"])
        self.rho_ = result["rho"]
        self.beta_ = result["beta"]
        self.beta0_ = result["beta0"]
        self.X0_ = X0
        if gp_on:
            # learned GP hyperparameters (reference exposes lGPspace_,
            # bGP_ and lGPinten_ after fitting, brsa.py:452-474)
            self.lGPspace_ = np.sqrt(np.exp(result["c_space"]))
            self.bGP_ = np.sqrt(result["tau2"])
            if gp["inten_on"]:
                self.lGPinten_ = np.sqrt(np.exp(result["c_inten"]))
        self._design = design
        self._scan_starts = scan_starts
        self._n_runs = n_runs
        self.X0_null_, self.beta0_null_ = self._fit_null_nuisance(
            data, n_t, scan_onsets, nuisance)
        return self

    def _fit_null_nuisance(self, data, n_t, scan_onsets, nuisance):
        """Task-free nuisance model for score()'s null likelihood
        (reference brsa.py:781-790): DC + provided nuisance regressors,
        plus — under auto_nuisance — principal components of the
        residuals WITHOUT any task response removed, with the spatial
        loading beta0_null estimated by least squares."""
        X0_null = self._dc_regressors(n_t, scan_onsets)
        if nuisance is not None:
            X0_null = np.column_stack([X0_null, nuisance])
        if self.auto_nuisance:
            resid = data - X0_null @ np.linalg.lstsq(
                X0_null, data, rcond=None)[0]
            X0_null = np.column_stack(
                [X0_null, self._nuisance_components(resid)])
        beta0_null = np.linalg.lstsq(X0_null, data, rcond=None)[0]
        return X0_null, beta0_null

    def _nuisance_components(self, resid):
        """Shared auto-nuisance recipe (reference brsa.py:757-776):
        optionally z-score the residuals, auto-select the component count
        by Gavish-Donoho when n_nureg is None, and return std-normalized
        components from the configured sklearn decomposition (reference
        brsa.py:546-558: FA / whitened PCA / SparsePCA / FastICA).
        These run on host once per outer round — same as the reference's
        CPU sklearn calls — while the marginal-likelihood optimization
        stays on device."""
        n_t, n_v = resid.shape
        if self.nureg_zscore:
            resid = (resid - resid.mean(0)) / (resid.std(0) + 1e-12)
        n_nureg = self.n_nureg
        if n_nureg is None:
            n_nureg = max(Ncomp_SVHT_MG_DLD_approx(
                resid, zscore=False), 1)
        n_comp = min(n_nureg, n_v - 1, n_t - 1)
        if self.nureg_method == 'FA':
            from sklearn.decomposition import FactorAnalysis
            est = FactorAnalysis(n_components=n_comp)
        elif self.nureg_method == 'SPCA':
            from sklearn.decomposition import SparsePCA
            est = SparsePCA(n_components=n_comp, max_iter=20,
                            tol=self.tol,
                            random_state=getattr(
                                self, 'random_state_', None))
        elif self.nureg_method == 'ICA':
            from sklearn.decomposition import FastICA
            est = FastICA(n_components=n_comp,
                          whiten='unit-variance',
                          random_state=getattr(
                              self, 'random_state_', None))
        else:
            est = PCA(n_components=n_comp)
        comps = est.fit_transform(resid)
        return comps / (comps.std(0) + 1e-12)

    def _fit_once(self, data, design, X0, scan_starts, n_runs, n_c, rank,
                  gp=None):
        n_t, n_v = data.shape
        n_l = len(np.tril_indices(n_c, m=rank)[0])
        rng = self.random_state_
        gp_on = gp is not None
        flat0 = np.concatenate([
            rng.randn(n_l) * 0.1 + 0.5,
            np.zeros(n_v),               # log snr
            np.log(np.var(data, axis=0)),  # log sigma2
            np.zeros(n_v),               # rho (unconstrained)
            np.zeros(n_v),               # log lambda2
            # unconstrained GP length-scale params; -2 puts the sigmoid
            # near the lower (voxel-scale) box edge, the reference's
            # small-l starting point
            [-2.0, -2.0] if gp_on else [],
        ])
        if self.tau2_prior is prior_GP_var_half_cauchy:
            tau2_prior = "halfcauchy"
        elif self.tau2_prior is prior_GP_var_inv_gamma:
            tau2_prior = "invgamma"
        elif gp_on:
            raise ValueError(
                "tau2_prior must be prior_GP_var_inv_gamma or "
                "prior_GP_var_half_cauchy (the profiled tau2 inside the "
                "jitted objective supports exactly these two)")
        else:
            tau2_prior = "invgamma"
        lims = gp["lims"] if gp_on else (0.0, 1.0, 0.0, 1.0)
        flat, value = _fit_brsa_params(
            jnp.asarray(flat0), jnp.asarray(data), jnp.asarray(design),
            jnp.asarray(X0), jnp.asarray(scan_starts), n_runs,
            jnp.asarray(gp["dist2"] if gp_on else np.zeros((1, 1))),
            jnp.asarray(gp["inten_d2"] if gp_on else np.zeros((1, 1))),
            self.tau_range,
            gp["space_range"] if gp_on else 1.0,
            gp["inten_range"] if gp_on else 1.0,
            self.eta, jnp.asarray(lims), n_c=n_c, rank=rank,
            max_iters=self.lbfgs_iters, gp_on=gp_on,
            gp_inten_on=bool(gp_on and gp["inten_on"]),
            tau2_prior=tau2_prior, tol=self.tol)
        flat = np.asarray(flat)
        l_flat = flat[:n_l]
        log_snr = flat[n_l:n_l + n_v]
        log_sigma2 = flat[n_l + n_v:n_l + 2 * n_v]
        rho = np.tanh(flat[n_l + 2 * n_v:n_l + 3 * n_v])
        log_lam2 = flat[n_l + 3 * n_v:n_l + 4 * n_v]

        L = np.asarray(_make_L(jnp.asarray(l_flat), n_c, rank))
        snr = np.exp(log_snr)
        sigma2 = np.exp(log_sigma2)
        beta, beta0 = self._posterior_betas(
            data, design, X0, L, snr, sigma2, rho, np.exp(log_lam2),
            scan_starts)
        result = {"U": L @ L.T, "L": L, "snr": snr, "sigma2": sigma2,
                  "rho": rho, "beta": beta, "beta0": beta0,
                  "loss": float(value)}
        if gp_on:
            def sig(z):
                return 1.0 / (1.0 + np.exp(-z))

            cs_lo, cs_hi, ci_lo, ci_hi = gp["lims"]
            c_space = cs_lo + (cs_hi - cs_lo) * sig(flat[n_l + 4 * n_v])
            c_inten = ci_lo + (ci_hi - ci_lo) * \
                sig(flat[n_l + 4 * n_v + 1])
            result["c_space"] = float(c_space)
            result["c_inten"] = float(c_inten)
            result["tau2"] = self._gp_tau2_map(
                log_snr, gp, c_space, c_inten)
        return result

    def _gp_tau2_map(self, log_snr, gp, c_space, c_inten):
        """MAP GP variance at the learned length scales (host replay of
        the profiled tau² inside the objective)."""
        n_v = log_snr.shape[0]
        quad = gp["dist2"] / np.exp(c_space)
        if gp["inten_on"]:
            quad = quad + gp["inten_d2"] / np.exp(c_inten)
        K = np.exp(-0.5 * quad) + self.eta * np.eye(n_v)
        y_invk_y = float(log_snr @ np.linalg.solve(K, log_snr))
        tau2, _ = self.tau2_prior(y_invk_y, n_v, self.tau_range)
        return tau2

    def _posterior_betas(self, data, design, X0, L, snr, sigma2, rho,
                         lam2, scan_starts):
        """Posterior mean of β and β₀ given the fitted parameters."""
        n_c = design.shape[1]
        n_0 = X0.shape[1]
        rankL = L.shape[1]

        def one_voxel(y, s, sig2, r, l2):
            F = jnp.concatenate(
                [jnp.asarray(design) @ jnp.asarray(L) *
                 (s * jnp.sqrt(sig2)), jnp.asarray(X0) * jnp.sqrt(l2)],
                axis=1)
            wy = _ar1_whiten(y[:, None], r,
                             jnp.asarray(scan_starts))[:, 0]
            wF = _ar1_whiten(F, r, jnp.asarray(scan_starts))
            cap = jnp.eye(rankL + n_0) + wF.T @ wF / sig2
            alpha = jnp.linalg.solve(cap, wF.T @ wy / sig2)
            beta_v = jnp.asarray(L) @ alpha[:rankL] * (s * jnp.sqrt(sig2))
            beta0_v = alpha[rankL:] * jnp.sqrt(l2)
            return beta_v, beta0_v

        beta, beta0 = jax.vmap(one_voxel, in_axes=(1, 0, 0, 0, 0),
                               out_axes=1)(
            jnp.asarray(data), jnp.asarray(snr), jnp.asarray(sigma2),
            jnp.asarray(rho), jnp.asarray(lam2))
        n_v = data.shape[1]
        return np.asarray(beta).reshape(n_c, n_v), \
            np.asarray(beta0).reshape(n_0, n_v)

    def _latent_ar1_params(self):
        return _latent_ar1_params(self._design, self.X0_)

    def transform(self, X, y=None, scan_onsets=None):
        """Decode the task time course (ts) and shared nuisance time course
        (ts0) from new data by exact posterior inference in a
        linear-Gaussian state-space model: the fitted spatial patterns are
        the emission weights, AR(1) smoothness priors on the latent time
        courses are estimated from the training design/nuisance regressors,
        and the per-voxel AR(1) noise model is the fitted one (reference
        brsa.py:793-851 and the forward-backward pass at 1530-1582)."""
        assert hasattr(self, 'beta_'), 'Model has not been fit'
        assert X.ndim == 2 and X.shape[1] == self.beta_.shape[1], \
            'The shape of X is not consistent with the shape of data ' \
            'used in the fitting step.'
        n_t = X.shape[0]
        onsets = self._check_onsets(scan_onsets, n_t)
        n_c = self.beta_.shape[0]
        weight = np.vstack([self.beta_, self.beta0_])
        rho_d, sig2_d, rho_0, sig2_0 = self._latent_ar1_params()
        mu, _ = _decode_timecourses(
            X, weight, self.sigma_ ** 2, self.rho_,
            np.concatenate([rho_d, rho_0]),
            np.concatenate([sig2_d, sig2_0]), onsets)
        return mu[:, :n_c], mu[:, n_c:]

    def score(self, X, design, scan_onsets=None):
        """Cross-validated log-likelihood of new data with the unknown
        shared nuisance time course marginalized under its AR(1) prior
        (reference brsa.py:852-952, 1583-1631): the predicted task
        response is subtracted (full model only), then the data
        likelihood is evaluated with the nuisance spatial pattern beta0
        as emission weights.  Matching the reference, the null model
        reuses the FULL model's beta0/X0 AR(1) priors (reference
        brsa.py:920-928 passes beta0_ and _rho_X0_ for both
        likelihoods); the separately fitted task-free model is exposed
        as X0_null_/beta0_null_ (reference brsa.py:781-790) for users
        who want a task-free baseline.  Returns (ll, ll_null)."""
        assert hasattr(self, 'beta_'), 'Model has not been fit'
        n_t = X.shape[0]
        onsets = self._check_onsets(scan_onsets, n_t)
        _, _, rho_0, sig2_0 = self._latent_ar1_params()
        pred = np.asarray(design) @ self.beta_
        _, ll = _decode_timecourses(
            np.asarray(X) - pred, self.beta0_, self.sigma_ ** 2,
            self.rho_, rho_0, sig2_0, onsets)
        _, ll_null = _decode_timecourses(
            np.asarray(X), self.beta0_, self.sigma_ ** 2, self.rho_,
            rho_0, sig2_0, onsets)
        return ll, ll_null


class GBRSA(BRSA):
    """Group BRSA with per-voxel SNR/ρ marginalized on grids
    (reference brsa.py:2696-3390).

    fit(X, design) accepts a LIST of per-subject data matrices (or one
    array).  U is shared across subjects; σ² is profiled analytically per
    grid point and SNR/ρ are summed over grid posteriors.
    """

    def __init__(self, n_iter=2, rank=None, auto_nuisance=True,
                 n_nureg=None, nureg_zscore=True, nureg_method='PCA',
                 baseline_single=False, logS_range=1.0, SNR_prior='exp',
                 SNR_bins=11, rho_bins=10, random_state=None,
                 anneal_speed=10, lbfgs_iters=200, tol=1e-4, mesh=None):
        super().__init__(n_iter=n_iter, rank=rank,
                         auto_nuisance=auto_nuisance, n_nureg=n_nureg,
                         nureg_zscore=nureg_zscore,
                         nureg_method=nureg_method,
                         baseline_single=baseline_single,
                         random_state=random_state,
                         anneal_speed=anneal_speed,
                         lbfgs_iters=lbfgs_iters, tol=tol)
        self.logS_range = logS_range
        self.SNR_prior = SNR_prior
        self.SNR_bins = SNR_bins
        self.rho_bins = rho_bins
        # mesh with a 'voxel' axis: the grid-marginal likelihood is
        # voxelwise independent, so each subject's voxel dimension is
        # sharded across devices (NaN-free zero padding, mask-weighted)
        self.mesh = mesh

    def _snr_grid_and_logprior(self):
        """Grid of SNR values plus log prior weights (reference
        brsa.py:3014 validates the prior name; grid points are weighted
        by the prior density rather than uniformly)."""
        if self.SNR_prior not in ('exp', 'unif', 'equal', 'lognorm'):
            raise ValueError(
                "SNR_prior must be one of 'exp', 'unif', 'equal', "
                "'lognorm'")
        if self.SNR_prior == 'exp':
            grid = np.exp(np.linspace(-2, 2, self.SNR_bins)
                          * self.logS_range)
            logp = -grid
        elif self.SNR_prior == 'lognorm':
            grid = np.exp(np.linspace(-2, 2, self.SNR_bins)
                          * self.logS_range)
            logp = -0.5 * (np.log(grid) / self.logS_range) ** 2
        else:  # 'unif' / 'equal'
            grid = np.linspace(0.1, 3.0, self.SNR_bins)
            logp = np.zeros_like(grid)
        logp = logp - np.log(np.sum(np.exp(logp - logp.max()))) - \
            logp.max()
        return grid, logp

    def fit(self, X, design, nuisance=None, scan_onsets=None):
        """Fit shared U across subjects (reference brsa.py:3030-3189).

        ``nuisance`` may be one array or a per-subject list; its columns
        (plus per-run DC components, plus — when ``auto_nuisance`` — the
        top principal components of the residuals from a first fitting
        round) are projected out before the grid likelihood."""
        if isinstance(X, np.ndarray):
            X = [X]
            design = [design]
        n_subj = len(X)
        self.random_state_ = check_random_state(self.random_state)
        n_c = design[0].shape[1]
        rank = self.rank if self.rank is not None else n_c

        snr_grid, snr_logprior = self._snr_grid_and_logprior()
        rho_grid = np.tanh(np.linspace(-1.2, 1.2, self.rho_bins))

        def subject_nuisance(s):
            if nuisance is None:
                return None
            return nuisance[s] if isinstance(nuisance, list) else nuisance

        def subject_onsets(s, n_t):
            return self._subject_onsets(scan_onsets, s, n_t)

        def build_subject(s, extra_nuisance=None):
            x = np.asarray(X[s], dtype=float)
            d = np.asarray(design[s], dtype=float)
            n_t = x.shape[0]
            onsets = subject_onsets(s, n_t)
            starts = np.zeros(n_t, dtype=bool)
            starts[onsets] = True
            cols = [self._dc_regressors(n_t, onsets)]
            nu = subject_nuisance(s)
            if nu is not None:
                cols.append(np.asarray(nu, float))
            if extra_nuisance is not None:
                cols.append(extra_nuisance)
            X0 = np.column_stack(cols)
            Q, _ = np.linalg.qr(X0)
            x_proj = x - Q @ (Q.T @ x)
            # Project X0 out of the DESIGN as well: profiling beta0
            # under a flat prior (what the reference's X0TAX0 solves do,
            # reference brsa.py:2160-2189) residualizes y AND X against
            # X0.  Leaving the design unprojected forces the grid
            # likelihood to explain the removed X0 span with task betas,
            # which biases off-diagonal U toward spurious negative
            # values (measured r4: across-block C_ of -0.8 vs the
            # reference's -0.2 on shared data).  The identity-metric
            # projection is exact at rho=0 and a documented
            # approximation otherwise.
            d_proj = d - Q @ (Q.T @ d)
            return (x_proj, d_proj, starts, len(onsets)), \
                (x, d, X0, onsets)

        built = [build_subject(s) for s in range(n_subj)]
        subj_data = [b[0] for b in built]
        subj_aux = [b[1] for b in built]

        n_l = len(np.tril_indices(n_c, m=rank)[0])

        snr_g = jnp.asarray(snr_grid)
        rho_g = jnp.asarray(rho_grid)
        # joint log prior over the (snr, rho) grid; rho uniform
        logprior = jnp.asarray(snr_logprior)[:, None] - \
            jnp.log(float(len(rho_grid)))

        def neg_ll(l_flat, x, mask, d, starts, n_runs):
            L = _make_L(l_flat, n_c, rank)
            XL = d @ L

            def voxel_ll(y):
                lls, _ = jax.vmap(lambda s: jax.vmap(
                    lambda r: _grid_marginal_ll(y, XL, s, r, starts,
                                                n_runs))(rho_g))(snr_g)
                return jax.scipy.special.logsumexp(lls + logprior)

            # mask zero-weights padded voxel columns (their grid LL is
            # parameter-dependent, so padding must not contribute)
            return -jnp.sum(mask * jax.vmap(voxel_ll, in_axes=1)(x))

        def place_voxels(x):
            """Shard a [T, V] array's voxel axis over the mesh; padding
            repeats the first voxel column — zero columns would make the
            grid LL (and, through the 0*NaN vjp trap, the whole
            gradient) NaN even though the mask zero-weights them.
            Returns (array, mask)."""
            mask = np.ones(x.shape[1])
            if self.mesh is not None:
                from ..parallel.mesh import (DEFAULT_VOXEL_AXIS,
                                             place_on_mesh)
                from jax.sharding import NamedSharding, PartitionSpec
                n_shards = self.mesh.shape[DEFAULT_VOXEL_AXIS]
                pad = (-x.shape[1]) % n_shards
                x = np.concatenate(
                    [x, np.repeat(x[:, :1], pad, axis=1)], axis=1)
                mask = np.pad(mask, (0, pad))
                spec = NamedSharding(
                    self.mesh, PartitionSpec(None, DEFAULT_VOXEL_AXIS))
                return (place_on_mesh(x, spec),
                        place_on_mesh(mask, NamedSharding(
                            self.mesh,
                            PartitionSpec(DEFAULT_VOXEL_AXIS))))
            return jnp.asarray(x), jnp.asarray(mask)

        def fit_U(subjects):
            placed = []
            run_counts = []
            for x, d, starts, n_runs in subjects:
                x_j, mask_j = place_voxels(x)
                placed.append((x_j, mask_j, jnp.asarray(d),
                               jnp.asarray(starts)))
                run_counts.append(n_runs)

            flat0 = self.random_state_.randn(n_l) * 0.1 + 0.5

            # ``placed`` is passed as an ARGUMENT, not closed over: a
            # jitted closure embeds captured arrays as constants, which
            # requires fetching their full value — impossible for
            # cross-process-sharded arrays in a multi-process mesh
            # (run_counts are python ints, safe to capture)
            @jax.jit
            def run(flat0, placed_args):
                def total_loss(l_flat):
                    total = 0.0
                    for (x_j, mask_j, d_j, starts_j), n_runs in zip(
                            placed_args, run_counts):
                        total = total + neg_ll(l_flat, x_j, mask_j,
                                               d_j, starts_j, n_runs)
                    return total

                return minimize_lbfgs(total_loss, flat0,
                                      max_iters=self.lbfgs_iters,
                                      tol=self.tol)

            flat, value = run(jnp.asarray(flat0), placed)
            return np.asarray(_make_L(jnp.asarray(np.asarray(flat)),
                                      n_c, rank)), float(value)

        L, value = fit_U(subj_data)
        if self.auto_nuisance:
            # one auto-nuisance round: PCA of residuals after removing the
            # current grid-posterior task prediction, then refit U
            new_subj = []
            for s, (x, d, starts, n_runs) in enumerate(subj_data):
                _, _, _, beta_v = self._grid_posteriors(
                    x, d, starts, n_runs, L, snr_grid, rho_grid,
                    snr_logprior)
                resid = x - d @ beta_v
                new_subj.append(build_subject(
                    s, self._nuisance_components(resid)))
            subj_data = [b[0] for b in new_subj]
            subj_aux = [b[1] for b in new_subj]
            L, value = fit_U(subj_data)

        self.L_ = L
        self.U_ = L @ L.T
        self.C_ = cov2corr(self.U_ + 1e-12 * np.eye(n_c))
        self._final_loss = value

        # per-subject, per-voxel posterior over the grids -> SNR and rho;
        # beta0 (spatial loading of the nuisance regressors, needed for
        # the marginalized decoding in transform/score) is estimated on
        # the UNprojected data after removing the posterior task response
        self.nSNR_ = []
        self.rho_ = []
        self.sigma_ = []
        self.beta_ = []
        self.beta0_ = []
        self.beta0_null_ = []
        self._X0_list = []
        self._X0_null_list = []
        self._design_list = []
        for s_idx, ((x, d, starts, n_runs),
                    (raw, raw_d, X0, onsets)) in \
                enumerate(zip(subj_data, subj_aux)):
            snr_v, rho_v, sig_v, beta_v = self._grid_posteriors(
                x, d, starts, n_runs, L, snr_grid, rho_grid,
                snr_logprior)
            self.nSNR_.append(snr_v / np.exp(np.mean(np.log(snr_v))))
            self.rho_.append(rho_v)
            self.sigma_.append(sig_v)
            self.beta_.append(beta_v)
            # beta0 against the RAW design: the X0-span part of the
            # task response (removed from d for fitting) belongs to
            # beta0, matching score()'s `design @ beta` subtraction
            self.beta0_.append(np.linalg.lstsq(
                X0, raw - raw_d @ beta_v, rcond=None)[0])
            X0n, beta0n = self._fit_null_nuisance(
                raw, raw.shape[0], onsets, subject_nuisance(s_idx))
            self.beta0_null_.append(beta0n)
            self._X0_list.append(X0)
            self._X0_null_list.append(X0n)
            self._design_list.append(raw_d)
        if n_subj == 1:
            (self.nSNR_, self.rho_, self.sigma_, self.beta_,
             self.beta0_, self.beta0_null_) = (
                self.nSNR_[0], self.rho_[0], self.sigma_[0],
                self.beta_[0], self.beta0_[0], self.beta0_null_[0])
        return self

    def _grid_posteriors(self, x, d, starts, n_runs, L, snr_grid,
                         rho_grid, snr_logprior):
        XL = jnp.asarray(d @ L)
        starts_j = jnp.asarray(starts)
        logprior = jnp.asarray(snr_logprior)[:, None] - \
            jnp.log(float(len(rho_grid)))

        def voxel_post(y):
            lls, sig2s = jax.vmap(lambda s: jax.vmap(
                lambda r: _grid_marginal_ll(y, XL, s, r, starts_j,
                                            n_runs))(
                jnp.asarray(rho_grid)))(jnp.asarray(snr_grid))
            w = jax.nn.softmax((lls + logprior).reshape(-1)) \
                .reshape(lls.shape)
            snr_hat = jnp.sum(w * jnp.asarray(snr_grid)[:, None])
            rho_hat = jnp.sum(w * jnp.asarray(rho_grid)[None, :])
            sig2_hat = jnp.sum(w * sig2s)
            return snr_hat, rho_hat, sig2_hat

        snr_v, rho_v, sig2_v = jax.vmap(voxel_post, in_axes=1)(
            jnp.asarray(x))
        snr_v = np.asarray(snr_v)
        rho_v = np.asarray(rho_v)
        sig_v = np.sqrt(np.asarray(sig2_v))
        beta_v, _ = self._posterior_betas(
            x, d, np.zeros((x.shape[0], 0)), L, snr_v, sig_v ** 2, rho_v,
            np.ones(x.shape[1]), starts)
        return snr_v, rho_v, sig_v, beta_v

    def transform(self, X, y=None, scan_onsets=None):
        """Decode per-subject task time courses (ts) and nuisance time
        courses (ts0) from new data by exact posterior inference in the
        linear-Gaussian state-space model (reference brsa.py:3190-3250,
        decoded there by the forward-backward pass at 1530-1582): the
        fitted task patterns beta and nuisance patterns beta0 are the
        emission weights, AR(1) smoothness priors come Yule-Walker from
        the training design/nuisance regressors, and the per-voxel noise
        model is the grid-posterior one.  Accepts one array or a
        per-subject list; returns (ts, ts0)."""
        if not hasattr(self, 'U_'):
            raise NotFittedError("The model fit has not been run yet.")
        single = isinstance(X, np.ndarray)
        Xs = [X] if single else list(X)
        betas = [self.beta_] if not isinstance(self.beta_, list) \
            else self.beta_
        beta0s = [self.beta0_] if not isinstance(self.beta0_, list) \
            else self.beta0_
        sigmas = [self.sigma_] if not isinstance(self.sigma_, list) \
            else self.sigma_
        rhos = [self.rho_] if not isinstance(self.rho_, list) \
            else self.rho_
        if len(Xs) != len(betas):
            raise ValueError(
                "The number of subjects ({}) does not match the fitted "
                "model ({})".format(len(Xs), len(betas)))
        ts_all, ts0_all = [], []
        for s, (x, beta, beta0, sigma, rho) in enumerate(
                zip(Xs, betas, beta0s, sigmas, rhos)):
            n_t = x.shape[0]
            onsets = self._subject_onsets(scan_onsets, s, n_t)
            rho_d, sig2_d, rho_0, sig2_0 = _latent_ar1_params(
                self._design_list[s], self._X0_list[s])
            n_c = beta.shape[0]
            mu, _ = _decode_timecourses(
                x, np.vstack([beta, beta0]), sigma ** 2, rho,
                np.concatenate([rho_d, rho_0]),
                np.concatenate([sig2_d, sig2_0]), onsets)
            ts_all.append(mu[:, :n_c])
            ts0_all.append(mu[:, n_c:])
        if single:
            return ts_all[0], ts0_all[0]
        return ts_all, ts0_all

    def score(self, X, design, scan_onsets=None):
        """Held-out log-likelihood per subject with the unknown nuisance
        time course marginalized under its AR(1) prior through the fitted
        spatial pattern beta0 for BOTH likelihoods, matching the
        reference (brsa.py:3325-3337); see BRSA.score."""
        if isinstance(X, np.ndarray):
            X = [X]
            design = [design]
        scores, scores_null = [], []
        for s in range(len(X)):
            beta = self.beta_ if not isinstance(self.beta_, list) \
                else self.beta_[s]
            beta0 = self.beta0_ if not isinstance(self.beta0_, list) \
                else self.beta0_[s]
            rho = self.rho_ if not isinstance(self.rho_, list) \
                else self.rho_[s]
            sigma = self.sigma_ if not isinstance(self.sigma_, list) \
                else self.sigma_[s]
            n_t = X[s].shape[0]
            onsets = self._subject_onsets(scan_onsets, s, n_t)
            _, _, rho_0, sig2_0 = _latent_ar1_params(
                self._design_list[s], self._X0_list[s])

            pred = np.asarray(design[s]) @ beta
            _, ll = _decode_timecourses(
                np.asarray(X[s]) - pred, beta0, sigma ** 2, rho,
                rho_0, sig2_0, onsets)
            _, ll_null = _decode_timecourses(
                np.asarray(X[s]), beta0, sigma ** 2, rho,
                rho_0, sig2_0, onsets)
            scores.append(ll)
            scores_null.append(ll_null)
        if len(scores) == 1:
            return scores[0], scores_null[0]
        return scores, scores_null
