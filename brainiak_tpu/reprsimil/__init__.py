"""Bayesian representational similarity analysis (BRSA/GBRSA)."""

from .brsa import BRSA, GBRSA  # noqa: F401
