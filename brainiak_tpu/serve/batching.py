"""Shape-bucketed request batching: the policy layer of the engine.

TPU inference economics in one sentence: XLA compiles one program per
input *shape*, so serving heterogeneous requests (a 40-TR scan here,
a 900-TR scan there) naively compiles per request — the batching
layer instead rounds every dynamic extent UP to a power of two
(:func:`bucket_length`), so an unbounded family of request shapes
lands in a small, enumerable set of **buckets** and the compile count
is bounded by the bucket count, not the request count (the engine's
``retrace_total{site=serve.*}`` makes that bound observable).

Padding is only used where it is *exact* for the model family being
served (zero TR-columns of an SRM transform produce zero shared-
response columns that are sliced off; see docs/serving.md for the
per-kind table) — a kind whose math is not padding-invariant
(EventSegment's forward–backward over time) buckets on the exact
extent instead and batches only across requests.

This module holds the data types (:class:`Request`,
:class:`ServeResult`), the flush policy (:class:`BucketPolicy`), the
padding helpers, and the request-file codec the offline CLI driver
uses; the dispatch loop lives in :mod:`brainiak_tpu.serve.engine`.
"""

import dataclasses
import time
from typing import Any, Optional

import numpy as np

from ..obs.runtime import counted_cache

__all__ = [
    "BucketPolicy",
    "Request",
    "ServeResult",
    "bucket_length",
    "load_requests",
    "pad_axis",
    "program_cache",
    "save_requests",
]


def program_cache(site, maxsize=None, signature=None,
                  float_keys_ok=()):
    """The serve program cache: a retrace-counting
    :func:`~brainiak_tpu.obs.runtime.counted_cache` over the bucket
    program builders, under serve's ``site`` naming convention
    (``serve.<family>``).  jaxlint's JX001 recognizes it as a caching
    decorator, so constructing ``jax.jit`` inside a builder it
    decorates is clean by construction; like every
    ``counted_cache``, each decorated builder self-registers for the
    jaxlint-IR audit (``signature`` attaches the canonical trace
    signature, see :func:`~brainiak_tpu.obs.runtime.trace_signature`).

    It lives in the batching (policy) layer because the cache key IS
    the bucket: every extent the batching layer pads to, plus
    trace-time statics."""
    return counted_cache(site, maxsize=maxsize, signature=signature,
                         float_keys_ok=float_keys_ok)


def bucket_length(n, floor=16):
    """Smallest power of two >= ``max(n, floor)``.

    The floor keeps tiny requests from fragmenting the program cache
    into 1/2/4/8 buckets nobody benefits from (padding a 3-TR request
    to 16 costs nothing next to a compile).
    """
    n = max(int(n), int(floor))
    return 1 << (n - 1).bit_length()


def pad_axis(x, axis, target):
    """Zero-pad ``x`` along ``axis`` up to ``target`` (no-op when
    already there)."""
    x = np.asarray(x)
    have = x.shape[axis]
    if have == target:
        return x
    if have > target:  # pragma: no cover - caller bucketing bug
        raise ValueError(f"axis {axis} is {have}, beyond {target}")
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, target - have)
    return np.pad(x, widths)


@dataclasses.dataclass
class BucketPolicy:
    """Flush policy knobs.

    - ``max_batch``: a bucket flushes as soon as it holds this many
      requests (rounded up to a power of two at dispatch, so keep it
      a power of two to avoid an extra partial-batch program shape);
    - ``max_wait_s``: a bucket flushes when its OLDEST request has
      queued this long, full or not — the tail-latency bound;
    - ``min_bucket``: floor passed to :func:`bucket_length` for the
      padded data axis;
    - ``min_batch_bucket``: floor for the padded batch axis (1 keeps
      singleton flushes cheap while still power-of-two).
    """

    max_batch: int = 64
    max_wait_s: float = 0.05
    min_bucket: int = 16
    min_batch_bucket: int = 1

    def batch_bucket(self, n):
        """Padded batch extent for ``n`` queued requests."""
        return min(bucket_length(n, floor=self.min_batch_bucket),
                   bucket_length(self.max_batch,
                                 floor=self.min_batch_bucket))


@dataclasses.dataclass
class Request:
    """One inference request.

    ``x`` is the kind-specific payload (an array, or for the FCMA
    classifier a 2-sequence of arrays); ``subject`` selects the
    fitted per-subject map for the SRM family; ``deadline_s`` is a
    per-request budget in seconds measured from submission — a
    request still queued past it is failed at dispatch time with a
    ``deadline_exceeded`` error record instead of consuming device
    time.

    ``submitted`` is stamped by the engine on first submit and never
    overwritten, so a caller may pre-stamp it (network-ingress time)
    for truer queue-time SLOs.  The flip side: RESUBMITTING a
    Request object (e.g. retrying a ``deadline_exceeded``) keeps the
    stale clock and fails again immediately — reset
    ``submitted = None`` before resubmission.

    ``model`` names the target model for multi-model serving
    (:class:`~brainiak_tpu.serve.service.ServeService` routes on it;
    the single-model engine ignores it).

    ``trace_id``/``parent_id`` carry the request's end-to-end trace
    (:mod:`brainiak_tpu.obs.trace`): minted at service submit when
    obs is live, or pre-assigned by an upstream submitter (and
    carried through the npz codec) so multi-process replicas join
    one trace.  ``parent_id`` always names the most recent span in
    the request's causal chain — each instrumented stage advances it.
    """

    request_id: str
    x: Any
    subject: Optional[int] = None
    deadline_s: Optional[float] = None
    submitted: Optional[float] = None
    model: Optional[str] = None
    trace_id: Optional[str] = None
    parent_id: Optional[str] = None

    def expired(self, now=None):
        if self.deadline_s is None or self.submitted is None:
            return False
        if now is None:
            now = time.monotonic()
        return (now - self.submitted) > self.deadline_s


@dataclasses.dataclass
class ServeResult:
    """The engine's answer for one request: a result or a structured
    error, never silence.  ``error`` is a stable machine code
    (``invalid_payload``, ``invalid_shape``, ``invalid_subject``,
    ``non_finite_input``, ``deadline_exceeded``,
    ``execution_failed``, ``shed_overload``); ``message`` is the
    human detail.  ``seq`` is the engine's submission index — the
    ordering key, so duplicate ``request_id`` values cannot misorder
    results.  ``retry_after_s`` is set only on admission-control
    shed records (``error == "shed_overload"``): the client-facing
    backoff hint, stamped BEFORE the request ever touched a queue."""

    request_id: str
    ok: bool
    result: Any = None
    error: Optional[str] = None
    message: Optional[str] = None
    bucket: Optional[tuple] = None
    latency_s: Optional[float] = None
    seq: Optional[int] = None
    retry_after_s: Optional[float] = None


# -- request-file codec (offline CLI driver) --------------------------

def save_requests(file, payloads, subjects=None, deadlines=None,
                  ids=None, models=None, traces=None):
    """Write a batch of requests as one npz.

    ``payloads``: list of arrays (or 2-sequences of arrays for the
    FCMA pair layout, stored as ``x.<i>.0`` / ``x.<i>.1``);
    ``subjects`` / ``deadlines`` / ``models``: optional per-request
    sequences (None entries are omitted; ``models`` carries the
    multi-model routing name the ``service`` CLI honors); ``ids``
    default to ``"r<i>"``; ``traces``: optional per-request
    ``(trace_id, parent_id)`` pairs (or bare trace-id strings) —
    the cross-process propagation path of
    :mod:`brainiak_tpu.obs.trace`, so a replica process serving this
    file continues the submitter's trace.  Returns ``file``.
    """
    from ..obs import trace as obs_trace

    out = {"n": np.asarray(len(payloads))}
    for i, payload in enumerate(payloads):
        if isinstance(payload, (tuple, list)):
            out[f"x.{i}.pair"] = np.asarray(len(payload))
            for j, part in enumerate(payload):
                out[f"x.{i}.{j}"] = np.asarray(part)
        else:
            out[f"x.{i}"] = np.asarray(payload)
        if ids is not None:
            out[f"id.{i}"] = np.asarray(str(ids[i]))
        if subjects is not None and subjects[i] is not None:
            out[f"subject.{i}"] = np.asarray(int(subjects[i]))
        if deadlines is not None and deadlines[i] is not None:
            out[f"deadline.{i}"] = np.asarray(float(deadlines[i]))
        if models is not None and models[i] is not None:
            out[f"model.{i}"] = np.asarray(str(models[i]))
        if traces is not None and traces[i] is not None:
            entry = traces[i]
            if isinstance(entry, str):
                entry = (entry, None)
            obs_trace.inject_npz(out, i, entry[0], entry[1])
    np.savez_compressed(file, **out)
    return file


def load_requests(file):
    """Read a request npz back into a list of :class:`Request`
    (trace context, when present, rides back onto the Request — a
    served request then continues the submitter's trace)."""
    from ..obs import trace as obs_trace

    with np.load(file, allow_pickle=False) as z:
        n = int(z["n"])
        out = []
        for i in range(n):
            if f"x.{i}.pair" in z.files:
                parts = int(z[f"x.{i}.pair"])
                x = tuple(np.asarray(z[f"x.{i}.{j}"])
                          for j in range(parts))
            else:
                x = np.asarray(z[f"x.{i}"])
            rid = str(np.asarray(z[f"id.{i}"])) \
                if f"id.{i}" in z.files else f"r{i}"
            subject = int(z[f"subject.{i}"]) \
                if f"subject.{i}" in z.files else None
            deadline = float(z[f"deadline.{i}"]) \
                if f"deadline.{i}" in z.files else None
            model = str(np.asarray(z[f"model.{i}"])) \
                if f"model.{i}" in z.files else None
            trace_id, parent_id = obs_trace.extract_npz(z, i)
            out.append(Request(request_id=rid, x=x, subject=subject,
                               deadline_s=deadline, model=model,
                               trace_id=trace_id,
                               parent_id=parent_id))
    return out
