"""brainiak_tpu.serve: persisted models + batched inference.

The framework's fifth subsystem (after resilience, jaxlint, obs, and
perf attribution): everything before it targeted the *fit* path; this
is the *deployment* path the ROADMAP's heavy-traffic north star
needs.  Three layers:

- :mod:`~brainiak_tpu.serve.artifacts` — one versioned npz artifact
  schema (``save_model``/``load_model``) with adapters for SRM,
  DetSRM, RSRM, EventSegment, IEM (1-D/2-D), and the FCMA
  classifier; loads retry transient I/O faults via
  :func:`brainiak_tpu.resilience.retry`;
- :mod:`~brainiak_tpu.serve.batching` +
  :mod:`~brainiak_tpu.serve.engine` — an in-process engine that pads
  heterogeneous requests into power-of-two shape buckets, runs one
  jitted program per (model, bucket) through a retrace-counted
  program cache, donates batch buffers, enforces
  max-wait/max-batch flushes and per-request deadlines, and
  isolates poison requests into structured error records;
- :mod:`~brainiak_tpu.serve.__main__` — ``python -m
  brainiak_tpu.serve run|bench``: the offline batch driver and the
  serving micro-benchmark, both emitting obs spans/metrics so
  ``obs report``/``export``/``regress`` work on serving rounds.

See docs/serving.md.
"""

from .artifacts import (  # noqa: F401
    ADAPTERS,
    SCHEMA_VERSION,
    detect_kind,
    load_model,
    model_digest,
    model_nbytes,
    save_model,
    save_model_bytes,
)
from .batching import (  # noqa: F401
    BucketPolicy,
    Request,
    ServeResult,
    bucket_length,
    load_requests,
    pad_axis,
    save_requests,
)
from .engine import (  # noqa: F401
    InferenceEngine,
    program_cache,
)
from .aot import (  # noqa: F401
    AOTProgramCache,
)
from .residency import (  # noqa: F401
    AdmissionError,
    ModelResidency,
)
from .service import (  # noqa: F401
    ServeService,
    ServiceClosed,
    ServiceTicket,
)

__all__ = [
    "ADAPTERS",
    "AOTProgramCache",
    "AdmissionError",
    "SCHEMA_VERSION",
    "BucketPolicy",
    "InferenceEngine",
    "ModelResidency",
    "Request",
    "ServeResult",
    "ServeService",
    "ServiceClosed",
    "ServiceTicket",
    "bucket_length",
    "detect_kind",
    "load_model",
    "load_requests",
    "model_digest",
    "model_nbytes",
    "pad_axis",
    "program_cache",
    "save_model",
    "save_model_bytes",
    "save_requests",
]
