"""Always-on serving: the continuous-batching service loop.

PR 5's :class:`~brainiak_tpu.serve.engine.InferenceEngine` is a
one-shot batch driver — callers flush a fixed request list and wait,
one model per engine.  :class:`ServeService` is the long-lived layer
production serving needs, with no new runtime dependencies (one
``threading.Thread``):

- **continuous batching** — :meth:`submit` enqueues into the
  per-(model, bucket) queues and returns a :class:`ServiceTicket`
  immediately; a request submitted while a bucket's batch is
  in flight simply joins the bucket queue and rides the NEXT
  dispatch of the same bucket — no flush-and-wait barrier.
  Dispatch fires on max-batch (inside ``engine.submit``) or
  max-wait (the loop's ``engine.poll`` timer), and deadlines keep
  counting from the ORIGINAL enqueue: :meth:`submit` stamps
  ``request.submitted`` with the same ``time.monotonic`` clock the
  engine's dispatch-time deadline check reads;
- **multi-model** — requests route by model name through a
  :class:`~brainiak_tpu.serve.residency.ModelResidency`, so an
  evicted model is transparently re-admitted on its next request
  and an over-budget model fails with a typed
  ``admission_refused`` record instead of an OOM;
- **graceful shutdown** — :meth:`shutdown` with ``drain=True``
  flushes every queue and delivers every result;
  ``drain=False`` fails all queued work with a clear ``shutdown``
  status.  Either way every submitted request resolves exactly one
  ticket.

Threading contract: the engines and the residency are single-caller
by design, so ALL engine/residency access happens on the service
thread; :meth:`submit` only appends to a locked ingress queue (safe
from any thread), and :meth:`summary`/:meth:`shutdown` synchronize
through the same lock.  Results are delivered by resolving tickets
— ``ticket.result(timeout)`` blocks the caller, never the loop.

Telemetry (live while an obs sink is active): ``serve.service.tick``
spans around every loop tick that did work (ingress routed, batches
flushed, records delivered), ``serve_service_ingress_depth`` /
``serve_service_queue_depth{model=}`` gauges, and the engine-level
``serve_request_seconds`` histograms / ``serve_padding_waste_ratio``
gauges the bench tier's p50/p99 and padding-waste gates read.
"""

import collections
import logging
import threading
import time

from ..obs import metrics as obs_metrics
from ..obs import sink as obs_sink
from ..obs import trace as obs_trace
from ..obs.sketch import QuantileSketch
from ..resilience import faults
from .batching import ServeResult
from .residency import AdmissionError

logger = logging.getLogger(__name__)

__all__ = ["ServeService", "ServiceClosed", "ServiceTicket",
           "serve_retrace_total"]


def serve_retrace_total():
    """Process-wide ``retrace_total{site=serve.*}`` sum — the
    zero-cold-start headline the restart acceptance test and the
    SRV002 gate assert on."""
    total = 0.0
    for labels, value in obs_metrics.counter(
            "retrace_total").samples():
        if str(labels.get("site", "")).startswith("serve."):
            total += value
    return total



class ServiceClosed(RuntimeError):
    """submit() after shutdown() — the service no longer accepts
    work."""


class ServiceTicket:
    """One request's future: resolved with exactly one
    :class:`~brainiak_tpu.serve.batching.ServeResult` (a result or a
    structured error, never silence — the engine contract, extended
    across threads)."""

    __slots__ = ("request_id", "model", "record", "_event",
                 "_chained")

    def __init__(self, request_id, model):
        self.request_id = request_id
        self.model = model
        self.record = None
        self._event = threading.Event()
        # tickets this one's record forwards to on resolution (the
        # failover re-placement path: a survivor's fresh ticket
        # chains to the ticket the original caller already holds)
        self._chained = []

    def done(self):
        return self._event.is_set()

    def result(self, timeout=None):
        """Block until the record arrives; raises ``TimeoutError``
        if it does not within ``timeout`` seconds."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id!r} (model "
                f"{self.model!r}) not served within {timeout}s")
        return self.record

    def _resolve(self, record):
        self.record = record
        self._event.set()
        for ticket in list(self._chained):
            if not ticket._event.is_set():
                ticket._resolve(record)

    def _chain(self, target):
        """Forward this ticket's eventual record to ``target`` too
        (failover re-placement: the original caller keeps waiting on
        ``target`` while a survivor serves through this ticket).
        Safe against a concurrent :meth:`_resolve`: the append is
        atomic, and whichever side observes the other's progress
        performs the (idempotent) resolution — worst case ``target``
        is resolved twice with the same record."""
        self._chained.append(target)
        if self._event.is_set() and not target._event.is_set():
            target._resolve(self.record)


class ServeService:
    """The always-on serving loop over a
    :class:`~brainiak_tpu.serve.residency.ModelResidency`.

    Usage::

        residency = ModelResidency(budget_bytes=..., aot=cache_dir)
        residency.register("subj01", source="subj01.npz")
        with ServeService(residency) as svc:
            ticket = svc.submit(Request("r0", x, model="subj01"))
            record = ticket.result(timeout=5.0)

    ``tick_interval`` bounds how long the loop sleeps between
    max-wait checks (default: half the bucket policy's
    ``max_wait_s``, clipped to [5 ms, 50 ms]); submissions wake the
    loop immediately, so idle ticks cost one condition wait.

    ``slos`` declares service-level objectives
    (:class:`~brainiak_tpu.obs.slo.Objective` list, or a
    pre-configured :class:`~brainiak_tpu.obs.slo.SLOTracker`):
    every delivered record feeds the tracker and every working tick
    re-evaluates its multi-window burn rates — violations emit
    ``slo_violation`` events, budget gauges land in ``/metrics``.

    ``http_port`` opts into the live exposition endpoint
    (:class:`~brainiak_tpu.obs.http.TelemetryServer`: ``/metrics``,
    ``/healthz``, ``/readyz``); 0 binds an ephemeral port (read
    ``summary()["http_port"]``), None falls back to the
    ``BRAINIAK_TPU_OBS_HTTP_PORT`` env var (unset = no listener).
    ``/readyz`` derives from :meth:`readiness` — model residency
    plus AOT warm state.

    ``name`` labels this replica: every ``serve_service_*`` gauge
    this instance publishes carries ``replica=<name>``, so N
    replicas in one process (the federation tier) stay separable in
    the registry — exactly the series the
    :class:`~brainiak_tpu.serve.federation.Router` places by.
    Unnamed services publish unlabeled series (the pre-federation
    shape, and what single-replica dashboards scrape).

    ``admission`` attaches load-shedding admission control
    (:class:`~brainiak_tpu.serve.federation.AdmissionController`):
    :meth:`submit`/:meth:`submit_many` consult it BEFORE enqueue,
    and an over-bound request resolves its ticket immediately with
    a typed ``shed_overload`` record carrying ``retry_after_s`` —
    never an exception, never device time, and still exactly one
    ticket per request.
    """

    def __init__(self, residency, tick_interval=None,
                 default_model=None, slos=None, http_port=None,
                 name=None, admission=None):
        self.residency = residency
        self.name = name
        # replica label threaded onto every service-level gauge
        # (empty for the unnamed single-replica shape)
        self._labels = {"replica": name} if name else {}
        self._admission = admission
        policy = residency.policy
        max_wait = policy.max_wait_s if policy is not None else 0.05
        self.tick_interval = (
            tick_interval if tick_interval is not None
            else min(0.05, max(0.005, max_wait / 2.0)))
        self._default_model = default_model
        self._cond = threading.Condition()
        # serializes engine/residency access between the loop's
        # ticks and caller-thread summary() reads
        self._engine_lock = threading.Lock()
        self._ingress = collections.deque()  # guarded-by: _cond
        self._state = "idle"                 # guarded-by: _cond
        self._drain_on_stop = True           # guarded-by: _cond
        self._thread = None                  # guarded-by: _cond
        self._n_submitted = 0                # guarded-by: _cond
        self._n_shed = 0                     # guarded-by: _cond
        # loop-iteration heartbeat: the fleet supervisor probes
        # progress here WITHOUT touching the engine lock, so a
        # wedged or fault-stalled tick can never block the probe
        self._n_loop_iters = 0               # guarded-by: _cond
        # (model, engine seq) -> ticket
        self._pending = {}           # guarded-by: _engine_lock
        # (model, engine seq) -> the un-delivered request itself,
        # kept in lockstep with _pending: the failover harvest
        # (unresolved_work) re-places these when the loop dies
        self._pending_requests = {}  # guarded-by: _engine_lock
        # ok-latency distribution: a mergeable log-bucketed sketch
        # (O(1) memory for a week-long process, O(1) observe, O(1)
        # quantiles under the tick lock — the PR 8 sorted deque paid
        # an O(n log n) sort per summary() call there, and its raw
        # samples could not be pooled across replicas)
        self._latency_sketch = \
            QuantileSketch()         # guarded-by: _engine_lock
        self._n_delivered = 0        # guarded-by: _engine_lock
        self._n_ok = 0               # guarded-by: _engine_lock
        self._errors_by_code = {}    # guarded-by: _engine_lock
        self._n_ticks = 0            # guarded-by: _engine_lock
        self._n_active_ticks = 0     # guarded-by: _engine_lock
        # dispatched-element stats of engines that were evicted:
        # summary()'s padding waste must cover the WHOLE drive,
        # not just the engines that happen to be resident at read
        # time (re-admission builds a fresh engine with zeroed
        # stats)
        self._retired_real = 0       # guarded-by: _engine_lock
        self._retired_padded = 0     # guarded-by: _engine_lock
        # deliver results stranded on an engine evicted mid-queue
        # (the residency only runs on the service thread inside the
        # engine-lock tick, so these callbacks inherit the lock)
        residency.on_evict_records = self._deliver_many
        residency.on_evict = self._accrue_evicted
        # SLO tracking: the tracker carries its OWN lock; the
        # service only ever calls it engine-lock-held (record on
        # delivery, evaluate per working tick), and the tracker
        # never calls back — no inversion (JX202-clean)
        if slos is None:
            self._slo = None
        else:
            from ..obs.slo import SLOTracker
            self._slo = slos if isinstance(slos, SLOTracker) \
                else SLOTracker(slos)
        self._http_port = http_port
        self._http = None  # guarded-by: _cond

    def _accrue_evicted(self, entry):  # requires-lock: _engine_lock
        stats = entry.engine._stats
        self._retired_real += stats["real_elements"]
        self._retired_padded += stats["padded_elements"]

    # -- lifecycle ----------------------------------------------------

    def start(self):
        """Start the service thread (idempotent) and — when a port
        was opted into (``http_port=`` or the
        ``BRAINIAK_TPU_OBS_HTTP_PORT`` env var) — the live
        exposition endpoint; returns self."""
        from ..obs import http as obs_http

        with self._cond:
            if self._state == "running":
                return self
            if self._state not in ("idle",):
                raise ServiceClosed(
                    "service was shut down; build a new one")
            self._state = "running"
            self._thread = threading.Thread(
                target=self._loop, name="serve-service",
                daemon=True)
            self._thread.start()
            if self._http is None:
                if self._http_port is not None:
                    self._http = obs_http.TelemetryServer(
                        port=self._http_port,
                        readiness=self.readiness).start()
                else:
                    self._http = obs_http.maybe_start_from_env(
                        readiness=self.readiness)
        return self

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.shutdown(drain=exc_type is None)

    def shutdown(self, drain=True, timeout=None):
        """Stop the loop and resolve every outstanding ticket.

        ``drain=True`` flushes all queues and serves the queued work
        to completion first; ``drain=False`` fails everything still
        queued with a ``shutdown`` error record.  Returns
        :meth:`summary`.  ``timeout`` bounds the join; a loop that
        does not finish in time is abandoned (daemon thread) after
        a warning."""
        with self._cond:
            if self._state == "running":
                self._drain_on_stop = bool(drain)
                self._state = "stopping"
                self._cond.notify_all()
            thread = self._thread
        if thread is not None:
            thread.join(timeout)
            if thread.is_alive():  # pragma: no cover - timing
                logger.warning(
                    "service loop did not stop within %ss", timeout)
        with self._cond:
            self._state = "stopped"
            http = self._http
            self._http = None
        # the summary below must still report the bound port, and
        # the exposition must answer scrapes for the whole serving
        # lifetime — stop the listener only after the state flip
        summary = self.summary()
        if http is not None:
            summary["http_port"] = http.port
            http.stop()
        return summary

    def unresolved_work(self):
        """Harvest every accepted-but-undelivered request off a DEAD
        loop: the ``(model, request, ticket)`` triples still waiting
        in ingress or in the pending map, in submission order (routed
        work first, by engine sequence, then unrouted ingress).

        This is the failover source: the
        :class:`~brainiak_tpu.serve.federation.fleet.FleetSupervisor`
        re-places these onto surviving replicas, chaining each
        survivor ticket back to the ticket the original caller holds
        — so a replica crash costs latency, never silent loss.

        Only legal once the loop thread is no longer running (crashed
        or stopped): raises ``RuntimeError`` against a live loop,
        whose engines are single-caller by contract.  The harvested
        entries are removed, so a second call returns nothing."""
        with self._cond:
            thread = self._thread
            if (self._state == "running" and thread is not None
                    and thread.is_alive()):
                raise RuntimeError(
                    "unresolved_work() needs a dead service loop; "
                    "this one is still running (shutdown() first, "
                    "or let the supervisor declare it dead)")
            if self._state == "running":
                # crashed thread under a stale "running" state:
                # close the door so late submit() callers get
                # ServiceClosed instead of enqueueing into a void
                self._state = "crashed"
            leftovers = list(self._ingress)
            self._ingress.clear()
        out = []
        with self._engine_lock:
            for (name, seq), ticket in sorted(
                    self._pending.items(),
                    key=lambda item: item[0][1]):
                request = self._pending_requests.get((name, seq))
                if request is not None and not ticket.done():
                    out.append((name, request, ticket))
            self._pending.clear()
            self._pending_requests.clear()
        for name, request, ticket in leftovers:
            if not ticket.done():
                out.append((name, request, ticket))
        return out

    def reshard(self, mesh=None, devices=None):
        """Re-lay-out the residency over a new device set (the
        drain-and-handoff core): under the engine lock — so no
        request can observe a half-resharded model — every resident
        entry is dropped and the residency's mesh/device slots are
        swapped; the next ``acquire`` re-admits with per-shard
        charges computed over the NEW device count
        (:func:`~brainiak_tpu.serve.artifacts.model_shard_nbytes`).

        Requires a drained service (no pending tickets, empty
        ingress): raises ``RuntimeError`` otherwise — the supervisor
        removes the replica from the router and waits out
        :meth:`drained` first.  Returns the names of the re-laid-out
        models."""
        with self._engine_lock:
            with self._cond:
                busy = bool(self._ingress)
            if busy or self._pending:
                raise RuntimeError(
                    "reshard() needs a drained service: "
                    f"{len(self._pending)} pending tickets, "
                    f"ingress {'non-empty' if busy else 'empty'}")
            return self.residency.reshard(mesh=mesh,
                                          devices=devices)

    # -- submission (any thread) --------------------------------------

    def submit(self, request, model=None, low_latency=False):
        """Enqueue one request; returns its :class:`ServiceTicket`.

        The target model is ``model`` or ``request.model`` or the
        service's default (a single registered model).  The deadline
        clock starts HERE: ``request.submitted`` is stamped with
        ``time.monotonic()`` on enqueue (unless the caller
        pre-stamped ingress time), and the engine's dispatch-time
        deadline check counts from that same stamp no matter how
        many ticks the request waits through.

        ``low_latency=True`` is the single-request fast path: the
        loop (woken immediately by this submit) flushes the
        request's bucket on the NEXT tick instead of waiting out the
        batch window — the continuous-batching ``max_wait_s`` flush
        otherwise adds a full wait-window to every singleton round
        trip, which a closed-loop per-TR caller
        (:mod:`brainiak_tpu.realtime`) cannot afford.  Requests
        queued in the same bucket ride the expedited batch, so
        mixing low-latency and batched traffic sacrifices batching
        efficiency, never correctness."""
        name = model or request.model or self._default_model
        if name is None:
            names = self.residency.names()
            if len(names) == 1:
                name = names[0]
            else:
                raise ValueError(
                    "request names no model and the service has "
                    f"no default ({len(names)} registered)")
        if request.submitted is None:
            request.submitted = time.monotonic()
        # rides the request into _route on the service thread (the
        # ingress tuple shape stays (name, request, ticket)); set
        # unconditionally so a RESUBMITTED request honors this
        # call's choice, not a stale flag from an earlier submit
        request._low_latency = bool(low_latency)
        clock = obs_trace.stage_clock()
        # admission reads the ENGINE-queue gauge this replica
        # publishes (at most one tick stale, by design) BEFORE the
        # lock: the shed fast path must not serialize on ingress
        # contention.  Ingress depth is counted live under the lock
        # below — adding the ingress gauge here would double-count
        # it (submit itself keeps that gauge at len(_ingress))
        queued = self._engine_queue_depth() \
            if self._admission is not None else 0
        # trace root: mint (or adopt an injected) trace id and emit
        # the serve.submit span BEFORE the request becomes visible
        # to the loop — the loop's serve.enqueue span reads and
        # advances request.parent_id, so publishing first would
        # race the chain (no-op, no records while obs is disabled)
        obs_trace.start_trace(request)
        obs_trace.traced_span("serve.submit", clock.elapsed(),
                              request, attrs={"model": name})
        ticket = ServiceTicket(request.request_id, name)
        shed = None
        with self._cond:
            if self._state != "running":
                raise ServiceClosed(
                    f"service is {self._state}; submit() needs a "
                    "running loop (start()/with-block)")
            if self._admission is not None:
                shed = self._admission.evaluate(
                    len(self._ingress) + queued)
            if shed is None:
                self._ingress.append((name, request, ticket))
                depth = len(self._ingress)
                self._n_submitted += 1
                self._cond.notify_all()
            else:
                self._n_shed += 1
        if shed is not None:
            return self._shed_ticket(request, ticket, shed)
        obs_metrics.gauge(
            "serve_service_ingress_depth",
            help="requests accepted but not yet routed").set(
                depth, **self._labels)
        return ticket

    def submit_many(self, requests, model=None):
        """Atomically enqueue a wave of requests (one lock take, one
        loop wake-up): the whole wave is routed in a single tick, so
        its bucket-queue composition — and therefore the padded
        batch extents the flush compiles — is deterministic, not a
        race between submission and the max-wait timer.  Returns the
        tickets in order."""
        now = time.monotonic()
        clock = obs_trace.stage_clock()
        staged = []
        for request in requests:
            name = (model or request.model or self._default_model)
            if name is None:
                names = self.residency.names()
                if len(names) != 1:
                    raise ValueError(
                        "request names no model and the service "
                        f"has no default ({len(names)} registered)")
                name = names[0]
            if request.submitted is None:
                request.submitted = now
            # waves are batched traffic: clear any stale fast-path
            # flag a prior low-latency submit left on the request
            request._low_latency = False
            obs_trace.start_trace(request)
            staged.append((name, request,
                           ServiceTicket(request.request_id, name)))
        # submit spans BEFORE publishing the wave: the loop's
        # serve.enqueue advances request.parent_id, so emitting
        # after the ingress extend would race the chain
        if obs_sink.enabled():
            wave_s = clock.elapsed()
            for name, request, _ in staged:
                obs_trace.traced_span("serve.submit", wave_s,
                                      request,
                                      attrs={"model": name,
                                             "wave": len(staged)})
        # engine-queue gauge only: len(_ingress) is counted live
        # under the lock (the ingress gauge would double-count it)
        queued = self._engine_queue_depth() \
            if self._admission is not None else 0
        shed_out = []
        with self._cond:
            if self._state != "running":
                raise ServiceClosed(
                    f"service is {self._state}; submit_many() "
                    "needs a running loop (start()/with-block)")
            if self._admission is None:
                admitted = staged
            else:
                # per-request admission over the wave: each accept
                # raises the depth the next decision sees, so a
                # wave overflows the bound deterministically — the
                # head admits, the tail sheds
                admitted = []
                for name, request, ticket in staged:
                    shed = self._admission.evaluate(
                        len(self._ingress) + queued
                        + len(admitted))
                    if shed is None:
                        admitted.append((name, request, ticket))
                    else:
                        shed_out.append((request, ticket, shed))
                self._n_shed += len(shed_out)
            self._ingress.extend(admitted)
            depth = len(self._ingress)
            self._n_submitted += len(admitted)
            self._cond.notify_all()
        for request, ticket, shed in shed_out:
            self._shed_ticket(request, ticket, shed)
        obs_metrics.gauge(
            "serve_service_ingress_depth",
            help="requests accepted but not yet routed").set(
                depth, **self._labels)
        return [ticket for _, _, ticket in staged]

    def _shed_ticket(self, request, ticket, shed):
        """Resolve one ticket with the typed pre-enqueue shed
        record (the exactly-one-ticket invariant holds for sheds
        too): ``shed_overload`` + ``retry_after_s``, never an
        exception, never a queue slot, never device time."""
        rec = ServeResult(
            request_id=request.request_id, ok=False,
            error="shed_overload",
            message=(f"admission control shed the request before "
                     f"enqueue ({shed.reason}: depth {shed.depth} "
                     f">= bound {shed.bound}); retry after "
                     f"{shed.retry_after_s:.3f}s"),
            latency_s=0.0, retry_after_s=shed.retry_after_s)
        ticket._resolve(rec)
        obs_metrics.counter(
            "serve_shed_total",
            help="requests shed by admission control before "
                 "enqueue").inc(reason=shed.reason, **self._labels)
        obs_sink.event("shed", reason=shed.reason,
                       depth=shed.depth, bound=shed.bound,
                       retry_after_s=shed.retry_after_s,
                       request_id=request.request_id,
                       replica=self.name)
        return ticket

    def queued_depth(self):
        """This replica's routed-but-undispatched load estimate:
        the sum of the ``serve_service_ingress_depth`` and
        ``serve_service_queue_depth`` gauges it publishes (a
        registry read — no service locks, at most one tick stale).
        The placement signal the federation router reads, per
        ROADMAP item 3.  (The service's OWN admission path counts
        ingress live instead — see :meth:`_engine_queue_depth`.)"""
        return self._gauge_depth_sum(
            ("serve_service_ingress_depth",
             "serve_service_queue_depth"))

    def _engine_queue_depth(self):
        """Routed-into-engine depth alone (the
        ``serve_service_queue_depth`` gauge): the admission fast
        path adds the live ingress length under ``_cond``, so
        including the ingress GAUGE here would count every queued
        request twice and halve the effective bound."""
        return self._gauge_depth_sum(("serve_service_queue_depth",))

    def _gauge_depth_sum(self, metrics):
        total = 0.0
        for metric in metrics:
            for labels, value in \
                    obs_metrics.gauge(metric).samples():
                if self._owns_labels(labels):
                    total += value
        return int(total)

    def _owns_labels(self, labels):
        """Whether a gauge sample belongs to this replica (named
        replicas match their label; the unnamed service owns the
        unlabeled series)."""
        if self.name:
            return labels.get("replica") == self.name
        return "replica" not in labels

    # -- the loop (service thread only) -------------------------------

    def _loop(self):
        n_iters = 0
        while True:
            n_iters += 1
            with self._cond:
                self._n_loop_iters = n_iters
            # fault hooks run LOCK-FREE between iterations: an
            # injected death can never strand a held lock, and an
            # injected stall degrades tick progression (the
            # supervisor's heartbeat signal) without wedging
            # summary()/submit() callers
            try:
                stall = faults.slow_point(n_iters, site="serve.loop",
                                          name=self.name)
                if stall > 0:
                    time.sleep(stall)
                faults.crash_point(n_iters, site="serve.loop",
                                   name=self.name)
            except faults.ReplicaCrashError as exc:
                # injected replica death: the loop dies WITHOUT
                # resolving its queued tickets — the stranded work
                # is exactly what the fleet failover path re-places
                # (unresolved_work); state "crashed" makes further
                # submit() raise ServiceClosed like a dead host
                with self._cond:
                    self._state = "crashed"
                    self._cond.notify_all()
                logger.warning("service loop %r died: %s",
                               self.name or "<unnamed>", exc)
                return
            with self._cond:
                if self._state == "running" and not self._ingress:
                    self._cond.wait(self.tick_interval)
                batch = list(self._ingress)
                self._ingress.clear()
                stopping = self._state != "running"
                # read under _cond (its guard): the engine-lock
                # region below must not touch _cond-guarded state
                drain = self._drain_on_stop
            with self._engine_lock:
                self._tick(batch)
                if stopping:
                    self._finish(batch_failed=not drain)
                    return

    def _tick(self, batch):  # requires-lock: _engine_lock
        self._n_ticks += 1
        t0 = time.perf_counter()
        n_records = 0
        routed = 0
        for name, request, ticket in batch:
            routed += self._route(name, request, ticket)
        for entry in self.residency.entries():
            entry.engine.poll()
            records = entry.engine.drain()
            if records:
                n_records += len(records)
                self._deliver_many(entry.name, records)
            obs_metrics.gauge(
                "serve_service_queue_depth",
                help="requests queued in a model's bucket "
                     "queues").set(
                    sum(len(q) for q in entry.engine._queues
                        .values()), model=entry.name,
                    **self._labels)
        if batch or n_records:
            # one span per tick that did work (routed ingress or
            # delivered results), carrying the measured tick
            # duration — idle ticks stay out of the trace
            self._n_active_ticks += 1
            if obs_sink.enabled():
                obs_sink.emit(obs_sink.make_record(
                    "span", "serve.service.tick",
                    path="serve.service.tick",
                    dur_s=time.perf_counter() - t0,
                    attrs={"n_ingress": len(batch),
                           "n_routed": routed,
                           "n_delivered": n_records}))
        if batch:
            obs_metrics.gauge(
                "serve_service_ingress_depth",
                help="requests accepted but not yet "
                     "routed").set(0, **self._labels)
        if self._slo is not None and (batch or n_records):
            # burn rates re-evaluated on every working tick: cheap
            # (a few dozen slice sums) and keeps the slo_* gauges
            # the exposition serves at most one tick stale
            self._slo.evaluate()

    def _route(self, name, request,
               ticket):  # requires-lock: _engine_lock
        """One ingress request into its model's engine; failures
        become typed error records on the ticket, never loop
        crashes.  Returns 1 when the request reached a queue."""
        try:
            entry = self.residency.acquire(name)
        except AdmissionError as exc:
            self._fail(ticket, request, "admission_refused",
                       str(exc))
            return 0
        except KeyError as exc:
            self._fail(ticket, request, "unknown_model",
                       str(exc.args[0] if exc.args else exc))
            return 0
        except Exception as exc:
            self._fail(ticket, request, "model_load_failed",
                       f"{type(exc).__name__}: {exc}")
            return 0
        rejection = entry.engine.submit(request)
        if rejection is not None:
            # submit-time rejection: the engine's sync return is
            # the only delivery — resolve the ticket with it
            self._account(rejection)
            ticket._resolve(rejection)
            return 0
        self._pending[(name, request._seq_index)] = ticket
        self._pending_requests[(name, request._seq_index)] = request
        if getattr(request, "_low_latency", False):
            # single-request fast path: dispatch the bucket in THIS
            # tick (the same tick's drain below then delivers the
            # record — a one-tick round trip instead of max_wait_s)
            entry.engine.expedite(request)
        return 1

    def _fail(self, ticket, request, code,
              message):  # requires-lock: _engine_lock
        latency = None
        if request.submitted is not None:
            latency = time.monotonic() - request.submitted
        rec = ServeResult(
            request_id=request.request_id, ok=False, error=code,
            message=message, latency_s=latency)
        self._account(rec)
        ticket._resolve(rec)

    def _deliver_many(self, name,
                      records):  # requires-lock: _engine_lock
        for rec in records:
            ticket = self._pending.pop((name, rec.seq), None)
            self._pending_requests.pop((name, rec.seq), None)
            self._account(rec)
            if ticket is not None:
                ticket._resolve(rec)
            else:  # pragma: no cover - engine driven out of band
                logger.warning(
                    "record for %r seq %s has no waiting ticket",
                    name, rec.seq)

    def _account(self, rec):  # requires-lock: _engine_lock
        self._n_delivered += 1
        if rec.ok:
            self._n_ok += 1
            if rec.latency_s is not None:
                self._latency_sketch.observe(rec.latency_s)
        else:
            code = rec.error or "error"
            self._errors_by_code[code] = \
                self._errors_by_code.get(code, 0) + 1
        if self._slo is not None:
            self._slo.record(rec.ok, latency_s=rec.latency_s)

    def _finish(self, batch_failed):  # requires-lock: _engine_lock
        """Final phase after stop: drain or fail everything queued
        so every ticket resolves."""
        with self._cond:
            leftovers = list(self._ingress)
            self._ingress.clear()
        if batch_failed:
            for name, request, ticket in leftovers:
                self._fail(ticket, request, "shutdown",
                           "service shut down before the request "
                           "was routed")
            for entry in self.residency.entries():
                entry.engine.fail_pending("shutdown")
                self._deliver_many(entry.name,
                                   entry.engine.drain())
            return
        for name, request, ticket in leftovers:
            self._route(name, request, ticket)
        for entry in self.residency.entries():
            entry.engine.flush()
            self._deliver_many(entry.name, entry.engine.drain())

    # -- reporting ----------------------------------------------------

    def alive(self):
        """Whether the loop thread is actually running — the
        supervisor's hard liveness probe (``_state == "running"``
        alone cannot see a crashed thread)."""
        with self._cond:
            return (self._state == "running"
                    and self._thread is not None
                    and self._thread.is_alive())

    def heartbeat(self):
        """``(alive, loop iterations, live ingress length)`` without
        touching the engine lock: the supervisor's progress probe
        stays responsive even while a tick is wedged or
        fault-stalled.  A replica whose iteration count stops
        advancing between probes while work is queued is degraded; a
        dead thread is down.  The ingress length is the LIVE deque
        (not the gauge, which a stalled loop never refreshes), so
        the probe can see work a stuck replica is sitting on."""
        with self._cond:
            alive = (self._state == "running"
                     and self._thread is not None
                     and self._thread.is_alive())
            return alive, self._n_loop_iters, len(self._ingress)

    def drained(self):
        """True when no accepted request is still in flight (empty
        ingress AND no pending ticket) — the precondition
        :meth:`reshard`'s drain-and-handoff waits on."""
        with self._cond:
            if self._ingress:
                return False
        with self._engine_lock:
            return not self._pending

    def readiness(self):
        """``(ready, detail)`` for the ``/readyz`` endpoint.

        Ready means "traffic served now meets the zero-cold-start
        contract": the loop is running, at least one model is
        registered, and either a model is already resident or the
        attached AOT cache is warm (persisted programs / hits — a
        restarted replica over a warm cache serves its first
        request without a compile stall, PR 8's SRV002 contract).
        The detail dict carries the facts either way, so an
        orchestrator can see WHY a replica is not ready."""
        with self._cond:
            state = self._state
        res = self.residency.stats()
        aot = self.residency.aot
        aot_stats = aot.stats() if aot is not None else None
        aot_warm = aot is not None and aot.warm()
        ready = (state == "running"
                 and res["n_registered"] > 0
                 and (res["n_resident"] > 0 or aot_warm))
        detail = {
            "state": state,
            "n_registered": res["n_registered"],
            "n_resident": res["n_resident"],
            "resident": res["resident"],
            "aot_warm": aot_warm,
        }
        if aot_stats is not None:
            detail["aot"] = aot_stats
        return ready, detail

    def latency_sketch(self):
        """A **copy** of this replica's ok-latency
        :class:`~brainiak_tpu.obs.sketch.QuantileSketch` — the
        summary a router merges (``a.merge(b)``) to compute pooled
        cross-replica percentiles with the single-sketch error
        bound; ``to_dict()`` is its JSON wire format."""
        with self._engine_lock:
            return QuantileSketch.from_dict(
                self._latency_sketch.to_dict())

    def summary(self):
        """Service-level aggregate: delivery counts, latency
        percentiles over the retained window, padding waste,
        per-model engine summaries, residency occupancy and churn,
        and the AOT hit/miss ledger when a cache is attached.

        ``retrace_total`` is the process-wide
        ``retrace_total{site=serve.*}`` sum — the acceptance
        headline: on a warm AOT cache a restarted process serves
        with this at 0.

        ``p50_latency_s``/``p99_latency_s`` come from the mergeable
        latency sketch (documented relative error:
        ``sketch.DEFAULT_RELATIVE_ACCURACY``) — an O(1) read under
        the tick lock instead of the old per-call deque sort.
        They summarize the service's LIFETIME distribution (the
        sketch is O(1)-memory and never reset; the old deque kept
        the most recent 64k samples) — recency-sensitive alerting
        is the SLO tracker's job (``slos=``), whose burn windows
        are time-bounded by construction."""
        models = {}
        with self._cond:
            # under its own guard: submit() increments on caller
            # threads while the engine lock is NOT held
            n_submitted = self._n_submitted
            n_shed = self._n_shed
        with self._engine_lock:
            # under the tick lock: the loop observes into the
            # sketch while delivering
            p50 = self._latency_sketch.quantile(0.50)
            p99 = self._latency_sketch.quantile(0.99)
            # evicted engines' dispatched elements accrued via
            # on_evict + the currently-resident ones: padding
            # waste covers the whole drive across residency churn
            real = self._retired_real
            padded = self._retired_padded
            for entry in self.residency.entries():
                models[entry.name] = entry.engine.summary()
                stats = entry.engine._stats
                real += stats["real_elements"]
                padded += stats["padded_elements"]
            residency = self.residency.stats()
            n_delivered = self._n_delivered
            n_ok = self._n_ok
            errors_by_code = dict(self._errors_by_code)
            ticks = self._n_ticks
            active_ticks = self._n_active_ticks
        out = {
            "n_submitted": n_submitted,
            "n_delivered": n_delivered,
            "n_ok": n_ok,
            "n_shed": n_shed,
            "n_errors": sum(errors_by_code.values()),
            "errors_by_code": errors_by_code,
            "p50_latency_s": p50,
            "p99_latency_s": p99,
            "padding_waste": (1.0 - real / padded) if padded
            else 0.0,
            "retrace_total": serve_retrace_total(),
            "ticks": ticks,
            "active_ticks": active_ticks,
            "models": models,
            "residency": residency,
        }
        if self.name:
            out["replica"] = self.name
        if self.residency.aot is not None:
            out["aot"] = self.residency.aot.stats()
        if self._admission is not None:
            out["admission"] = self._admission.stats()
        if self._slo is not None:
            out["slo"] = self._slo.evaluate()
        with self._cond:
            http = self._http
        if http is not None:
            out["http_port"] = http.port
        return out
