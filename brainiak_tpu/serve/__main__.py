"""``python -m brainiak_tpu.serve`` — the serving CLI.

Three subcommands:

- ``run --model M.npz --requests R.npz [--out OUT.npz]`` — offline
  batch driver: load a persisted model
  (:func:`brainiak_tpu.serve.load_model`), read a request file
  (:func:`brainiak_tpu.serve.load_requests`), drive the
  :class:`~brainiak_tpu.serve.InferenceEngine` to completion, and
  print one JSON summary (requests, errors, buckets, retraces,
  padding waste, latency percentiles).  Exit status 0 means every
  request produced a result; 1 means at least one structured error
  record; 2 means the driver itself failed.
- ``service --model [NAME=]M.npz ... --requests R.npz`` — the
  always-on path (:class:`~brainiak_tpu.serve.ServeService`):
  multiple resident models under an HBM budget, continuous batching
  in staggered waves, optional ``--aot-cache DIR`` persisted
  programs (a restart over a warm cache serves with zero serve
  retraces), ``--duration``-bounded with a drain-or-fail
  ``--drain``/``--no-drain`` shutdown; JSON summary carries
  p50/p99, padding waste, evictions, and AOT hits/misses.  Same
  0/1/2 exit contract as ``run``.
- ``bench [--model M.npz] [--n-requests N]`` — serving
  micro-benchmark: mixed-TR synthetic requests against the model (a
  tiny deterministic SRM is fitted in-process when no artifact is
  given; generators exist for the :data:`BENCH_KINDS` — SRM-family
  transform and ``ridge_encoding`` held-out-scan scoring — and any
  other artifact kind is rejected rc=2 with the supported kinds
  named), one warm pass (compiles) + one timed steady pass, printed
  as a bench-schema JSON line (``metric``/``value``/``unit``/
  ``vs_baseline``/``tier="serve"``) that
  ``python -m brainiak_tpu.obs regress`` can gate.

Run with ``BRAINIAK_TPU_OBS_DIR`` set to capture ``serve.request``/
``serve.batch`` spans and serve metrics for ``obs report``/
``export``.

``BENCH_FORCE_CPU=1`` pins the CPU platform in-process before any
backend init — the same knob bench.py's tier children honor, because
the ``JAX_PLATFORMS`` env var alone can hang once a wedged tunnel
PJRT plugin is registered (docs/performance.md operational rule 4).
"""

import argparse
import json
import os
import sys
import time

import numpy as np

from .artifacts import detect_kind, load_model, save_model
from .batching import BucketPolicy, Request, load_requests
from .engine import InferenceEngine

__all__ = ["BENCH_KINDS", "bench_record", "build_demo_model",
           "build_encoding_model", "build_encoding_requests",
           "build_mixed_requests", "drive_service", "main",
           "measure", "naive_requests_per_sec", "summary_to_out"]


def _policy(args):
    return BucketPolicy(max_batch=args.max_batch,
                        max_wait_s=args.max_wait,
                        min_bucket=args.min_bucket)


def _write_results(path, records):
    """Persist per-request outcomes as one npz: ``result.<i>`` (or
    ``result.<i>.<j>`` for tuple results), ``error.<i>`` +
    ``message.<i>`` for failures, ``id.<i>`` always.  Returns the
    path actually written (np.savez_compressed appends ".npz" to
    extensionless paths, same normalization as ``save_model``)."""
    if not path.endswith(".npz"):
        path += ".npz"
    out = {"n": np.asarray(len(records))}
    for i, rec in enumerate(records):
        out[f"id.{i}"] = np.asarray(rec.request_id)
        if not rec.ok:
            out[f"error.{i}"] = np.asarray(rec.error)
            out[f"message.{i}"] = np.asarray(rec.message or "")
            continue
        if isinstance(rec.result, tuple):
            out[f"result.{i}.parts"] = np.asarray(len(rec.result))
            for j, part in enumerate(rec.result):
                out[f"result.{i}.{j}"] = np.asarray(part)
        else:
            out[f"result.{i}"] = np.asarray(rec.result)
    np.savez_compressed(path, **out)
    return path


def _run(args):
    model = load_model(args.model)
    requests = load_requests(args.requests)
    engine = InferenceEngine(model, policy=_policy(args))
    t0 = time.perf_counter()
    records = engine.run(requests)
    wall = time.perf_counter() - t0
    summary = engine.summary()
    summary["wall_s"] = round(wall, 6)
    summary["requests_per_sec"] = (
        round(len(requests) / wall, 3) if wall > 0 else None)
    if args.out:
        summary["out"] = _write_results(args.out, records)
    if args.format == "json":
        print(json.dumps(summary, indent=2))
    else:
        print(f"serve run: {summary['n_ok']}/"
              f"{summary['n_requests']} ok, "
              f"{summary['n_errors']} error(s), "
              f"{summary['n_batches']} batch(es) over "
              f"{len(summary['buckets'])} bucket(s), "
              f"retraces={summary['retrace_total']:.0f}, "
              f"padding waste="
              f"{summary['padding_waste']:.1%}")
        for code, count in sorted(
                summary["errors_by_code"].items()):
            print(f"  {count:>4}  {code}")
    return 0 if summary["n_errors"] == 0 else 1


def _parse_model_args(values):
    """``[NAME=]PATH`` pairs from repeated ``--model`` flags; a bare
    path names the model after its file stem."""
    out = []
    seen = set()
    for value in values:
        if "=" in value:
            name, path = value.split("=", 1)
        else:
            path = value
            name = os.path.splitext(os.path.basename(value))[0]
        if not name or not path:
            raise ValueError(
                f"--model expects [NAME=]PATH, got {value!r}")
        if name in seen:
            raise ValueError(f"duplicate model name {name!r}")
        seen.add(name)
        out.append((name, path))
    return out


def drive_service(residency, requests, default_model, waves=4,
                  wave_gap_s=None, duration_s=None, drain=True,
                  http_port=None, slos=None):
    """Submit ``requests`` to a fresh
    :class:`~brainiak_tpu.serve.ServeService` in ``waves`` staggered
    waves (the late-joiner shape: later waves join buckets already
    in flight), wait for the tickets, and shut down gracefully.

    ``duration_s`` caps the drive's wall clock; on expiry the
    service shuts down per ``drain`` (serve everything queued, or
    fail it with ``shutdown`` records) — either way every ticket
    resolves.  ``http_port`` opts into the live
    ``/metrics``/``/healthz``/``/readyz`` exposition for the
    drive's lifetime (0 = ephemeral; the summary carries the bound
    port); ``slos`` declares objectives for burn-rate tracking.
    Returns ``(service summary, records, wall seconds)``
    — shared by the ``service`` subcommand and bench.py's service
    tier so the measured drive cannot drift between them."""
    from .service import ServeService

    policy = residency.policy
    if wave_gap_s is None:
        wave_gap_s = min(0.05, policy.max_wait_s / 2.0
                         if policy is not None else 0.02)
    waves = max(1, min(int(waves), len(requests) or 1))
    per_wave = -(-len(requests) // waves)  # ceil
    svc = ServeService(residency, default_model=default_model,
                       http_port=http_port, slos=slos).start()
    t0 = time.perf_counter()
    deadline = (t0 + duration_s) if duration_s else None
    try:
        tickets = []
        for w in range(waves):
            # one atomic wave: deterministic bucket composition,
            # so repeat drives (warm AOT cache) reuse shapes
            tickets.extend(svc.submit_many(
                requests[w * per_wave:(w + 1) * per_wave]))
            if w + 1 < waves and wave_gap_s > 0:
                gap = wave_gap_s
                if deadline is not None:
                    gap = min(gap,
                              deadline - time.perf_counter())
                if gap > 0:
                    time.sleep(gap)
        for ticket in tickets:
            if deadline is None:
                # backstop, not an SLO: a lost record is a driver
                # bug and must surface as rc=2, not a hang
                ticket.result(timeout=600.0)
                continue
            left = deadline - time.perf_counter()
            try:
                ticket.result(timeout=max(0.0, left))
            except TimeoutError:
                break  # duration expired: shutdown resolves rest
    finally:
        summary = svc.shutdown(drain=drain)
    wall = time.perf_counter() - t0
    return summary, [t.record for t in tickets], wall


def drive_federation(models, requests, n_replicas, policy=None,
                     aot=None, budget_bytes=None, pinned=(),
                     shed_queue_depth=None, waves=1, drain=True,
                     http_port=None, timeout_s=600.0):
    """Drive a request file through ``n_replicas`` in-process
    replicas behind a :class:`~brainiak_tpu.serve.federation.
    Router` — the ``service --replicas N`` path, shared with the
    SRV003 gate and bench.py's federation tier.

    Every replica registers the same models over its OWN residency
    but ONE shared AOT cache, so replica 2..N admit warm (zero
    serve retraces — the content-addressed keys make programs
    shareable).  ``http_port`` starts the exposition on the first
    replica only: the metric registry is process-global, so one
    listener serves every replica's labeled series.  Returns
    ``(summary, records, wall seconds)``; the summary merges
    per-replica counts, pools latency percentiles through the
    mergeable sketch, and carries the router's routed/shed ledger
    under ``"federation"``."""
    from ..obs.sketch import QuantileSketch
    from .federation import AdmissionController, LocalReplica, Router
    from .residency import ModelResidency
    from .service import ServeService, serve_retrace_total

    admission = None
    if shed_queue_depth is not None:
        admission = AdmissionController(max_depth=shed_queue_depth)
    replicas = []
    for i in range(int(n_replicas)):
        residency = ModelResidency(budget_bytes=budget_bytes,
                                   policy=policy, aot=aot)
        for name, source in models:
            residency.register(
                name,
                **({"source": source}
                   if isinstance(source, (str, os.PathLike))
                   else {"model": source}),
                pinned=name in set(pinned))
        svc = ServeService(
            residency, default_model=models[0][0],
            name=f"r{i + 1}",
            http_port=http_port if i == 0 else None).start()
        replicas.append(LocalReplica(svc))
    router = Router(replicas, admission=admission)
    waves = max(1, min(int(waves), len(requests) or 1))
    per_wave = -(-len(requests) // waves)  # ceil
    t0 = time.perf_counter()
    try:
        tickets = []
        for w in range(waves):
            tickets.extend(router.submit_many(
                requests[w * per_wave:(w + 1) * per_wave]))
        records = [t.result(timeout=timeout_s) for t in tickets]
    finally:
        summaries = [r.service.shutdown(drain=drain)
                     for r in replicas]
    wall = time.perf_counter() - t0
    pooled = QuantileSketch()
    for replica in replicas:
        pooled.merge(replica.service.latency_sketch())
    errors_by_code = {}
    for s in summaries:
        for code, count in s["errors_by_code"].items():
            errors_by_code[code] = \
                errors_by_code.get(code, 0) + count
    route = router.summary()
    summary = {
        "n_submitted": sum(s["n_submitted"] for s in summaries),
        "n_delivered": sum(s["n_delivered"] for s in summaries),
        "n_ok": sum(s["n_ok"] for s in summaries),
        "n_shed": route["n_shed"]
        + sum(s["n_shed"] for s in summaries),
        "n_errors": sum(errors_by_code.values()),
        "errors_by_code": errors_by_code,
        "p50_latency_s": pooled.quantile(0.50),
        "p99_latency_s": pooled.quantile(0.99),
        "retrace_total": serve_retrace_total(),
        "federation": dict(
            route,
            replicas={s.get("replica", f"r{i + 1}"): s
                      for i, s in enumerate(summaries)}),
    }
    port = summaries[0].get("http_port")
    if port is not None:
        summary["http_port"] = port
    if aot is not None:
        summary["aot"] = aot.stats()
    return summary, records, wall


def _service(args):
    from .aot import AOTProgramCache
    from .residency import ModelResidency

    models = _parse_model_args(args.model)
    pinned = set(args.pin or [])
    unknown = pinned - {name for name, _ in models}
    if unknown:
        raise ValueError(
            f"--pin names no registered model: "
            f"{', '.join(sorted(unknown))}")
    aot = AOTProgramCache(args.aot_cache) if args.aot_cache else None
    requests = load_requests(args.requests)
    if args.replicas > 1:
        summary, _, wall = drive_federation(
            models, requests, args.replicas,
            policy=_policy(args), aot=aot,
            budget_bytes=args.budget_bytes, pinned=pinned,
            shed_queue_depth=args.shed_queue_depth,
            waves=args.waves, drain=args.drain,
            http_port=args.http_port)
    else:
        residency = ModelResidency(budget_bytes=args.budget_bytes,
                                   policy=_policy(args), aot=aot)
        if args.shed_queue_depth is not None:
            raise ValueError(
                "--shed-queue-depth requires --replicas >= 2 (the "
                "router owns fleet-level admission; single-replica "
                "shedding is the ServeService admission= API)")
        for name, path in models:
            residency.register(name, source=path,
                               pinned=name in pinned)
        summary, _, wall = drive_service(
            residency, requests, default_model=models[0][0],
            waves=args.waves, duration_s=args.duration,
            drain=args.drain, http_port=args.http_port)
    summary["wall_s"] = round(wall, 6)
    summary["requests_per_sec"] = (
        round(len(requests) / wall, 3) if wall > 0 else None)
    summary["drain"] = bool(args.drain)
    if args.format == "json":
        print(json.dumps(summary, indent=2))
    elif args.replicas > 1:
        fed = summary["federation"]
        print(f"serve federation: {summary['n_ok']}/"
              f"{summary['n_submitted']} ok over "
              f"{fed['n_replicas']} replica(s), "
              f"{summary['n_shed']} shed, "
              f"{summary['n_errors']} error(s), retraces="
              f"{summary['retrace_total']:.0f}, routed="
              f"{fed['routed']}")
        for code, count in sorted(
                summary["errors_by_code"].items()):
            print(f"  {count:>4}  {code}")
    else:
        aot_stats = summary.get("aot") or {}
        print(f"serve service: {summary['n_ok']}/"
              f"{summary['n_submitted']} ok, "
              f"{summary['n_errors']} error(s), "
              f"{summary['residency']['n_resident']} resident "
              f"model(s), {summary['residency']['evictions']} "
              f"eviction(s), retraces="
              f"{summary['retrace_total']:.0f}, aot hits="
              f"{aot_stats.get('hits', 0)}")
        for code, count in sorted(
                summary["errors_by_code"].items()):
            print(f"  {count:>4}  {code}")
    return 0 if summary["n_errors"] == 0 else 1


def build_demo_model(n_subjects=4, voxels=48, samples=40,
                     features=8, n_iter=5, seed=0, ragged=True):
    """A small fitted SRM for benches/fixtures: deterministic
    synthetic ``X_i = W_i S + noise`` data, mixed voxel counts when
    ``ragged``."""
    from ..funcalign.srm import SRM

    rng = np.random.RandomState(seed)
    shared = rng.randn(features, samples)
    data = []
    for i in range(n_subjects):
        v = voxels + (i if ragged else 0)
        q, _ = np.linalg.qr(rng.randn(v, features))
        data.append(q @ shared + 0.1 * rng.randn(v, samples))
    model = SRM(n_iter=n_iter, features=features, rand_seed=seed)
    model.fit(data)
    return model


def build_mixed_requests(model, n_requests, seed=0,
                         tr_choices=(24, 40, 100, 150)):
    """Mixed-shape transform requests against a fitted SRM-family
    model: TR lengths drawn from ``tr_choices`` (several buckets),
    subjects round-robin."""
    rng = np.random.RandomState(seed)
    counts = [w.shape[0] for w in model.w_]
    out = []
    for i in range(n_requests):
        subject = i % len(counts)
        trs = int(tr_choices[i % len(tr_choices)])
        x = rng.randn(counts[subject], trs).astype(np.float32)
        out.append(Request(request_id=f"r{i}", x=x,
                           subject=subject))
    return out


def build_encoding_model(voxels=64, features=16, samples=80,
                         n_folds=4, seed=0):
    """A small fitted :class:`~brainiak_tpu.encoding.RidgeEncoder`
    for benches/fixtures: deterministic synthetic ``Y = X W + noise``
    data, a 3-point lambda grid."""
    from ..encoding import RidgeEncoder

    rng = np.random.RandomState(seed)
    x = rng.randn(samples, features).astype(np.float32)
    w = rng.randn(features, voxels).astype(np.float32)
    y = (x @ w + 0.5 * rng.randn(samples, voxels)).astype(np.float32)
    return RidgeEncoder(lambdas=(1.0, 10.0, 100.0),
                        n_folds=n_folds).fit(x, y)


def build_encoding_requests(model, n_requests, seed=0,
                            tr_choices=(24, 40, 100, 150)):
    """Mixed-TR held-out-scan scoring requests against a fitted
    encoding model: each payload is a ``(features, responses)`` pair
    whose responses are the model's own predictions plus noise, TR
    lengths drawn from ``tr_choices`` (several buckets)."""
    rng = np.random.RandomState(seed)
    f, v = model.W_.shape
    out = []
    for i in range(n_requests):
        trs = int(tr_choices[i % len(tr_choices)])
        feats = rng.randn(trs, f).astype(np.float32)
        resp = (model.predict(feats)
                + 0.5 * rng.randn(trs, v)).astype(np.float32)
        out.append(Request(request_id=f"r{i}", x=(feats, resp)))
    return out


#: kind -> synthetic request generator for the ``bench`` subcommand
#: (the model kinds bench can drive without a request file; every
#: other kind serves fine through ``run``).
BENCH_KINDS = {
    "srm": build_mixed_requests,
    "detsrm": build_mixed_requests,
    "rsrm": build_mixed_requests,
    "ridge_encoding": build_encoding_requests,
}


def measure(model, requests, policy=None, warm=True):
    """Requests/s + latency percentiles for one engine drive.

    ``warm=True`` runs a first (untimed) engine over the same
    requests so the timed pass measures steady-state dispatch, not
    compiles — the program cache is module-level, so the warm
    engine's programs are reused.
    """
    if warm:
        InferenceEngine(model, policy=policy).run(
            [Request(request_id=f"w{i}", x=r.x, subject=r.subject,
                     deadline_s=r.deadline_s)
             for i, r in enumerate(requests)])
    engine = InferenceEngine(model, policy=policy)
    for req in requests:  # fresh queue-time stamps for this drive
        req.submitted = None
    t0 = time.perf_counter()
    records = engine.run(requests)
    wall = time.perf_counter() - t0
    summary = engine.summary()
    summary["wall_s"] = wall
    summary["requests_per_sec"] = len(requests) / wall \
        if wall > 0 else float("inf")
    summary["n_results"] = len(records)
    return summary


def naive_requests_per_sec(model, requests):
    """The unbatched reference path: one host-BLAS pass per request,
    no bucketing, no reuse — the ``vs_baseline`` denominator for the
    serve bench.  Dispatches on the model's artifact kind (the same
    key :data:`BENCH_KINDS` uses): SRM-family models run ``W_iᵀ x``
    per request; encoding models run predict + per-voxel correlation
    (the same work the engine's scoring program batches)."""
    kind = detect_kind(model)
    if kind == "ridge_encoding":  # per-request host scoring
        t0 = time.perf_counter()
        for req in requests:
            model.score(np.asarray(req.x[0]), np.asarray(req.x[1]))
    else:  # SRM family: per-subject projection
        w = [np.asarray(wi) for wi in model.w_]
        t0 = time.perf_counter()
        for req in requests:
            w[req.subject].T @ np.asarray(req.x)
    wall = time.perf_counter() - t0
    return len(requests) / wall if wall > 0 else float("inf")


def summary_to_out(summary, baseline_rps=None, backend=None):
    """Project an engine :meth:`~InferenceEngine.summary` onto the
    measurement dict :func:`bench_record` consumes — the ONE place
    the summary→record key mapping lives (used by this CLI's
    ``bench`` subcommand and by ``bench.py``'s serve tier)."""
    out = {
        "requests_per_sec": summary["requests_per_sec"],
        "p50_latency_s": summary["p50_latency_s"],
        "p99_latency_s": summary["p99_latency_s"],
        "padding_waste": summary["padding_waste"],
        "n_buckets": len(summary["buckets"]),
        "retrace_total": summary["retrace_total"],
    }
    if baseline_rps is not None:
        out["baseline_rps"] = baseline_rps
    if backend is not None:
        out["backend"] = backend
    return out


def bench_record(out, n_requests, kind="srm", max_batch=None,
                 stages=None):
    """The serve bench-schema JSON record, shared by this CLI's
    ``bench`` subcommand and ``bench.py``'s serve tier so the two
    cannot drift.  ``out`` carries ``requests_per_sec`` /
    ``baseline_rps`` / latency percentiles / ``padding_waste`` /
    ``n_buckets`` / ``retrace_total`` and optionally ``backend``;
    the record carries the PR-4 provenance stamps
    (``schema_version``, ``git_commit``) regress.py trusts.

    Tier separation mirrors the FCMA tiers: a run whose backend is
    not a TPU is stamped ``tier="serve_cpu_fallback"`` so ``obs
    regress`` never compares a host-fallback rate against an
    on-chip serve baseline (and vice versa).
    """
    from ..obs.report import BENCH_SCHEMA_VERSION

    rps = float(out["requests_per_sec"])
    baseline = float(out.get("baseline_rps") or 0.0)
    vs = round(rps / baseline, 3) \
        if baseline > 0 and np.isfinite(baseline) else 0.0
    # the encoding read path scores held-out scans; every other
    # bench-able kind transforms
    op = "score" if kind == "ridge_encoding" else "transform"
    config = {
        "n_requests": n_requests,
        "n_buckets": out["n_buckets"],
        "retrace_total": out["retrace_total"],
        "padding_waste_pct":
            round(100.0 * out["padding_waste"], 2),
    }
    for key in ("p50_latency_s", "p99_latency_s"):
        # None when no request produced a latency (empty drive)
        if out.get(key) is not None:
            config[key] = round(out[key], 6)
    if max_batch is not None:
        config["max_batch"] = max_batch
    backend = out.get("backend")
    tier = "serve" if backend == "tpu" else "serve_cpu_fallback"
    if backend:
        config["backend"] = backend
    rec = {"schema_version": BENCH_SCHEMA_VERSION,
           "metric": f"serve_{kind}_{op}_requests_per_sec",
           "value": round(rps, 2),
           "unit": "requests/sec",
           "vs_baseline": vs,
           "tier": tier,
           "config": config}
    from ..obs.report import git_commit_stamp
    commit = git_commit_stamp()
    if commit:
        rec["git_commit"] = commit
    if stages:
        rec["stages"] = stages
    return rec


def _bench(args):
    if args.model:
        model = load_model(args.model)
        # the synthetic workload generators cover the SRM-family
        # transform kinds and encoding-model scoring; other kinds
        # load and serve fine via `run`, but bench has no request
        # generator for them — fail as a driver error (rc=2) that
        # NAMES the supported kinds, not a traceback
        kind = detect_kind(model)
        if kind not in BENCH_KINDS:
            raise ValueError(
                "bench generates synthetic requests only for kinds "
                f"{', '.join(sorted(BENCH_KINDS))}; model artifact "
                f"is kind {kind!r} — use `run` with a request file "
                "instead")
    else:
        model = build_demo_model()
        kind = "srm"
        if args.save_model:
            save_model(model, args.save_model)
    requests = BENCH_KINDS[kind](model, args.n_requests,
                                 seed=args.seed)
    policy = _policy(args)
    summary = measure(model, requests, policy=policy)
    import jax

    out = summary_to_out(
        summary,
        baseline_rps=naive_requests_per_sec(model, requests),
        backend=jax.default_backend())
    print(json.dumps(bench_record(
        out, args.n_requests, kind=summary["kind"],
        max_batch=args.max_batch)))
    return 0


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m brainiak_tpu.serve",
        description="persisted-model batch serving "
                    "(docs/serving.md)")
    sub = parser.add_subparsers(dest="command", required=True)

    run_p = sub.add_parser(
        "run", help="drive a request file through the engine "
                    "(one-shot; see `service` for the always-on "
                    "multi-model loop)")
    run_p.add_argument("--model", required=True,
                       help="model artifact (save_model npz)")
    run_p.add_argument("--requests", required=True,
                       help="request file (save_requests npz)")
    run_p.add_argument("--out", help="write per-request results npz")
    run_p.add_argument("--format", choices=("text", "json"),
                       default="text")

    service_p = sub.add_parser(
        "service",
        help="always-on continuous-batching service: multiple "
             "resident models, HBM budget, persisted AOT programs")
    service_p.add_argument(
        "--model", action="append", required=True,
        metavar="[NAME=]PATH",
        help="model artifact; repeatable (name defaults to the "
             "file stem)")
    service_p.add_argument("--requests", required=True,
                           help="request file (save_requests npz; "
                                "per-request model.<i> keys route)")
    service_p.add_argument(
        "--aot-cache", metavar="DIR",
        help="persisted-program cache directory: a restarted "
             "service over a warm cache serves its first request "
             "without a compile stall")
    service_p.add_argument(
        "--budget-bytes", type=int,
        help="residency byte budget (default: device HBM limit, "
             "or BRAINIAK_TPU_SERVE_BUDGET_BYTES)")
    service_p.add_argument(
        "--pin", action="append", metavar="NAME",
        help="never evict this model; repeatable")
    service_p.add_argument(
        "--duration", type=float, metavar="SECONDS",
        help="wall-clock cap; on expiry pending work drains or "
             "fails per --drain")
    service_p.add_argument(
        "--drain", action=argparse.BooleanOptionalAction,
        default=True,
        help="on shutdown, serve queued work to completion "
             "(--no-drain fails it with `shutdown` records)")
    service_p.add_argument(
        "--waves", type=int, default=4,
        help="stagger submissions into this many waves "
             "(default %(default)s)")
    service_p.add_argument(
        "--replicas", type=int, default=1, metavar="N",
        help="run N warm replicas behind the federation router "
             "(each its own ServeService + residency, one shared "
             "AOT cache; requests place by residency + live queue "
             "depth; --duration applies to single-replica mode "
             "only)")
    service_p.add_argument(
        "--shed-queue-depth", type=int, metavar="DEPTH",
        help="fleet-level admission control (needs --replicas>=2): "
             "shed with retry_after once EVERY replica is at this "
             "queue depth (default: unbounded ingress)")
    service_p.add_argument(
        "--http-port", type=int, metavar="PORT",
        help="serve live /metrics (Prometheus text), /healthz and "
             "/readyz on this port for the run's lifetime (0 = "
             "ephemeral, reported as http_port in the summary; "
             "default: the BRAINIAK_TPU_OBS_HTTP_PORT env var, "
             "else no listener)")
    service_p.add_argument("--format", choices=("text", "json"),
                           default="json")

    bench_p = sub.add_parser(
        "bench", help="serving throughput micro-benchmark "
                      "(steady-state tiers live in the `service` "
                      "bench of bench.py)")
    bench_p.add_argument("--model",
                         help="model artifact (default: fit a tiny "
                              "demo SRM in-process)")
    bench_p.add_argument("--save-model",
                         help="persist the demo model artifact here")
    bench_p.add_argument("--n-requests", type=int, default=256)
    bench_p.add_argument("--seed", type=int, default=0)

    for p in (run_p, service_p, bench_p):
        p.add_argument("--max-batch", type=int, default=64)
        p.add_argument("--max-wait", type=float, default=0.05)
        p.add_argument("--min-bucket", type=int, default=16)

    args = parser.parse_args(argv)
    if os.environ.get("BENCH_FORCE_CPU"):
        import jax
        jax.config.update("jax_platforms", "cpu")
    if args.command == "run":
        return _run(args)
    if args.command == "service":
        return _service(args)
    return _bench(args)


if __name__ == "__main__":
    import zipfile

    try:
        sys.exit(main())
    except (OSError, ValueError, zipfile.BadZipFile) as exc:
        # rc=2 is the driver-failure contract: a missing/corrupt
        # artifact (a truncated npz raises BadZipFile, not OSError)
        # must not read as "ran with per-request errors" (rc=1)
        print(f"serve: {exc}", file=sys.stderr)
        sys.exit(2)
