"""SRV004 selfcheck: the elastic fleet, end to end in one child.

The ``fleet`` gate of ``tools/run_checks.py`` runs
:func:`selfcheck` in a child pinned to the 8-device CPU mesh (the
same harness as the federation/distla/encoding gates): one
deterministic :func:`~brainiak_tpu.serve.federation.fleet.
chaos_soak` — fmrisim heavy-tailed traffic that triples mid-run
while replica ``r1`` is degraded by an injected ``slow_replica``
fault and killed by an injected ``replica_crash`` fault with a wave
still queued — and verifies, with one JSON verdict line:

- **zero lost tickets** — EVERY submitted request resolves exactly
  one ticket, as ``delivered``, ``shed_overload``, or a typed
  ``replica_lost`` record (``n_unresolved == 0``: a ticket that
  never resolves is the invariant violation this gate exists to
  catch);
- **failover routing** — the supervisor declared ``r1`` dead and
  the router re-placed its stranded work onto survivors
  (``failover.n_replaced > 0``, survivors routed);
- **zero retraces on scale-up** — the surge scaled the fleet up
  and the mid-run joiners SERVED requests off the shared AOT cache
  without compiling a single new serve program
  (``final_retraces == warm_retraces`` — the SRV003 warm-fleet
  property, extended to mid-run scale-up).

Exit 0 on success, 1 with the verdict naming what failed.
"""

import json

__all__ = ["selfcheck"]


def selfcheck(out=None):
    """Run the elastic-fleet chaos soak (see module docstring);
    returns the process exit code."""
    import sys

    from .fleet import chaos_soak

    stream = out or sys.stdout
    verdict = {"ok": False}
    try:
        facts = chaos_soak(n_requests=48, seed=0)
        verdict["n_requests"] = facts["n_requests"]
        verdict["n_unresolved"] = facts["n_unresolved"]
        verdict["all_resolved"] = facts["n_unresolved"] == 0
        verdict["by_code"] = facts["by_code"]
        verdict["n_replica_lost"] = facts["n_replica_lost"]
        verdict["degraded_seen"] = facts.get("degraded_seen",
                                             False)
        verdict["crash_fired"] = facts.get("crash_fired", 0)
        failover = facts.get("failover") or {}
        verdict["failover"] = failover
        verdict["failover_ok"] = bool(
            facts.get("crash_fired")
            and failover.get("n_replaced", 0) > 0
            and failover.get("n_lost", 0) == 0)
        routed = facts["supervisor"]["router"]["routed"]
        verdict["routed"] = routed
        verdict["survivor_routed_ok"] = routed.get("r2", 0) > 0
        verdict["scaled_replicas"] = facts.get("scaled_replicas",
                                               [])
        verdict["n_scaled_up_served"] = facts.get(
            "n_scaled_up_served", 0)
        verdict["scale_up_ok"] = bool(
            verdict["scaled_replicas"]
            and verdict["n_scaled_up_served"] > 0)
        verdict["states"] = facts["states"]
        # normalized like every selfcheck gate: 1.0 means "no
        # program rebuilt after warmup"; anything above is counted
        # retraces, classified by the shared gate harness
        warm = facts.get("warm_retraces", 0.0)
        final = facts.get("final_retraces", 0.0)
        verdict["warm_retraces"] = warm
        verdict["final_retraces"] = final
        verdict["retraces"] = {
            "serve.fleet": 1.0 + max(0.0, final - warm)}
        verdict["ok"] = bool(
            verdict["all_resolved"]
            and verdict["failover_ok"]
            and verdict["survivor_routed_ok"]
            and verdict["degraded_seen"]
            and verdict["scale_up_ok"]
            and final <= warm)
    except Exception as exc:  # noqa: BLE001 - verdict carries it
        verdict["error"] = f"{type(exc).__name__}: {exc}"
    json.dump(verdict, stream)
    stream.write("\n")
    return 0 if verdict["ok"] else 1
