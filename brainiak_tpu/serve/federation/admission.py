"""Load-shedding admission control: bounded ingress with a typed
reject-with-``retry_after`` fast path.

The PR 8 service accepts unboundedly: under overload the ingress
queue grows without limit, every queued request eventually times out,
and p99 melts for EVERYONE.  Admission control is the standard fix
(reject early, reject cheaply): a request over the depth bound is
refused BEFORE it touches a queue, with a machine-readable
``retry_after_s`` hint so well-behaved clients back off — goodput
stays near capacity and the accepted requests' p99 stays bounded by
``depth bound / service rate`` instead of the backlog.

:class:`AdmissionController` is the decision point, shared by the
:class:`~brainiak_tpu.serve.service.ServeService` submit fast path
(consulted before enqueue; a shed resolves the ticket immediately
with a ``shed_overload`` :class:`~brainiak_tpu.serve.batching.
ServeResult` — never an exception mid-batch) and the
:class:`~brainiak_tpu.serve.federation.router.Router` (shed only
when EVERY replica is over bound).  Two signals drive it:

- **queue depth** — the ``serve_service_ingress_depth`` +
  ``serve_service_queue_depth`` gauges the service publishes (the
  PR 11 in-process registry; at most one tick stale by design);
- **SLO burn rate** — with an attached
  :class:`~brainiak_tpu.obs.slo.SLOTracker`, a live burn-rule
  violation *brown-outs* the depth bound by ``brownout_factor``
  (default 0.5): when the error budget is burning, the service
  sheds earlier to recover, the multi-window rules un-fire, and the
  bound relaxes back — a proportional controller with the SLO
  machinery as its sensor.  The tracker poll is throttled
  (``slo_poll_interval_s``) so the submit fast path never pays a
  full burn evaluation per request.

``retry_after_s`` grows with the overflow (clipped at 8x the base):
the deeper past the bound the fleet is, the longer clients are told
to stay away — the cheap stand-in for exponential client backoff.
"""

import dataclasses
import threading
import time
from typing import Optional

__all__ = ["AdmissionController", "Shed"]


@dataclasses.dataclass(frozen=True)
class Shed:
    """One shed decision: the facts a shed record (and its client)
    needs — how long to stay away, why, and the depth-vs-bound
    evidence."""

    retry_after_s: float
    reason: str          # "queue_full" | "slo_burn" | "tenant_quota"
    depth: int
    bound: int
    tenant: Optional[str] = None


class AdmissionController:
    """Depth-bounded, SLO-aware admission control (see module
    docstring).

    Parameters
    ----------
    max_depth : int
        Ingress + queued depth at (or beyond) which requests shed.
        Size it as ``target p99 x expected service rate``: the
        bound IS the queueing-delay budget.
    retry_after_s : float
        Base client backoff hint; scaled up with the overflow
        (clipped at 8x).
    slo : :class:`~brainiak_tpu.obs.slo.SLOTracker`, optional
        Burn-rate sensor: while any objective is violating, the
        depth bound multiplies by ``brownout_factor`` so the
        service sheds its way back inside the error budget.
    brownout_factor : float
        Bound multiplier under SLO violation (0 < f <= 1).
    slo_poll_interval_s : float
        Minimum spacing between tracker evaluations (the submit
        fast path must not pay a burn evaluation per request).
    clock : callable
        Monotonic time source (tests inject a fake).
    tenant_quotas : dict, optional
        Per-tenant in-flight bounds (tenant name -> max jobs/requests
        that tenant may hold admitted at once).  Consulted by
        :meth:`evaluate` when the caller supplies ``tenant`` +
        ``tenant_depth`` — the jobs scheduler passes a tenant's
        queued+running+parked count so one tenant's thousand-subject
        SRM backlog sheds at its own quota long before it can fill
        the global ``max_depth``.  Tenants without an entry fall back
        to ``default_tenant_quota`` (None = unbounded).
    default_tenant_quota : int, optional
        Quota applied to tenants absent from ``tenant_quotas``.
    """

    def __init__(self, max_depth=256, retry_after_s=0.05, slo=None,
                 brownout_factor=0.5, slo_poll_interval_s=0.25,
                 clock=time.monotonic, tenant_quotas=None,
                 default_tenant_quota=None):
        if max_depth < 0:
            raise ValueError(
                f"max_depth must be >= 0, got {max_depth}")
        if not 0.0 < brownout_factor <= 1.0:
            raise ValueError(
                f"brownout_factor must be in (0, 1], got "
                f"{brownout_factor}")
        self.max_depth = int(max_depth)
        self.retry_after_s = float(retry_after_s)
        self.slo = slo
        self.brownout_factor = float(brownout_factor)
        self.slo_poll_interval_s = float(slo_poll_interval_s)
        self.clock = clock
        self.tenant_quotas = dict(tenant_quotas or {})
        self.default_tenant_quota = default_tenant_quota
        self._lock = threading.Lock()
        self._n_admitted = 0       # guarded-by: _lock
        self._n_shed = 0           # guarded-by: _lock
        self._shed_by_reason = {}  # guarded-by: _lock
        self._last_poll = None     # guarded-by: _lock
        self._violating = False    # guarded-by: _lock

    # -- the decision (any thread) ------------------------------------

    def depth_bound(self):
        """The live depth bound: ``max_depth``, browned out while
        the SLO tracker reports a burn-rule violation."""
        if self.slo is None:
            return self.max_depth
        if self._poll_slo():
            return max(1, int(self.max_depth
                              * self.brownout_factor))
        return self.max_depth

    def burning(self):
        """Whether the attached SLO tracker currently reports a
        burn-rule violation (False without a tracker) — the browned-
        out state, exposed for the fleet supervisor's scale-up
        signal (same throttled poll as :meth:`depth_bound`)."""
        if self.slo is None:
            return False
        return bool(self._poll_slo())

    def tenant_quota(self, tenant):
        """The in-flight bound for ``tenant`` (None = unbounded)."""
        if tenant in self.tenant_quotas:
            return self.tenant_quotas[tenant]
        return self.default_tenant_quota

    def evaluate(self, queued_depth, tenant=None,
                 tenant_depth=None) -> Optional[Shed]:
        """None to admit a request at ``queued_depth``, else the
        :class:`Shed` (O(1); the throttled SLO poll is the only
        non-constant ingredient).

        With ``tenant`` + ``tenant_depth`` supplied, the tenant's
        quota (see ``tenant_quotas``) is checked first: a tenant at
        or over its own bound sheds with reason ``tenant_quota``
        even when the global queue has room.
        """
        if tenant is not None and tenant_depth is not None:
            quota = self.tenant_quota(tenant)
            if quota is not None and int(tenant_depth) >= int(quota):
                overflow = int(tenant_depth) - int(quota)
                retry = self.retry_after_s * min(
                    8.0, 1.0 + overflow / max(int(quota), 1))
                with self._lock:
                    self._n_shed += 1
                    self._shed_by_reason["tenant_quota"] = \
                        self._shed_by_reason.get("tenant_quota", 0) + 1
                return Shed(retry_after_s=retry, reason="tenant_quota",
                            depth=int(tenant_depth), bound=int(quota),
                            tenant=tenant)
        bound = self.depth_bound()
        depth = int(queued_depth)
        if depth < bound:
            with self._lock:
                self._n_admitted += 1
            return None
        reason = "slo_burn" if bound < self.max_depth \
            else "queue_full"
        overflow = depth - bound
        retry = self.retry_after_s * min(
            8.0, 1.0 + overflow / max(bound, 1))
        with self._lock:
            self._n_shed += 1
            self._shed_by_reason[reason] = \
                self._shed_by_reason.get(reason, 0) + 1
        return Shed(retry_after_s=retry, reason=reason,
                    depth=depth, bound=bound)

    def _poll_slo(self):
        """Current SLO-violating state, re-evaluated at most every
        ``slo_poll_interval_s`` (the cached verdict serves the fast
        path in between)."""
        now = self.clock()
        with self._lock:
            fresh = (self._last_poll is None
                     or now - self._last_poll
                     >= self.slo_poll_interval_s)
            if fresh:
                self._last_poll = now
        if fresh:
            state = self.slo.evaluate()
            violating = any(
                obj.get("violating")
                for obj in state.get("objectives", {}).values())
            with self._lock:
                self._violating = violating
        with self._lock:
            return self._violating

    # -- reporting ----------------------------------------------------

    def stats(self):
        """Admission ledger for the service/router summaries."""
        with self._lock:
            return {
                "max_depth": self.max_depth,
                "depth_bound": None if self.slo is None
                else (max(1, int(self.max_depth
                                 * self.brownout_factor))
                      if self._violating else self.max_depth),
                "n_admitted": self._n_admitted,
                "n_shed": self._n_shed,
                "shed_by_reason": dict(self._shed_by_reason),
                "retry_after_s": self.retry_after_s,
            }
