"""fmrisim-driven synthetic serving traffic with heavy tails.

A serving bench that replays uniform arrivals at a constant rate
flatters every queueing policy: real request streams are **bursty**
(heavy-tailed inter-arrivals — a scanner session ends and a batch
of subjects uploads at once) and **mixed** (scan lengths spread over
an order of magnitude, with a long tail of long scans).  This
module builds that workload from the repo's own simulator:

- **payloads** come from :mod:`brainiak_tpu.utils.fmrisim` — a
  boxcar event train (``generate_stimfunction``) convolved with the
  double-gamma HRF (``convolve_hrf``) drives per-voxel loadings
  plus Gaussian noise, so each request is a plausible BOLD
  ``[voxels, TRs]`` scan rather than white noise;
- **scan lengths** draw from ``tr_choices`` with Zipf-ish weights
  (mostly short scans, occasional long ones — several shape
  buckets, like the real encoding read path);
- **arrivals** are Pareto inter-arrival times (``alpha`` default
  1.5: finite mean, heavy tail) rescaled so the MEAN rate matches
  ``target_rps`` — the overload bench dials ``target_rps`` to 2x
  measured capacity and the bursts do the rest.

Everything is seeded and deterministic, so a bench round or CI gate
replays the identical mix.

The same generator also emits **fit workloads** for the training
control plane (:mod:`brainiak_tpu.jobs`): :meth:`TrafficGenerator.
fit_jobs` mints :class:`~brainiak_tpu.jobs.spec.JobSpec` batches
with a Zipf-weighted tenant mix (one hospital dominates, a long
tail of small labs) and :meth:`TrafficGenerator.job_schedule` gives
them Pareto inter-arrival submission offsets — the jobs soak test
and the ``jobs`` bench tier replay the identical stream.
"""

import time

import numpy as np

from ..batching import Request

__all__ = ["TrafficGenerator", "replay"]


class TrafficGenerator:
    """Synthetic request traffic against a fitted SRM-family model
    (see module docstring).

    Parameters
    ----------
    model : fitted SRM/DetSRM (``w_`` per-subject maps — the demo
        and fixture serving workload), or ``None`` for a fit-only
        generator (:meth:`fit_jobs` / :meth:`job_schedule` never
        touch the model; :meth:`requests` raises without one)
    model_name : str, optional
        Stamped on every request's ``model`` field (multi-model
        routing through the federation router).
    tr_choices : tuple of int
        Scan lengths in TRs, ascending; drawn with Zipf weights
        (``P(choice i) ∝ 1/(i+1)``) so short scans dominate.
    alpha : float
        Pareto tail index for inter-arrival times (smaller =
        burstier; must be > 1 so the mean exists).
    tr_duration : float
        Simulated TR length in seconds (drives the HRF kernel).
    """

    def __init__(self, model=None, model_name=None, seed=0,
                 tr_choices=(16, 32, 64, 128), alpha=1.5,
                 tr_duration=1.0):
        if alpha <= 1.0:
            raise ValueError(
                f"alpha must be > 1 (finite-mean Pareto), got "
                f"{alpha}")
        self.model = model
        self.model_name = model_name
        self.voxel_counts = [w.shape[0] for w in model.w_] \
            if model is not None else []
        self.tr_choices = tuple(int(t) for t in tr_choices)
        self.alpha = float(alpha)
        self.tr_duration = float(tr_duration)
        self.rng = np.random.RandomState(seed)
        weights = 1.0 / np.arange(1, len(self.tr_choices) + 1)
        self._tr_weights = weights / weights.sum()

    def _payload(self, subject, n_trs):
        """One fmrisim-flavored scan: an event-driven BOLD course
        broadcast through random per-voxel loadings + noise."""
        from ...utils import fmrisim

        total_time = n_trs * self.tr_duration
        n_events = max(1, n_trs // 8)
        onsets = np.sort(self.rng.uniform(
            0.0, max(total_time - 2.0, 1.0), size=n_events))
        stim = fmrisim.generate_stimfunction(
            onsets.tolist(), [self.tr_duration], total_time,
            temporal_resolution=10.0)
        bold = fmrisim.convolve_hrf(
            stim, self.tr_duration,
            temporal_resolution=10.0)[:n_trs, 0]
        v = self.voxel_counts[subject]
        loadings = self.rng.randn(v, 1)
        data = loadings * bold[None, :] \
            + 0.5 * self.rng.randn(v, n_trs)
        return data.astype(np.float32)

    def requests(self, n, prefix="t", deadline_s=None):
        """``n`` deterministic requests: heavy-tailed scan-length
        mix, subjects round-robin, fmrisim payloads."""
        if self.model is None:
            raise ValueError(
                "requests() needs a fitted model; this generator "
                "was built fit-only (model=None)")
        out = []
        for i in range(n):
            subject = i % len(self.voxel_counts)
            n_trs = int(self.rng.choice(self.tr_choices,
                                        p=self._tr_weights))
            out.append(Request(
                request_id=f"{prefix}{i}",
                x=self._payload(subject, n_trs),
                subject=subject, model=self.model_name,
                deadline_s=deadline_s))
        return out

    def schedule(self, n, target_rps, prefix="t", deadline_s=None):
        """``[(arrival_offset_s, Request)]`` — Pareto inter-arrival
        times rescaled so the mean rate over the schedule is
        ``target_rps`` exactly (the tail stays heavy: individual
        gaps spread over orders of magnitude)."""
        if target_rps <= 0:
            raise ValueError(
                f"target_rps must be > 0, got {target_rps}")
        gaps = self.rng.pareto(self.alpha, size=n) + 1.0
        arrivals = np.cumsum(gaps)
        arrivals *= n / (float(target_rps) * arrivals[-1])
        reqs = self.requests(n, prefix=prefix,
                             deadline_s=deadline_s)
        return list(zip(arrivals.tolist(), reqs))

    def fit_jobs(self, n, tenants=("hospital-a", "hospital-b",
                                   "lab-c"),
                 kinds=("srm",), n_iter=6, features=3,
                 n_subjects=3, voxels=16, samples=20,
                 priorities=(0,), deadline_s=None):
        """``n`` deterministic :class:`~brainiak_tpu.jobs.spec.
        JobSpec` values with a Zipf-weighted tenant mix
        (``P(tenant i) ∝ 1/(i+1)`` — the first tenant dominates,
        the tail trickles) and uniform draws over ``kinds`` /
        ``priorities``.  Seeds are minted per job so two jobs never
        share a synthetic dataset."""
        from ...jobs.spec import JobSpec

        tenants = tuple(tenants)
        weights = 1.0 / np.arange(1, len(tenants) + 1)
        weights /= weights.sum()
        specs = []
        for i in range(n):
            tenant = tenants[int(self.rng.choice(
                len(tenants), p=weights))]
            kind = kinds[int(self.rng.randint(len(kinds)))]
            priority = int(priorities[int(self.rng.randint(
                len(priorities)))])
            specs.append(JobSpec(
                tenant=tenant, kind=kind, priority=priority,
                n_iter=n_iter, features=features,
                seed=int(self.rng.randint(0, 2**31 - 1)),
                n_subjects=n_subjects, voxels=voxels,
                samples=samples, deadline_s=deadline_s))
        return specs

    def job_schedule(self, n, target_jobs_per_s, **kwargs):
        """``[(arrival_offset_s, JobSpec)]`` — the fit-workload
        twin of :meth:`schedule`: Pareto inter-arrival submission
        times rescaled so the mean rate is ``target_jobs_per_s``,
        over a :meth:`fit_jobs` batch (``kwargs`` pass through)."""
        if target_jobs_per_s <= 0:
            raise ValueError(
                f"target_jobs_per_s must be > 0, got "
                f"{target_jobs_per_s}")
        gaps = self.rng.pareto(self.alpha, size=n) + 1.0
        arrivals = np.cumsum(gaps)
        arrivals *= n / (float(target_jobs_per_s) * arrivals[-1])
        specs = self.fit_jobs(n, **kwargs)
        return list(zip(arrivals.tolist(), specs))


def replay(schedule, submit_many, time_scale=1.0,
           sleep=time.sleep, now=time.perf_counter):
    """Drive a schedule against a submit surface (a
    :class:`~brainiak_tpu.serve.federation.router.Router` or
    :class:`~brainiak_tpu.serve.service.ServeService` bound
    method): sleeps to each arrival offset (scaled by
    ``time_scale``) and submits every request whose time has come
    as one wave.  Returns the tickets in schedule order.  Requests
    are stamped ``submitted=None`` first so a reused schedule gets
    fresh deadline clocks."""
    schedule = sorted(schedule, key=lambda pair: pair[0])
    for _, request in schedule:
        request.submitted = None
    tickets = []
    t0 = now()
    i = 0
    while i < len(schedule):
        due = schedule[i][0] * time_scale
        wait = due - (now() - t0)
        if wait > 0:
            sleep(wait)
        elapsed = now() - t0
        wave = []
        while i < len(schedule) and \
                schedule[i][0] * time_scale <= elapsed:
            wave.append(schedule[i][1])
            i += 1
        if not wave:  # clock did not advance past the next arrival
            wave.append(schedule[i][1])
            i += 1
        tickets.extend(submit_many(wave))
    return tickets
