"""Elastic fault-tolerant fleet: supervised replicas, failover
re-placement, autoscaling, live resharding.

The PR 13 federation serves pod-scale traffic but is statically
provisioned and fragile: ``--replicas N`` is fixed at launch, a
replica that dies takes its queued work with it, and sharded
residency assumes a device set that never changes.  None of that
survives real TPU-pod operation, where preemption and load swings
are the norm (arXiv:2112.09017).  :class:`FleetSupervisor` closes
the loop from the signals the obs plane already exports:

- **health checking with hysteresis** — every supervision round
  probes each replica's loop-iteration heartbeat
  (:meth:`~brainiak_tpu.serve.service.ServeService.heartbeat`, the
  lock-free progress counter) and ``/readyz`` readiness, and walks
  a ``healthy | degraded | dead`` state machine with
  consecutive-probe thresholds (``degraded_after`` bad probes to
  degrade, ``dead_after`` down probes to declare death,
  ``healthy_after`` good probes to heal) — one missed beat never
  kills a replica, and a flapping one never heals instantly;
- **failover re-placement** — a replica declared dead is detached
  from the :class:`~brainiak_tpu.serve.federation.router.Router`,
  its accepted-but-undelivered work harvested
  (:meth:`~brainiak_tpu.serve.service.ServeService.
  unresolved_work`) and re-placed onto the survivors as one atomic
  wave per survivor (:meth:`~brainiak_tpu.serve.federation.router.
  Router.failover`): every caller-held ticket still resolves
  exactly once — delivered by a survivor, shed by admission, or a
  typed ``replica_lost`` record when past deadline or out of
  survivors.  Never silence;
- **autoscaling** — replica count floats between ``min_replicas``
  and ``max_replicas`` on the signals already on ``/metrics``:
  mean queue depth (``serve_queue_depth`` family), the shed-ratio
  delta, and the SLO burn state
  (:meth:`~brainiak_tpu.serve.federation.admission.
  AdmissionController.burning`).  Scale-up builds replicas through
  the caller's ``factory`` over the SHARED content-addressed AOT
  cache, so a mid-run joiner serves at zero retraces (the SRV003
  property, extended to scale-up and gated by SRV004); scale-down
  detaches and drains through ``shutdown(drain=True)``;
- **live resharding** — when the device set changes,
  :meth:`FleetSupervisor.reshard_replica` runs drain-and-handoff:
  detach from the router (traffic flows to the rest of the fleet),
  wait out :meth:`~brainiak_tpu.serve.service.ServeService.
  drained`, swap the residency layout under the engine lock
  (per-shard charges recomputed via
  :func:`~brainiak_tpu.serve.artifacts.model_shard_nbytes` over the
  new device count), re-attach.  No request ever observes a
  half-resharded model.

All of it is exercised deterministically by :func:`chaos_soak` —
fmrisim heavy-tailed traffic that triples mid-run while a targeted
``replica_crash`` fault (:mod:`brainiak_tpu.resilience.faults`)
kills a replica — the shared driver behind the SRV004 gate
(``tools/run_checks.py``), the ``elastic`` bench tier, and the
fleet tests.  See docs/serving.md ("Elastic fleet").
"""

import threading
import time

import numpy as np

from ...obs import metrics as obs_metrics
from ...obs import sink as obs_sink
from ...resilience import faults

__all__ = ["FleetSupervisor", "chaos_soak"]

#: Health states, in descending order of usefulness; the
#: ``serve_replica_health`` gauge publishes their numeric rank.
HEALTH_STATES = ("dead", "degraded", "healthy")


class _ReplicaHealth:
    """One replica's supervision ledger (owned by the supervisor's
    poll lock): hysteresis counters + the last heartbeat reading."""

    def __init__(self):
        self.state = "healthy"
        self.bad = 0          # consecutive slow/unready probes
        self.down = 0         # consecutive dead-thread probes
        self.good = 0         # consecutive clean probes
        self.last_iters = None  # loop-iteration count at last probe


class FleetSupervisor:
    """Supervision, failover, autoscaling, and resharding over a
    :class:`~brainiak_tpu.serve.federation.router.Router` (see
    module docstring).

    Parameters
    ----------
    router : :class:`~brainiak_tpu.serve.federation.router.Router`
        The fleet under supervision; membership is edited through
        its ``add_replica``/``remove_replica``.
    factory : callable ``(name) -> LocalReplica``, optional
        Builds a warm replica for scale-up (and for the
        no-survivors failover path).  Share one AOT cache directory
        across every replica the factory builds — that is what
        makes mid-run scale-up retrace-free.  Without a factory the
        fleet can shrink but never grow.
    min_replicas, max_replicas : int
        Autoscale bounds (scale-down never goes below
        ``min_replicas``; scale-up never above ``max_replicas``).
    degraded_after, dead_after, healthy_after : int
        Hysteresis thresholds: consecutive slow/unready probes
        before ``healthy -> degraded``, consecutive dead-thread
        probes before ``-> dead``, and consecutive clean probes
        before ``degraded -> healthy``.
    scale_up_depth, scale_down_depth : float
        Mean queued requests per replica beyond which the fleet
        grows, and at-or-below which it is scale-down-eligible.
    scale_down_after : int
        Consecutive idle polls (depth at/under ``scale_down_depth``,
        no sheds, no SLO burn) before one replica drains away —
        scale-down is the slowest decision by design.
    drain_timeout_s : float
        Bound on graceful drains (gray-failure decommission,
        scale-down, reshard handoff).
    clock, sleep : callables
        Time sources (tests inject fakes).

    Threading: :meth:`poll` is the whole control loop, deterministic
    and re-entrant-safe (one round at a time under the poll lock);
    :meth:`start` merely drives it from a background thread.  The
    supervisor holds NO lock while calling into router or services,
    so a slow drain can never deadlock a probe.
    """

    def __init__(self, router, factory=None, min_replicas=1,
                 max_replicas=4, degraded_after=2, dead_after=2,
                 healthy_after=2, scale_up_depth=8.0,
                 scale_down_depth=1.0, scale_down_after=3,
                 drain_timeout_s=30.0, clock=time.monotonic,
                 sleep=time.sleep):
        if min_replicas < 0 or max_replicas < max(min_replicas, 1):
            raise ValueError(
                f"need 0 <= min_replicas <= max_replicas (>= 1), "
                f"got {min_replicas}/{max_replicas}")
        self.router = router
        self.factory = factory
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.degraded_after = int(degraded_after)
        self.dead_after = int(dead_after)
        self.healthy_after = int(healthy_after)
        self.scale_up_depth = float(scale_up_depth)
        self.scale_down_depth = float(scale_down_depth)
        self.scale_down_after = int(scale_down_after)
        self.drain_timeout_s = float(drain_timeout_s)
        self.clock = clock
        self._sleep = sleep
        # one supervision round at a time; all ledger state below
        # is read/written inside poll() only
        self._poll_lock = threading.Lock()
        self._health = {}        # guarded-by: _poll_lock
        self._dead = {}          # guarded-by: _poll_lock (replica)
        self._last_shed = 0      # guarded-by: _poll_lock
        self._idle_polls = 0     # guarded-by: _poll_lock
        self._spawn_seq = 0      # guarded-by: _poll_lock
        self._n_polls = 0        # guarded-by: _poll_lock
        self._n_failovers = 0    # guarded-by: _poll_lock
        self._scaled_up = []     # guarded-by: _poll_lock
        self._scaled_down = []   # guarded-by: _poll_lock
        # background driver bookkeeping
        self._bg_lock = threading.Lock()
        self._thread = None      # guarded-by: _bg_lock
        self._stop = threading.Event()

    # -- probing ------------------------------------------------------

    def _probe(self, replica, health):
        """One replica's instantaneous verdict: ``"ok"``, ``"slow"``
        (alive but unready or not progressing past queued work), or
        ``"down"`` (loop thread dead)."""
        service = getattr(replica, "service", None)
        if service is None:  # non-local replicas: depth-read probe
            try:
                replica.queue_depth()
                return "ok"
            except Exception:
                return "down"
        alive, iters, n_ingress = service.heartbeat()
        if not alive:
            return "down"
        ready, _ = service.readiness()
        # iters frozen between probes while work waits (gauge depth
        # OR live ingress — a stalled loop never refreshes gauges)
        stalled = (health.last_iters is not None
                   and iters <= health.last_iters
                   and (n_ingress > 0
                        or replica.queue_depth() > 0))
        health.last_iters = iters
        return "ok" if ready and not stalled else "slow"

    def _update_health(self, name, probe):
        """Walk the hysteresis state machine for one probe verdict;
        returns the (possibly new) state."""
        health = self._health.setdefault(name, _ReplicaHealth())
        if probe == "down":
            health.down += 1
            health.good = 0
            if health.down >= self.dead_after:
                health.state = "dead"
            elif health.state == "healthy":
                health.state = "degraded"
        elif probe == "slow":
            health.bad += 1
            health.good = 0
            health.down = 0
            if health.state == "healthy" \
                    and health.bad >= self.degraded_after:
                health.state = "degraded"
        else:
            health.good += 1
            health.bad = 0
            health.down = 0
            if health.state == "degraded" \
                    and health.good >= self.healthy_after:
                health.state = "healthy"
        obs_metrics.gauge(
            "serve_replica_health",
            help="supervisor verdict per replica "
                 "(2 healthy, 1 degraded, 0 dead)").set(
            HEALTH_STATES.index(health.state), replica=name)
        return health.state

    # -- the control loop ---------------------------------------------

    def poll(self):
        """One supervision round: probe every routed replica, walk
        health states, fail over the newly dead, then evaluate the
        autoscale signals.  Deterministic — tests and the chaos soak
        call this directly; :meth:`start` drives it on a timer.
        Returns the round's actions
        (``{"states", "failed_over", "scaled_up", "scaled_down"}``).
        """
        with self._poll_lock:
            return self._poll_locked()

    def _poll_locked(self):  # requires-lock: _poll_lock
        self._n_polls += 1
        actions = {"states": {}, "failed_over": [],
                   "scaled_up": [], "scaled_down": []}
        for replica in list(self.router.replicas):
            health = self._health.setdefault(replica.name,
                                             _ReplicaHealth())
            state = self._update_health(
                replica.name, self._probe(replica, health))
            actions["states"][replica.name] = state
            if state == "dead":
                result = self._fail_over(replica)
                actions["failed_over"].append(
                    {"replica": replica.name, **result})
        self._autoscale(actions)
        return actions

    def _fail_over(self, replica):  # requires-lock: _poll_lock
        """Detach a dead replica and re-place its work (see module
        docstring).  A replica whose thread still breathes (declared
        dead by hysteresis — the gray-failure case) is decommissioned
        through a bounded graceful drain instead: the drain delivers
        everything, so there is nothing to re-place."""
        name = replica.name
        try:
            self.router.remove_replica(name)
        except KeyError:
            pass  # already detached by an earlier round
        self._dead[name] = replica
        service = getattr(replica, "service", None)
        work = []
        if service is not None:
            if service.alive():
                service.shutdown(drain=True,
                                 timeout=self.drain_timeout_s)
            else:
                work = service.unresolved_work()
        # out of survivors: replace the dead replica BEFORE
        # re-placement so the harvested work lands somewhere
        # instead of resolving replica_lost wholesale
        if not self.router.replicas and self.factory is not None \
                and len(self.router.replicas) < self.max_replicas:
            self._scale_up("failover replacement")
        result = self.router.failover(work, source=name)
        self._n_failovers += 1
        obs_sink.event("replica_dead", replica=name,
                       n_harvested=len(work), **result)
        # replica death is an incident: snapshot the flight ring so
        # the probes/requests leading up to it survive the failover
        from ...obs import flight
        flight.dump("replica_death",
                    state={"replica": name, "n_harvested": len(work),
                           **result})
        return result

    def _autoscale(self, actions):  # requires-lock: _poll_lock
        replicas = self.router.replicas
        summary = self.router.summary()
        shed_delta = summary["n_shed"] - self._last_shed
        self._last_shed = summary["n_shed"]
        admission = self.router.admission
        burning = admission.burning() if admission is not None \
            else False
        depths = [r.queue_depth() for r in replicas]
        mean_depth = (sum(depths) / len(depths)) if depths \
            else float("inf")
        pressed = (not replicas
                   or mean_depth >= self.scale_up_depth
                   or shed_delta > 0 or burning)
        if pressed and self.factory is not None \
                and len(replicas) < self.max_replicas:
            reason = ("empty_fleet" if not replicas
                      else "shed" if shed_delta > 0
                      else "slo_burn" if burning else "queue_depth")
            name = self._scale_up(reason)
            actions["scaled_up"].append(name)
            self._idle_polls = 0
            return
        idle = (replicas and mean_depth <= self.scale_down_depth
                and shed_delta == 0 and not burning)
        self._idle_polls = self._idle_polls + 1 if idle else 0
        if self._idle_polls >= self.scale_down_after \
                and len(replicas) > self.min_replicas:
            name = self._scale_down()
            actions["scaled_down"].append(name)
            self._idle_polls = 0

    def _scale_up(self, reason):  # requires-lock: _poll_lock
        self._spawn_seq += 1
        name = f"auto{self._spawn_seq}"
        replica = self.factory(name)
        self.router.add_replica(replica)
        self._health[replica.name] = _ReplicaHealth()
        self._scaled_up.append(replica.name)
        obs_metrics.counter(
            "serve_scale_events_total",
            help="fleet size changes by the supervisor").inc(
            direction="up", reason=reason)
        obs_sink.event("scale_up", replica=replica.name,
                       reason=reason,
                       n_replicas=len(self.router.replicas))
        return replica.name

    def _scale_down(self):  # requires-lock: _poll_lock
        """Drain one replica away: prefer the most recent
        supervisor-spawned joiner (LIFO keeps the operator-provisioned
        base fleet intact), else the router's last member."""
        replicas = self.router.replicas
        spawned = [n for n in self._scaled_up
                   if any(r.name == n for r in replicas)]
        name = spawned[-1] if spawned else replicas[-1].name
        replica = self.router.remove_replica(name)
        service = getattr(replica, "service", None)
        if service is not None:
            service.shutdown(drain=True,
                             timeout=self.drain_timeout_s)
        self._scaled_down.append(name)
        obs_metrics.counter(
            "serve_scale_events_total",
            help="fleet size changes by the supervisor").inc(
            direction="down", reason="idle")
        obs_sink.event("scale_down", replica=name,
                       n_replicas=len(self.router.replicas))
        return name

    # -- resharding ---------------------------------------------------

    def reshard_replica(self, name, mesh=None, devices=None,
                        drain_timeout_s=None, poll_interval_s=0.005):
        """Drain-and-handoff reshard of one replica: detach from the
        router (the rest of the fleet keeps taking traffic), wait
        until the replica is fully drained, swap its residency
        layout under the engine lock
        (:meth:`~brainiak_tpu.serve.service.ServeService.reshard` —
        per-shard charges recomputed over the new device count),
        then re-attach.  No request ever observes a half-resharded
        model: requests routed before the detach drain first, and
        requests after the re-attach meet the new layout whole.
        Returns the names of the re-laid-out models."""
        timeout = (self.drain_timeout_s if drain_timeout_s is None
                   else float(drain_timeout_s))
        replica = self.router.remove_replica(name)
        try:
            service = getattr(replica, "service", None)
            if service is None:
                raise TypeError(
                    f"replica {name!r} has no local service to "
                    "reshard")
            deadline = self.clock() + timeout
            while not service.drained():
                if self.clock() >= deadline:
                    raise TimeoutError(
                        f"replica {name!r} did not drain within "
                        f"{timeout}s for resharding")
                self._sleep(poll_interval_s)
            dropped = service.reshard(mesh=mesh, devices=devices)
        finally:
            self.router.add_replica(replica)
        obs_sink.event("reshard_handoff", replica=name,
                       models=dropped)
        return dropped

    # -- background driver --------------------------------------------

    def start(self, interval_s=0.05):
        """Drive :meth:`poll` from a daemon thread every
        ``interval_s`` seconds (idempotent); returns self.
        Deterministic callers (tests, the chaos soak) skip this and
        call :meth:`poll` themselves."""
        with self._bg_lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()

            def run():
                while not self._stop.wait(interval_s):
                    try:
                        self.poll()
                    except Exception:  # pragma: no cover - defensive
                        # supervision must outlive one bad round
                        import logging
                        logging.getLogger(__name__).exception(
                            "fleet supervision round failed")

            self._thread = threading.Thread(
                target=run, name="fleet-supervisor", daemon=True)
            self._thread.start()
        return self

    def stop(self):
        """Stop the background driver (no-op when not started)."""
        with self._bg_lock:
            self._stop.set()
            thread = self._thread
            self._thread = None
        if thread is not None:
            thread.join(timeout=5.0)

    # -- reporting ----------------------------------------------------

    def states(self):
        """``{replica name: health state}`` for every replica ever
        supervised (dead ones included — their terminal state is
        part of the fleet's story)."""
        with self._poll_lock:
            return {name: h.state
                    for name, h in sorted(self._health.items())}

    def summary(self):
        """Supervision ledger + the router's own summary."""
        with self._poll_lock:
            out = {
                "n_polls": self._n_polls,
                "n_failovers": self._n_failovers,
                "states": {name: h.state
                           for name, h in
                           sorted(self._health.items())},
                "scaled_up": list(self._scaled_up),
                "scaled_down": list(self._scaled_down),
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
            }
        out["router"] = self.router.summary()
        return out


# -- the chaos soak ---------------------------------------------------


def _await(predicate, what, timeout_s=30.0, interval_s=0.001):
    """Spin until ``predicate()`` holds (bounded — the soak must
    fail loudly, never hang CI)."""
    deadline = time.monotonic() + timeout_s
    while not predicate():
        if time.monotonic() > deadline:
            raise RuntimeError(what)
        time.sleep(interval_s)


def chaos_soak(model=None, n_requests=96, seed=0, aot_dir=None,
               base_rps=400.0, traffic_multiplier=3.0,
               deadline_s=60.0, max_replicas=3, max_batch=8,
               tr_choices=(16, 32), chaos=True, time_scale=1.0,
               result_timeout_s=180.0):
    """The deterministic chaos soak (SRV004 / ``elastic`` bench /
    fleet-test shared driver): fmrisim heavy-tailed traffic against
    a supervised 2-replica fleet; mid-run, replica ``r1`` is
    degraded by a targeted ``slow_replica`` fault, killed by a
    targeted ``replica_crash`` fault with a wave still queued, the
    supervisor fails its work over to the survivor, and the traffic
    then TRIPLES so the fleet scales up off the shared AOT cache.

    Phases (all seeded):

    1. **warm** — one wave per (TR bucket x power-of-two batch
       extent) drives every serve program once, so the shared AOT
       cache is fully populated; the ``retrace_total{site=serve.*}``
       reading after this phase is the zero-retrace baseline;
    2. **steady** — half the requests replayed at ``base_rps``
       through the router, supervisor polling each wave;
    3. **chaos** (``chaos=True``) — ``slow_replica`` degrades r1
       (hysteresis walks healthy -> degraded), then
       ``replica_crash`` kills it with a freshly-submitted hold
       wave still in ingress; the next poll declares death and
       fails over;
    4. **surge** — the other half of the requests at
       ``traffic_multiplier x base_rps``; polls scale the fleet up
       to ``max_replicas``;
    5. **settle** — every ticket resolved (bounded wait) and
       classified: ``delivered`` / ``shed_overload`` /
       ``replica_lost`` / other typed errors.  A ticket that never
       resolves is a LOST ticket — the invariant violation the
       SRV004 gate exists to catch.

    ``chaos=False`` runs the same mix on a static 2-replica fleet
    (no faults, no supervisor actions) — the bench baseline.

    Returns a facts dict (counts, routed/summary ledgers, health
    states, retrace readings, post-failure p99, wall seconds); the
    callers assert on it.
    """
    from ..__main__ import build_demo_model
    from ..batching import BucketPolicy, Request
    from ..residency import ModelResidency
    from ..service import ServeService, serve_retrace_total
    from .admission import AdmissionController
    from .router import LocalReplica, Router
    from .traffic import TrafficGenerator, replay

    if model is None:
        model = build_demo_model(n_subjects=2, voxels=48,
                                 samples=32, features=6, n_iter=2,
                                 seed=seed)
    policy = BucketPolicy(max_batch=max_batch, max_wait_s=0.01)

    # every replica — including mid-run joiners — MUST share one
    # AOT cache: that is the whole zero-retrace-on-scale-up story
    owned_tmp = None
    if aot_dir is None:
        import tempfile
        owned_tmp = tempfile.TemporaryDirectory(
            prefix="chaos-soak-aot-")
        aot_dir = owned_tmp.name

    def factory(name):
        residency = ModelResidency(budget_bytes=1 << 30,
                                   policy=policy, aot=aot_dir)
        residency.register("demo", model=model)
        return LocalReplica(ServeService(
            residency, default_model="demo", name=name).start())

    r1, r2 = factory("r1"), factory("r2")
    admission = AdmissionController(max_depth=64,
                                    retry_after_s=0.02)
    router = Router([r1, r2], admission=admission)
    supervisor = FleetSupervisor(
        router, factory=factory, min_replicas=1,
        max_replicas=max_replicas, degraded_after=2, dead_after=1,
        healthy_after=2, scale_up_depth=4.0, scale_down_depth=0.0,
        scale_down_after=10 ** 9)  # soak never scales down mid-run

    facts = {"chaos": bool(chaos), "n_requests": 0}
    rng = np.random.RandomState(seed + 1)
    tickets = []
    t_start = time.perf_counter()
    try:
        # -- phase 1: warm every (tr bucket, batch extent) program
        voxel_counts = [w.shape[0] for w in model.w_]
        warm_id = 0
        for n_trs in tr_choices:
            extent = 1
            while extent <= max_batch:
                wave = []
                for _ in range(extent):
                    subject = warm_id % len(voxel_counts)
                    wave.append(Request(
                        request_id=f"warm{warm_id}",
                        x=rng.randn(voxel_counts[subject],
                                    n_trs).astype(np.float32),
                        subject=subject, model="demo"))
                    warm_id += 1
                for ticket in r1.service.submit_many(wave):
                    ticket.result(timeout=result_timeout_s)
                extent *= 2
        facts["warm_retraces"] = serve_retrace_total()

        # -- phase 2: steady traffic at base_rps
        gen = TrafficGenerator(model, model_name="demo", seed=seed,
                               tr_choices=tr_choices)
        n_steady = n_requests // 2
        n_surge = n_requests - n_steady

        def drive(schedule):
            def submit(wave):
                out = router.submit_many(wave)
                if chaos:  # static baseline: no supervisor actions
                    supervisor.poll()
                return out
            return replay(schedule, submit,
                          time_scale=time_scale)

        tickets += drive(gen.schedule(n_steady, base_rps,
                                      prefix="s",
                                      deadline_s=deadline_s))

        # -- phase 3: degrade, then kill, r1 (chaos only)
        if chaos:
            # 3a: one long stall freezes r1's loop mid-iteration;
            # work submitted during the stall sits in live ingress,
            # so consecutive probes see frozen iters + queued work
            # and the hysteresis walks healthy -> degraded
            with faults.inject("slow_replica", times=1, leaf=1.5,
                               target="r1") as stall:
                _await(lambda: stall.fired >= 1,
                       "slow_replica stall never began")
                tickets += r1.service.submit_many(
                    gen.requests(2, prefix="d",
                                 deadline_s=deadline_s))
                supervisor.poll()   # freezes last_iters reading
                supervisor.poll()   # slow x1
                supervisor.poll()   # slow x2 -> degraded
            facts["degraded_seen"] = (
                supervisor.states().get("r1") == "degraded")
            # let r1 wake from the 3a stall and deliver the "d"
            # wave before arming the kill — the crash must land in
            # a FRESH iteration, after a fresh stall
            _await(r1.service.drained,
                   "r1 never recovered from the 3a stall")
            # 3b: stall + crash in ONE iteration: the loop sleeps
            # (slow fires first), the hold wave lands in ingress
            # during the sleep, then crash_point fires BEFORE the
            # ingress drain — guaranteed stranded work for the
            # failover path, no race with delivery
            with faults.inject("slow_replica", times=1, leaf=1.5,
                               target="r1") as stall, \
                    faults.inject("replica_crash",
                                  target="r1") as crash:
                _await(lambda: stall.fired >= 1,
                       "pre-crash stall never began")
                hold = gen.requests(8, prefix="h",
                                    deadline_s=deadline_s)
                tickets += r1.service.submit_many(hold)
                _await(lambda: not r1.service.alive(),
                       "injected crash did not kill r1")
            facts["crash_fired"] = crash.fired
            actions = supervisor.poll()
            facts["failover"] = (
                actions["failed_over"][0]
                if actions["failed_over"] else None)

        # -- phase 4: the surge (traffic triples)
        tickets += drive(gen.schedule(
            n_surge, base_rps * traffic_multiplier, prefix="x",
            deadline_s=deadline_s))
        if chaos:
            supervisor.poll()

        # drive every mid-run joiner directly: the zero-retrace-on-
        # scale-up claim is only meaningful if the scaled-up
        # replicas actually SERVE off the shared warm cache
        scaled = {r.name: r for r in router.replicas
                  if r.name.startswith("auto")}
        scaled_ids = set()
        for i, replica in enumerate(scaled.values()):
            wave = gen.requests(4, prefix=f"a{i}",
                                deadline_s=deadline_s)
            scaled_ids.update(r.request_id for r in wave)
            tickets += replica.submit_many(wave)
        facts["scaled_replicas"] = sorted(scaled)

        # -- phase 5: settle and classify every ticket
        facts["n_requests"] = len(tickets)
        unresolved = 0
        by_code = {}
        ok_latencies = []
        post_failure = []
        n_scaled_served = 0
        for ticket in tickets:
            try:
                rec = ticket.result(timeout=result_timeout_s)
            except TimeoutError:
                unresolved += 1
                continue
            if rec.ok:
                by_code["delivered"] = by_code.get(
                    "delivered", 0) + 1
                if ticket.request_id in scaled_ids:
                    n_scaled_served += 1
                if rec.latency_s is not None:
                    ok_latencies.append(rec.latency_s)
                    if ticket.request_id[0] in ("h", "x", "a"):
                        post_failure.append(rec.latency_s)
            else:
                code = rec.error or "error"
                by_code[code] = by_code.get(code, 0) + 1
        facts["n_unresolved"] = unresolved
        facts["n_scaled_up_served"] = n_scaled_served
        facts["by_code"] = by_code
        facts["n_delivered_ok"] = by_code.get("delivered", 0)
        facts["n_shed"] = by_code.get("shed_overload", 0)
        facts["n_replica_lost"] = by_code.get("replica_lost", 0)
        if ok_latencies:
            facts["p99_latency_s"] = float(np.percentile(
                np.asarray(ok_latencies), 99))
        if post_failure:
            facts["post_failure_p99_s"] = float(np.percentile(
                np.asarray(post_failure), 99))
        facts["final_retraces"] = serve_retrace_total()
        facts["states"] = supervisor.states()
        facts["supervisor"] = supervisor.summary()
        facts["wall_s"] = time.perf_counter() - t_start
        if facts["wall_s"] > 0:
            facts["requests_per_sec"] = (
                facts["n_requests"] / facts["wall_s"])
    finally:
        supervisor.stop()
        for replica in list(router.replicas):
            try:
                replica.service.shutdown(drain=True, timeout=30.0)
            except Exception:  # pragma: no cover - teardown
                pass
        if owned_tmp is not None:
            owned_tmp.cleanup()
    return facts
