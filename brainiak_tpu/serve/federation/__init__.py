"""brainiak_tpu.serve.federation: pod-scale serving federation.

The next tier above one :class:`~brainiak_tpu.serve.service.
ServeService` (ROADMAP open item 3, the arXiv:2403.19421
massive-individual serving setting under DrJAX's observed-state
placement discipline, arXiv:2403.07128) — three coupled pieces:

- **sharded-model serving** — models over one device's HBM budget
  partition over the mesh through the engine's ``serve.*_sharded``
  programs and the residency's per-device accounting (both live in
  :mod:`~brainiak_tpu.serve.engine` /
  :mod:`~brainiak_tpu.serve.residency`; this package is where the
  fleet-level pieces compose);
- **multi-replica operation** — :class:`Router` +
  :class:`LocalReplica` place requests over N replicas by model
  residency and live queue depth (the PR 11 gauges;
  :func:`scrape_replica_state` reads the same series off a remote
  ``/metrics``), all replicas warm-starting from one shared
  content-addressed AOT cache;
- **load-shedding admission control** — :class:`AdmissionController`
  bounds ingress with a typed reject-with-``retry_after`` fast path
  before enqueue, browned out by the PR 11 SLO burn-rate tracker;
  :class:`TrafficGenerator`/:func:`replay` soak it with
  fmrisim-driven heavy-tailed request mixes;
- **elastic fault tolerance** — :class:`FleetSupervisor` health-
  checks replicas with hysteresis, fails a dead replica's stranded
  work over to survivors (exactly-one-ticket preserved; typed
  ``replica_lost`` records, never silence), autoscales between
  ``min_replicas``/``max_replicas`` off the ``/metrics`` signals
  (joiners warm-start retrace-free from the shared AOT cache), and
  reshards resident models with drain-and-handoff when the device
  set changes.  :func:`chaos_soak` exercises all of it under
  injected ``replica_crash``/``slow_replica`` faults
  (:mod:`brainiak_tpu.resilience.faults`).

CI: the ``federation`` gate (SRV003 in ``tools/run_checks.py``)
drives replica warm-start at true process granularity and runs
:mod:`~brainiak_tpu.serve.federation.selfcheck` on the 8-device CPU
mesh; the ``fleet`` gate (SRV004) runs
:mod:`~brainiak_tpu.serve.federation.fleet_selfcheck` — the chaos
soak — on the same mesh.  See docs/serving.md ("Pod-scale
federation", "Elastic fleet").
"""

from .admission import (  # noqa: F401
    AdmissionController,
    Shed,
)
from .fleet import (  # noqa: F401
    FleetSupervisor,
    chaos_soak,
)
from .router import (  # noqa: F401
    LocalReplica,
    Router,
    scrape_replica_state,
)
from .traffic import (  # noqa: F401
    TrafficGenerator,
    replay,
)

__all__ = [
    "AdmissionController",
    "FleetSupervisor",
    "LocalReplica",
    "Router",
    "Shed",
    "TrafficGenerator",
    "chaos_soak",
    "replay",
    "scrape_replica_state",
]
