"""Multi-replica request routing: place by residency, then by load.

One :class:`~brainiak_tpu.serve.service.ServeService` is one thread
in one process; the federation tier runs N of them — warm-started
off one shared :class:`~brainiak_tpu.serve.aot.AOTProgramCache`
(keys are content-addressed and platform-stamped, so replica 2..N
serve with zero retraces) — behind this thin router.  Placement
follows the DrJAX mapreduce discipline (arXiv:2403.07128): decide
from *observed* state, never by reaching into a replica's internals:

1. **residency first** — replicas where the target model is already
   resident beat replicas that would have to (re)admit it: an
   artifact load + upload on the hot path is the exact churn the
   residency layer exists to avoid;
2. **least load** — among those, the smallest live queue depth wins,
   read from the ``serve_service_ingress_depth`` /
   ``serve_service_queue_depth{replica=}`` gauges each replica
   publishes (the PR 11 in-process registry for same-process
   replicas; :func:`scrape_replica_state` reads the same series off
   a remote replica's ``/metrics`` endpoint);
3. **in-flight correction** — gauges update once per service tick,
   so within one routed wave the router adds its own just-assigned
   counts to each replica's depth estimate (otherwise a whole wave
   herds onto whichever replica's gauge was read first).

Admission control composes at both levels: a router-level
:class:`~brainiak_tpu.serve.federation.admission.
AdmissionController` sheds only when EVERY candidate replica is
over bound (one hot replica is a placement problem, not an overload
problem), resolving the ticket itself with the same typed
``shed_overload`` + ``retry_after_s`` record the service-level path
produces — every request still resolves exactly one ticket.
"""

import threading
import urllib.request

from ...obs import metrics as obs_metrics
from ..batching import ServeResult
from ..service import ServiceTicket

__all__ = ["LocalReplica", "Router", "scrape_replica_state"]


class LocalReplica:
    """One same-process replica behind the router: a named
    :class:`~brainiak_tpu.serve.service.ServeService` plus the
    read-only placement accessors the router needs."""

    def __init__(self, service, name=None):
        self.service = service
        self.name = name or service.name
        if not self.name:
            raise ValueError(
                "replica needs a name (ServeService(name=...)): "
                "unnamed replicas publish indistinguishable gauges")
        if service.name and name and service.name != name:
            raise ValueError(
                f"replica name {name!r} contradicts the service's "
                f"replica label {service.name!r}")

    def queue_depth(self):
        """Routed-but-undispatched depth from the replica's own
        gauges (at most one service tick stale)."""
        return self.service.queued_depth()

    def resident_models(self):
        return set(self.service.residency.resident_names())

    def registered_models(self):
        return set(self.service.residency.names())

    def submit_many(self, requests):
        return self.service.submit_many(requests)


class Router:
    """Residency- and depth-aware placement over N replicas (see
    module docstring).

    Parameters
    ----------
    replicas : sequence of :class:`LocalReplica` (or objects with
        the same accessor surface)
    admission : :class:`~brainiak_tpu.serve.federation.admission.
        AdmissionController`, optional
        Fleet-level load shedding: consulted with the MINIMUM
        candidate depth, so the router sheds only when no replica
        has room.
    """

    def __init__(self, replicas, admission=None):
        self.replicas = list(replicas)
        if not self.replicas:
            raise ValueError("Router needs >= 1 replica")
        names = [r.name for r in self.replicas]
        if len(set(names)) != len(names):
            raise ValueError(
                f"duplicate replica names: {sorted(names)}")
        self.admission = admission
        self._lock = threading.Lock()
        self._routed = {name: 0 for name in names}  # guarded-by: _lock
        self._n_shed = 0                            # guarded-by: _lock
        self._rr = 0                                # guarded-by: _lock

    # -- placement ----------------------------------------------------

    def _snapshot_models(self):
        """One read of every replica's registered/resident model
        sets (each is a residency-lock acquisition): taken once per
        routed wave, like the depth snapshot — never per request."""
        return ({r.name: r.registered_models()
                 for r in self.replicas},
                {r.name: r.resident_models()
                 for r in self.replicas})

    def place(self, model=None, depths=None, models=None):
        """The replica one request for ``model`` should land on
        (pure decision — no submission): resident-first, then least
        depth, round-robin tie-break.  ``depths`` overrides the
        live gauge reads and ``models`` the
        ``(registered, resident)`` snapshot — the per-wave
        estimates :meth:`submit_many` maintains."""
        if depths is None:
            depths = {r.name: r.queue_depth()
                      for r in self.replicas}
        registered_by, resident_by = (
            models if models is not None
            else self._snapshot_models())
        candidates = self.replicas
        if model is not None:
            registered = [r for r in self.replicas
                          if model in registered_by[r.name]]
            candidates = registered or candidates
        with self._lock:
            rr = self._rr
            self._rr += 1
        order = {r.name: (i - rr) % len(candidates)
                 for i, r in enumerate(candidates)}
        if model is not None:
            resident = {r.name: model in resident_by[r.name]
                        for r in candidates}
        else:
            resident = {r.name: True for r in candidates}
        return min(candidates,
                   key=lambda r: (not resident[r.name],
                                  depths.get(r.name, 0),
                                  order[r.name]))

    # -- submission ---------------------------------------------------

    def submit(self, request, model=None):
        """Route one request; returns its ticket (possibly already
        resolved with a shed record)."""
        return self.submit_many([request], model=model)[0]

    def submit_many(self, requests, model=None):
        """Route a wave: each request placed resident-first /
        least-depth with in-flight correction, then ONE atomic
        ``submit_many`` per replica (so each replica's bucket
        composition stays deterministic — the property the shared
        AOT warm-start rides on).  Returns one ticket per request
        in input order; shed tickets are already resolved."""
        requests = list(requests)
        depths = {r.name: r.queue_depth() for r in self.replicas}
        models = self._snapshot_models()
        by_name = {r.name: r for r in self.replicas}
        assigned = {r.name: [] for r in self.replicas}
        slots = [None] * len(requests)   # (replica name, index) | rec
        n_shed = 0
        for i, request in enumerate(requests):
            target = model or request.model
            if self.admission is not None:
                floor = min(depths.values())
                shed = self.admission.evaluate(floor)
                if shed is not None:
                    slots[i] = self._shed_ticket(request, target,
                                                 shed)
                    n_shed += 1
                    continue
            replica = self.place(target, depths=depths,
                                 models=models)
            # in-flight correction: the gauge will not move until
            # the replica's next tick, but this wave already did
            depths[replica.name] = depths.get(replica.name, 0) + 1
            slots[i] = (replica.name, len(assigned[replica.name]))
            assigned[replica.name].append(request)
        tickets_by_name = {
            name: by_name[name].submit_many(reqs) if reqs else []
            for name, reqs in assigned.items()}
        with self._lock:
            self._n_shed += n_shed
            for name, reqs in assigned.items():
                self._routed[name] += len(reqs)
        out = []
        for slot in slots:
            if isinstance(slot, ServiceTicket):
                out.append(slot)
            else:
                name, idx = slot
                out.append(tickets_by_name[name][idx])
        return out

    def _shed_ticket(self, request, model, shed):
        """Fleet-level shed: resolve a router-minted ticket with
        the typed record (same schema as the service-level path)."""
        ticket = ServiceTicket(request.request_id, model)
        ticket._resolve(ServeResult(
            request_id=request.request_id, ok=False,
            error="shed_overload",
            message=(f"router shed the request before placement "
                     f"({shed.reason}: every replica at depth >= "
                     f"{shed.bound}); retry after "
                     f"{shed.retry_after_s:.3f}s"),
            latency_s=0.0, retry_after_s=shed.retry_after_s))
        obs_metrics.counter(
            "serve_shed_total",
            help="requests shed by admission control before "
                 "enqueue").inc(reason=shed.reason,
                                replica="router")
        return ticket

    # -- reporting ----------------------------------------------------

    def summary(self):
        """Routed/shed counts per replica for the federation
        summaries and the SRV003 gate."""
        with self._lock:
            out = {"n_replicas": len(self.replicas),
                   "routed": dict(self._routed),
                   "n_shed": self._n_shed}
        if self.admission is not None:
            out["admission"] = self.admission.stats()
        return out


def scrape_replica_state(url, timeout=5.0):
    """One remote replica's placement signals off its ``/metrics``
    endpoint (:mod:`brainiak_tpu.obs.http`): the same
    ``serve_service_*`` / ``serve_resident_*`` series the in-process
    router reads from the registry, parsed with the in-repo
    Prometheus parser.  Returns ``{"queue_depth", "ingress_depth",
    "resident_bytes", "queue_by_model", "by_replica"}`` —
    ``by_replica`` splits the depth per replica label when the
    scraped process runs several.  This is the cross-process half of
    the placement contract: a front-end partitioning request files
    across ``serve service`` processes reads state here instead of
    guessing."""
    from ...obs.http import parse_prometheus_text

    target = url if "://" in url else f"http://{url}"
    with urllib.request.urlopen(
            target.rstrip("/") + "/metrics",
            timeout=timeout) as resp:
        text = resp.read().decode("utf-8")
    families, errors = parse_prometheus_text(text)
    if errors:
        raise ValueError(
            f"{target}/metrics is not valid Prometheus text: "
            f"{'; '.join(errors[:3])}")

    def samples(name):
        return families.get(name, {"samples": []})["samples"]

    out = {"queue_depth": 0.0, "ingress_depth": 0.0,
           "resident_bytes": 0.0, "queue_by_model": {},
           "by_replica": {}}
    for _, labels, value in samples("serve_service_ingress_depth"):
        out["ingress_depth"] += value
        rep = labels.get("replica", "")
        out["by_replica"].setdefault(rep, 0.0)
        out["by_replica"][rep] += value
    for _, labels, value in samples("serve_service_queue_depth"):
        out["queue_depth"] += value
        model = labels.get("model", "")
        out["queue_by_model"][model] = \
            out["queue_by_model"].get(model, 0.0) + value
        rep = labels.get("replica", "")
        out["by_replica"].setdefault(rep, 0.0)
        out["by_replica"][rep] += value
    for _, labels, value in samples("serve_resident_bytes"):
        out["resident_bytes"] += value
    out["queue_depth"] += out["ingress_depth"]
    return out
