"""Multi-replica request routing: place by residency, then by load.

One :class:`~brainiak_tpu.serve.service.ServeService` is one thread
in one process; the federation tier runs N of them — warm-started
off one shared :class:`~brainiak_tpu.serve.aot.AOTProgramCache`
(keys are content-addressed and platform-stamped, so replica 2..N
serve with zero retraces) — behind this thin router.  Placement
follows the DrJAX mapreduce discipline (arXiv:2403.07128): decide
from *observed* state, never by reaching into a replica's internals:

1. **residency first** — replicas where the target model is already
   resident beat replicas that would have to (re)admit it: an
   artifact load + upload on the hot path is the exact churn the
   residency layer exists to avoid;
2. **least load** — among those, the smallest live queue depth wins,
   read from the ``serve_service_ingress_depth`` /
   ``serve_service_queue_depth{replica=}`` gauges each replica
   publishes (the PR 11 in-process registry for same-process
   replicas; :func:`scrape_replica_state` reads the same series off
   a remote replica's ``/metrics`` endpoint);
3. **in-flight correction** — gauges update once per service tick,
   so within one routed wave the router adds its own just-assigned
   counts to each replica's depth estimate (otherwise a whole wave
   herds onto whichever replica's gauge was read first).

Admission control composes at both levels: a router-level
:class:`~brainiak_tpu.serve.federation.admission.
AdmissionController` sheds only when EVERY candidate replica is
over bound (one hot replica is a placement problem, not an overload
problem), resolving the ticket itself with the same typed
``shed_overload`` + ``retry_after_s`` record the service-level path
produces — every request still resolves exactly one ticket.
"""

import threading
import time
import urllib.request

from ...obs import metrics as obs_metrics
from ...obs import sink as obs_sink
from ...resilience.retry import retry
from ..batching import ServeResult
from ..service import ServiceTicket

__all__ = ["LocalReplica", "Router", "scrape_replica_state"]


class LocalReplica:
    """One same-process replica behind the router: a named
    :class:`~brainiak_tpu.serve.service.ServeService` plus the
    read-only placement accessors the router needs."""

    def __init__(self, service, name=None):
        self.service = service
        self.name = name or service.name
        if not self.name:
            raise ValueError(
                "replica needs a name (ServeService(name=...)): "
                "unnamed replicas publish indistinguishable gauges")
        if service.name and name and service.name != name:
            raise ValueError(
                f"replica name {name!r} contradicts the service's "
                f"replica label {service.name!r}")

    def queue_depth(self):
        """Routed-but-undispatched depth from the replica's own
        gauges (at most one service tick stale)."""
        return self.service.queued_depth()

    def resident_models(self):
        return set(self.service.residency.resident_names())

    def registered_models(self):
        return set(self.service.residency.names())

    def submit_many(self, requests):
        return self.service.submit_many(requests)


class Router:
    """Residency- and depth-aware placement over N replicas (see
    module docstring).

    Parameters
    ----------
    replicas : sequence of :class:`LocalReplica` (or objects with
        the same accessor surface)
    admission : :class:`~brainiak_tpu.serve.federation.admission.
        AdmissionController`, optional
        Fleet-level load shedding: consulted with the MINIMUM
        candidate depth, so the router sheds only when no replica
        has room.
    """

    def __init__(self, replicas, admission=None):
        # the membership list is COPY-ON-WRITE: add_replica /
        # remove_replica rebind it under _lock, and every wave
        # snapshots the reference once (`_membership`) — an
        # in-flight wave keeps routing over the membership it
        # started with
        self.replicas = list(replicas)  # guarded-by: _lock
        if not self.replicas:
            raise ValueError("Router needs >= 1 replica")
        names = [r.name for r in self.replicas]
        if len(set(names)) != len(names):
            raise ValueError(
                f"duplicate replica names: {sorted(names)}")
        self.admission = admission
        self._lock = threading.Lock()
        self._routed = {name: 0 for name in names}  # guarded-by: _lock
        self._n_shed = 0                            # guarded-by: _lock
        self._n_lost = 0                            # guarded-by: _lock
        self._n_failed_over = 0                     # guarded-by: _lock
        self._rr = 0                                # guarded-by: _lock

    # -- elastic membership -------------------------------------------

    def add_replica(self, replica):
        """Join a replica to the fleet (scale-up / failover
        re-placement target): visible to the NEXT wave — waves
        already in flight keep their membership snapshot.  Returns
        the replica."""
        with self._lock:
            if any(r.name == replica.name for r in self.replicas):
                raise ValueError(
                    f"replica {replica.name!r} already routed")
            self.replicas = self.replicas + [replica]
            self._routed.setdefault(replica.name, 0)
        return replica

    def remove_replica(self, name):
        """Detach a replica from the fleet (scale-down drain, or a
        death declared by the supervisor): no NEW wave will place on
        it.  Its routed history stays in :meth:`summary` (ledger
        continuity).  Removing the last replica is legal — a fleet
        can be all-dead; submission then raises until a replica
        joins.  Returns the detached replica."""
        with self._lock:
            target = next((r for r in self.replicas
                           if r.name == name), None)
            if target is None:
                raise KeyError(f"no replica named {name!r}")
            self.replicas = [r for r in self.replicas
                             if r.name != name]
        return target

    def _membership(self):
        """One locked read of the copy-on-write membership list —
        the per-wave snapshot: an in-flight wave keeps routing over
        the list reference it grabbed here, and a concurrent
        add/remove rebinds ``self.replicas`` for the NEXT wave."""
        with self._lock:
            return self.replicas

    # -- placement ----------------------------------------------------

    def _snapshot_models(self, replicas=None):
        """One read of every replica's registered/resident model
        sets (each is a residency-lock acquisition): taken once per
        routed wave, like the depth snapshot — never per request."""
        replicas = self._membership() if replicas is None else replicas
        return ({r.name: r.registered_models()
                 for r in replicas},
                {r.name: r.resident_models()
                 for r in replicas})

    def place(self, model=None, depths=None, models=None,
              replicas=None):
        """The replica one request for ``model`` should land on
        (pure decision — no submission): resident-first, then least
        depth, round-robin tie-break.  ``depths`` overrides the
        live gauge reads, ``models`` the
        ``(registered, resident)`` snapshot, and ``replicas`` the
        membership — the per-wave estimates :meth:`submit_many`
        maintains."""
        replicas = self._membership() if replicas is None else replicas
        if not replicas:
            raise RuntimeError(
                "no replicas to place on (the fleet is empty)")
        if depths is None:
            depths = {r.name: r.queue_depth() for r in replicas}
        registered_by, resident_by = (
            models if models is not None
            else self._snapshot_models(replicas))
        candidates = replicas
        if model is not None:
            registered = [r for r in replicas
                          if model in registered_by[r.name]]
            candidates = registered or candidates
        with self._lock:
            rr = self._rr
            self._rr += 1
        order = {r.name: (i - rr) % len(candidates)
                 for i, r in enumerate(candidates)}
        if model is not None:
            resident = {r.name: model in resident_by[r.name]
                        for r in candidates}
        else:
            resident = {r.name: True for r in candidates}
        return min(candidates,
                   key=lambda r: (not resident[r.name],
                                  depths.get(r.name, 0),
                                  order[r.name]))

    # -- submission ---------------------------------------------------

    def submit(self, request, model=None):
        """Route one request; returns its ticket (possibly already
        resolved with a shed record)."""
        return self.submit_many([request], model=model)[0]

    def submit_many(self, requests, model=None):
        """Route a wave: each request placed resident-first /
        least-depth with in-flight correction, then ONE atomic
        ``submit_many`` per replica (so each replica's bucket
        composition stays deterministic — the property the shared
        AOT warm-start rides on).  Returns one ticket per request
        in input order; shed tickets are already resolved."""
        requests = list(requests)
        # ONE membership snapshot per wave (copy-on-write list): a
        # concurrent add/remove affects the next wave, not this one
        replicas = self._membership()
        if not replicas:
            raise RuntimeError(
                "cannot route: the fleet has no replicas "
                "(all removed/dead; scale up first)")
        depths = {r.name: r.queue_depth() for r in replicas}
        models = self._snapshot_models(replicas)
        by_name = {r.name: r for r in replicas}
        assigned = {r.name: [] for r in replicas}
        slots = [None] * len(requests)   # (replica name, index) | rec
        n_shed = 0
        for i, request in enumerate(requests):
            target = model or request.model
            if self.admission is not None:
                floor = min(depths.values())
                shed = self.admission.evaluate(floor)
                if shed is not None:
                    slots[i] = self._shed_ticket(request, target,
                                                 shed)
                    n_shed += 1
                    continue
            replica = self.place(target, depths=depths,
                                 models=models, replicas=replicas)
            # in-flight correction: the gauge will not move until
            # the replica's next tick, but this wave already did
            depths[replica.name] = depths.get(replica.name, 0) + 1
            slots[i] = (replica.name, len(assigned[replica.name]))
            assigned[replica.name].append(request)
        tickets_by_name = {
            name: by_name[name].submit_many(reqs) if reqs else []
            for name, reqs in assigned.items()}
        with self._lock:
            self._n_shed += n_shed
            for name, reqs in assigned.items():
                self._routed[name] += len(reqs)
        out = []
        for slot in slots:
            if isinstance(slot, ServiceTicket):
                out.append(slot)
            else:
                name, idx = slot
                out.append(tickets_by_name[name][idx])
        return out

    def _shed_ticket(self, request, model, shed):
        """Fleet-level shed: resolve a router-minted ticket with
        the typed record (same schema as the service-level path)."""
        ticket = ServiceTicket(request.request_id, model)
        ticket._resolve(ServeResult(
            request_id=request.request_id, ok=False,
            error="shed_overload",
            message=(f"router shed the request before placement "
                     f"({shed.reason}: every replica at depth >= "
                     f"{shed.bound}); retry after "
                     f"{shed.retry_after_s:.3f}s"),
            latency_s=0.0, retry_after_s=shed.retry_after_s))
        obs_metrics.counter(
            "serve_shed_total",
            help="requests shed by admission control before "
                 "enqueue").inc(reason=shed.reason,
                                replica="router")
        return ticket

    # -- failover -----------------------------------------------------

    def failover(self, work, source=None, now=None):
        """Re-place a dead replica's un-delivered work onto the
        survivors.

        ``work`` is the ``(model, request, ticket)`` triples
        harvested from the dead replica
        (:meth:`~brainiak_tpu.serve.service.ServeService.
        unresolved_work`).  Requests already past their deadline —
        and every request when no survivor remains — resolve their
        (original, caller-held) tickets with typed ``replica_lost``
        records: an accounted loss, never a silent one.  The rest
        are re-submitted as ONE router wave (atomic ``submit_many``
        per survivor, deterministic bucket composition), each fresh
        ticket chained back to the original so the caller's wait
        resolves when the survivor delivers — the
        exactly-one-ticket-per-request invariant holds throughout
        (a re-placed request that the admission controller sheds
        resolves the original ticket with the shed record through
        the same chain).  Deadlines keep counting from the ORIGINAL
        enqueue: ``request.submitted`` is preserved across the
        re-placement.

        Returns ``{"n_replaced", "n_lost"}``."""
        now = time.monotonic() if now is None else now
        survivors = self._membership()
        lost, replace = [], []
        for name, request, ticket in work:
            if ticket.done():
                continue
            if not survivors or request.expired(now):
                lost.append((name, request, ticket))
            else:
                replace.append((name, request, ticket))
        for name, request, ticket in lost:
            reason = ("no_survivors" if not survivors
                      else "deadline")
            self._lost_ticket(request, name, ticket,
                              source=source, reason=reason)
        if replace:
            for name, request, _ in replace:
                # the harvest knows the resolved target model even
                # when the request rode a service default — pin it
                # so the re-placement wave routes identically
                if request.model is None:
                    request.model = name
            fresh = self.submit_many(
                [request for _, request, _ in replace])
            for (_, _, ticket), new_ticket in zip(replace, fresh):
                new_ticket._chain(ticket)
        with self._lock:
            self._n_lost += len(lost)
            self._n_failed_over += len(replace)
        obs_metrics.counter(
            "serve_failover_total",
            help="requests re-placed onto survivors after a "
                 "replica death").inc(len(replace),
                                      replica=source or "unknown")
        obs_sink.event("failover", replica=source,
                       n_replaced=len(replace), n_lost=len(lost))
        return {"n_replaced": len(replace), "n_lost": len(lost)}

    def _lost_ticket(self, request, model, ticket, source=None,
                     reason="deadline"):
        """Resolve one caller-held ticket with the typed
        ``replica_lost`` record (same shape discipline as the shed
        record: a structured loss, never an exception or silence)."""
        latency = None
        if request.submitted is not None:
            latency = time.monotonic() - request.submitted
        ticket._resolve(ServeResult(
            request_id=request.request_id, ok=False,
            error="replica_lost",
            message=(f"replica {source or '<unknown>'} died before "
                     f"serving the request and it was not "
                     f"re-placed ({reason}); resubmit with a fresh "
                     f"deadline"),
            latency_s=latency))
        obs_metrics.counter(
            "serve_replica_lost_total",
            help="requests lost with a replica death (past "
                 "deadline or no survivors)").inc(
                replica=source or "unknown", reason=reason)

    # -- reporting ----------------------------------------------------

    def summary(self):
        """Routed/shed/failover counts per replica for the
        federation summaries and the SRV003/SRV004 gates."""
        with self._lock:
            out = {"n_replicas": len(self.replicas),
                   "routed": dict(self._routed),
                   "n_shed": self._n_shed,
                   "n_lost": self._n_lost,
                   "n_failed_over": self._n_failed_over}
        if self.admission is not None:
            out["admission"] = self.admission.stats()
        return out


def scrape_replica_state(url, timeout=2.0, retries=2,
                         backoff=0.05):
    """One remote replica's placement signals off its ``/metrics``
    endpoint (:mod:`brainiak_tpu.obs.http`): the same
    ``serve_service_*`` / ``serve_resident_*`` series the in-process
    router reads from the registry, parsed with the in-repo
    Prometheus parser.  Returns ``{"state", "queue_depth",
    "ingress_depth", "resident_bytes", "queue_by_model",
    "by_replica"}`` — ``by_replica`` splits the depth per replica
    label when the scraped process runs several.  This is the
    cross-process half of the placement contract: a front-end
    partitioning request files across ``serve service`` processes
    reads state here instead of guessing.

    The fetch is wired through :func:`~brainiak_tpu.resilience.
    retry.retry` with a bounded PER-ATTEMPT ``timeout``: a hung or
    dead remote endpoint costs at most ``(retries + 1) * timeout``
    plus backoff, then the call returns a typed
    ``state="unreachable"`` dict (zeroed signals plus the final
    error) instead of raising — so a supervisor probing the fleet
    degrades the replica and moves on, never stalls.  Malformed
    Prometheus text still raises ``ValueError``: that is a bug on
    the replica, not a transient reachability failure."""
    from ...obs.http import parse_prometheus_text

    target = url if "://" in url else f"http://{url}"

    def fetch():
        with urllib.request.urlopen(
                target.rstrip("/") + "/metrics",
                timeout=timeout) as resp:
            return resp.read().decode("utf-8")

    try:
        text = retry(fetch, retries=retries, backoff=backoff,
                     retriable=(OSError,),
                     name="scrape_replica_state")()
    except OSError as exc:
        return {"state": "unreachable",
                "error": f"{type(exc).__name__}: {exc}",
                "queue_depth": 0.0, "ingress_depth": 0.0,
                "resident_bytes": 0.0, "queue_by_model": {},
                "by_replica": {}}
    families, errors = parse_prometheus_text(text)
    if errors:
        raise ValueError(
            f"{target}/metrics is not valid Prometheus text: "
            f"{'; '.join(errors[:3])}")

    def samples(name):
        return families.get(name, {"samples": []})["samples"]

    out = {"state": "ok", "queue_depth": 0.0,
           "ingress_depth": 0.0, "resident_bytes": 0.0,
           "queue_by_model": {}, "by_replica": {}}
    for _, labels, value in samples("serve_service_ingress_depth"):
        out["ingress_depth"] += value
        rep = labels.get("replica", "")
        out["by_replica"].setdefault(rep, 0.0)
        out["by_replica"][rep] += value
    for _, labels, value in samples("serve_service_queue_depth"):
        out["queue_depth"] += value
        model = labels.get("model", "")
        out["queue_by_model"][model] = \
            out["queue_by_model"].get(model, 0.0) + value
        rep = labels.get("replica", "")
        out["by_replica"].setdefault(rep, 0.0)
        out["by_replica"][rep] += value
    for _, labels, value in samples("serve_resident_bytes"):
        out["resident_bytes"] += value
    out["queue_depth"] += out["ingress_depth"]
    return out
