"""SRV003 selfcheck: the federation plane, end to end in one child.

The ``federation`` gate of ``tools/run_checks.py`` runs
:func:`selfcheck` in a child pinned to the 8-device CPU mesh (the
same harness as the distla/encoding/kernels/data gates) and
verifies, with one JSON verdict line:

- **sharded serving** — a demo SRM whose ``model_nbytes`` exceeds
  one device's budget auto-admits SHARDED over the mesh, serves a
  mixed wave with bit-level parity against the host reference
  (``W_iᵀ x``), and its per-device residency accounting charges
  every mesh device at most the budget;
- **router placement** — two named in-process replicas behind a
  :class:`~brainiak_tpu.serve.federation.router.Router` both take
  traffic from one mixed wave, and every ticket resolves ok;
- **load shedding** — with a fleet-level
  :class:`~brainiak_tpu.serve.federation.admission.
  AdmissionController` and a burst wave over the bound, sheds fire
  (typed ``shed_overload`` records carrying ``retry_after_s > 0``),
  every shed request still resolves exactly one ticket, and every
  ADMITTED request still serves ok;
- **retrace stability** — a repeat serving pass rebuilds no
  ``serve.srm_sharded`` program (counted like every other gate).

Exit 0 on success, 1 with the verdict naming what failed.
"""

import json

__all__ = ["selfcheck"]


def selfcheck(out=None):
    """Run the federation check (see module docstring); returns
    the process exit code."""
    import sys

    import numpy as np

    from ...obs import metrics as obs_metrics
    from ...parallel.mesh import make_mesh
    from .. import artifacts
    from ..__main__ import build_demo_model
    from ..batching import BucketPolicy
    from ..residency import ModelResidency
    from ..service import ServeService
    from .admission import AdmissionController
    from .router import LocalReplica, Router
    from .traffic import TrafficGenerator

    stream = out or sys.stdout
    verdict = {"ok": False}
    policy = BucketPolicy(max_batch=8, max_wait_s=0.01)
    try:
        import jax
        n_dev = len(jax.devices())
        mesh = make_mesh(("voxel",), (n_dev,))
        verdict["n_devices"] = n_dev

        # -- sharded serving: over one device's budget ------------
        model = build_demo_model(n_subjects=3, voxels=96,
                                 samples=48, features=8, n_iter=3)
        nbytes = artifacts.model_nbytes(model)
        budget = max(int(nbytes * 0.6),
                     artifacts.model_shard_nbytes(
                         model, n_dev)[0]
                     + artifacts.model_shard_nbytes(
                         model, n_dev)[1] + 1)
        res = ModelResidency(budget_bytes=budget, policy=policy,
                             mesh=mesh)
        res.register("big", model=model)
        gen = TrafficGenerator(model, model_name="big", seed=0)
        errs = []
        retrace = obs_metrics.counter("retrace_total")
        reqs = gen.requests(12, prefix="s")
        with ServeService(res, default_model="big",
                          name="shard0") as svc:
            for pass_no in range(2):
                for req in reqs:  # identical mix both passes
                    req.submitted = None
                records = [t.result(timeout=120.0)
                           for t in svc.submit_many(reqs)]
                for req, rec in zip(reqs, records):
                    if not rec.ok:
                        raise RuntimeError(
                            f"sharded serve failed: {rec.error}: "
                            f"{rec.message}")
                    want = np.asarray(
                        model.w_[req.subject]).T @ np.asarray(req.x)
                    errs.append(float(np.max(np.abs(
                        np.asarray(rec.result) - want))))
                if pass_no == 0:
                    # pass 2 replays the same shapes: any further
                    # compile is a per-call retrace bug
                    warm_retraces = retrace.value(
                        site="serve.srm_sharded")
            stats = res.stats()
        verdict["sharded"] = stats["sharded"]
        verdict["max_err"] = max(errs)
        verdict["tol"] = 1e-4
        per_device = stats["per_device"]
        verdict["per_device_ok"] = bool(
            len(per_device) == n_dev
            and all(0 < b <= budget for b in per_device.values()))
        verdict["per_device"] = per_device
        sharded_ok = (stats["sharded"] == ["big"]
                      and nbytes > budget
                      and verdict["per_device_ok"]
                      and verdict["max_err"] < verdict["tol"])

        # -- router placement: two replicas, one mixed wave -------
        small = build_demo_model(n_subjects=2, voxels=24,
                                 samples=20, features=4, n_iter=2)
        gen2 = TrafficGenerator(small, model_name="demo", seed=1)

        def replica(name):
            r = ModelResidency(budget_bytes=1 << 30, policy=policy)
            r.register("demo", model=small)
            return LocalReplica(ServeService(
                r, default_model="demo", name=name).start())

        r1, r2 = replica("r1"), replica("r2")
        router = Router([r1, r2])
        try:
            tickets = router.submit_many(gen2.requests(16,
                                                       prefix="w"))
            records = [t.result(timeout=120.0) for t in tickets]
            routed = router.summary()["routed"]
            verdict["routed"] = routed
            routed_ok = (all(rec.ok for rec in records)
                         and all(v > 0 for v in routed.values()))

            # -- load shedding: burst over the fleet bound --------
            shed_router = Router(
                [r1, r2],
                admission=AdmissionController(max_depth=4,
                                              retry_after_s=0.02))
            burst = gen2.requests(24, prefix="b")
            tickets = shed_router.submit_many(burst)
            records = [t.result(timeout=120.0) for t in tickets]
            sheds = [rec for rec in records
                     if rec.error == "shed_overload"]
            served = [rec for rec in records if rec.ok]
            verdict["n_shed"] = len(sheds)
            verdict["n_served"] = len(served)
            verdict["all_resolved"] = len(records) == len(burst)
            verdict["retry_after_ok"] = bool(
                sheds and all((rec.retry_after_s or 0) > 0
                              for rec in sheds))
            shed_ok = (verdict["all_resolved"]
                       and verdict["retry_after_ok"]
                       and len(sheds) + len(served) == len(burst))
        finally:
            r1.service.shutdown()
            r2.service.shutdown()

        # retrace stability: the second sharded pass replayed the
        # first's exact shapes, so the counter must not have moved
        # (the per-repeat-rebuild contract every gate enforces);
        # report a normalized "grew vs warm" count so the shared
        # gate harness classifies it like any other site
        final = retrace.value(site="serve.srm_sharded")
        sites = {"serve.srm_sharded":
                 1.0 + max(0.0, final - warm_retraces)}
        verdict["retraces"] = sites
        verdict["ok"] = bool(sharded_ok and routed_ok and shed_ok
                             and final == warm_retraces
                             and final > 0)
    except Exception as exc:  # the gate wants a verdict, not a trace
        verdict["error"] = f"{type(exc).__name__}: {exc}"
    json.dump(verdict, stream)
    stream.write("\n")
    return 0 if verdict["ok"] else 1
