"""Persisted-model registry: one save/load surface for every servable
estimator.

The fit path persists *training state* (``resilience`` checkpoints);
this module persists *fitted models* — the deployment artifact a
serving process loads.  One uniform, versioned npz schema replaces the
per-estimator ad-hoc formats (``funcalign.srm.SRM.save`` /
``funcalign.srm.load`` being the only one that existed):

- every artifact carries ``serve_kind`` (the adapter that wrote it)
  and ``serve_schema_version`` (:data:`SCHEMA_VERSION`);
- all payload arrays are plain numpy arrays — the file loads with
  ``allow_pickle=False``.  Ragged per-subject lists (mixed voxel
  counts) are stored under indexed keys (``w_.0``, ``w_.1``, ...)
  with an ``w_.n`` count, never as object arrays, so the
  pickle-disabled load the reference's ``srm.load`` promises actually
  holds for EVERY artifact;
- the one exception is the FCMA :class:`~brainiak_tpu.fcma.Classifier`
  adapter, whose wrapped sklearn estimator has no array-only form: it
  is embedded as a pickle byte payload inside a uint8 array, opted
  into explicitly at load time (``np.load`` itself still runs with
  pickle disabled — only the clearly-labeled ``clf_pickle`` bytes go
  through ``pickle.loads``).  Load FCMA artifacts only from trusted
  stores.

Loading is wired through :func:`brainiak_tpu.resilience.retry`: a
shared-filesystem read that races a preemption retries with backoff
instead of killing the serving process.

Round-trip fidelity is bit-exact: adapters store the fitted arrays
verbatim (no re-quantization, no recompute on load), so
``load_model(save_model(m, f)).transform(X)`` equals
``m.transform(X)`` to the last bit — acceptance-tested per adapter in
``tests/serve/test_artifacts.py``.
"""

import io
import logging
import os
import pickle

import numpy as np

from ..resilience.retry import retry

logger = logging.getLogger(__name__)

__all__ = [
    "ADAPTERS",
    "KIND_KEY",
    "SCHEMA_VERSION",
    "SHARDED_KINDS",
    "VERSION_KEY",
    "detect_kind",
    "load_model",
    "model_digest",
    "model_nbytes",
    "model_shard_nbytes",
    "save_model",
    "save_model_bytes",
]

#: Bump on any backwards-incompatible change to an adapter's key set.
#: Loaders reject artifacts stamped with a NEWER version than they
#: understand (an old server must not half-read a new artifact).
SCHEMA_VERSION = 1

KIND_KEY = "serve_kind"
VERSION_KEY = "serve_schema_version"


# -- npz packing helpers ----------------------------------------------

def _put_list(out, key, arrays):
    """Store a list of (possibly ragged) arrays under indexed keys."""
    out[f"{key}.n"] = np.asarray(len(arrays))
    for i, arr in enumerate(arrays):
        out[f"{key}.{i}"] = np.asarray(arr)


def _get_list(z, key):
    n = int(z[f"{key}.n"])
    return [np.asarray(z[f"{key}.{i}"]) for i in range(n)]


def _put_scalar(out, key, value):
    out[key] = np.asarray(value)


def _scalar(z, key):
    """A 0-d npz entry back as a Python scalar (or str)."""
    val = np.asarray(z[key])
    if val.dtype.kind in "US":
        return str(val)
    return val.item()


def _maybe(out, key, value):
    """Store ``value`` unless it is None (optional keys are absent)."""
    if value is not None:
        out[key] = np.asarray(value)


# -- adapter protocol -------------------------------------------------

class ModelAdapter:
    """One estimator's mapping to/from the artifact schema.

    Subclasses set ``kind`` (the schema tag) and implement
    ``model_class`` (resolved lazily — importing an adapter must not
    import every estimator), ``pack(model) -> {key: array}`` and
    ``unpack(arrays) -> model``.
    """

    kind = None

    def model_class(self):
        raise NotImplementedError

    def matches(self, model):
        # exact-type match: DetSRM must not be claimed by the SRM
        # adapter (and vice versa) through a shared base class
        return type(model) is self.model_class()

    def pack(self, model):
        raise NotImplementedError

    def unpack(self, arrays):
        raise NotImplementedError

    @staticmethod
    def _fitted(model, *attrs):
        missing = [a for a in attrs if not hasattr(model, a)]
        if missing:
            raise ValueError(
                f"model is not fitted: missing {', '.join(missing)}")


class SRMAdapter(ModelAdapter):
    """Probabilistic SRM — subsumes the ad-hoc ``SRM.save``/``load``
    pair (and unlike it, stays pickle-free for mixed voxel counts)."""

    kind = "srm"

    def model_class(self):
        from ..funcalign.srm import SRM
        return SRM

    def pack(self, model):
        self._fitted(model, "w_", "s_", "sigma_s_", "mu_", "rho2_")
        out = {}
        _put_list(out, "w_", model.w_)
        _put_list(out, "mu_", model.mu_)
        out["s_"] = np.asarray(model.s_)
        out["sigma_s_"] = np.asarray(model.sigma_s_)
        out["rho2_"] = np.asarray(model.rho2_)
        _maybe(out, "logprob_", getattr(model, "logprob_", None))
        _put_scalar(out, "features", model.features)
        _put_scalar(out, "n_iter", model.n_iter)
        _put_scalar(out, "rand_seed", model.rand_seed)
        return out

    def unpack(self, z):
        model = self.model_class()(
            n_iter=_scalar(z, "n_iter"),
            features=_scalar(z, "features"),
            rand_seed=_scalar(z, "rand_seed"))
        model.w_ = _get_list(z, "w_")
        model.mu_ = _get_list(z, "mu_")
        model.s_ = np.asarray(z["s_"])
        model.sigma_s_ = np.asarray(z["sigma_s_"])
        model.rho2_ = np.asarray(z["rho2_"])
        if "logprob_" in z:
            model.logprob_ = _scalar(z, "logprob_")
        return model


class DetSRMAdapter(ModelAdapter):
    kind = "detsrm"

    def model_class(self):
        from ..funcalign.srm import DetSRM
        return DetSRM

    def pack(self, model):
        self._fitted(model, "w_", "s_")
        out = {}
        _put_list(out, "w_", model.w_)
        out["s_"] = np.asarray(model.s_)
        _maybe(out, "objective_", getattr(model, "objective_", None))
        _put_scalar(out, "features", model.features)
        _put_scalar(out, "n_iter", model.n_iter)
        _put_scalar(out, "rand_seed", model.rand_seed)
        return out

    def unpack(self, z):
        model = self.model_class()(
            n_iter=_scalar(z, "n_iter"),
            features=_scalar(z, "features"),
            rand_seed=_scalar(z, "rand_seed"))
        model.w_ = _get_list(z, "w_")
        model.s_ = np.asarray(z["s_"])
        if "objective_" in z:
            model.objective_ = _scalar(z, "objective_")
        return model


class RSRMAdapter(ModelAdapter):
    kind = "rsrm"

    def model_class(self):
        from ..funcalign.rsrm import RSRM
        return RSRM

    def pack(self, model):
        self._fitted(model, "w_", "r_", "s_")
        out = {}
        _put_list(out, "w_", model.w_)
        _put_list(out, "s_", model.s_)
        out["r_"] = np.asarray(model.r_)
        _maybe(out, "objective_", getattr(model, "objective_", None))
        _put_scalar(out, "features", model.features)
        _put_scalar(out, "gamma", model.gamma)
        _put_scalar(out, "n_iter", model.n_iter)
        _put_scalar(out, "rand_seed", model.rand_seed)
        return out

    def unpack(self, z):
        model = self.model_class()(
            n_iter=_scalar(z, "n_iter"),
            features=_scalar(z, "features"),
            gamma=_scalar(z, "gamma"),
            rand_seed=_scalar(z, "rand_seed"))
        model.w_ = _get_list(z, "w_")
        model.s_ = _get_list(z, "s_")
        model.r_ = np.asarray(z["r_"])
        if "objective_" in z:
            model.objective_ = _scalar(z, "objective_")
        return model


class EventSegmentAdapter(ModelAdapter):
    """Event patterns + variance — the ``find_events``/``predict``
    surface.  ``step_var`` (a callable) is not persisted: inference on
    held-out scans uses the annealed ``event_var_`` the fit landed
    on, exactly as :meth:`EventSegment.find_events` does."""

    kind = "eventseg"

    def model_class(self):
        from ..eventseg.event import EventSegment
        return EventSegment

    def pack(self, model):
        self._fitted(model, "event_pat_", "event_var_")
        out = {
            "event_pat_": np.asarray(model.event_pat_),
            "event_var_": np.asarray(model.event_var_),
            "event_chains": np.asarray(model.event_chains),
        }
        _put_scalar(out, "n_events", model.n_events)
        _maybe(out, "ll_", getattr(model, "ll_", None))
        return out

    def unpack(self, z):
        model = self.model_class()(
            n_events=_scalar(z, "n_events"),
            event_chains=np.asarray(z["event_chains"]))
        model.event_pat_ = np.asarray(z["event_pat_"])
        var = np.asarray(z["event_var_"])
        # a scalar variance round-trips as the Python float the fit
        # stored (find_events broadcasts either form identically)
        model.event_var_ = var.item() if var.ndim == 0 else var
        if "ll_" in z:
            model.ll_ = np.asarray(z["ll_"])
        model.classes_ = np.arange(model.n_events)
        return model


class IEM1DAdapter(ModelAdapter):
    kind = "iem1d"

    def model_class(self):
        from ..reconstruct.iem import InvertedEncoding1D
        return InvertedEncoding1D

    def pack(self, model):
        self._fitted(model, "W_", "channels_", "channel_centers_")
        out = {
            "W_": np.asarray(model.W_),
            "channels_": np.asarray(model.channels_),
            "channel_centers_": np.asarray(model.channel_centers_),
        }
        _put_scalar(out, "n_channels", model.n_channels)
        _put_scalar(out, "channel_exp", model.channel_exp)
        _put_scalar(out, "stimulus_mode", model.stimulus_mode)
        _put_scalar(out, "range_start", model.range_start)
        _put_scalar(out, "range_stop", model.range_stop)
        _put_scalar(out, "channel_density", model.channel_density)
        _put_scalar(out, "stim_res", model.stim_res)
        return out

    def unpack(self, z):
        model = self.model_class()(
            n_channels=_scalar(z, "n_channels"),
            channel_exp=_scalar(z, "channel_exp"),
            stimulus_mode=_scalar(z, "stimulus_mode"),
            range_start=_scalar(z, "range_start"),
            range_stop=_scalar(z, "range_stop"),
            channel_density=_scalar(z, "channel_density"),
            stimulus_resolution=_scalar(z, "stim_res"))
        model.W_ = np.asarray(z["W_"])
        model.channels_ = np.asarray(z["channels_"])
        model.channel_centers_ = np.asarray(z["channel_centers_"])
        return model


class IEM2DAdapter(ModelAdapter):
    kind = "iem2d"

    def model_class(self):
        from ..reconstruct.iem import InvertedEncoding2D
        return InvertedEncoding2D

    def pack(self, model):
        self._fitted(model, "W_")
        if model.channels is None:
            raise ValueError("model has no channel basis defined")
        out = {
            "W_": np.asarray(model.W_),
            "channels": np.asarray(model.channels),
            "stim_fov": np.asarray(model.stim_fov),
            "stim_resolution": np.asarray(
                [len(model.stim_pixels[0]), len(model.stim_pixels[1])]),
            "channel_limits": np.asarray(model.channel_limits),
        }
        _put_scalar(out, "channel_exp", model.channel_exp)
        _maybe(out, "stim_radius_px", model.stim_radius_px)
        return out

    def unpack(self, z):
        fov = np.asarray(z["stim_fov"])
        res = np.asarray(z["stim_resolution"])
        limits = np.asarray(z["channel_limits"])
        radius = _scalar(z, "stim_radius_px") \
            if "stim_radius_px" in z else None
        model = self.model_class()(
            stim_xlim=list(fov[0]), stim_ylim=list(fov[1]),
            stimulus_resolution=[int(res[0]), int(res[1])],
            stim_radius=radius,
            chan_xlim=list(limits[0]), chan_ylim=list(limits[1]),
            channels=np.asarray(z["channels"]),
            channel_exp=_scalar(z, "channel_exp"))
        model.W_ = np.asarray(z["W_"])
        return model


class RidgeEncodingAdapter(ModelAdapter):
    """Voxel-wise encoding models
    (:class:`brainiak_tpu.encoding.RidgeEncoder` and its banded
    subclass, distinguished by the ``banded`` flag): the deployment
    surface is the affine map ``predict`` applies — coefficients,
    preprocessing parameters — plus the CV-selected per-voxel
    lambdas as provenance.  ``cv_scores_`` (the full [L, V] sweep
    matrix) is deliberately NOT persisted: it is fit diagnostics,
    not a serving input, and can dominate the artifact size."""

    kind = "ridge_encoding"

    def model_class(self):
        from ..encoding.ridge import RidgeEncoder
        return RidgeEncoder

    def _banded_class(self):
        from ..encoding.ridge import BandedRidgeEncoder
        return BandedRidgeEncoder

    def matches(self, model):
        return type(model) in (self.model_class(),
                               self._banded_class())

    def pack(self, model):
        self._fitted(model, "W_", "lambda_", "x_mean_", "x_scale_",
                     "y_mean_", "lambdas_")
        banded = type(model) is self._banded_class()
        out = {
            "W_": np.asarray(model.W_),
            "lambda_": np.asarray(model.lambda_),
            "x_mean_": np.asarray(model.x_mean_),
            "x_scale_": np.asarray(model.x_scale_),
            "y_mean_": np.asarray(model.y_mean_),
            "lambdas_": np.asarray(model.lambdas_),
        }
        _put_scalar(out, "banded", banded)
        _put_scalar(out, "n_folds", model.n_folds)
        _put_scalar(out, "fit_intercept", model.fit_intercept)
        _put_scalar(out, "standardize", model.standardize)
        if banded:
            out["bands"] = np.asarray(model.bands)
            out["candidates_"] = np.asarray(model.candidates_)
        return out

    def unpack(self, z):
        lambdas = np.asarray(z["lambdas_"])
        kwargs = dict(lambdas=tuple(float(x) for x in lambdas),
                      n_folds=_scalar(z, "n_folds"),
                      fit_intercept=bool(_scalar(z, "fit_intercept")),
                      standardize=bool(_scalar(z, "standardize")))
        if bool(_scalar(z, "banded")):
            model = self._banded_class()(
                bands=np.asarray(z["bands"]),
                candidates=np.asarray(z["candidates_"]), **kwargs)
            model.candidates_ = np.asarray(z["candidates_"])
        else:
            model = self.model_class()(**kwargs)
        model.W_ = np.asarray(z["W_"])
        model.lambda_ = np.asarray(z["lambda_"])
        model.x_mean_ = np.asarray(z["x_mean_"])
        model.x_scale_ = np.asarray(z["x_scale_"])
        model.y_mean_ = np.asarray(z["y_mean_"])
        model.lambdas_ = lambdas
        return model


class FCMAClassifierAdapter(ModelAdapter):
    """FCMA correlation classifier.  The wrapped sklearn estimator is
    stored as labeled pickle bytes (see the module docstring's trust
    caveat); everything else is plain arrays."""

    kind = "fcma"

    def model_class(self):
        from ..fcma.classifier import Classifier
        return Classifier

    def pack(self, model):
        self._fitted(model, "num_voxels_", "num_features_",
                     "num_samples_")
        out = {
            "clf_pickle": np.frombuffer(
                pickle.dumps(model.clf), dtype=np.uint8),
        }
        _put_scalar(out, "num_processed_voxels",
                    model.num_processed_voxels)
        _put_scalar(out, "epochs_per_subj", model.epochs_per_subj)
        _put_scalar(out, "use_pallas", bool(model.use_pallas))
        _put_scalar(out, "num_digits_", model.num_digits_)
        _put_scalar(out, "num_voxels_", model.num_voxels_)
        _put_scalar(out, "num_features_", model.num_features_)
        _put_scalar(out, "num_samples_", model.num_samples_)
        _maybe(out, "training_data_",
               getattr(model, "training_data_", None))
        return out

    def unpack(self, z):
        clf = pickle.loads(np.asarray(z["clf_pickle"]).tobytes())
        model = self.model_class()(
            clf,
            num_processed_voxels=_scalar(z, "num_processed_voxels"),
            epochs_per_subj=_scalar(z, "epochs_per_subj"),
            use_pallas=bool(_scalar(z, "use_pallas")))
        model.num_digits_ = _scalar(z, "num_digits_")
        model.num_voxels_ = _scalar(z, "num_voxels_")
        model.num_features_ = _scalar(z, "num_features_")
        model.num_samples_ = _scalar(z, "num_samples_")
        model.training_data_ = (
            np.asarray(z["training_data_"])
            if "training_data_" in z else None)
        model.test_raw_data_ = None
        model.test_data_ = None
        return model


class NullDistributionAdapter(ModelAdapter):
    """Resampling-null summary (:class:`brainiak_tpu.stats.engine.
    NullDistribution`) — the servable significance artifact.

    Persists provenance (family, statistic, seed, side, exact), the
    observed statistic, the FULL mergeable accumulator state (the
    exact wire format of :meth:`NullAccumulator.to_state`, under
    ``acc.``-prefixed keys), and the precomputed threshold table.
    The materialized ``[n_total, V]`` distribution is deliberately
    NOT persisted: the accumulator reproduces p-values bit-for-bit
    from integer counts and its size is independent of
    ``n_resamples`` — that O(K * V) bound is what makes population-
    scale nulls a deployable artifact at all."""

    kind = "null_distribution"

    def model_class(self):
        from ..stats.engine import NullDistribution
        return NullDistribution

    def pack(self, model):
        self._fitted(model, "accumulator", "observed")
        if model.accumulator is None:
            raise ValueError("model is not fitted: accumulator is None")
        out = {}
        _put_scalar(out, "family", model.family)
        _put_scalar(out, "statistic",
                    "" if model.statistic is None
                    else str(model.statistic))
        # -1 encodes "no seed" (seeds are validated non-negative by
        # the isc wrappers' _resolve_seed)
        _put_scalar(out, "seed",
                    -1 if model.seed is None else int(model.seed))
        _put_scalar(out, "side", model.side)
        _put_scalar(out, "exact", bool(model.exact))
        out["observed"] = np.asarray(model.observed)
        for key, arr in model.accumulator.to_state().items():
            out[f"acc.{key}"] = np.asarray(arr)
        keys = sorted(model.thresholds)
        out["thr_keys"] = np.asarray(keys)
        out["thr_values"] = np.asarray(
            [float(model.thresholds[k]) for k in keys])
        return out

    def unpack(self, z):
        from ..stats.accum import NullAccumulator
        from ..stats.engine import NullDistribution
        state = {key[len("acc."):]: np.asarray(z[key])
                 for key in z if key.startswith("acc.")}
        seed = int(_scalar(z, "seed"))
        thresholds = {
            str(k): float(v)
            for k, v in zip(np.asarray(z["thr_keys"]).tolist(),
                            np.asarray(z["thr_values"]).tolist())}
        return NullDistribution(
            family=_scalar(z, "family"),
            statistic=_scalar(z, "statistic") or None,
            seed=None if seed < 0 else seed,
            side=_scalar(z, "side"),
            exact=bool(_scalar(z, "exact")),
            observed=np.asarray(z["observed"]),
            accumulator=NullAccumulator.from_state(state),
            thresholds=thresholds)


#: kind -> adapter instance, in dispatch order.
ADAPTERS = {a.kind: a for a in (
    SRMAdapter(), DetSRMAdapter(), RSRMAdapter(),
    EventSegmentAdapter(), IEM1DAdapter(), IEM2DAdapter(),
    RidgeEncodingAdapter(), FCMAClassifierAdapter(),
    NullDistributionAdapter())}


def detect_kind(model):
    """The artifact ``kind`` serving this model, or raise TypeError."""
    for kind, adapter in ADAPTERS.items():
        if adapter.matches(model):
            return kind
    raise TypeError(
        f"no serve adapter registered for {type(model).__name__} "
        f"(known kinds: {', '.join(ADAPTERS)})")


def save_model(model, file):
    """Persist a fitted model as a versioned npz artifact.

    ``file`` is a path or file-like object; returns ``file``.  The
    adapter is selected by the model's type (:func:`detect_kind`).
    """
    kind = detect_kind(model)
    arrays = ADAPTERS[kind].pack(model)
    for key in (KIND_KEY, VERSION_KEY):
        if key in arrays:  # pragma: no cover - adapter authoring bug
            raise ValueError(f"adapter {kind} may not write {key}")
    arrays[KIND_KEY] = np.asarray(kind)
    arrays[VERSION_KEY] = np.asarray(SCHEMA_VERSION)
    if isinstance(file, (str, os.PathLike)):
        # np.savez_compressed appends ".npz" to extensionless paths
        # behind the caller's back; normalize up front so the
        # returned path is the one actually written and
        # load_model(save_model(m, f)) round-trips for any f
        file = os.fspath(file)
        if not file.endswith(".npz"):
            file += ".npz"
    np.savez_compressed(file, **arrays)
    return file


@retry(name="serve.load_model",
       retry_if=lambda exc: not isinstance(
           exc, (FileNotFoundError, IsADirectoryError,
                 NotADirectoryError)))
def _read_arrays(file):
    """All npz entries materialized under the retry guard, so a
    transient shared-filesystem fault on ANY member read retries the
    whole load (NpzFile reads members lazily).  File-like inputs are
    rewound at the top of every attempt — a retry after a partial
    read must not resume mid-stream."""
    seek = getattr(file, "seek", None)
    if callable(seek):
        try:
            seek(0)
        except (OSError, ValueError):
            pass  # non-seekable stream: first attempt still works
    with np.load(file, allow_pickle=False) as z:
        return {key: np.asarray(z[key]) for key in z.files}


def load_model(file):
    """Load a model artifact written by :func:`save_model`.

    The read retries transient ``OSError`` with exponential backoff
    (:func:`brainiak_tpu.resilience.retry`); deterministic path
    errors (missing file, directory-in-the-way) and schema
    violations — missing kind, unknown kind, newer schema version —
    raise immediately (retrying cannot fix a bad path or artifact).
    """
    arrays = _read_arrays(file)
    if KIND_KEY not in arrays or VERSION_KEY not in arrays:
        raise ValueError(
            f"{file!r} is not a serve artifact (missing "
            f"{KIND_KEY}/{VERSION_KEY}; wrote with save_model?)")
    kind = str(arrays[KIND_KEY])
    version = int(arrays[VERSION_KEY])
    if version > SCHEMA_VERSION:
        # checked BEFORE any adapter unpack: a future artifact must
        # fail with this message, never a KeyError mid-decode
        raise ValueError(
            f"unsupported schema version: artifact is v{version}, "
            f"newer than this loader understands "
            f"(v{SCHEMA_VERSION}); upgrade brainiak_tpu")
    adapter = ADAPTERS.get(kind)
    if adapter is None:
        raise ValueError(
            f"unknown artifact kind {kind!r} "
            f"(known: {', '.join(ADAPTERS)})")
    model = adapter.unpack(arrays)
    logger.info("loaded %s artifact (schema v%d) from %r",
                kind, version, file)
    return model


def save_model_bytes(model):
    """The artifact as bytes (for object stores without a filesystem
    path); :func:`load_model` accepts the ``io.BytesIO`` round-trip."""
    buf = io.BytesIO()
    save_model(model, buf)
    return buf.getvalue()


def model_digest(model):
    """Stable content hash of a fitted model's artifact surface.

    sha256 over the adapter's packed arrays (key names, dtypes,
    shapes, raw bytes) plus the kind and schema version — the same
    surface :func:`save_model` persists, so a save/load round trip
    (bit-exact by contract) keeps the digest, while any refit that
    changes a fitted array changes it.  Used as the artifact half of
    the :mod:`~brainiak_tpu.serve.aot` cache key.
    """
    import hashlib

    kind = detect_kind(model)
    arrays = ADAPTERS[kind].pack(model)
    h = hashlib.sha256()
    h.update(f"{kind}|{SCHEMA_VERSION}".encode())
    for key in sorted(arrays):
        arr = np.ascontiguousarray(np.asarray(arrays[key]))
        h.update(f"|{key}|{arr.dtype}|{arr.shape}|".encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def model_nbytes(model):
    """Byte size of a fitted model's artifact surface (sum of the
    packed arrays' ``nbytes``) — the admission weight
    :class:`~brainiak_tpu.serve.residency.ModelResidency` charges
    against its HBM budget.  An estimate by construction: the engine
    uploads (a padded stack of) these arrays to the device, so the
    packed size tracks device residency without touching the
    backend."""
    kind = detect_kind(model)
    arrays = ADAPTERS[kind].pack(model)
    return int(sum(np.asarray(a).nbytes for a in arrays.values()))


# -- sharded layouts (serving federation) -----------------------------
#
# Which packed keys partition over the mesh when a model is served
# SHARDED (brainiak_tpu/serve/federation): the voxel-dimensioned
# weights split (the engine's sharded programs consume one voxel
# shard per device), everything else — shared-space statistics,
# per-feature preprocessing, scalars — replicates.  Adding a kind
# here requires a matching sharded program in serve/engine.py.

def _list_keys(prefix):
    """Predicate for indexed ragged-list keys (``w_.0``, ``w_.1``,
    ...; the ``.n`` count entry is bookkeeping, not payload)."""
    return lambda key: key.startswith(prefix + ".") \
        and not key.endswith(".n")


_SHARDED_KEYS = {
    # per-subject voxel maps shard over their voxel rows
    "srm": _list_keys("w_"),
    "detsrm": _list_keys("w_"),
    # voxel-wise encoding surface shards over its voxel columns
    "ridge_encoding": lambda key: key in ("W_", "y_mean_",
                                          "lambda_"),
}

#: Artifact kinds the engine can serve sharded over a device mesh.
SHARDED_KINDS = frozenset(_SHARDED_KEYS)


def model_shard_nbytes(model, n_shards):
    """The per-device byte layout of a model served sharded over
    ``n_shards`` devices: ``(per_shard_bytes, replicated_bytes)``.

    ``per_shard_bytes`` is the ceil-divided slice of the shardable
    arrays (:data:`SHARDED_KINDS` — the voxel-dimensioned weights);
    ``replicated_bytes`` is everything else, which every device
    holds whole.  Each device is charged
    ``per_shard_bytes + replicated_bytes`` by the per-device
    residency accounting, so a model over one device's budget
    admits exactly when its largest shard fits."""
    kind = detect_kind(model)
    shardable = _SHARDED_KEYS.get(kind)
    if shardable is None:
        raise ValueError(
            f"kind {kind!r} has no sharded serve layout "
            f"(shardable: {', '.join(sorted(SHARDED_KINDS))})")
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    arrays = ADAPTERS[kind].pack(model)
    sharded = replicated = 0
    for key, arr in arrays.items():
        nbytes = int(np.asarray(arr).nbytes)
        if shardable(key):
            sharded += nbytes
        else:
            replicated += nbytes
    return -(-sharded // int(n_shards)), replicated
