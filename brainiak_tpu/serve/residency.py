"""HBM-aware multi-model residency: many models, per-device budgets.

A production serving process answers for MANY fitted models (one
encoding model per individual in the arXiv:2403.19421 setting), but
HBM is finite: loading every artifact eagerly OOMs, and loading per
request pays artifact I/O + upload on the hot path.
:class:`ModelResidency` is the middle ground — a byte-weighted LRU
of loaded (model, engine) pairs under an explicit **per-device**
budget:

- **admission** — :meth:`acquire` loads a registered artifact on
  first use and charges its packed byte size
  (:func:`~brainiak_tpu.serve.artifacts.model_nbytes`) against the
  budget of the device(s) it lands on, evicting least-recently-used
  unpinned residents of the constrained device until it fits; a
  model that cannot fit even after evicting everything evictable
  raises the **typed** :class:`AdmissionError` — the refusal
  happens at admission time in Python, never as a device OOM
  mid-batch;
- **per-device accounting** (the federation tier of ROADMAP item
  3) — the budget is PER DEVICE, not one global pool: an unsharded
  model is placed on the least-loaded device and charges only that
  device; a **sharded** model (see below) charges every mesh device
  its per-shard slice (:func:`~brainiak_tpu.serve.artifacts.
  model_shard_nbytes`), and eviction decisions name the device that
  is actually over budget;
- **sharded-model serving** — with a ``mesh=``, a model whose packed
  bytes exceed one device's budget (or one registered with
  ``sharded=True``) is served through the engine's device-sharded
  programs (:mod:`~brainiak_tpu.serve.engine`, the
  :mod:`~brainiak_tpu.ops.distla` idiom): weights partitioned over
  the mesh axes, per-device residency charged per shard, answers
  bit-identical to the unsharded path (zero padding is exact);
- **pinning** — ``register(..., pinned=True)`` exempts a model from
  eviction (the always-hot tier); pinned bytes still count against
  the budget, so over-pinning surfaces as ``AdmissionError`` at the
  next admission, not as silent thrash;
- **transparent re-admission** — eviction drops the resident entry
  (the engine and its device arrays), but the registration (source
  path / loader) stays, so the next :meth:`acquire` reloads and
  re-admits without the caller noticing anything but latency (the
  AOT cache of :mod:`~brainiak_tpu.serve.aot` keeps even that
  reload compile-free).

The default budget comes from the device — the smallest device's
``bytes_limit`` from
:func:`brainiak_tpu.obs.runtime.device_memory_snapshot` (the same
stats the PR 4 memory-watermark gauges read), scaled by
:data:`DEFAULT_BUDGET_FRACTION` to leave headroom for batch buffers
— with the ``BRAINIAK_TPU_SERVE_BUDGET_BYTES`` env override winning
and a conservative constant fallback on backends without memory
stats (CPU).

Telemetry: ``serve_resident_models`` / ``serve_resident_bytes``
gauges track occupancy (plus ``serve_resident_device_bytes{device=}``
per mesh device), ``serve_evictions_total{model=}`` counts victims,
and every eviction emits an ``eviction`` event naming the victim,
its bytes, and the admission that displaced it.
"""

import dataclasses
import logging
import os
import threading
import time
from typing import Any, Optional

from ..obs import metrics as obs_metrics
from ..obs import sink as obs_sink
from ..obs.runtime import device_memory_snapshot
from . import artifacts
from .engine import InferenceEngine

logger = logging.getLogger(__name__)

__all__ = [
    "AdmissionError",
    "BUDGET_ENV",
    "DEFAULT_BUDGET_BYTES",
    "DEFAULT_BUDGET_FRACTION",
    "ModelResidency",
    "ResidentModel",
    "default_budget_bytes",
]

BUDGET_ENV = "BRAINIAK_TPU_SERVE_BUDGET_BYTES"

#: Fallback budget on backends without ``memory_stats`` (CPU): big
#: enough that single-host test serving never thrashes, small enough
#: to be an honest stand-in for one accelerator's HBM.
DEFAULT_BUDGET_BYTES = 8 << 30

#: Fraction of the smallest device's ``bytes_limit`` granted to
#: model residency; the rest is headroom for padded batch buffers
#: and XLA scratch.
DEFAULT_BUDGET_FRACTION = 0.8


def default_budget_bytes():
    """The residency byte budget: the ``BRAINIAK_TPU_SERVE_BUDGET_
    BYTES`` env override, else :data:`DEFAULT_BUDGET_FRACTION` of
    the smallest device's ``bytes_limit``
    (:func:`~brainiak_tpu.obs.runtime.device_memory_snapshot`), else
    :data:`DEFAULT_BUDGET_BYTES` when the backend exposes no memory
    stats (CPU) or jax is not initialized."""
    raw = os.environ.get(BUDGET_ENV)
    if raw:
        try:
            return int(raw)
        except ValueError:
            # a malformed override must name itself, not surface as
            # a bare `int()` traceback deep inside admission
            raise ValueError(
                f"{BUDGET_ENV} must be an integer byte count, "
                f"got {raw!r}") from None
    limits = [d["bytes_limit"]
              for d in device_memory_snapshot(emit=False)
              if "bytes_limit" in d]
    if limits:
        return int(min(limits) * DEFAULT_BUDGET_FRACTION)
    return DEFAULT_BUDGET_BYTES


def _device_label(dev):
    """Stable string form of an accounting device slot (a jax
    Device's repr, or the label verbatim)."""
    return str(dev)


def _is_jax_device(dev):
    """A real backend device (an engine placement target) vs a
    planning label — duck-typed so no jax import is needed."""
    return hasattr(dev, "platform") and hasattr(dev, "id")


class AdmissionError(RuntimeError):
    """A model could not be admitted under the byte budget — the
    typed, pre-device refusal the serving layer returns instead of
    an OOM.  Carries the sizing facts a capacity dashboard needs;
    ``device`` names the constrained mesh device when the refusal is
    per-device (the federation accounting)."""

    def __init__(self, name, needed, budget, resident, pinned,
                 device=None):
        self.model = name
        self.needed_bytes = int(needed)
        self.budget_bytes = int(budget)
        self.resident_bytes = int(resident)
        self.pinned_bytes = int(pinned)
        self.device = device
        where = f" on device {device}" if device is not None else ""
        super().__init__(
            f"cannot admit model {name!r}{where}: needs "
            f"{self.needed_bytes} bytes against a "
            f"{self.budget_bytes}-byte per-device budget with "
            f"{self.pinned_bytes} bytes pinned "
            f"({self.resident_bytes} resident) — raise the budget, "
            "unpin a model, shard it over a mesh, or shrink the "
            "artifact")


@dataclasses.dataclass
class _Registration:
    """How to (re)load one named model: a filesystem source (path or
    loader callable) or a held instance."""

    name: str
    source: Optional[Any] = None   # path or callable -> model
    model: Optional[Any] = None    # held instance (host memory)
    kind: Optional[str] = None
    pinned: bool = False
    #: None = decide at admission (shard iff the model exceeds one
    #: device's budget, a mesh is attached, and the kind has a
    #: sharded serve program); True/False force either way.
    sharded: Optional[bool] = None
    admissions: int = 0            # lifetime admits (re-admits too)
    nbytes: Optional[int] = None   # learned at first load
    digest: Optional[str] = None   # learned at first AOT admit

    def load(self):
        if self.model is not None:
            return self.model
        if callable(self.source):
            return self.source()
        return artifacts.load_model(self.source)


@dataclasses.dataclass
class ResidentModel:
    """One admitted model: the loaded estimator, its engine, and the
    accounting the LRU runs on.  ``device_nbytes`` maps each device
    this entry occupies to the bytes it charges there — one entry
    for an unsharded model, one per mesh device for a sharded one."""

    name: str
    model: Any
    engine: InferenceEngine
    nbytes: int
    pinned: bool = False
    last_used: float = 0.0
    admissions: int = 1
    sharded: bool = False
    device_nbytes: dict = dataclasses.field(default_factory=dict)

    def touch(self):
        self.last_used = time.monotonic()


class ModelResidency:
    """Byte-weighted LRU of loaded models with pinning, accounted
    per device.

    Parameters
    ----------
    budget_bytes : int, optional
        Admission budget **per device**; default
        :func:`default_budget_bytes` (itself derived from the
        smallest device's HBM).  On a single-device backend this is
        exactly the pre-federation global-pool behavior.
    policy : :class:`~brainiak_tpu.serve.batching.BucketPolicy`,
        optional
        Shared by every engine this residency constructs.
    aot : :class:`~brainiak_tpu.serve.aot.AOTProgramCache` or str,
        optional
        Threaded into every engine, so evict/re-admit cycles and
        process restarts stay compile-free.  Engines serving a
        SHARDED model bypass the cache (their programs close over
        the mesh and are not portable across device counts).
    mesh : :class:`jax.sharding.Mesh`, optional
        Enables sharded-model serving: a model over one device's
        budget whose kind has a sharded serve program
        (:data:`~brainiak_tpu.serve.artifacts.SHARDED_KINDS`) is
        partitioned over ALL mesh axes (the
        :mod:`~brainiak_tpu.ops.distla` flattened-ring idiom) and
        charged per device.
    devices : sequence, optional
        The accounting device slots (default: the mesh's devices,
        else ``jax.devices()``, resolved lazily so an explicit
        budget never initializes a backend at construction).  Any
        hashable labels are accepted — capacity planning and tests
        can model a fleet without touching the backend; engine
        placement only happens for real ``jax.Device`` entries.

    The registry/LRU bookkeeping is guarded by one reentrant lock
    (``register()`` is legal from any thread while the service loop
    runs), but the ENGINES this residency hands out remain
    single-caller: only the
    :class:`~brainiak_tpu.serve.service.ServeService` loop may
    drive them (the same contract as the engine).  The lock is
    reentrant because admission evicts: ``acquire -> _make_room ->
    evict`` re-enters.
    """

    def __init__(self, budget_bytes=None, policy=None, aot=None,
                 mesh=None, devices=None):
        self.budget_bytes = int(budget_bytes
                                if budget_bytes is not None
                                else default_budget_bytes())
        if self.budget_bytes <= 0:
            raise ValueError(
                f"budget_bytes must be positive, got "
                f"{self.budget_bytes}")
        self.policy = policy
        self.mesh = mesh
        if aot is not None:
            from . import aot as aot_mod
            if not isinstance(aot, aot_mod.AOTProgramCache):
                aot = aot_mod.AOTProgramCache(aot)
        self.aot = aot
        self._lock = threading.RLock()
        self._devices = (list(devices) if devices is not None
                         else None)  # guarded-by: _lock
        self._registry = {}    # guarded-by: _lock
        self._resident = {}    # guarded-by: _lock
        self._n_evictions = 0  # guarded-by: _lock
        #: optional ``fn(name, records)`` called with the error
        #: records of work stranded on an evicted engine — the
        #: service loop installs its delivery path here so evicted
        #: queues resolve their tickets instead of vanishing
        self.on_evict_records = None
        #: optional ``fn(entry)`` called for EVERY eviction with
        #: the dying :class:`ResidentModel` (before it is dropped)
        #: — the service accrues the engine's batch/padding stats
        #: here so summary metrics survive residency churn
        self.on_evict = None

    # -- registration -------------------------------------------------

    def register(self, name, source=None, model=None, kind=None,
                 pinned=False, sharded=None):
        """Register a named model without loading it.

        Exactly one of ``source`` (artifact path, or a zero-arg
        loader callable) and ``model`` (a fitted instance; host
        memory is the caller's — eviction then only frees the
        engine's device arrays) must be given.  ``pinned`` models
        are never evicted.  ``sharded`` forces mesh-sharded serving
        (True), forbids it (False), or leaves the decision to
        admission (None, the default: shard exactly when the model
        exceeds one device's budget and the mesh + kind allow it).
        Returns ``name``."""
        if (source is None) == (model is None):
            raise ValueError(
                "register() takes exactly one of source= / model=")
        if sharded and self.mesh is None:
            raise ValueError(
                f"model {name!r} registered sharded=True but the "
                "residency has no mesh")
        with self._lock:
            if name in self._registry:
                raise ValueError(
                    f"model {name!r} already registered")
            self._registry[name] = _Registration(
                name=name, source=source, model=model, kind=kind,
                pinned=bool(pinned),
                sharded=None if sharded is None else bool(sharded))
        return name

    def names(self):
        """Registered model names (resident or not)."""
        with self._lock:
            return sorted(self._registry)

    def devices(self):
        """The accounting device slots, resolved lazily: explicit
        ``devices=``, else the mesh's devices, else
        ``jax.devices()`` (deferred so an explicitly-budgeted
        residency never initializes a backend at construction)."""
        with self._lock:
            if self._devices is None:
                if self.mesh is not None:
                    self._devices = [d for d in
                                     self.mesh.devices.flat]
                else:
                    import jax
                    self._devices = list(jax.devices())
            return list(self._devices)

    def resident_names(self):
        with self._lock:
            return sorted(self._resident)

    def entries(self):
        """The live :class:`ResidentModel` entries, name-sorted."""
        with self._lock:
            return [self._resident[name]
                    for name in sorted(self._resident)]

    # -- the LRU ------------------------------------------------------

    def acquire(self, name):
        """The live :class:`ResidentModel` for ``name``, loading and
        admitting it first if necessary (the transparent-re-admission
        path).  Raises ``KeyError`` for an unregistered name and
        :class:`AdmissionError` when it cannot fit."""
        with self._lock:
            entry = self._resident.get(name)
            if entry is not None:
                entry.touch()
                return entry
            reg = self._registry.get(name)
            if reg is None:
                raise KeyError(
                    f"model {name!r} is not registered "
                    f"(known: "
                    f"{', '.join(sorted(self._registry)) or 'none'})")
            # a size learned on a PRIOR load makes an over-budget
            # model refuse in O(1): a request stream aimed at an
            # inadmissible artifact must not re-read it from disk
            # on every route (a model the mesh could still shard is
            # not refused here — the decision needs the layout)
            if reg.nbytes is not None and \
                    reg.nbytes > self.budget_bytes and \
                    not self._may_shard(reg):
                raise AdmissionError(
                    reg.name, reg.nbytes, self.budget_bytes,
                    self.resident_bytes(), self.pinned_bytes())
        # artifact I/O and digest hashing run OUTSIDE the lock: a
        # multi-GB load must not block register()/stats() callers
        # (same principle as the aot ledger lock); a racing double
        # load is benign — the re-check below keeps one winner
        model = reg.load()
        nbytes = artifacts.model_nbytes(model)
        kind = reg.kind or artifacts.detect_kind(model)
        sharded = reg.sharded
        if sharded is None:
            sharded = (self.mesh is not None
                       and kind in artifacts.SHARDED_KINDS
                       and nbytes > self.budget_bytes)
        per_device = None
        if sharded:
            if kind not in artifacts.SHARDED_KINDS:
                raise ValueError(
                    f"model {name!r} (kind {kind!r}) has no "
                    "sharded serve program (shardable: "
                    f"{', '.join(sorted(artifacts.SHARDED_KINDS))})")
            shard_bytes, replicated = artifacts.model_shard_nbytes(
                model, int(self.mesh.devices.size))
            per_device = shard_bytes + replicated
        # the digest cannot change between admissions of the same
        # registration (bit-exact load contract): hash once, not on
        # every evict/re-admit cycle of a GB artifact.  Sharded
        # engines bypass the AOT cache, so they skip the hash too.
        digest = reg.digest
        if self.aot is not None and digest is None and not sharded:
            digest = artifacts.model_digest(model)
        with self._lock:
            entry = self._resident.get(name)
            if entry is None:
                reg.nbytes = nbytes
                reg.kind = kind
                reg.digest = digest
                entry = self._admit(reg, model, nbytes,
                                    sharded=sharded,
                                    per_device=per_device)
            entry.touch()
            return entry

    def _may_shard(self, reg):  # requires-lock: _lock
        """Whether an over-budget registration could still admit
        through the sharded path (kind unknown = maybe)."""
        if self.mesh is None or reg.sharded is False:
            return False
        return reg.kind is None or reg.kind in artifacts.SHARDED_KINDS

    def _admit(self, reg, model, nbytes, sharded=False,
               per_device=None):  # requires-lock: _lock
        if sharded:
            device_nbytes = {dev: int(per_device)
                             for dev in self.devices()}
        else:
            dev = self._place_device(nbytes)
            device_nbytes = {dev: int(nbytes)}
        self._make_room(reg.name, device_nbytes)
        device = None
        if not sharded:
            dev = next(iter(device_nbytes))
            device = dev if _is_jax_device(dev) else None
        engine = InferenceEngine(
            model, kind=reg.kind, policy=self.policy,
            # sharded programs close over the mesh (not portable
            # across device counts) and are excluded from AOT
            # persistence, same as the host-delegated fcma kind
            aot=None if sharded else self.aot,
            digest=reg.digest,
            mesh=self.mesh if sharded else None,
            device=device)
        reg.admissions += 1
        entry = ResidentModel(
            name=reg.name, model=model, engine=engine,
            nbytes=nbytes, pinned=reg.pinned,
            last_used=time.monotonic(),
            admissions=reg.admissions, sharded=sharded,
            device_nbytes=device_nbytes)
        self._resident[reg.name] = entry
        self._gauge()
        return entry

    def _place_device(self, nbytes):  # requires-lock: _lock
        """Least-loaded device for an unsharded admission: prefer
        a device where the model fits without evicting anyone,
        else one where evicting unpinned residents CAN make room
        (pinned bytes are immovable — placing on a pinned-full
        device would refuse a model another device could admit),
        else fall back least-loaded so ``_make_room`` raises the
        typed refusal naming that device.  ``min`` is stable, so
        ties resolve to the first device in slot order —
        deterministic placement."""
        occ = self._device_bytes_locked()
        devs = self.devices()
        free = [d for d in devs
                if occ.get(d, 0) + nbytes <= self.budget_bytes]
        evictable = [d for d in devs
                     if self._pinned_device_bytes(d) + nbytes
                     <= self.budget_bytes]
        return min(free or evictable or devs,
                   key=lambda d: occ.get(d, 0))

    def _make_room(self, incoming,
                   device_nbytes):  # requires-lock: _lock
        """Evict LRU unpinned residents OF EACH over-budget device
        until the incoming per-device charges fit; the typed refusal
        when even that is not enough.  Eviction frees every device a
        victim occupies, so evicting for one constrained device
        never strands partial accounting on another."""
        for dev, need in device_nbytes.items():
            if need > self.budget_bytes:
                raise AdmissionError(
                    incoming, need, self.budget_bytes,
                    self.resident_bytes(), self.pinned_bytes(),
                    device=_device_label(dev))
        while True:
            occ = self._device_bytes_locked()
            over = next(
                (dev for dev, need in device_nbytes.items()
                 if occ.get(dev, 0) + need > self.budget_bytes),
                None)
            if over is None:
                return
            victims = sorted(
                (e for e in self._resident.values()
                 if not e.pinned and e.name != incoming
                 and over in e.device_nbytes),
                key=lambda e: e.last_used)
            if not victims:
                raise AdmissionError(
                    incoming, device_nbytes[over],
                    self.budget_bytes, occ.get(over, 0),
                    self._pinned_device_bytes(over),
                    device=_device_label(over))
            self.evict(victims[0].name,
                       reason=f"admission of {incoming!r}")

    def evict(self, name, reason="manual"):
        """Drop a resident model (engine + device arrays); the
        registration survives so the next :meth:`acquire` re-admits.
        Pinned models refuse with ``ValueError``.  Queued work on
        the evicted engine is failed with ``evicted`` records and
        returned (the service loop delivers them)."""
        with self._lock:
            entry = self._resident.get(name)
            if entry is None:
                raise KeyError(f"model {name!r} is not resident")
            if entry.pinned:
                raise ValueError(f"model {name!r} is pinned")
            entry.engine.fail_pending(
                "evicted", "model was evicted while the request "
                           "was queued; resubmit")
            records = entry.engine.drain()
            if records and self.on_evict_records is not None:
                self.on_evict_records(name, records)
            if self.on_evict is not None:
                self.on_evict(entry)
            del self._resident[name]
            self._n_evictions += 1
            self._gauge()
        # telemetry outside the lock: sink writes are file I/O and
        # must not serialize admission on a slow disk
        obs_metrics.counter(
            "serve_evictions_total",
            help="models evicted from residency").inc(model=name)
        obs_sink.event("eviction", model=name,
                       nbytes=entry.nbytes, reason=reason,
                       admissions=entry.admissions)
        logger.info("evicted model %r (%d bytes, %s)", name,
                    entry.nbytes, reason)
        return records

    def reshard(self, mesh=None, devices=None):
        """Swap the accounting device set (a fleet-elasticity event:
        the mesh grew, shrank, or moved): EVERY resident entry is
        dropped — pinned included, a pin protects against capacity
        eviction, not against the devices changing under it — and
        the new ``mesh``/``devices`` are installed, so the next
        :meth:`acquire` re-admits each model with per-shard charges
        computed over the NEW device count
        (:func:`~brainiak_tpu.serve.artifacts.model_shard_nbytes`).

        ``mesh=None`` keeps the current mesh; ``devices=None``
        re-resolves the slots lazily (mesh devices, else
        ``jax.devices()``).  Queued work on dropped engines is
        failed with typed ``resharded`` records through the usual
        eviction delivery hooks — never silently lost.  Returns the
        names of the models that were re-laid-out."""
        with self._lock:
            dropped = sorted(self._resident)
            for name in dropped:
                entry = self._resident[name]
                entry.engine.fail_pending(
                    "resharded",
                    "model was re-laid-out over a new device set "
                    "while the request was queued; resubmit")
                records = entry.engine.drain()
                if records and self.on_evict_records is not None:
                    self.on_evict_records(name, records)
                if self.on_evict is not None:
                    self.on_evict(entry)
                del self._resident[name]
                self._n_evictions += 1
            # zero the OLD per-device occupancy series first: a
            # shrunk device set must not leave stale bytes on
            # /metrics (only when the slots were ever resolved)
            old_devices = (list(self._devices)
                           if self._devices is not None else [])
            if old_devices:
                gauge = obs_metrics.gauge(
                    "serve_resident_device_bytes", unit="bytes",
                    help="resident model bytes charged per device")
                for dev in old_devices:
                    gauge.set(0, device=_device_label(dev))
            if mesh is not None:
                self.mesh = mesh
            self._devices = (list(devices)
                             if devices is not None else None)
            self._gauge()
        # telemetry outside the lock (same discipline as evict)
        for name in dropped:
            obs_metrics.counter(
                "serve_reshard_total",
                help="models re-laid-out by a device-set "
                     "change").inc(model=name)
        # device count reported without resolving lazy slots (that
        # could initialize a backend from a planning-only caller)
        n_devices = (len(devices) if devices is not None
                     else int(mesh.devices.size)
                     if mesh is not None else None)
        obs_sink.event("reshard", models=dropped,
                       n_devices=n_devices)
        return dropped

    # -- accounting ---------------------------------------------------

    def resident_bytes(self):
        with self._lock:
            return sum(e.nbytes for e in self._resident.values())

    def pinned_bytes(self):
        with self._lock:
            return sum(e.nbytes for e in self._resident.values()
                       if e.pinned)

    def device_bytes(self):
        """``{device label: resident bytes}`` — the per-device
        occupancy the router and capacity dashboards read."""
        with self._lock:
            occ = self._device_bytes_locked()
            return {_device_label(dev): occ.get(dev, 0)
                    for dev in self.devices()}

    def _device_bytes_locked(self):  # requires-lock: _lock
        occ = {}
        for entry in self._resident.values():
            for dev, nbytes in entry.device_nbytes.items():
                occ[dev] = occ.get(dev, 0) + nbytes
        return occ

    def _pinned_device_bytes(self, dev):  # requires-lock: _lock
        return sum(e.device_nbytes.get(dev, 0)
                   for e in self._resident.values() if e.pinned)

    def _gauge(self):  # requires-lock: _lock
        obs_metrics.gauge(
            "serve_resident_models",
            help="models currently resident").set(
                len(self._resident))
        obs_metrics.gauge(
            "serve_resident_bytes", unit="bytes").set(
                self.resident_bytes())
        occ = self._device_bytes_locked()
        # per-device occupancy only once accounting touched a
        # device: an idle residency must not initialize a backend
        # just to publish zeros
        if occ or self._devices is not None:
            gauge = obs_metrics.gauge(
                "serve_resident_device_bytes", unit="bytes",
                help="resident model bytes charged per device")
            for dev in self.devices():
                gauge.set(occ.get(dev, 0),
                          device=_device_label(dev))

    def stats(self):
        """Occupancy + churn for the service summary."""
        with self._lock:
            per_device = {}
            if self._resident or self._devices is not None:
                occ = self._device_bytes_locked()
                per_device = {_device_label(dev): occ.get(dev, 0)
                              for dev in self.devices()}
            return {
                "budget_bytes": self.budget_bytes,
                "resident_bytes": self.resident_bytes(),
                "pinned_bytes": self.pinned_bytes(),
                "per_device": per_device,
                "sharded": sorted(
                    e.name for e in self._resident.values()
                    if e.sharded),
                "n_registered": len(self._registry),
                "n_resident": len(self._resident),
                "resident": self.resident_names(),
                "evictions": self._n_evictions,
                "admissions": {
                    name: r.admissions
                    for name, r in sorted(self._registry.items())
                    if r.admissions},
            }
