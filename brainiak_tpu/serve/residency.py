"""HBM-aware multi-model residency: many models, one byte budget.

A production serving process answers for MANY fitted models (one
encoding model per individual in the arXiv:2403.19421 setting), but
HBM is finite: loading every artifact eagerly OOMs, and loading per
request pays artifact I/O + upload on the hot path.
:class:`ModelResidency` is the middle ground — a byte-weighted LRU
of loaded (model, engine) pairs under an explicit budget:

- **admission** — :meth:`acquire` loads a registered artifact on
  first use and charges its packed byte size
  (:func:`~brainiak_tpu.serve.artifacts.model_nbytes`) against the
  budget, evicting least-recently-used unpinned residents until it
  fits; a model that cannot fit even after evicting everything
  evictable raises the **typed** :class:`AdmissionError` — the
  refusal happens at admission time in Python, never as a device
  OOM mid-batch;
- **pinning** — ``register(..., pinned=True)`` exempts a model from
  eviction (the always-hot tier); pinned bytes still count against
  the budget, so over-pinning surfaces as ``AdmissionError`` at the
  next admission, not as silent thrash;
- **transparent re-admission** — eviction drops the resident entry
  (the engine and its device arrays), but the registration (source
  path / loader) stays, so the next :meth:`acquire` reloads and
  re-admits without the caller noticing anything but latency (the
  AOT cache of :mod:`~brainiak_tpu.serve.aot` keeps even that
  reload compile-free).

The default budget comes from the device — the smallest device's
``bytes_limit`` from
:func:`brainiak_tpu.obs.runtime.device_memory_snapshot` (the same
stats the PR 4 memory-watermark gauges read), scaled by
:data:`DEFAULT_BUDGET_FRACTION` to leave headroom for batch buffers
— with the ``BRAINIAK_TPU_SERVE_BUDGET_BYTES`` env override winning
and a conservative constant fallback on backends without memory
stats (CPU).

Telemetry: ``serve_resident_models`` / ``serve_resident_bytes``
gauges track occupancy, ``serve_evictions_total{model=}`` counts
victims, and every eviction emits an ``eviction`` event naming the
victim, its bytes, and the admission that displaced it.
"""

import dataclasses
import logging
import os
import threading
import time
from typing import Any, Optional

from ..obs import metrics as obs_metrics
from ..obs import sink as obs_sink
from ..obs.runtime import device_memory_snapshot
from . import artifacts
from .engine import InferenceEngine

logger = logging.getLogger(__name__)

__all__ = [
    "AdmissionError",
    "BUDGET_ENV",
    "DEFAULT_BUDGET_BYTES",
    "DEFAULT_BUDGET_FRACTION",
    "ModelResidency",
    "ResidentModel",
    "default_budget_bytes",
]

BUDGET_ENV = "BRAINIAK_TPU_SERVE_BUDGET_BYTES"

#: Fallback budget on backends without ``memory_stats`` (CPU): big
#: enough that single-host test serving never thrashes, small enough
#: to be an honest stand-in for one accelerator's HBM.
DEFAULT_BUDGET_BYTES = 8 << 30

#: Fraction of the smallest device's ``bytes_limit`` granted to
#: model residency; the rest is headroom for padded batch buffers
#: and XLA scratch.
DEFAULT_BUDGET_FRACTION = 0.8


def default_budget_bytes():
    """The residency byte budget: the ``BRAINIAK_TPU_SERVE_BUDGET_
    BYTES`` env override, else :data:`DEFAULT_BUDGET_FRACTION` of
    the smallest device's ``bytes_limit``
    (:func:`~brainiak_tpu.obs.runtime.device_memory_snapshot`), else
    :data:`DEFAULT_BUDGET_BYTES` when the backend exposes no memory
    stats (CPU) or jax is not initialized."""
    raw = os.environ.get(BUDGET_ENV)
    if raw:
        return int(raw)
    limits = [d["bytes_limit"]
              for d in device_memory_snapshot(emit=False)
              if "bytes_limit" in d]
    if limits:
        return int(min(limits) * DEFAULT_BUDGET_FRACTION)
    return DEFAULT_BUDGET_BYTES


class AdmissionError(RuntimeError):
    """A model could not be admitted under the byte budget — the
    typed, pre-device refusal the serving layer returns instead of
    an OOM.  Carries the sizing facts a capacity dashboard needs."""

    def __init__(self, name, needed, budget, resident, pinned):
        self.model = name
        self.needed_bytes = int(needed)
        self.budget_bytes = int(budget)
        self.resident_bytes = int(resident)
        self.pinned_bytes = int(pinned)
        super().__init__(
            f"cannot admit model {name!r}: needs "
            f"{self.needed_bytes} bytes against a "
            f"{self.budget_bytes}-byte budget with "
            f"{self.pinned_bytes} bytes pinned "
            f"({self.resident_bytes} resident) — raise the budget, "
            "unpin a model, or shrink the artifact")


@dataclasses.dataclass
class _Registration:
    """How to (re)load one named model: a filesystem source (path or
    loader callable) or a held instance."""

    name: str
    source: Optional[Any] = None   # path or callable -> model
    model: Optional[Any] = None    # held instance (host memory)
    kind: Optional[str] = None
    pinned: bool = False
    admissions: int = 0            # lifetime admits (re-admits too)
    nbytes: Optional[int] = None   # learned at first load
    digest: Optional[str] = None   # learned at first AOT admit

    def load(self):
        if self.model is not None:
            return self.model
        if callable(self.source):
            return self.source()
        return artifacts.load_model(self.source)


@dataclasses.dataclass
class ResidentModel:
    """One admitted model: the loaded estimator, its engine, and the
    accounting the LRU runs on."""

    name: str
    model: Any
    engine: InferenceEngine
    nbytes: int
    pinned: bool = False
    last_used: float = 0.0
    admissions: int = 1

    def touch(self):
        self.last_used = time.monotonic()


class ModelResidency:
    """Byte-weighted LRU of loaded models with pinning.

    Parameters
    ----------
    budget_bytes : int, optional
        Admission budget; default :func:`default_budget_bytes`.
    policy : :class:`~brainiak_tpu.serve.batching.BucketPolicy`,
        optional
        Shared by every engine this residency constructs.
    aot : :class:`~brainiak_tpu.serve.aot.AOTProgramCache` or str,
        optional
        Threaded into every engine, so evict/re-admit cycles and
        process restarts stay compile-free.

    The registry/LRU bookkeeping is guarded by one reentrant lock
    (``register()`` is legal from any thread while the service loop
    runs), but the ENGINES this residency hands out remain
    single-caller: only the
    :class:`~brainiak_tpu.serve.service.ServeService` loop may
    drive them (the same contract as the engine).  The lock is
    reentrant because admission evicts: ``acquire -> _make_room ->
    evict`` re-enters.
    """

    def __init__(self, budget_bytes=None, policy=None, aot=None):
        self.budget_bytes = int(budget_bytes
                                if budget_bytes is not None
                                else default_budget_bytes())
        if self.budget_bytes <= 0:
            raise ValueError(
                f"budget_bytes must be positive, got "
                f"{self.budget_bytes}")
        self.policy = policy
        if aot is not None:
            from . import aot as aot_mod
            if not isinstance(aot, aot_mod.AOTProgramCache):
                aot = aot_mod.AOTProgramCache(aot)
        self.aot = aot
        self._lock = threading.RLock()
        self._registry = {}    # guarded-by: _lock
        self._resident = {}    # guarded-by: _lock
        self._n_evictions = 0  # guarded-by: _lock
        #: optional ``fn(name, records)`` called with the error
        #: records of work stranded on an evicted engine — the
        #: service loop installs its delivery path here so evicted
        #: queues resolve their tickets instead of vanishing
        self.on_evict_records = None
        #: optional ``fn(entry)`` called for EVERY eviction with
        #: the dying :class:`ResidentModel` (before it is dropped)
        #: — the service accrues the engine's batch/padding stats
        #: here so summary metrics survive residency churn
        self.on_evict = None

    # -- registration -------------------------------------------------

    def register(self, name, source=None, model=None, kind=None,
                 pinned=False):
        """Register a named model without loading it.

        Exactly one of ``source`` (artifact path, or a zero-arg
        loader callable) and ``model`` (a fitted instance; host
        memory is the caller's — eviction then only frees the
        engine's device arrays) must be given.  ``pinned`` models
        are never evicted.  Returns ``name``."""
        if (source is None) == (model is None):
            raise ValueError(
                "register() takes exactly one of source= / model=")
        with self._lock:
            if name in self._registry:
                raise ValueError(
                    f"model {name!r} already registered")
            self._registry[name] = _Registration(
                name=name, source=source, model=model, kind=kind,
                pinned=bool(pinned))
        return name

    def names(self):
        """Registered model names (resident or not)."""
        with self._lock:
            return sorted(self._registry)

    def resident_names(self):
        with self._lock:
            return sorted(self._resident)

    def entries(self):
        """The live :class:`ResidentModel` entries, name-sorted."""
        with self._lock:
            return [self._resident[name]
                    for name in sorted(self._resident)]

    # -- the LRU ------------------------------------------------------

    def acquire(self, name):
        """The live :class:`ResidentModel` for ``name``, loading and
        admitting it first if necessary (the transparent-re-admission
        path).  Raises ``KeyError`` for an unregistered name and
        :class:`AdmissionError` when it cannot fit."""
        with self._lock:
            entry = self._resident.get(name)
            if entry is not None:
                entry.touch()
                return entry
            reg = self._registry.get(name)
            if reg is None:
                raise KeyError(
                    f"model {name!r} is not registered "
                    f"(known: "
                    f"{', '.join(sorted(self._registry)) or 'none'})")
            # a size learned on a PRIOR load makes an over-budget
            # model refuse in O(1): a request stream aimed at an
            # inadmissible artifact must not re-read it from disk
            # on every route
            if reg.nbytes is not None and \
                    reg.nbytes > self.budget_bytes:
                raise AdmissionError(
                    reg.name, reg.nbytes, self.budget_bytes,
                    self.resident_bytes(), self.pinned_bytes())
        # artifact I/O and digest hashing run OUTSIDE the lock: a
        # multi-GB load must not block register()/stats() callers
        # (same principle as the aot ledger lock); a racing double
        # load is benign — the re-check below keeps one winner
        model = reg.load()
        nbytes = artifacts.model_nbytes(model)
        # the digest cannot change between admissions of the same
        # registration (bit-exact load contract): hash once, not on
        # every evict/re-admit cycle of a GB artifact
        digest = reg.digest
        if self.aot is not None and digest is None:
            digest = artifacts.model_digest(model)
        with self._lock:
            entry = self._resident.get(name)
            if entry is None:
                reg.nbytes = nbytes
                reg.digest = digest
                entry = self._admit(reg, model, nbytes)
            entry.touch()
            return entry

    def _admit(self, reg, model, nbytes):  # requires-lock: _lock
        self._make_room(reg.name, nbytes)
        engine = InferenceEngine(model, kind=reg.kind,
                                 policy=self.policy, aot=self.aot,
                                 digest=reg.digest)
        reg.admissions += 1
        entry = ResidentModel(
            name=reg.name, model=model, engine=engine,
            nbytes=nbytes, pinned=reg.pinned,
            last_used=time.monotonic(),
            admissions=reg.admissions)
        self._resident[reg.name] = entry
        self._gauge()
        return entry

    def _make_room(self, incoming, nbytes):  # requires-lock: _lock
        """Evict LRU unpinned residents until ``nbytes`` fits; the
        typed refusal when even that is not enough."""
        if nbytes > self.budget_bytes:
            raise AdmissionError(
                incoming, nbytes, self.budget_bytes,
                self.resident_bytes(), self.pinned_bytes())
        while self.resident_bytes() + nbytes > self.budget_bytes:
            victims = sorted(
                (e for e in self._resident.values()
                 if not e.pinned and e.name != incoming),
                key=lambda e: e.last_used)
            if not victims:
                raise AdmissionError(
                    incoming, nbytes, self.budget_bytes,
                    self.resident_bytes(), self.pinned_bytes())
            self.evict(victims[0].name,
                       reason=f"admission of {incoming!r}")

    def evict(self, name, reason="manual"):
        """Drop a resident model (engine + device arrays); the
        registration survives so the next :meth:`acquire` re-admits.
        Pinned models refuse with ``ValueError``.  Queued work on
        the evicted engine is failed with ``evicted`` records and
        returned (the service loop delivers them)."""
        with self._lock:
            entry = self._resident.get(name)
            if entry is None:
                raise KeyError(f"model {name!r} is not resident")
            if entry.pinned:
                raise ValueError(f"model {name!r} is pinned")
            entry.engine.fail_pending(
                "evicted", "model was evicted while the request "
                           "was queued; resubmit")
            records = entry.engine.drain()
            if records and self.on_evict_records is not None:
                self.on_evict_records(name, records)
            if self.on_evict is not None:
                self.on_evict(entry)
            del self._resident[name]
            self._n_evictions += 1
            self._gauge()
        # telemetry outside the lock: sink writes are file I/O and
        # must not serialize admission on a slow disk
        obs_metrics.counter(
            "serve_evictions_total",
            help="models evicted from residency").inc(model=name)
        obs_sink.event("eviction", model=name,
                       nbytes=entry.nbytes, reason=reason,
                       admissions=entry.admissions)
        logger.info("evicted model %r (%d bytes, %s)", name,
                    entry.nbytes, reason)
        return records

    # -- accounting ---------------------------------------------------

    def resident_bytes(self):
        with self._lock:
            return sum(e.nbytes for e in self._resident.values())

    def pinned_bytes(self):
        with self._lock:
            return sum(e.nbytes for e in self._resident.values()
                       if e.pinned)

    def _gauge(self):  # requires-lock: _lock
        obs_metrics.gauge(
            "serve_resident_models",
            help="models currently resident").set(
                len(self._resident))
        obs_metrics.gauge(
            "serve_resident_bytes", unit="bytes").set(
                self.resident_bytes())

    def stats(self):
        """Occupancy + churn for the service summary."""
        with self._lock:
            return {
                "budget_bytes": self.budget_bytes,
                "resident_bytes": self.resident_bytes(),
                "pinned_bytes": self.pinned_bytes(),
                "n_registered": len(self._registry),
                "n_resident": len(self._resident),
                "resident": self.resident_names(),
                "evictions": self._n_evictions,
                "admissions": {
                    name: r.admissions
                    for name, r in sorted(self._registry.items())
                    if r.admissions},
            }
