"""In-process batched inference engine over persisted models.

The serving analog of the fit path's resilient loop: heterogeneous
requests (mixed TR lengths, mixed batch sizes, mixed subjects) are
padded into the power-of-two shape buckets of
:mod:`brainiak_tpu.serve.batching` and each (model, bucket) runs ONE
jitted program, built by a :func:`program_cache`-decorated builder so
every fresh compile is counted in ``retrace_total{site=serve.*}`` —
the acceptance bound is compiles <= distinct buckets, never compiles
per request.  Input batch buffers are donated to XLA (they are
assembled fresh per dispatch and never reused), so the padded batch
does not double-buffer in HBM.

Failure isolation: a *poison* request — wrong shape, non-finite
payload, or one that makes the whole batch fail — produces a
structured :class:`~brainiak_tpu.serve.batching.ServeResult` error
record; validation rejects what it can before batching, and a batch
whose dispatch raises falls back to per-request execution so the
poison request alone fails.  Per-request deadlines are enforced at
dispatch: a request still queued past its budget is failed without
consuming device time.

Telemetry (live only while :mod:`brainiak_tpu.obs` has a sink):
``serve.batch`` spans around every dispatch, ``serve.request`` span
records carrying per-request latency, ``serve_request_seconds`` /
``serve_batch_seconds`` histograms, ``serve_queue_depth`` and
``serve_padding_waste_ratio`` gauges, ``serve_requests_total``
counters by outcome, and — with ``BRAINIAK_TPU_OBS_PROFILE`` on —
schema-v2 ``cost`` records for every serve program via
:func:`brainiak_tpu.obs.profile.profile_program`.
"""

import logging
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..obs import metrics as obs_metrics
from ..obs import profile as obs_profile
from ..obs import runtime as obs_runtime
from ..obs import sink as obs_sink
from ..obs import spans as obs_spans
from ..obs import trace as obs_trace
from ..obs import sanitize as obs_sanitize
from ..ops.correlation import PRECISION
from . import artifacts
from .batching import (BucketPolicy, ServeResult, bucket_length,
                       pad_axis, program_cache)

logger = logging.getLogger(__name__)

__all__ = ["InferenceEngine", "program_cache"]


# -- bucket program builders ------------------------------------------
#
# One builder per program family; the lru key IS the bucket (every
# extent that shapes the traced arrays, plus trace-time statics), so
# counted_cache misses == distinct compiled programs.  The padded
# batch buffer is donated in every family (argument 2 by convention,
# except eventseg where it is argument 5): it is assembled fresh per
# dispatch and never reused, so XLA may overwrite it in place instead
# of double-buffering the padded batch in HBM.

def _donate(*argnums):
    """``donate_argnums`` for the batch buffer — skipped on CPU,
    where XLA cannot use donations and jax warns per compile."""
    return () if jax.default_backend() == "cpu" else argnums


def _mesh_axes(mesh):
    """The sharding axes of a serve mesh: ALL mesh axes, flattened
    (the :mod:`~brainiak_tpu.ops.distla` ring idiom — a 2-D
    ``('subject', 'voxel')`` mesh shards serve weights over the
    whole device grid).  Returns ``(axis-name tuple, n_shards)``;
    the tuple is hashable, so it rides in program-cache keys."""
    names = tuple(mesh.axis_names)
    n = int(np.prod([mesh.shape[a] for a in names]))
    return names, n


def _axis_arg(axis_names):
    """The PartitionSpec/psum axis argument for a flattened-ring
    axis tuple (a 1-tuple collapses to its bare name)."""
    return axis_names if len(axis_names) > 1 else axis_names[0]


@program_cache("serve.srm")
def _srm_program(n_subjects, v_pad, k, t_bucket, b_pad, dtype):
    """SRM / DetSRM transform: ``s_i = W_iᵀ x_i`` over a padded
    batch.  Zero voxel-padding is exact (zero rows of both W and x);
    zero TR-padding yields zero output columns sliced off on host."""

    @partial(jax.jit, donate_argnums=_donate(2))
    def run(w_stack, subjects, x):
        w = jnp.take(w_stack, subjects, axis=0)
        return jnp.einsum('bvk,bvt->bkt', w, x, precision=PRECISION)

    return obs_profile.profile_program(run, "serve.srm",
                                       span="serve.batch")


# canonical trace extents for the serve.* signatures: S=2 subjects,
# v_pad=8 (divides the 8-device trace ring), K=3 features, t_bucket=4,
# b_pad=2 — small enough to trace in milliseconds, shaped like a real
# bucket
_TRACE_S, _TRACE_V, _TRACE_K, _TRACE_T, _TRACE_B = 2, 8, 3, 4, 2


def _serve_aval(*shape, dtype=None):
    return jax.ShapeDtypeStruct(shape, dtype or jnp.float32)


def _serve_mesh():
    from ..parallel.mesh import DEFAULT_VOXEL_AXIS, make_mesh
    mesh = make_mesh((DEFAULT_VOXEL_AXIS,), (-1,))
    return mesh, (DEFAULT_VOXEL_AXIS,)


def _srm_call_avals():
    s, v, k, t, b = (_TRACE_S, _TRACE_V, _TRACE_K, _TRACE_T,
                     _TRACE_B)
    return (_serve_aval(s, v, k), _serve_aval(b, dtype=jnp.int32),
            _serve_aval(b, v, t))


@obs_runtime.trace_signature("serve.srm")
def _srm_trace_signature():
    return [{"key": (_TRACE_S, _TRACE_V, _TRACE_K, _TRACE_T,
                     _TRACE_B, "float32"),
             "args": _srm_call_avals(), "donate": (2,)}]


@program_cache("serve.srm_sharded")
def _srm_sharded_program(mesh, axis_names, n_subjects, v_pad, k,
                         t_bucket, b_pad, dtype):
    """SRM / DetSRM transform with the voxel axis SHARDED over the
    mesh (the serving half of the :mod:`~brainiak_tpu.ops.distla`
    idiom): each device holds one voxel shard of the per-subject
    maps AND of the padded batch, contracts locally, and one
    ``psum`` over the flattened ring completes ``W_iᵀ x_i`` — so a
    model bigger than one device's HBM still serves, bit-exact
    (zero voxel padding contributes zero on every shard).  The
    program closes over the mesh; it is excluded from AOT
    persistence (not portable across device counts)."""
    from ..parallel.compat import shard_map

    axis = _axis_arg(axis_names)
    spec = PartitionSpec(None, axis, None)

    def run_local(w_stack, subjects, x):
        w = jnp.take(w_stack, subjects, axis=0)
        part = jnp.einsum('bvk,bvt->bkt', w, x,
                          precision=PRECISION)
        return jax.lax.psum(part, axis)

    run = jax.jit(shard_map(
        run_local, mesh,
        in_specs=(spec, PartitionSpec(), spec),
        out_specs=PartitionSpec()))
    return obs_profile.profile_program(run, "serve.srm_sharded",
                                       span="serve.batch")


@obs_runtime.trace_signature("serve.srm_sharded")
def _srm_sharded_trace_signature():
    mesh, names = _serve_mesh()
    return [{"key": (mesh, names, _TRACE_S, _TRACE_V, _TRACE_K,
                     _TRACE_T, _TRACE_B, "float32"),
             "args": _srm_call_avals(), "mesh": mesh}]


@program_cache("serve.rsrm")
def _rsrm_program(n_subjects, v_pad, k, t_bucket, b_pad, gamma,
                  n_iter, dtype):
    """RSRM transform-new-data, vmapped over the padded batch (the
    alternating shrinkage/projection loop of
    :func:`brainiak_tpu.funcalign.rsrm._transform_new_data`); both
    paddings are exact — every update is per-column and zero-padded
    voxel rows stay zero."""
    # estimator modules import lazily (once per bucket): building a
    # serve artifact host must not pay for every estimator
    from ..funcalign.rsrm import _transform_new_data

    @partial(jax.jit, donate_argnums=_donate(2))
    def run(w_stack, subjects, x):
        w = jnp.take(w_stack, subjects, axis=0)
        return jax.vmap(
            lambda wi, xi: _transform_new_data(xi, wi, gamma,
                                               n_iter))(w, x)

    return obs_profile.profile_program(run, "serve.rsrm",
                                       span="serve.batch")


@obs_runtime.trace_signature("serve.rsrm", float_keys_ok=("gamma",))
def _rsrm_trace_signature():
    return [{"key": (_TRACE_S, _TRACE_V, _TRACE_K, _TRACE_T,
                     _TRACE_B, 0.1, 3, "float32"),
             "args": _srm_call_avals(), "donate": (2,)}]


# eventseg's bucket space is request-controlled (the bucket is the
# EXACT T), so unlike the pow2-bucketed kinds its program cache must
# be explicitly bounded: LRU-evict beyond 64 (T, batch) shapes — see
# the operational note in docs/serving.md
_EVENTSEG_CACHE_PROGRAMS = 64


@program_cache("serve.eventseg", maxsize=_EVENTSEG_CACHE_PROGRAMS)
def _eventseg_program(n_vox, t_len, k, b_pad, dtype):
    """Batched ``find_events``: observation log-probs + forward-
    backward per request, vmapped.  The time axis is NOT padded (the
    transition chain and the z-scoring are T-dependent); the bucket
    is the exact T, batching only across requests."""
    from ..eventseg.event import (_forward_backward_core,
                                  _logprob_obs_core)

    @partial(jax.jit, donate_argnums=_donate(5))
    def run(mean_pat, var, log_p, log_p_start, log_p_end, x):
        def one(xi):
            lp = _logprob_obs_core(xi, mean_pat, var)
            lp_ext = jnp.concatenate(
                [lp, jnp.full((lp.shape[0], 1), -jnp.inf, lp.dtype)],
                axis=1)
            lg, ll = _forward_backward_core(lp_ext, log_p,
                                            log_p_start, log_p_end)
            return lg[:, :-1], ll

        return jax.vmap(one)(x)

    return obs_profile.profile_program(run, "serve.eventseg",
                                       span="serve.batch")


@obs_runtime.trace_signature("serve.eventseg")
def _eventseg_trace_signature():
    v, t, k, b = 5, 6, _TRACE_K, _TRACE_B
    return [{"key": (v, t, k, b, "float64"),
             "args": (_serve_aval(v, k), _serve_aval(k),
                      _serve_aval(k + 1, k + 1), _serve_aval(k + 1),
                      _serve_aval(k + 1), _serve_aval(b, v, t)),
             "donate": (5,)}]


@program_cache("serve.encoding")
def _encoding_program(n_feat, n_vox, t_bucket, b_pad, dtype):
    """Batched encoding-model scoring: predict every scan from its
    features through the fitted affine map, then per-voxel Pearson r
    against the observed responses.  The TR axis is zero-padded to
    the bucket and the pad rows are MASKED out of the correlation
    moments before the reduction (``t_real`` carries each request's
    true length), so padding is exact for the real rows."""

    @partial(jax.jit, donate_argnums=_donate(2, 3))
    def run(w, b, x, y, t_real):
        pred = jnp.einsum('btf,fv->btv', x, w,
                          precision=PRECISION) + b[None, None, :]
        mask = (jnp.arange(x.shape[1])[None, :]
                < t_real[:, None]).astype(x.dtype)
        n = jnp.maximum(t_real, 1).astype(x.dtype)[:, None]
        pm = jnp.einsum('btv,bt->bv', pred, mask) / n
        ym = jnp.einsum('btv,bt->bv', y, mask) / n
        pc = (pred - pm[:, None, :]) * mask[:, :, None]
        yc = (y - ym[:, None, :]) * mask[:, :, None]
        cov = jnp.einsum('btv,btv->bv', pc, yc)
        den = jnp.sqrt(jnp.einsum('btv,btv->bv', pc, pc)
                       * jnp.einsum('btv,btv->bv', yc, yc))
        return jnp.where(den > 0,
                         cov / jnp.where(den > 0, den, 1.0), 0.0)

    return obs_profile.profile_program(run, "serve.encoding",
                                       span="serve.batch")


def _encoding_call_avals(v):
    f, t, b = 3, _TRACE_T, _TRACE_B
    return (_serve_aval(f, v), _serve_aval(v), _serve_aval(b, t, f),
            _serve_aval(b, t, v), _serve_aval(b, dtype=jnp.int32))


@obs_runtime.trace_signature("serve.encoding")
def _encoding_trace_signature():
    v = 5
    return [{"key": (3, v, _TRACE_T, _TRACE_B, "float32"),
             "args": _encoding_call_avals(v), "donate": (2, 3)}]


@program_cache("serve.encoding_sharded")
def _encoding_sharded_program(mesh, axis_names, n_feat, v_pad,
                              t_bucket, b_pad, dtype):
    """Encoding-model scoring with the voxel axis SHARDED over the
    mesh: the affine map's columns, the observed responses, and the
    per-voxel correlation reduction are all voxel-local, so each
    device scores its own voxel shard with NO collective at all —
    the output stays voxel-sharded and the host gathers it once.
    Same masked-moment math as the replicated program (padding
    exact for real rows); closes over the mesh, so AOT persistence
    is skipped."""
    from ..parallel.compat import shard_map

    axis = _axis_arg(axis_names)

    def run_local(w, b, x, y, t_real):
        pred = jnp.einsum('btf,fv->btv', x, w,
                          precision=PRECISION) + b[None, None, :]
        mask = (jnp.arange(x.shape[1])[None, :]
                < t_real[:, None]).astype(x.dtype)
        n = jnp.maximum(t_real, 1).astype(x.dtype)[:, None]
        pm = jnp.einsum('btv,bt->bv', pred, mask) / n
        ym = jnp.einsum('btv,bt->bv', y, mask) / n
        pc = (pred - pm[:, None, :]) * mask[:, :, None]
        yc = (y - ym[:, None, :]) * mask[:, :, None]
        cov = jnp.einsum('btv,btv->bv', pc, yc)
        den = jnp.sqrt(jnp.einsum('btv,btv->bv', pc, pc)
                       * jnp.einsum('btv,btv->bv', yc, yc))
        return jnp.where(den > 0,
                         cov / jnp.where(den > 0, den, 1.0), 0.0)

    run = jax.jit(shard_map(
        run_local, mesh,
        in_specs=(PartitionSpec(None, axis), PartitionSpec(axis),
                  PartitionSpec(),
                  PartitionSpec(None, None, axis),
                  PartitionSpec()),
        out_specs=PartitionSpec(None, axis)))
    return obs_profile.profile_program(
        run, "serve.encoding_sharded", span="serve.batch")


@obs_runtime.trace_signature("serve.encoding_sharded")
def _encoding_sharded_trace_signature():
    mesh, names = _serve_mesh()
    return [{"key": (mesh, names, 3, _TRACE_V, _TRACE_T, _TRACE_B,
                     "float32"),
             "args": _encoding_call_avals(_TRACE_V), "mesh": mesh}]


@program_cache("serve.iem")
def _iem_program(t_bucket, n_vox, k_chan, density, b_pad, dtype):
    """IEM1D predict: channel responses via the precomputed
    pseudo-inverse, feature responses, argmax over the channel
    domain.  Trials are independent, so zero trial-padding is exact
    for the real rows."""

    @partial(jax.jit, donate_argnums=_donate(2))
    def run(pinv_w, channels, x):
        resp = jnp.einsum('kv,btv->btk', pinv_w, x,
                          precision=PRECISION)
        feat = jnp.einsum('kd,btk->btd', channels, resp,
                          precision=PRECISION)
        return jnp.argmax(feat, axis=2)

    return obs_profile.profile_program(run, "serve.iem",
                                       span="serve.batch")


@obs_runtime.trace_signature("serve.iem")
def _iem_trace_signature():
    v, k_chan, density, t, b = 5, 4, 6, _TRACE_T, _TRACE_B
    return [{"key": (t, v, k_chan, density, b, "float32"),
             "args": (_serve_aval(k_chan, v),
                      _serve_aval(k_chan, density),
                      _serve_aval(b, t, v)),
             "donate": (2,)}]


@program_cache("serve.null_threshold")
def _null_threshold_program(n_grid, n_vox, b_pad, mode, dtype):
    """Served significance lookup against a persisted null artifact:
    bucketed tail-count search.  ``grid`` is the ascending bucket-
    representative axis (already side-transformed on host for the
    artifact's ``mode``), ``tail[k, v]`` the per-voxel count of null
    values in buckets ``>= k`` (with an appended all-zero row for
    queries past the top bucket), so a batch of statistic maps
    resolves to p-values with one searchsorted + gather — no null
    array, no recompute, O(log K) per voxel.  p follows the
    ``(count + 1) / (n + 1)`` convention; ``sig`` is the upper-tail
    max-statistic FWER verdict (False wherever the artifact carries
    no threshold, via NaN comparison)."""

    @partial(jax.jit, donate_argnums=_donate(4))
    def run(grid, tail, n_null, thr, x):
        if mode == "left":
            q = -x
        elif mode == "two-sided":
            q = jnp.abs(x)
        else:
            q = x
        idx = jnp.searchsorted(grid, q, side="left")
        counts = jnp.take_along_axis(tail, idx, axis=0)
        p = (counts.astype(grid.dtype) + 1.0) / (n_null + 1.0)
        sig = x >= thr
        return p, sig

    return obs_profile.profile_program(run, "serve.null_threshold",
                                       span="serve.batch")


@obs_runtime.trace_signature("serve.null_threshold")
def _null_threshold_trace_signature():
    k, v, b = 6, _TRACE_V, _TRACE_B
    return [{"key": (k, v, b, "right", "float32"),
             "args": (_serve_aval(k),
                      _serve_aval(k + 1, v, dtype=jnp.int32),
                      _serve_aval(), _serve_aval(),
                      _serve_aval(b, v)),
             "donate": (4,)}]


# -- per-kind serve ops -----------------------------------------------

class _ServeOp:
    """Kind-specific half of the engine: payload validation, bucket
    keying, batch assembly, and result slicing.

    ``isolate_on_failure``: whether a failed batch may be retried
    request-by-request.  True wherever requests are independent
    (every jitted-program kind); an op whose batch members interact
    (FCMA's batch-dependent normalization) sets it False, because a
    singleton re-run would silently CHANGE the survivors' answers.
    """

    site = None
    isolate_on_failure = True
    #: bound on the per-op program memo (None = unbounded); set by
    #: ops whose bucket space is request-controlled (eventseg)
    program_memo_max = None

    def __init__(self, model, policy, mesh=None, device=None):
        self.model = model
        self.policy = policy
        #: device mesh for SHARDED weights (kinds that implement a
        #: sharded program), else None — set by the engine from the
        #: per-device residency's placement decision
        self.mesh = mesh
        #: explicit placement device for UNSHARDED weights (the
        #: per-device residency's least-loaded pick), else None =
        #: the backend default
        self.device = device
        # engine-level program memo + AOT wiring (filled in by the
        # engine when an AOT cache is attached): one resolved
        # callable per bucket key, so the AOT lookup happens at most
        # once per (engine, bucket)
        self._programs = {}
        self.aot = None
        self.digest = None

    def _place(self, arr):
        """Host weights onto this op's assigned device (committed,
        so dispatches execute there); backend default when the
        residency did not pick one."""
        if self.device is not None:
            return jax.device_put(jnp.asarray(arr), self.device)
        return jnp.asarray(arr)

    def run_program(self, builder, key_args, call_args):
        """Resolve + run the jitted program for one bucket.

        Resolution order: the per-op memo (already resolved this
        engine) -> the AOT cache (a persisted program from a prior
        process — no trace, no builder, so a warm cache serves with
        ``retrace_total{site=serve.*} == 0``) -> the counted jit
        builder (whose compile lands in ``retrace_total``), which is
        then exported into the AOT cache for the next process.
        ``call_args`` must be the exact dispatch arguments: their
        shapes/dtypes are the export signature."""
        prog = self._programs.get(key_args)
        if prog is None:
            if self.aot is not None:
                key = self.aot.key_for(self.digest, self.site,
                                       key_args)
                prog = self.aot.get(key, self.site)
                if prog is None:
                    prog = builder(*key_args)
                    self.aot.put(key, self.site, prog, call_args)
            else:
                prog = builder(*key_args)
            if self.program_memo_max is not None and \
                    len(self._programs) >= self.program_memo_max:
                self._programs.pop(next(iter(self._programs)))
            self._programs[key_args] = prog
        if obs_sanitize.enabled():
            # the checkify lane (BRAINIAK_TPU_SANITIZE=1): a tripped
            # NaN/div/OOB check becomes a typed ``sanitizer`` obs
            # event and fails the batch through the engine's normal
            # execution_failed machinery (isolation retries still
            # apply for independent-request kinds)
            error, out = obs_sanitize.call_checked(
                prog, call_args, site=self.site, scope="serve")
            if error is not None:
                raise RuntimeError(
                    f"sanitizer: {self.site}: {error}")
            return out
        return prog(*call_args)

    def validate(self, req):
        """(error_code, message) for a rejectable payload, else
        None."""
        raise NotImplementedError

    def bucket_key(self, req):
        raise NotImplementedError

    def real_elements(self, req):
        x = req.x
        if isinstance(x, (tuple, list)):
            return int(sum(np.asarray(p).size for p in x))
        return int(np.asarray(x).size)

    def batch_extent(self, n):
        return self.policy.batch_bucket(n)

    def padded_elements(self, key, b_pad):
        raise NotImplementedError

    def dispatch(self, reqs, key, b_pad):
        """Run one padded batch; returns per-request results (host
        arrays)."""
        raise NotImplementedError

    @staticmethod
    def _check_finite(x):
        arrs = x if isinstance(x, (tuple, list)) else (x,)
        for arr in arrs:
            if not np.all(np.isfinite(np.asarray(arr))):
                return ("non_finite_input",
                        "payload contains NaN/Inf")
        return None


class _SRMFamilyOp(_ServeOp):
    """SRM and DetSRM ``transform``: per-subject shared-space
    projection."""

    site = "serve.srm"

    def __init__(self, model, policy, mesh=None, device=None):
        super().__init__(model, policy, mesh=mesh, device=device)
        self.voxel_counts = [w.shape[0] for w in model.w_]
        self.v_pad = max(self.voxel_counts)
        self.k = model.w_[0].shape[1]
        self.dtype = np.asarray(model.w_[0]).dtype
        if mesh is not None:
            # sharded serving: the padded voxel axis must divide
            # the flattened mesh ring; zero pad rows are exact
            # (zero W rows x zero x rows contribute zero to psum).
            # The retrace site follows the program family actually
            # compiled, so summaries attribute sharded compiles.
            self.site = self.site + "_sharded"
            self.axis_names, self.n_shards = _mesh_axes(mesh)
            self.v_pad = -(-self.v_pad // self.n_shards) \
                * self.n_shards
        stack = np.zeros(
            (len(model.w_), self.v_pad, self.k), dtype=self.dtype)
        for i, w in enumerate(model.w_):
            stack[i, :w.shape[0]] = w
        if mesh is not None:
            from ..parallel.mesh import place_on_mesh
            self.w_stack = place_on_mesh(
                stack, NamedSharding(
                    mesh, PartitionSpec(
                        None, _axis_arg(self.axis_names), None)))
        else:
            self.w_stack = self._place(stack)

    def validate(self, req):
        if req.subject is None or not (
                0 <= int(req.subject) < len(self.voxel_counts)):
            return ("invalid_subject",
                    f"subject must be in [0, "
                    f"{len(self.voxel_counts)}), got {req.subject}")
        x = np.asarray(req.x)
        if x.ndim != 2:
            return ("invalid_shape",
                    f"expected [voxels, TRs], got ndim={x.ndim}")
        want = self.voxel_counts[int(req.subject)]
        if x.shape[0] != want:
            return ("invalid_shape",
                    f"subject {req.subject} has {want} voxels, "
                    f"payload has {x.shape[0]}")
        return self._check_finite(x)

    def bucket_key(self, req):
        return (bucket_length(np.asarray(req.x).shape[1],
                              floor=self.policy.min_bucket),)

    def padded_elements(self, key, b_pad):
        return b_pad * self.v_pad * key[0]

    def _assemble(self, reqs, t_b, b_pad):
        """The padded batch buffer + subject indices shared by the
        SRM-family programs."""
        x = np.zeros((b_pad, self.v_pad, t_b), dtype=self.dtype)
        subjects = np.zeros((b_pad,), dtype=np.int32)
        for i, req in enumerate(reqs):
            xi = np.asarray(req.x, dtype=self.dtype)
            x[i, :xi.shape[0], :xi.shape[1]] = xi
            subjects[i] = int(req.subject)
        return x, subjects

    def _shard_batch(self, x):
        """The padded batch buffer onto the mesh, voxel-sharded to
        match the resident weight shards."""
        from ..parallel.mesh import place_on_mesh
        return place_on_mesh(
            x, NamedSharding(
                self.mesh, PartitionSpec(
                    None, _axis_arg(self.axis_names), None)))

    def dispatch(self, reqs, key, b_pad):
        t_b = key[0]
        x, subjects = self._assemble(reqs, t_b, b_pad)
        if self.mesh is not None:
            out = np.asarray(self.run_program(
                _srm_sharded_program,
                (self.mesh, self.axis_names,
                 len(self.voxel_counts), self.v_pad, self.k, t_b,
                 b_pad, str(self.dtype)),
                (self.w_stack, jnp.asarray(subjects),
                 self._shard_batch(x))))
        else:
            out = np.asarray(self.run_program(
                _srm_program,
                (len(self.voxel_counts), self.v_pad, self.k, t_b,
                 b_pad, str(self.dtype)),
                (self.w_stack, jnp.asarray(subjects),
                 jnp.asarray(x))))
        return [np.array(out[i, :, :np.asarray(r.x).shape[1]])
                for i, r in enumerate(reqs)]


class _RSRMTransformOp(_SRMFamilyOp):
    """RSRM ``transform``: (shared response, sparse individual
    term) per request via the alternating shrinkage loop."""

    site = "serve.rsrm"

    def dispatch(self, reqs, key, b_pad):
        t_b = key[0]
        x, subjects = self._assemble(reqs, t_b, b_pad)
        r, s = self.run_program(
            _rsrm_program,
            (len(self.voxel_counts), self.v_pad, self.k, t_b,
             b_pad, float(self.model.gamma),
             int(self.model.n_iter), str(self.dtype)),
            (self.w_stack, jnp.asarray(subjects),
             jnp.asarray(x)))
        r = np.asarray(r)
        s = np.asarray(s)
        out = []
        for i, req in enumerate(reqs):
            v_i, t_i = np.asarray(req.x).shape
            out.append((np.array(r[i, :, :t_i]),
                        np.array(s[i, :v_i, :t_i])))
        return out


class _EventSegmentOp(_ServeOp):
    """``find_events`` on held-out scans: returns
    ``(segments [T, K], log-likelihood)`` per request."""

    site = "serve.eventseg"
    # the bucket space is request-controlled (exact T), so the
    # per-op program memo is bounded like the builder's lru
    program_memo_max = _EVENTSEG_CACHE_PROGRAMS

    def __init__(self, model, policy, mesh=None, device=None):
        super().__init__(model, policy, mesh=mesh, device=device)
        self.n_vox, self.k = model.event_pat_.shape
        var = model.event_var_
        if not isinstance(var, np.ndarray):
            var = var * np.ones(model.n_events)
        self.var = self._place(np.asarray(var, dtype=float))
        self.mean_pat = self._place(model.event_pat_)
        self._transitions = {}

    def validate(self, req):
        x = np.asarray(req.x)
        if x.ndim != 2 or x.shape[1] != self.n_vox:
            return ("invalid_shape",
                    f"expected [TRs, {self.n_vox}], got "
                    f"{x.shape}")
        if x.shape[0] < self.k:
            return ("invalid_shape",
                    f"need at least {self.k} TRs for {self.k} "
                    "events")
        return self._check_finite(x)

    def bucket_key(self, req):
        # exact T: the transition chain and z-scoring are
        # T-dependent, so TR padding would change the answer
        return (int(np.asarray(req.x).shape[0]),)

    def padded_elements(self, key, b_pad):
        return b_pad * self.n_vox * key[0]

    def _transition_logs(self, t):
        cached = self._transitions.get(t)
        if cached is None:
            log_p, log_start, log_end = \
                self.model._build_transitions(t)
            cached = (jnp.asarray(log_p), jnp.asarray(log_start),
                      jnp.asarray(log_end))
            # bounded like the program cache: T is request-
            # controlled, and a long-lived server must not pin one
            # transition triple per distinct scan length forever
            if len(self._transitions) >= _EVENTSEG_CACHE_PROGRAMS:
                self._transitions.pop(
                    next(iter(self._transitions)))
            self._transitions[t] = cached
        return cached

    def dispatch(self, reqs, key, b_pad):
        t = key[0]
        log_p, log_start, log_end = self._transition_logs(t)
        x = np.empty((b_pad, self.n_vox, t), dtype=float)
        for i, req in enumerate(reqs):
            x[i] = np.asarray(req.x).T
        # pad lanes with a COPY of the last real scan (all-zero
        # padding would z-score to NaN; lanes are independent under
        # vmap, and pad results are discarded)
        for i in range(len(reqs), b_pad):
            x[i] = x[len(reqs) - 1]
        lg, ll = self.run_program(
            _eventseg_program,
            (self.n_vox, t, self.k, b_pad, str(x.dtype)),
            (self.mean_pat, self.var, log_p, log_start, log_end,
             jnp.asarray(x)))
        lg = np.asarray(lg)
        ll = np.asarray(ll)
        return [(np.exp(lg[i]), float(ll[i]))
                for i in range(len(reqs))]


class _IEM1DOp(_ServeOp):
    """``InvertedEncoding1D.predict``: decoded feature value per
    trial."""

    site = "serve.iem"

    def __init__(self, model, policy, mesh=None, device=None):
        super().__init__(model, policy, mesh=mesh, device=device)
        self.n_vox = model.W_.shape[0]
        self.dtype = np.asarray(model.W_).dtype
        self.pinv_w = jnp.linalg.pinv(self._place(model.W_))
        self.channels = self._place(
            np.asarray(model.channels_, dtype=self.dtype))
        self.k_chan = int(model.channels_.shape[0])
        self.density = int(model.channels_.shape[1])
        self.domain = np.asarray(model.channel_domain)

    def validate(self, req):
        x = np.asarray(req.x)
        if x.ndim != 2 or x.shape[1] != self.n_vox:
            return ("invalid_shape",
                    f"expected [trials, {self.n_vox}], got "
                    f"{x.shape}")
        return self._check_finite(x)

    def bucket_key(self, req):
        return (bucket_length(np.asarray(req.x).shape[0],
                              floor=self.policy.min_bucket),)

    def padded_elements(self, key, b_pad):
        return b_pad * key[0] * self.n_vox

    def dispatch(self, reqs, key, b_pad):
        t_b = key[0]
        x = np.zeros((b_pad, t_b, self.n_vox), dtype=self.dtype)
        for i, req in enumerate(reqs):
            xi = np.asarray(req.x, dtype=self.dtype)
            x[i, :xi.shape[0]] = xi
        idx = np.asarray(self.run_program(
            _iem_program,
            (t_b, self.n_vox, self.k_chan, self.density, b_pad,
             str(self.dtype)),
            (self.pinv_w, self.channels, jnp.asarray(x))))
        return [self.domain[idx[i, :np.asarray(r.x).shape[0]]]
                for i, r in enumerate(reqs)]


class _RidgeEncodingOp(_ServeOp):
    """Encoding-model scoring: a request is a ``(features [T, F],
    responses [T, V])`` pair for one held-out scan; the result is the
    per-voxel correlation [V] between the model's predicted and the
    observed responses — the heavy read path of the encoding tier.

    The fitted preprocessing (centering/standardization) is folded
    into one affine map at engine construction, so the program is a
    pure matmul + masked correlation; requests bucket on the TR
    length and pad rows are masked before the per-voxel reduction
    (padding-exact by construction)."""

    site = "serve.encoding"

    def __init__(self, model, policy, mesh=None, device=None):
        super().__init__(model, policy, mesh=mesh, device=device)
        self.n_features, self.n_vox = model.W_.shape
        self.dtype = np.asarray(model.W_).dtype
        w_eff = np.asarray(model.W_) \
            / np.asarray(model.x_scale_)[:, None]
        b_eff = np.asarray(model.y_mean_) \
            - (np.asarray(model.x_mean_)
               / np.asarray(model.x_scale_)) @ np.asarray(model.W_)
        w_eff = w_eff.astype(self.dtype)
        b_eff = b_eff.astype(self.dtype)
        self.v_pad = self.n_vox
        if mesh is not None:
            # sharded serving: voxel columns padded to the mesh
            # ring and partitioned; the scoring math is voxel-local
            # (pad columns score 0 and are sliced off on host)
            self.site = self.site + "_sharded"
            self.axis_names, self.n_shards = _mesh_axes(mesh)
            self.v_pad = -(-self.n_vox // self.n_shards) \
                * self.n_shards
            pad = self.v_pad - self.n_vox
            if pad:
                w_eff = np.pad(w_eff, ((0, 0), (0, pad)))
                b_eff = np.pad(b_eff, ((0, pad),))
            from ..parallel.mesh import place_on_mesh
            axis = _axis_arg(self.axis_names)
            self.w = place_on_mesh(
                w_eff, NamedSharding(mesh,
                                     PartitionSpec(None, axis)))
            self.b = place_on_mesh(
                b_eff, NamedSharding(mesh, PartitionSpec(axis)))
        else:
            self.w = self._place(w_eff)
            self.b = self._place(b_eff)

    def validate(self, req):
        x = req.x
        if not isinstance(x, (tuple, list)) or len(x) != 2:
            return ("invalid_shape",
                    "payload must be a (features, responses) pair")
        feats, resp = (np.asarray(p) for p in x)
        if feats.ndim != 2 or feats.shape[1] != self.n_features:
            return ("invalid_shape",
                    f"expected features [TRs, {self.n_features}], "
                    f"got {feats.shape}")
        if resp.ndim != 2 or resp.shape[1] != self.n_vox \
                or resp.shape[0] != feats.shape[0]:
            return ("invalid_shape",
                    f"expected responses [{feats.shape[0]}, "
                    f"{self.n_vox}], got {resp.shape}")
        if feats.shape[0] < 2:
            return ("invalid_shape",
                    "per-voxel correlation needs at least 2 TRs")
        return self._check_finite(x)

    def bucket_key(self, req):
        return (bucket_length(np.asarray(req.x[0]).shape[0],
                              floor=self.policy.min_bucket),)

    def padded_elements(self, key, b_pad):
        return b_pad * key[0] * (self.n_features + self.n_vox)

    def dispatch(self, reqs, key, b_pad):
        t_b = key[0]
        x = np.zeros((b_pad, t_b, self.n_features),
                     dtype=self.dtype)
        y = np.zeros((b_pad, t_b, self.v_pad), dtype=self.dtype)
        # pad lanes keep t_real=1 so the masked moments never
        # divide by zero; their (all-zero) scores are discarded
        t_real = np.ones((b_pad,), dtype=np.int32)
        for i, req in enumerate(reqs):
            feats = np.asarray(req.x[0], dtype=self.dtype)
            resp = np.asarray(req.x[1], dtype=self.dtype)
            x[i, :feats.shape[0]] = feats
            y[i, :resp.shape[0], :self.n_vox] = resp
            t_real[i] = feats.shape[0]
        if self.mesh is not None:
            from ..parallel.mesh import place_on_mesh
            axis = _axis_arg(self.axis_names)
            y_dev = place_on_mesh(
                y, NamedSharding(self.mesh,
                                 PartitionSpec(None, None, axis)))
            scores = np.asarray(self.run_program(
                _encoding_sharded_program,
                (self.mesh, self.axis_names, self.n_features,
                 self.v_pad, t_b, b_pad, str(self.dtype)),
                (self.w, self.b, jnp.asarray(x), y_dev,
                 jnp.asarray(t_real))))[:, :self.n_vox]
        else:
            scores = np.asarray(self.run_program(
                _encoding_program,
                (self.n_features, self.n_vox, t_b, b_pad,
                 str(self.dtype)),
                (self.w, self.b, jnp.asarray(x), jnp.asarray(y),
                 jnp.asarray(t_real))))
        return [np.array(scores[i]) for i in range(len(reqs))]


# (pair_voxels, TR bucket, flush size) combinations already traced by
# the FCMA classifier's process-global jitted programs — mirrors
# jax.jit's own cache lifetime, NOT any engine's (see dispatch below)
_FCMA_SEEN_SHAPES = set()


class _FCMAPredictOp(_ServeOp):
    """FCMA classifier ``predict`` on (region1, region2) epoch
    pairs.

    Host-delegated: the classifier's own jitted feature/Gram
    programs run the batch.  Only their TR extent is bounded by the
    bucket — the batch extent is the TRUE flush size, because the
    test-side normalization is computed over the dispatched batch
    (exactly :meth:`Classifier.predict` semantics), which makes
    results batch-composition-dependent by construction; the batch
    is therefore never padded with dummy requests, and TR
    zero-padding alone is exact (correlation sums over TRs).  The
    flip side is a compile per distinct (TR bucket, flush size) —
    dispatch counts each process-novel shape into
    ``retrace_total{site=serve.fcma}`` so the engine summary and
    SRV001 stay honest; online fcma workloads should pin
    ``max_batch``/``max_wait`` for steady flush sizes.

    ``isolate_on_failure`` is False for the same reason: re-running
    a failed batch's survivors one by one would renormalize each
    against a batch of 1 and silently change their predictions, so
    a failed FCMA batch fails as a unit.
    """

    site = "serve.fcma"
    isolate_on_failure = False

    def __init__(self, model, policy, mesh=None, device=None):
        super().__init__(model, policy, mesh=mesh, device=device)
        if model._is_precomputed_svm() and \
                getattr(model, "training_data_", None) is None:
            raise ValueError(
                "this FCMA artifact cannot serve predict: the SVM "
                "kernel was precomputed portion-by-portion and the "
                "training correlation features were not retained "
                "(refit with num_processed_voxels >= num voxels)")
        self.num_features = int(model.num_features_)
        self.pair_voxels = sorted(
            (int(model.num_voxels_),
             self.num_features // int(model.num_voxels_)))

    def validate(self, req):
        x = req.x
        if not isinstance(x, (tuple, list)) or len(x) != 2:
            return ("invalid_shape",
                    "payload must be a (region1, region2) pair")
        x1, x2 = (np.asarray(p) for p in x)
        if x1.ndim != 2 or x2.ndim != 2 \
                or x1.shape[0] != x2.shape[0]:
            return ("invalid_shape",
                    "pair members must be [TRs, voxels] with equal "
                    "TRs")
        # per-region counts, order-insensitive (matching the
        # _stack_pairs swap) — the product alone would accept a
        # (1, v1*v2)-shaped pair whose correlation geometry has
        # nothing to do with training
        if sorted((x1.shape[1], x2.shape[1])) != self.pair_voxels:
            return ("invalid_shape",
                    f"pair voxel counts ({x1.shape[1]}, "
                    f"{x2.shape[1]}) do not match the model's "
                    f"{tuple(self.pair_voxels)}")
        return self._check_finite(x)

    def bucket_key(self, req):
        return (bucket_length(np.asarray(req.x[0]).shape[0],
                              floor=self.policy.min_bucket),)

    def batch_extent(self, n):
        return n  # normalization depends on the true batch size

    def padded_elements(self, key, b_pad):
        # both pair members padded to the TR bucket
        return b_pad * key[0] * sum(self.pair_voxels)

    def dispatch(self, reqs, key, b_pad):
        t_b = key[0]
        # the classifier's jitted programs key on (voxel geometry,
        # flush size, TR bucket) — a novel combination means a fresh
        # trace+compile that the program_cache counter cannot see
        # (host delegation), so count it here.  The seen-set is
        # module-level because jax.jit's cache is process-global: a
        # fresh engine over already-compiled shapes must read 0, the
        # same warm-cache contract as the program_cache sites.
        shape = (tuple(self.pair_voxels), t_b, len(reqs))
        if shape not in _FCMA_SEEN_SHAPES:
            _FCMA_SEEN_SHAPES.add(shape)
            obs_metrics.counter("retrace_total").inc(site=self.site)
        # validate() accepts either region order, but _stack_pairs
        # swaps whole stacks keyed on the first pair only — a batch
        # mixing orders would np.stack ragged shapes and fail as a
        # unit.  Canonicalize per pair (larger region first, the
        # same order _stack_pairs settles on for a lone request).
        pairs = []
        for r in reqs:
            x1, x2 = (np.asarray(p) for p in r.x)
            if x1.shape[1] < x2.shape[1]:
                x1, x2 = x2, x1
            pairs.append((pad_axis(x1, 0, t_b),
                          pad_axis(x2, 0, t_b)))
        labels = np.asarray(self.model.predict(pairs))
        return [labels[i] for i in range(len(reqs))]


class _NullThresholdOp(_ServeOp):
    """Significance lookup against a ``null_distribution`` artifact:
    a request is a statistic map ``[V]`` and the result is
    ``(p [V], sig [V])`` — the bucketed-tail p-value at the
    artifact's ``side`` and the upper-tail max-statistic FWER
    verdict (``x >= thresholds['fwer_0.05']``; all-False when the
    artifact carries no threshold).

    The device tables are precomputed once from the accumulator's
    ordered bucket histogram: p is accurate to the accumulator's
    configured relative accuracy (the bucket width), with the exact
    ``(count + 1) / (n + 1)`` convention on the bucketed counts.
    Queries are vectorized over the batch lane, so a cohort of
    subject maps screens in one dispatch."""

    site = "serve.null_threshold"

    def __init__(self, model, policy, mesh=None, device=None):
        super().__init__(model, policy, mesh=mesh, device=device)
        acc = model.accumulator
        self.shape = tuple(acc.shape)
        self.n_vox = int(np.prod(self.shape, dtype=np.int64)) or 1
        self.dtype = np.asarray(model.observed).dtype
        if self.dtype.kind != "f":
            self.dtype = np.dtype(np.float64)
        counts, values = acc._ordered_counts()
        counts = counts.reshape(counts.shape[0], -1)
        self.mode = model.side
        if self.mode == "left":
            # count(null <= x) == count(-null >= -x): negate + flip
            grid = -values[::-1]
            c = counts[::-1]
        elif self.mode == "two-sided":
            # magnitude axis: the near-zero bucket then |value|
            # buckets, positive and negative halves folded together
            k = (len(values) - 1) // 2
            grid = np.concatenate([[0.0], values[k + 1:]])
            c = np.concatenate(
                [counts[k][None], counts[k + 1:] + counts[:k][::-1]],
                axis=0)
        else:
            grid = values
            c = counts
        # tail[j] = count of buckets >= j, plus a zero row so a
        # query past the top bucket gathers count 0 (p = 1/(n+1));
        # int32 holds any realistic resample count per voxel
        tail = np.concatenate(
            [np.cumsum(c[::-1], axis=0)[::-1],
             np.zeros((1, c.shape[1]), dtype=np.int64)], axis=0)
        self.n_grid = len(grid)
        self.grid = self._place(np.asarray(grid, dtype=self.dtype))
        self.tail = self._place(tail.astype(np.int32))
        self.n_null = self._place(
            np.asarray(acc.n, dtype=self.dtype))
        self.thr = self._place(np.asarray(
            model.thresholds.get("fwer_0.05", float("nan")),
            dtype=self.dtype))

    def validate(self, req):
        # accept any layout of the artifact's voxel extent: the
        # observed map itself may carry a leading length-1 axis
        # (the one-sample permutation convention) and dispatch
        # flattens anyway
        x = np.asarray(req.x)
        if x.size != self.n_vox:
            return ("invalid_shape",
                    f"expected statistic map {self.shape}, got "
                    f"{x.shape}")
        return self._check_finite(x)

    def bucket_key(self, req):
        # every query has the artifact's fixed voxel extent; the
        # only bucketed axis is the batch lane
        return ()

    def padded_elements(self, key, b_pad):
        return b_pad * self.n_vox

    def dispatch(self, reqs, key, b_pad):
        x = np.zeros((b_pad, self.n_vox), dtype=self.dtype)
        for i, req in enumerate(reqs):
            x[i] = np.asarray(req.x, dtype=self.dtype).reshape(-1)
        p, sig = self.run_program(
            _null_threshold_program,
            (self.n_grid, self.n_vox, b_pad, self.mode,
             str(self.dtype)),
            (self.grid, self.tail, self.n_null, self.thr,
             jnp.asarray(x)))
        p = np.asarray(p)
        sig = np.asarray(sig)
        return [(np.array(p[i]).reshape(self.shape),
                 np.array(sig[i]).reshape(self.shape))
                for i in range(len(reqs))]


_KIND_OPS = {
    "srm": _SRMFamilyOp,
    "detsrm": _SRMFamilyOp,
    "rsrm": _RSRMTransformOp,
    "eventseg": _EventSegmentOp,
    "iem1d": _IEM1DOp,
    "ridge_encoding": _RidgeEncodingOp,
    "fcma": _FCMAPredictOp,
    "null_distribution": _NullThresholdOp,
}


class InferenceEngine:
    """Shape-bucketed batched inference over one fitted model.

    Parameters
    ----------
    model : a fitted estimator with a registered serve adapter
        (:data:`brainiak_tpu.serve.artifacts.ADAPTERS`) and an
        engine op (SRM/DetSRM/RSRM transform, EventSegment
        find_events, InvertedEncoding1D predict, RidgeEncoder
        held-out-scan scoring, FCMA Classifier predict).
    kind : str, optional
        Override adapter detection (useful for duck-typed models).
    policy : :class:`~brainiak_tpu.serve.batching.BucketPolicy`
    aot : :class:`~brainiak_tpu.serve.aot.AOTProgramCache` or str,
        optional
        Persisted-program cache (a path constructs one): bucket
        programs are looked up there before the jit builders, so a
        process restarted over a warm cache serves its first
        request without a compile stall
        (``retrace_total{site=serve.*} == 0``), and every program
        this engine does build is exported for the next process.
        The host-delegated ``fcma`` kind has no exportable serve
        program and ignores the cache, as do SHARDED engines (their
        programs close over the mesh).
    mesh : :class:`jax.sharding.Mesh`, optional
        Serve this model SHARDED over the mesh (kinds in
        :data:`brainiak_tpu.serve.artifacts.SHARDED_KINDS` only):
        weights are partitioned over all mesh axes and dispatches
        run the ``serve.*_sharded`` programs — a model over one
        device's HBM still serves, answers bit-exact vs the
        replicated path.
    device : jax device, optional
        Place this engine's (unsharded) weights on an explicit
        device — the per-device residency's placement decision.
        Mutually exclusive with ``mesh``.

    Usage: :meth:`submit` requests (full buckets flush
    immediately), :meth:`poll` on a timer to enforce ``max_wait_s``,
    or :meth:`run` for the offline drive-to-completion mode.  Every
    submitted request yields exactly one
    :class:`~brainiak_tpu.serve.batching.ServeResult`.

    The engine is NOT thread-safe: drive ``submit``/``poll``/
    ``drain`` from a single thread (an event loop that interleaves
    them is the intended online shape).  A submit racing a
    concurrent flush could append to a just-popped bucket queue and
    the request would never dispatch — callers serving from
    multiple threads must serialize engine calls externally.
    """

    def __init__(self, model, kind=None, policy=None, aot=None,
                 digest=None, mesh=None, device=None):
        self.kind = kind or artifacts.detect_kind(model)
        if self.kind not in _KIND_OPS:
            raise ValueError(
                f"no serve engine op for kind {self.kind!r} "
                f"(supported: {', '.join(sorted(_KIND_OPS))})")
        if mesh is not None and self.kind not in \
                artifacts.SHARDED_KINDS:
            raise ValueError(
                f"kind {self.kind!r} has no sharded serve program "
                f"(shardable: "
                f"{', '.join(sorted(artifacts.SHARDED_KINDS))})")
        if mesh is not None and device is not None:
            raise ValueError(
                "mesh= (sharded weights) and device= (single-"
                "device placement) are mutually exclusive")
        self.mesh = mesh
        self.policy = policy or BucketPolicy()
        self.op = _KIND_OPS[self.kind](model, self.policy,
                                       mesh=mesh, device=device)
        if aot is not None and self.kind != "fcma" \
                and mesh is None:
            from . import aot as aot_mod
            if not isinstance(aot, aot_mod.AOTProgramCache):
                aot = aot_mod.AOTProgramCache(aot)
            self.op.aot = aot
            # the caller (residency) may pass the precomputed
            # artifact digest so evict/re-admit cycles do not
            # re-hash a large model on the request hot path
            self.op.digest = digest or artifacts.model_digest(model)
        self.aot = self.op.aot
        self._queues = {}   # bucket key -> [Request]
        self._records = []
        self._n_submitted = 0
        self._stats = {"n_batches": 0, "real_elements": 0,
                       "padded_elements": 0, "buckets": set(),
                       "n_ok": 0, "errors_by_code": {}}
        # summary() reports retraces as a delta from here, so a
        # fresh engine over an already-warm program cache reads 0
        # and a second model's compiles are not charged to it
        self._retrace_base = obs_metrics.counter(
            "retrace_total").value(site=self.op.site)

    # -- submission ---------------------------------------------------
    def submit(self, request):
        """Enqueue one request; returns an error
        :class:`ServeResult` for an immediately-rejected payload,
        else None (the record arrives at flush).

        An already-set ``request.submitted`` is honored (callers may
        pre-stamp ingress time) — when resubmitting a previously
        served Request, reset ``submitted = None`` first or its
        deadline counts from the ORIGINAL enqueue.

        The synchronous return is the ONLY delivery of a rejection:
        it is counted in :meth:`summary` and the serve metrics but
        never appears in :attr:`records`/:meth:`drain`, so an online
        caller replying from both channels cannot double-respond."""
        if request.submitted is None:
            request.submitted = time.monotonic()
        clock = obs_trace.stage_clock()
        # submission index travels on the request and into its
        # record: the ordering key must survive duplicate ids
        request._seq_index = self._n_submitted
        self._n_submitted += 1
        try:
            problem = self.op.validate(request)
            key = None if problem else self.op.bucket_key(request)
        except Exception as exc:
            # a payload weird enough to crash validation itself
            # (ragged nested lists, non-int subject) still owes the
            # caller a structured record, not an engine crash
            problem = ("invalid_payload",
                       f"{type(exc).__name__}: {exc}")
        if problem is not None:
            code, message = problem
            return self._record_error(request, code, message,
                                      store=False)
        queue = self._queues.setdefault(key, [])
        queue.append(request)
        # trace stage 2: the request joined a bucket queue (no-op
        # untraced/disabled; timing is host bookkeeping, no sync)
        obs_trace.traced_span(
            "serve.enqueue", clock.elapsed(), request,
            attrs={"kind": self.kind, "bucket": str(key),
                   "queue_depth": len(queue)})
        self._gauge_depth()
        if len(queue) >= self.policy.max_batch:
            self._flush_bucket(key)
        return None

    def expedite(self, request):
        """Flush the bucket holding ``request`` NOW, without waiting
        out ``max_wait_s`` — the low-latency single-request path
        (:meth:`ServeService.submit(..., low_latency=True)
        <brainiak_tpu.serve.service.ServeService.submit>`).  A
        closed-loop per-TR caller cannot afford the batch window: a
        max-wait flush adds the full window to every singleton round
        trip.  Returns True when a bucket was flushed (False: the
        request already dispatched, e.g. its bucket hit max_batch at
        submit).  Anything else queued in the same bucket rides the
        expedited batch — no reordering, no starvation."""
        try:
            key = self.op.bucket_key(request)
        except Exception:  # pragma: no cover - validated at submit
            return False
        if self._queues.get(key):
            self._flush_bucket(key)
            return True
        return False

    def poll(self, now=None):
        """Flush buckets whose oldest request has waited past
        ``max_wait_s`` (call on the serving loop's timer)."""
        if now is None:
            now = time.monotonic()
        for key in list(self._queues):
            queue = self._queues.get(key)
            if queue and (now - queue[0].submitted
                          >= self.policy.max_wait_s):
                self._flush_bucket(key)

    def flush(self):
        """Flush every queued bucket (offline drain)."""
        for key in list(self._queues):
            self._flush_bucket(key)

    def fail_pending(self, code="shutdown", message=None):
        """Fail every still-queued request with a structured error
        record (the non-draining half of a service
        ``shutdown(drain=False)``): each gets exactly one
        :class:`ServeResult` carrying ``code``, no device time is
        consumed, and the records land in the normal
        :meth:`drain` stream.  Returns the number failed."""
        n = 0
        if message is None:
            message = ("request was still queued when the engine "
                       "shut down")
        for key in list(self._queues):
            for req in self._queues.pop(key, []):
                self._record_error(req, code, message)
                n += 1
        self._gauge_depth()
        return n

    def run(self, requests):
        """Submit + drain, returning one record per passed request
        in submission order (the offline CLI path).  Exactly these
        requests' records are returned — selected by submission
        index, so work queued by EARLIER ``submit`` calls that this
        call's flush happens to complete is not interleaved; it
        stays in :attr:`records` for :meth:`drain`."""
        seq0 = self._n_submitted
        out = []
        for req in requests:
            rec = self.submit(req)
            if rec is not None:    # sync rejection: only delivery
                out.append(rec)
        self.flush()
        out.extend(r for r in self._records
                   if r.seq is not None and r.seq >= seq0)
        out.sort(key=lambda r: r.seq if r.seq is not None else 0)
        return out

    @property
    def records(self):
        """Completed records so far (submission-interleaved;
        submit-time rejections are delivered only via ``submit``'s
        return and never appear here).

        Records accumulate until :meth:`drain` — a long-lived online
        server must drain after each :meth:`poll`, or completed
        results (full arrays) pile up without bound."""
        return self._records

    def drain(self):
        """Pop and return the completed records (the online-mode
        companion of :meth:`poll`): the engine drops its references
        to the returned results, so steady-state serving memory is
        the queued work, not the history."""
        out = self._records
        self._records = []
        return out

    # -- internals ----------------------------------------------------
    def _gauge_depth(self):
        depth = sum(len(q) for q in self._queues.values())
        obs_metrics.gauge(
            "serve_queue_depth",
            help="requests queued awaiting a bucket flush").set(
                depth, kind=self.kind)

    def _record_error(self, request, code, message, latency=None,
                      store=True):
        if latency is None and request.submitted is not None:
            latency = time.monotonic() - request.submitted
        rec = ServeResult(
            request_id=request.request_id, ok=False, error=code,
            message=message, latency_s=latency,
            seq=getattr(request, "_seq_index", None))
        self._finish(request, rec, outcome=code, store=store)
        return rec

    def _finish(self, request, rec, outcome, store=True):
        """Account one finished request.  ``store=False`` (submit-
        time rejections) counts and instruments the record without
        adding it to the :meth:`drain` stream — the caller already
        holds it from ``submit``'s return."""
        if store:
            self._records.append(rec)
        if rec.ok:
            self._stats["n_ok"] += 1
        counts = self._stats["errors_by_code"]
        if not rec.ok:
            counts[outcome] = counts.get(outcome, 0) + 1
        obs_metrics.counter(
            "serve_requests_total",
            help="serve requests by outcome").inc(
                kind=self.kind, outcome="ok" if rec.ok else outcome)
        if rec.latency_s is not None:
            obs_metrics.histogram(
                "serve_request_seconds", unit="s").observe(
                    rec.latency_s, kind=self.kind,
                    outcome="ok" if rec.ok else "error")
        if obs_sink.enabled() and rec.latency_s is not None:
            # trace stage 4 (delivery): the per-request latency span
            # closes the request's trace chain — traced_span threads
            # trace_id/span_id/parent_id and is a plain span when
            # the request is untraced
            if getattr(request, "trace_id", None):
                obs_trace.traced_span(
                    "serve.request", rec.latency_s, request,
                    path="serve.request",
                    attrs={"kind": self.kind,
                           "outcome": "ok" if rec.ok else outcome,
                           "request_id": rec.request_id})
            else:
                obs_sink.emit(obs_sink.make_record(
                    "span", "serve.request", path="serve.request",
                    dur_s=rec.latency_s,
                    attrs={"kind": self.kind,
                           "outcome": "ok" if rec.ok else outcome,
                           "request_id": rec.request_id}))

    def _flush_bucket(self, key):
        queue = self._queues.pop(key, [])
        if not queue:
            return
        now = time.monotonic()
        ready = []
        for req in queue:
            if req.expired(now):
                self._record_error(
                    req, "deadline_exceeded",
                    f"queued {now - req.submitted:.3f}s past the "
                    f"{req.deadline_s:.3f}s deadline",
                    latency=now - req.submitted)
            else:
                ready.append(req)
        self._gauge_depth()
        size = max(int(self.policy.max_batch), 1)
        groups = [ready[i:i + size]
                  for i in range(0, len(ready), size)]
        for group in groups:
            self._run_group(key, group)

    def _dispatch_group(self, key, group, b_pad, isolated=False):
        """One ``op.dispatch`` call with its full accounting —
        batch/element/bucket stats, padding-waste gauge,
        ``serve.batch`` span, ``serve_batch_seconds`` histogram —
        shared by the normal path and the poison-recovery singleton
        re-runs so the two can never drift apart.  Stats count
        dispatch ATTEMPTS: a poison batch charges its elements once
        as the failed batch and again across the isolation
        singletons, which is the device work actually dispatched —
        padding waste for a round that hit poison reflects the
        recovery cost, not steady-state waste.  The span emits even
        when dispatch raises; the histogram records successful
        dispatches only."""
        bucket = key + (b_pad,)
        real = sum(self.op.real_elements(r) for r in group)
        padded = self.op.padded_elements(key, b_pad)
        self._stats["n_batches"] += 1
        self._stats["real_elements"] += real
        self._stats["padded_elements"] += padded
        self._stats["buckets"].add(bucket)
        if padded:
            obs_metrics.gauge(
                "serve_padding_waste_ratio",
                help="fraction of batch elements that are "
                     "padding").set(1.0 - real / padded,
                                    kind=self.kind)
        attrs = {"kind": self.kind, "bucket": str(bucket),
                 "batch": len(group)}
        if isolated:
            attrs["isolated"] = True
        t0 = time.perf_counter()
        with obs_spans.span("serve.batch", attrs=attrs):
            results = self.op.dispatch(group, key, b_pad)
        dispatch_s = time.perf_counter() - t0
        obs_metrics.histogram(
            "serve_batch_seconds", unit="s").observe(
                dispatch_s, kind=self.kind)
        if obs_sink.enabled():
            # trace stage 3: one serve.dispatch span per member
            # request (a batch spans many traces, so the shared
            # serve.batch span above cannot parent them), carrying
            # the program-resolution bucket the request rode in
            for req in group:
                obs_trace.traced_span(
                    "serve.dispatch", dispatch_s, req,
                    attrs=dict(attrs, site=self.op.site))
        return results

    def _run_group(self, key, group):
        b_pad = self.op.batch_extent(len(group))
        bucket = key + (b_pad,)
        try:
            results = self._dispatch_group(key, group, b_pad)
        except Exception as exc:  # poison batch: isolate per request
            obs_sink.event("serve_batch_failed", kind=self.kind,
                           bucket=str(bucket),
                           error=type(exc).__name__)
            if not self.op.isolate_on_failure:
                # batch members interact (FCMA normalization):
                # singleton re-runs would change survivors' answers
                logger.warning(
                    "serve batch %s failed (%s: %s); %s batches "
                    "fail as a unit", bucket, type(exc).__name__,
                    exc, self.kind)
                for req in group:
                    self._record_error(
                        req, "execution_failed",
                        f"{type(exc).__name__}: {exc} (batch "
                        "fails as a unit: results are batch-"
                        "composition-dependent for this kind)")
                return
            logger.warning(
                "serve batch %s failed (%s: %s); retrying "
                "per-request to isolate the poison payload",
                bucket, type(exc).__name__, exc)
            self._run_isolated(key, group, b_pad)
            return
        done = time.monotonic()
        for req, result in zip(group, results):
            rec = ServeResult(
                request_id=req.request_id, ok=True, result=result,
                bucket=bucket, latency_s=done - req.submitted,
                seq=getattr(req, "_seq_index", None))
            self._finish(req, rec, outcome="ok")

    def _run_isolated(self, key, group, b_pad):
        """Per-request fallback after a batch-level failure: each
        request runs in its own singleton batch so exactly the
        poison one fails.  Re-dispatches honor the same deadline and
        stats accounting as the normal path (the failed batch may
        have burned a queued request's remaining budget).

        Singletons are re-padded to the FAILED dispatch's batch
        extent, the smallest admissible bucket known to this flush —
        never re-bucketed through the batch table — so poison
        recovery adds **zero** new program shapes per kind
        (``retrace_total{site=serve.*}`` stays bounded by the
        bucket count; the old ``batch_extent(1)`` re-pad minted a
        fresh singleton shape per poisoned data bucket)."""
        for req in group:
            if req.expired():
                waited = time.monotonic() - req.submitted
                self._record_error(
                    req, "deadline_exceeded",
                    f"deadline passed during the failed batch "
                    f"({waited:.3f}s > {req.deadline_s:.3f}s)",
                    latency=waited)
                continue
            try:
                result = self._dispatch_group(
                    key, [req], b_pad, isolated=True)[0]
            except Exception as exc:
                self._record_error(
                    req, "execution_failed",
                    f"{type(exc).__name__}: {exc}")
                continue
            rec = ServeResult(
                request_id=req.request_id, ok=True, result=result,
                bucket=key + (b_pad,),
                latency_s=time.monotonic() - req.submitted,
                seq=getattr(req, "_seq_index", None))
            self._finish(req, rec, outcome="ok")

    # -- reporting ----------------------------------------------------
    def summary(self):
        """Aggregate serving stats for the CLI / bench drivers.

        ``retrace_total`` is the growth of this site's compile
        counter since THIS engine was constructed (the process-wide
        counter keeps accumulating across engines); engines of the
        same kind running concurrently may cross-attribute each
        other's compiles.  Counts (``n_requests``/``n_ok``/
        ``n_errors``/batch/bucket/padding stats) are running totals
        that survive :meth:`drain` and include submit-time
        rejections; only the latency percentiles are derived from
        the undrained ok records."""
        records = self._records
        # served latencies only: instant validation rejections would
        # otherwise drag p50/p99 toward zero whenever errors occur
        latencies = sorted(r.latency_s for r in records
                           if r.ok and r.latency_s is not None)

        def pct(q):
            if not latencies:
                return None
            idx = min(len(latencies) - 1,
                      int(round(q * (len(latencies) - 1))))
            return latencies[idx]

        padded = self._stats["padded_elements"]
        real = self._stats["real_elements"]
        return {
            "kind": self.kind,
            "n_requests": self._n_submitted,
            "n_ok": self._stats["n_ok"],
            "n_errors": sum(
                self._stats["errors_by_code"].values()),
            "errors_by_code": dict(self._stats["errors_by_code"]),
            "n_batches": self._stats["n_batches"],
            "buckets": sorted(
                str(b) for b in self._stats["buckets"]),
            "retrace_total": obs_metrics.counter(
                "retrace_total").value(site=self.op.site)
            - self._retrace_base,
            "padding_waste": (1.0 - real / padded) if padded
            else 0.0,
            "p50_latency_s": pct(0.50),
            "p99_latency_s": pct(0.99),
        }
