"""Zero cold-start: persisted AOT-compiled serve programs.

A fresh serving process pays one trace + XLA compile per (model,
bucket) program before its first answer — exactly the stall a
restart or a preemption (the resilience subsystem's bread and
butter) turns into user-visible cold-start latency.  This module
removes it: every serve program the engine builds is exported with
``jax.export`` and written to an on-disk cache, and the next process
deserializes the persisted program instead of re-tracing, so its
first request runs with ``retrace_total{site=serve.*} == 0``.

Cache key schema (one file per program)::

    sha256(artifact digest | site | bucket key | jax version
           | platform)

- **artifact digest** (:func:`~brainiak_tpu.serve.artifacts.
  model_digest`) — programs can bake model-specific statics (RSRM's
  ``gamma``/``n_iter`` ride in the bucket key, but the digest also
  invalidates on refit, the conservative choice);
- **site + bucket key** — the same key the
  :func:`~brainiak_tpu.serve.engine.program_cache` builders use, so
  AOT entries and jit programs are one-to-one;
- **jax version + platform** — serialized programs are not portable
  across either; a version bump or a CPU/TPU move simply misses and
  falls back to jit (then re-populates).

Invalidation is purely key-based: a stale entry is never *wrong*,
only unreachable (its key no longer matches), so the cache needs no
coherence protocol — prune old files at will.

Two layers of persistence remove the stall end to end: the
serialized export removes the Python trace + jax lowering, and —
because deserialized programs are still XLA-compiled on first call —
the cache also points jax's **persistent compilation cache**
(``jax_compilation_cache_dir``) at ``<dir>/xla``, so the compiled
executable itself is reused across processes.  The latter is a
process-global jax config (it benefits every jitted program, which
is the point for a serving process); set
``BRAINIAK_TPU_SERVE_XLA_CACHE=0`` to leave jax's config untouched,
and on jax builds without the knobs it degrades silently to
export-only persistence.

Fallback semantics: every miss is counted in
``serve_aot_miss_total{reason=}`` (``unsupported`` — this jax has no
usable export API; ``absent`` — no entry under the key;
``deserialize_failed`` — unreadable/corrupt entry) and the engine
falls back to the jit builder, so AOT failure can cost a compile
stall but never an answer.  Hits count in
``serve_aot_hit_total{site=}``.  Cache writes go through
:func:`brainiak_tpu.resilience.retry` (transient shared-filesystem
faults back off and retry) and are atomic (tmp + rename), and a
write that still fails only emits an ``aot_store_failed`` event —
persisting a program is an optimization, never a serving
dependency.

The ``jax.export`` import is guarded by a
:mod:`brainiak_tpu.parallel.compat`-style version shim (top-level
module on modern jax, ``jax.experimental.export`` on the
transitional releases, absent before that — in which case every
lookup misses with ``reason="unsupported"``).
"""

import hashlib
import logging
import os
import threading

from ..obs import metrics as obs_metrics
from ..obs import sink as obs_sink
from ..resilience.retry import retry

logger = logging.getLogger(__name__)

__all__ = ["AOTProgramCache", "XLA_CACHE_ENV",
           "export_available"]

#: Set to ``0`` to keep AOTProgramCache from pointing jax's
#: persistent compilation cache at its directory (a process-global
#: config; see the module docstring).
XLA_CACHE_ENV = "BRAINIAK_TPU_SERVE_XLA_CACHE"

#: Bound on the in-memory table of deserialized programs (FIFO
#: beyond it).  Request-controlled bucket spaces (eventseg's exact
#: T) could otherwise grow it without limit in a long-lived
#: service — the same hazard the engine's per-op memo cap guards;
#: an evicted entry simply deserializes again from disk (a counted
#: hit, no compile).
MAX_RESIDENT_PROGRAMS = 256

# -- version shim (parallel/compat.py style) --------------------------
#
# jax.export moved across the releases this framework supports:
# modern jax exports it at top level, the transitional line kept it
# in jax.experimental.export, and older releases have neither — the
# cache then degrades to always-miss (reason="unsupported") and the
# engine serves through plain jit, the same graceful fallback as a
# corrupt entry.
try:  # modern jax: top-level module
    from jax import export as _export
except ImportError:  # pragma: no cover - version-dependent
    try:  # transitional releases
        from jax.experimental import export as _export
    except ImportError:
        _export = None

if _export is not None and not (hasattr(_export, "export")
                                and hasattr(_export, "deserialize")):
    _export = None  # pragma: no cover - exotic/partial API


def export_available():
    """Whether this jax exposes a usable ``export``/``deserialize``
    pair (the shim above found one)."""
    return _export is not None


def _environment_tag():
    """``jax version | platform`` — the environment half of the cache
    key.  Serialized programs are portable across neither, so both
    ride in the key and a mismatch is an ordinary ``absent`` miss."""
    import jax

    return f"{jax.__version__}|{jax.default_backend()}"


@retry(name="serve.aot_store", retries=2, backoff=0.05)
def _atomic_write(path, blob):
    """One atomic cache-entry write (tmp + rename), retried on
    transient ``OSError`` via :func:`brainiak_tpu.resilience.retry` —
    a shared-filesystem hiccup backs off instead of losing the
    entry."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as fh:
        fh.write(blob)
    os.replace(tmp, path)


class AOTProgramCache:
    """On-disk store of serialized serve programs + an in-process
    table of the ones already deserialized.

    One instance is shared by every engine of a serving process (the
    :class:`~brainiak_tpu.serve.residency.ModelResidency` threads it
    through), so :meth:`stats` is the process-wide hit/miss ledger
    the service summary and the SRV002 gate read.

    ``get`` returns a ready-to-call program (the deserialized export
    re-wrapped in ``jax.jit`` so repeat dispatches do not re-stage
    the StableHLO) or None; ``put`` exports + persists a jit program
    and never raises — see the module docstring for the fallback
    contract.
    """

    def __init__(self, directory, create=True):
        self.directory = os.fspath(directory)
        if create:
            os.makedirs(self.directory, exist_ok=True)
        # one cache is shared by every engine of the process; the
        # ledger lock covers only the in-memory tables — disk reads
        # and deserialization run outside it (a racing double
        # deserialize is benign, a blocked service tick is not)
        self._lock = threading.Lock()
        # key -> deserialized jitted callable
        self._programs = {}   # guarded-by: _lock
        self._hits = 0        # guarded-by: _lock
        self._misses = {}     # guarded-by: _lock
        self._stores = 0      # guarded-by: _lock
        self.xla_cache_dir = None
        if os.environ.get(XLA_CACHE_ENV, "1") != "0":
            self.xla_cache_dir = self._enable_xla_cache()

    def _enable_xla_cache(self):
        """Best-effort: point jax's persistent compilation cache at
        ``<dir>/xla`` so the XLA executables behind both the jit
        builders and the deserialized exports survive restarts —
        the serialized export alone removes trace+lowering, but the
        first call would still re-compile the StableHLO.  Returns
        the directory on success, None when this jax lacks the
        knobs (export-only persistence still works)."""
        xla_dir = os.path.join(self.directory, "xla")
        try:
            import jax

            os.makedirs(xla_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", xla_dir)
            # serve programs are small and compile fast; without
            # zeroing the thresholds jax would skip caching them
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0)
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", -1)
        except Exception as exc:  # pragma: no cover - jax version
            logger.info(
                "persistent XLA cache unavailable (%s: %s); "
                "export-only persistence", type(exc).__name__, exc)
            return None
        try:
            # a process that already compiled something initialized
            # the (disabled) cache; re-init so the new dir takes.
            # Private API — failure just means the dir applies only
            # to processes configured before their first compile.
            from jax._src import compilation_cache
            compilation_cache.reset_cache()
        except Exception:  # pragma: no cover - jax version
            pass
        return xla_dir

    # -- keys ---------------------------------------------------------

    def key_for(self, digest, site, args):
        """The cache key for one (model, program family, bucket):
        sha256 over artifact digest, builder site, the builder's
        bucket-key arguments, and the jax-version/platform tag."""
        h = hashlib.sha256()
        for part in (digest, site, repr(tuple(args)),
                     _environment_tag()):
            h.update(str(part).encode())
            h.update(b"|")
        return h.hexdigest()

    def _path(self, key, site):
        # the site prefix is cosmetic (the key hash alone is the
        # identity): it makes `ls` on the cache dir legible
        fam = site.replace("/", "_").replace(".", "_")
        return os.path.join(self.directory,
                            f"{fam}-{key[:32]}.jaxprog")

    # -- accounting ---------------------------------------------------

    def _miss(self, site, reason):
        with self._lock:
            self._misses[reason] = \
                self._misses.get(reason, 0) + 1
        obs_metrics.counter(
            "serve_aot_miss_total",
            help="AOT program-cache misses by reason").inc(
                site=site, reason=reason)
        return None

    def stats(self):
        """``{"hits", "misses": {reason: n}, "stores"}`` for this
        process — the summary block the service CLI prints and the
        SRV002 gate asserts on."""
        with self._lock:
            return {"hits": self._hits,
                    "misses": dict(self._misses),
                    "stores": self._stores}

    def warm(self):
        """True when this cache can satisfy restarts without a
        compile stall: persisted ``.jaxprog`` entries exist on disk
        (a prior process stored programs) or this process already
        hit/stored some.  The AOT half of the service's ``/readyz``
        readiness signal."""
        with self._lock:
            if self._hits or self._stores or self._programs:
                return True
        try:
            return any(name.endswith(".jaxprog")
                       for name in os.listdir(self.directory))
        except OSError:
            return False

    # -- lookup -------------------------------------------------------

    def get(self, key, site):
        """The persisted program under ``key``, or None (counted
        miss).  A disk hit deserializes once per process; the engine
        memoizes the returned callable per bucket, so each key is
        looked up at most once per engine."""
        with self._lock:
            cached = self._programs.get(key)
        if cached is not None:
            return cached
        if not export_available():
            return self._miss(site, "unsupported")
        path = self._path(key, site)
        try:
            with open(path, "rb") as fh:
                blob = fh.read()
        except FileNotFoundError:
            return self._miss(site, "absent")
        except OSError as exc:
            logger.warning("aot cache read failed (%s): %s",
                           path, exc)
            return self._miss(site, "deserialize_failed")
        try:
            import jax

            exported = _export.deserialize(blob)
            # re-wrap in jit: .call re-stages the StableHLO per
            # invocation otherwise.  The jit cache makes repeat
            # dispatches of this bucket as cheap as the builder
            # path — without ever running the builder (so
            # retrace_total{site=serve.*} stays 0 on a warm cache).
            # Built once per key: _programs memoizes the wrapper
            # below, so this is not a per-call jit.
            prog = jax.jit(exported.call)  # jaxlint: disable=JX001
        except Exception as exc:
            logger.warning(
                "aot entry %s failed to deserialize (%s: %s); "
                "falling back to jit", path,
                type(exc).__name__, exc)
            return self._miss(site, "deserialize_failed")
        with self._lock:
            if len(self._programs) >= MAX_RESIDENT_PROGRAMS:
                self._programs.pop(next(iter(self._programs)))
            self._programs[key] = prog
            self._hits += 1
        obs_metrics.counter(
            "serve_aot_hit_total",
            help="AOT program-cache hits (compile stall "
                 "avoided)").inc(site=site)
        return prog

    # -- store --------------------------------------------------------

    def put(self, key, site, prog, example_args):
        """Export ``prog`` (a jit program, possibly
        :func:`~brainiak_tpu.obs.profile.profile_program`-wrapped)
        for the shapes of ``example_args`` and persist it under
        ``key``.  Never raises: export or write failure emits an
        ``aot_store_failed`` event and the process simply stays on
        the jit program it already has."""
        if not export_available():
            return False
        path = self._path(key, site)
        if os.path.exists(path):
            return False  # already persisted (idempotent)
        try:
            import jax

            fn = getattr(prog, "__wrapped__", prog)
            specs = [jax.ShapeDtypeStruct(a.shape, a.dtype)
                     for a in example_args]
            blob = _export.export(fn)(*specs).serialize()
            _atomic_write(path, blob)
        except Exception as exc:
            logger.warning(
                "aot export of %s failed (%s: %s); serving "
                "continues on jit", site, type(exc).__name__, exc)
            obs_sink.event("aot_store_failed", site=site,
                           error=type(exc).__name__)
            return False
        with self._lock:
            self._stores += 1
        obs_sink.event("aot_store", site=site,
                       bytes=len(blob))
        return True
