"""Device-mesh / sharding helpers — the TPU-native analog of the reference's
mpi4py communication layer (SURVEY.md §2.2).

Instead of MPI ranks exchanging messages, algorithms here are pure jitted
functions over stacked arrays; parallelism is expressed by placing those
arrays on a :class:`jax.sharding.Mesh` and letting XLA's SPMD partitioner
insert the collectives (psum/all_gather over ICI/DCN).
"""

from .compat import shard_map  # noqa: F401
from .mesh import (  # noqa: F401
    DEFAULT_SUBJECT_AXIS,
    DEFAULT_VOXEL_AXIS,
    initialize_distributed,
    make_mesh,
    max_divisible_shards,
    place_on_mesh,
    replicated,
    shard_along,
    subject_voxel_mesh,
)
