"""Mesh construction and sharding helpers.

Replaces the reference's injectable ``comm=MPI.COMM_WORLD`` parameter
(e.g. srm.py:211, htfa.py:171, fcma/preprocessing.py:157): estimators
accept an optional ``mesh=`` and place their stacked per-subject /
per-voxel arrays accordingly.  Collectives are inserted by XLA (GSPMD)
rather than called explicitly.
"""

import logging
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..obs import metrics as obs_metrics
from ..obs import runtime as obs_runtime
from ..resilience.retry import retry

logger = logging.getLogger(__name__)

__all__ = [
    "DEFAULT_SUBJECT_AXIS",
    "DEFAULT_VOXEL_AXIS",
    "fetch_replicated",
    "initialize_distributed",
    "make_mesh",
    "max_divisible_shards",
    "place_on_mesh",
    "replicated",
    "shard_along",
    "subject_voxel_mesh",
]

DEFAULT_SUBJECT_AXIS = "subject"
DEFAULT_VOXEL_AXIS = "voxel"


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Initialize multi-host JAX (DCN) — the analog of MPI_Init.

    No-op for single-process runs; on a pod slice each host calls this
    before building meshes so ``jax.devices()`` spans the slice.

    The coordinator connect retries with exponential backoff: on a
    freshly scheduled (or preemption-restarted) slice the workers
    routinely come up before the coordinator process is listening, and
    the resulting connect failure is transient, not fatal.
    """
    if num_processes is not None and num_processes > 1:
        def _transient(exc):
            # RuntimeError is retried only when it looks like a
            # transient connect failure; deterministic errors (already
            # initialized, bad config) propagate immediately instead
            # of burning the backoff budget.
            if not isinstance(exc, RuntimeError):
                return True
            msg = str(exc).lower()
            return any(tok in msg for tok in
                       ("deadline", "unavailable", "connect",
                        "timed out", "timeout"))

        connect = retry(
            jax.distributed.initialize, retries=4, backoff=1.0,
            retriable=(OSError, ConnectionError, RuntimeError),
            retry_if=_transient,
            name="jax.distributed.initialize")
        connect(coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id)


@obs_runtime.counted_cache("parallel.replicate_identity")
def _replicate_identity(mesh: Mesh):
    """Mesh-keyed cached jitted identity with replicated out_shardings —
    the collective-reshard fallback for :func:`fetch_replicated`.

    Caching per mesh matters: jit caches key on function identity, so a
    fresh ``jax.jit(lambda a: a, ...)`` per call would recompile (and
    re-lower the all-gather) on every fetch.  A cache miss counts as a
    ``retrace_total{site=parallel.replicate_identity}`` increment.
    """
    return jax.jit(lambda a: a,
                   out_shardings=NamedSharding(mesh, PartitionSpec()))


@obs_runtime.trace_signature("parallel.replicate_identity")
def _replicate_identity_trace_signature():
    import jax.numpy as jnp

    mesh = make_mesh((DEFAULT_VOXEL_AXIS,), (-1,))
    v = 2 * mesh.shape[DEFAULT_VOXEL_AXIS]
    return [{"key": (mesh,),
             "args": (jax.ShapeDtypeStruct((v,), jnp.float32),),
             "mesh": mesh}]


def fetch_replicated(x, mesh: Optional[Mesh] = None):
    """Host-fetch a possibly mesh-sharded array as a full numpy array on
    EVERY process — the analog of the reference's MPI gather of results
    to all ranks (e.g. voxel scores in fcma, reference
    voxelselector.py:208-238).

    Single-process (every shard addressable): a plain ``np.asarray``.
    Multi-process: relayout to a replicated sharding first (one
    all-gather over ICI/DCN), because indexing or ``np.asarray`` on a
    cross-process-sharded array raises.  Results in this framework are
    small (per-voxel scalars, factor parameters), so replication is
    cheap relative to the compute that produced them.

    Backend dependency (JAX-version-sensitive): the fast path relies
    on ``jax.device_put`` supporting CROSS-PROCESS resharding (moving
    shards between processes outside a jitted computation).  That
    capability landed in jax 0.4.x for TPU/ICI, remains
    backend-dependent in the 0.4-0.6 line — plugin PJRT backends (and
    some GPU transports) reject it with ``NotImplementedError`` /
    ``ValueError`` — and its error TYPE has shifted across jax
    releases (``RuntimeError`` on some), which is why all three are
    caught below.  On those backends this falls back to a mesh-keyed
    cached jitted identity whose replicated ``out_shardings`` makes
    XLA itself insert the all-gather, which every SPMD backend
    supports.  Each engagement of the fallback increments the obs
    counter ``fetch_replicated_fallback_total{reason=<ExcType>}`` so a
    fleet quietly running the slower path is visible in telemetry
    (ADVICE round 5).
    """
    if mesh is None and isinstance(x, jax.Array) \
            and not x.is_fully_addressable:
        mesh = x.sharding.mesh
    if mesh is None or jax.process_count() == 1:
        return np.asarray(x)
    try:
        # device_put reshards across process boundaries without tracing
        # a fresh jitted identity per call.
        rep = jax.device_put(x, NamedSharding(mesh, PartitionSpec()))
    except (NotImplementedError, ValueError, RuntimeError) as exc:
        # WARNING, not debug: if device_put failed for a reason other
        # than a missing backend capability (mesh mismatch, OOM), the
        # fallback will likely fail too and the root cause must not be
        # hidden in a suppressed log.
        logger.warning(
            "cross-process device_put reshard failed (%s: %s); falling "
            "back to the jitted-identity all-gather",
            type(exc).__name__, exc)
        obs_metrics.counter(
            "fetch_replicated_fallback_total",
            help="cross-process device_put reshards that fell back "
                 "to the jitted-identity all-gather").inc(
                reason=type(exc).__name__)
        rep = _replicate_identity(mesh)(x)
    return np.asarray(rep)


def max_divisible_shards(axis_length: int, devices=None) -> int:
    """Largest shard count that evenly divides ``axis_length`` and fits
    the available devices — sharded array dimensions must divide the mesh
    axis, so e.g. 6 subjects on 8 devices shard 6 ways."""
    n = len(jax.devices() if devices is None else devices)
    return max(d for d in range(1, n + 1) if axis_length % d == 0)


def make_mesh(axis_names: Sequence[str], axis_sizes: Sequence[int],
              devices=None) -> Mesh:
    """Build a Mesh with the given axes over ``devices`` (default: all).

    ``axis_sizes`` may contain one -1, filled with the remaining devices.
    """
    if devices is None:
        devices = jax.devices()
    sizes = list(axis_sizes)
    n = len(devices)
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        if known <= 0 or n % known:
            raise ValueError(
                f"Cannot infer -1 axis from {n} devices and sizes {sizes}")
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    if total > n:
        raise ValueError(f"Mesh of {sizes} needs {total} devices, have {n}")
    mesh_devices = np.asarray(devices[:total]).reshape(sizes)
    mesh = Mesh(mesh_devices, tuple(axis_names))
    # topology capture (no-op while obs is disabled): every mesh a run
    # builds lands in the trace with its axis map and backend
    obs_runtime.topology_event(mesh)
    return mesh


def subject_voxel_mesh(n_subject_shards: int = -1,
                       n_voxel_shards: int = 1,
                       devices=None) -> Mesh:
    """The framework's standard 2-D mesh ``('subject', 'voxel')``.

    Subject-parallel algorithms (SRM/HTFA/ISC) shard the leading subject
    axis; voxel-parallel ones (FCMA/searchlight) the voxel axis.
    """
    return make_mesh((DEFAULT_SUBJECT_AXIS, DEFAULT_VOXEL_AXIS),
                     (n_subject_shards, n_voxel_shards), devices)


def place_on_mesh(array, sharding):
    """Place a possibly-host array with ``sharding``.

    Single-process, or an input that is already a ``jax.Array``:
    plain ``device_put`` (for device arrays this is the collective
    reshard path).  Multi-process HOST values instead fill each
    addressable shard from THIS process's copy — the MPI-replica
    semantic (every rank holds its own logically-identical replica).
    ``device_put`` would assert bit-equality of the host value across
    processes, which fp32 reduction-order divergence legally violates
    (each process materialized its replica through its own reduction
    order).
    """
    if jax.process_count() == 1 or isinstance(array, jax.Array):
        return jax.device_put(array, sharding)
    arr = np.asarray(array)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


def shard_along(array, mesh: Mesh, axis_name: str, array_dim: int = 0):
    """Place ``array`` on ``mesh`` sharded over ``axis_name`` at dim
    ``array_dim`` (other dims replicated)."""
    spec = [None] * np.ndim(array)
    spec[array_dim] = axis_name
    return place_on_mesh(array, NamedSharding(mesh, PartitionSpec(*spec)))


def replicated(array, mesh: Mesh):
    """Place ``array`` on ``mesh`` fully replicated."""
    return place_on_mesh(array, NamedSharding(mesh, PartitionSpec()))
