"""Multi-process distributed test harness.

The TPU-native analog of the reference's vendored pytest-mpiexec plugin
(reference tests/pytest_mpiexec_plugin.py): where the reference re-executes
tests under ``mpiexec -n N`` to exercise MPI collectives on one machine,
this harness launches N OS processes that form a ``jax.distributed``
cluster over a local coordinator, each backed by virtual CPU devices — the
same code path (multi-controller runtime + GSPMD collectives over what
would be DCN on a pod) without TPU hardware.

Usage: write a worker function in an importable module with signature
``worker(process_id, num_processes)`` (it runs after jax.distributed is
initialized) and call :func:`run_distributed`.
"""

import os
import pickle
import socket
import subprocess
import sys
import tempfile

__all__ = ["run_distributed"]

_WORKER_TEMPLATE = """
import os, pickle, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    "--xla_force_host_platform_device_count={local_devices}")
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", {x64})
jax.distributed.initialize(coordinator_address="{coord}",
                           num_processes={n},
                           process_id={pid})
sys.path.insert(0, {extra_path!r})
from {module} import {fn} as worker
try:
    result = worker({pid}, {n})
except BaseException:
    import traceback
    traceback.print_exc(file=sys.stderr)
    sys.stderr.flush()
    # skip atexit: jax.distributed shutdown would block on peers that
    # are themselves blocked in a collective waiting for this process
    os._exit(1)
with open({out!r}, "wb") as f:
    pickle.dump(result, f)
"""


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_distributed(module, fn, n_procs=2, local_devices=2, timeout=240,
                    extra_path=None, x64=True):
    """Run ``module.fn(process_id, num_processes)`` in ``n_procs``
    OS processes forming one jax.distributed cluster.

    Each process sees ``local_devices`` virtual CPU devices, so the global
    device count is ``n_procs * local_devices``.  Returns the list of
    per-process return values (must be picklable).
    """
    coord = f"127.0.0.1:{_free_port()}"
    if extra_path is None:
        extra_path = os.getcwd()
    procs = []
    outs = []
    with tempfile.TemporaryDirectory() as tmp:
        for pid in range(n_procs):
            out = os.path.join(tmp, f"result_{pid}.pkl")
            outs.append(out)
            code = _WORKER_TEMPLATE.format(
                coord=coord, n=n_procs, pid=pid, module=module, fn=fn,
                out=out, local_devices=local_devices,
                extra_path=extra_path, x64=x64)
            env = dict(os.environ)
            env.pop("PYTHONPATH", None)
            # redirect output to files: PIPEs would fill and deadlock
            # verbose workers since the poll loop does not drain them
            err_path = os.path.join(tmp, f"stderr_{pid}.log")
            err_f = open(err_path, "wb")
            procs.append((subprocess.Popen(
                [sys.executable, "-c", code], env=env,
                stdout=err_f, stderr=err_f), err_path, err_f))
        # Poll: the moment any worker dies with an error, kill the rest —
        # peers blocked in a collective would otherwise hang to timeout.
        import time as _time

        deadline = _time.monotonic() + timeout
        errors = []
        results = []
        timed_out = False
        while True:
            rcs = [p.poll() for p, _, _ in procs]
            failed = [pid for pid, rc in enumerate(rcs)
                      if rc is not None and rc != 0]
            if failed or all(rc is not None for rc in rcs):
                break
            if _time.monotonic() > deadline:
                timed_out = True
                break
            _time.sleep(0.1)
        killed = set()
        for pid, (p, _, _) in enumerate(procs):
            if p.poll() is None:
                p.kill()
                killed.add(pid)
        if timed_out:
            errors.append(f"distributed run timed out after {timeout}s")
        for pid, (p, err_path, err_f) in enumerate(procs):
            p.wait()
            err_f.close()
            if p.returncode != 0 and pid not in killed:
                with open(err_path, "rb") as f:
                    tail = f.read()[-2000:].decode(errors="replace")
                errors.append(
                    f"process {pid} failed (rc={p.returncode}):\n"
                    f"{tail}")
        if errors:
            raise RuntimeError("\n".join(errors))
        for out in outs:
            with open(out, "rb") as f:
                results.append(pickle.load(f))
    return results
