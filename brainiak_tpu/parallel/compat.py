"""JAX API compatibility shims for the parallel layer.

One import point for APIs whose location or keyword spelling moved
across the jax releases this framework supports.  Today that is
``shard_map``:

- modern jax exports it at top level (``jax.shard_map``) and spells
  the replication-check flag ``check_vma``;
- the 0.4.x line keeps it in ``jax.experimental.shard_map`` and
  spells the same flag ``check_rep``.

Every ``shard_map`` use in the package goes through
:func:`shard_map` below (ISSUE 6 satellite) — the former scattered
``from jax import shard_map`` sites raised ``ImportError`` outright
on 0.4.x, which is exactly the class of environment drift a single
shim can absorb.  Call sites use the modern keyword (``check_vma``);
the shim translates for older jax.
"""

import inspect

try:  # modern jax: top-level export
    from jax import shard_map as _shard_map_impl
except ImportError:  # 0.4.x line: experimental namespace
    from jax.experimental.shard_map import shard_map as _shard_map_impl

__all__ = ["shard_map"]

#: Keyword the underlying implementation uses for the replication /
#: varying-manual-axes check (``check_vma`` on modern jax,
#: ``check_rep`` before the rename).
_CHECK_KW = None
for _name in ("check_vma", "check_rep"):
    try:
        if _name in inspect.signature(_shard_map_impl).parameters:
            _CHECK_KW = _name
            break
    except (TypeError, ValueError):  # pragma: no cover - exotic impl
        break


def shard_map(f, mesh, in_specs, out_specs, check_vma=None):
    """Version-portable ``shard_map``.

    Parameters mirror ``jax.shard_map``; ``check_vma`` (the modern
    spelling; ``None`` keeps the implementation default) is
    translated to ``check_rep`` on jax versions that predate the
    rename.  Positional layout is the one both generations accept.
    """
    kwargs = {}
    if check_vma is not None and _CHECK_KW is not None:
        kwargs[_CHECK_KW] = check_vma
    return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, **kwargs)
