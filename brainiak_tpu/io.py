"""I/O functionality: NIfTI images, boolean masks, condition-label files.

Re-design of /root/reference/src/brainiak/io.py with the same public surface,
backed by the self-contained :mod:`brainiak_tpu.nifti` codec instead of
nibabel.
"""

import logging
from pathlib import Path
from typing import Callable, Iterable, List, Optional, Union

import numpy as np

from . import nifti
from .image import SingleConditionSpec
from .resilience import faults
from .resilience.retry import retry

__all__ = [
    "load_boolean_mask",
    "load_images",
    "load_images_from_dir",
    "load_labels",
    "save_as_nifti_file",
]

logger = logging.getLogger(__name__)


def load_images_from_dir(in_dir: Union[str, Path], suffix: str = "nii.gz",
                         ) -> Iterable[nifti.NiftiImage]:
    """Lazily load all images in a directory whose names end with ``suffix``,
    in sorted order (reference io.py:39-72)."""
    if isinstance(in_dir, str):
        in_dir = Path(in_dir)
    for f in sorted(in_dir.glob("*" + suffix)):
        logger.debug('Starting to read file %s', f)
        yield nifti.load(str(f))


def load_images(image_paths: Iterable[Union[str, Path]]
                ) -> Iterable[nifti.NiftiImage]:
    """Lazily load images from explicit paths (reference io.py:75-103)."""
    for image_path in image_paths:
        string_path = str(image_path)
        logger.debug('Starting to read file %s', string_path)
        yield nifti.load(string_path)


def load_boolean_mask(path: Union[str, Path],
                      predicate: Optional[
                          Callable[[np.ndarray], np.ndarray]] = None
                      ) -> np.ndarray:
    """Load a boolean mask volume; ``predicate`` maps data to booleans
    (default: truthiness) (reference io.py:106-132)."""
    data = nifti.load(str(path)).get_fdata()
    if predicate is not None:
        return predicate(data)
    return data.astype(bool)


@retry(retries=3, backoff=0.25, retriable=(OSError,),
       name="io.load_labels")
def load_labels(path: Union[str, Path]) -> List[SingleConditionSpec]:
    """Load an ``.npy`` of condition-spec arrays as SingleConditionSpec views
    (reference io.py:135-149).  Retries transient filesystem errors
    like the image loaders (which inherit retry from ``nifti.load``)."""
    faults.io_point(str(path), site="io.load_labels")
    condition_specs = np.load(str(path))
    return [c.view(SingleConditionSpec) for c in condition_specs]


def save_as_nifti_file(data: np.ndarray, affine: np.ndarray,
                       path: Union[str, Path]) -> None:
    """Save a data volume with the given affine as a NIfTI file
    (reference io.py:152-168)."""
    nifti.save(nifti.NiftiImage(data, affine), str(path))
