"""Non-finite-state guards and the resilient fit-loop driver.

Every iterative estimator in the framework (EM, BCD, annealed HMM,
L-BFGS outer rounds) advances a flat dict of state arrays chunk by
chunk.  :func:`run_resilient_loop` drives that shape uniformly:

- **guard** — after each chunk the new state is checked for NaN/Inf
  (:func:`check_state`); a non-finite leaf triggers a rollback to the
  last good state and a deterministic re-run of the chunk, and after
  ``max_rollbacks`` consecutive failures the fit aborts with a
  :class:`DivergenceError` naming the offending leaves and iteration;
- **checkpoint/resume** — with ``checkpoint_dir`` the state is
  persisted every ``checkpoint_every`` iterations through
  :class:`~brainiak_tpu.utils.checkpoint.CheckpointManager` (orbax, npz
  fallback) and a later call resumes from the latest step, validated
  against a data/config ``fingerprint``;
- **fault hooks** — :mod:`brainiak_tpu.resilience.faults` injection
  points (``nan`` corruption before the guard, ``preempt`` after each
  checkpoint save) so CI exercises both recovery paths without real
  preemption;
- **telemetry** — with :mod:`brainiak_tpu.obs` enabled every chunk
  runs under a ``fit_chunk`` span and the loop emits
  ``resume``/``rollback``/``checkpoint``/``divergence_abort`` events
  plus ``fit_steps_total``/``rollback_total``/``checkpoint_seconds``
  metrics and per-chunk memory watermarks
  (``hbm_peak_bytes``/``hbm_bytes_in_use``/``host_peak_rss_bytes``
  via :func:`brainiak_tpu.obs.profile.memory_watermark`), all
  labeled with the loop ``name`` (disabled: no-ops).

The guard granularity is the chunk (``checkpoint_every`` iterations for
fused on-device loops, which cannot host-inspect intermediate
iterates); host-driven loops additionally call :func:`check_state`
per outer iteration inside their chunk callbacks.
"""

import contextlib
import logging
import threading
import time

import numpy as np

from . import faults
from ..obs import flight as obs_flight
from ..obs import metrics as obs_metrics
from ..obs import profile as obs_profile
from ..obs import progress as obs_progress
from ..obs import sanitize as obs_sanitize
from ..obs import sink as obs_sink
from ..obs import spans as obs_spans

logger = logging.getLogger(__name__)

__all__ = ["DivergenceError", "FitParked", "array_digest",
           "check_state", "leaves_to_device", "make_device_carry_chunk",
           "pack_rng_state", "park_scope", "run_resilient_loop",
           "unpack_rng_state"]


def array_digest(*arrays):
    """Order-sensitive content digest of arrays for checkpoint
    fingerprints.

    A plain ``sum(data)`` is ~0 for demeaned/z-scored inputs (the
    common fMRI preprocessing), and a sum of squares is constant for
    per-voxel z-scored data — either would let a checkpoint from one
    dataset silently resume against another of the same shape.  The
    cosine-ramp inner product is position- and content-sensitive;
    the squared term additionally scales with magnitude.
    """
    total = 0.0
    for a in arrays:
        flat = np.asarray(a, dtype=float).ravel()
        ramp = np.cos(np.arange(flat.size, dtype=float))
        total += float(flat @ ramp) + float(flat @ flat)
    return total


def leaves_to_device(state, keys, dtype=None):
    """Return ``state``'s leaves at ``keys``, in order, as device
    arrays of ``dtype`` — the standard round-trip when a jitted chunk
    resumes from :func:`run_resilient_loop` state (host numpy after a
    checkpoint restore, possibly device arrays mid-run)."""
    import jax.numpy as jnp
    return tuple(jnp.asarray(np.asarray(state[k]), dtype=dtype)
                 for k in keys)


def make_device_carry_chunk(chunk_fn, leaf_keys, fetch=np.asarray,
                            dtype=None):
    """Build ``(run_chunk, final_leaves)`` for a fused on-device fit.

    ``chunk_fn(leaves, n_steps) -> leaves`` advances the jitted loop.
    The returned ``run_chunk`` feeds :func:`run_resilient_loop`: the
    host dict it returns serves the guard + checkpoint, while the
    device outputs are carried across chunks so the next chunk (and
    ``final_leaves(state, step)`` after the loop) reuse them directly
    — no re-upload/reshard per chunk.  On a resume or a rollback the
    carried step no longer matches and the leaves are rebuilt from the
    (host) driver state.
    """
    carry = {}

    def run_chunk(state, step, n_steps):
        if carry.get("step") == step:
            dev = carry["leaves"]
        else:
            dev = leaves_to_device(state, leaf_keys, dtype)
        dev = chunk_fn(dev, n_steps)
        carry["step"] = step + n_steps
        carry["leaves"] = dev
        return {k: fetch(v) for k, v in zip(leaf_keys, dev)}, False

    def final_leaves(state, step):
        if carry.get("step") == step:
            return carry["leaves"]
        # resumed straight to completion: no chunk ran this process
        return leaves_to_device(state, leaf_keys, dtype)

    return run_chunk, final_leaves


def pack_rng_state(rng):
    """Serialize a ``np.random.RandomState`` as two checkpointable
    arrays ``(keys uint32[624], meta float[3])`` — stochastic fit loops
    (TFA's voxel/TR subsampling, BRSA's random restarts) must persist
    their stream position for a resumed fit to reproduce the
    uninterrupted iterates."""
    kind, keys, pos, has_gauss, cached = rng.get_state()
    assert kind == "MT19937"
    return (np.asarray(keys, dtype=np.uint32),
            np.array([pos, has_gauss, cached], dtype=float))


def unpack_rng_state(rng, keys, meta):
    """Restore a ``np.random.RandomState`` from
    :func:`pack_rng_state` arrays (possibly round-tripped through a
    checkpoint)."""
    meta = np.asarray(meta, dtype=float)
    rng.set_state(("MT19937", np.asarray(keys).astype(np.uint32),
                   int(meta[0]), int(meta[1]), float(meta[2])))
    return rng


class DivergenceError(FloatingPointError):
    """An iterative fit produced non-finite state.

    Attributes
    ----------
    leaves : list of str
        Names of the offending state leaves.
    iteration : int or None
        Iteration at which the guard tripped.
    where : str or None
        Estimator / loop label.
    """

    def __init__(self, leaves, iteration=None, where=None):
        self.leaves = list(leaves)
        self.iteration = iteration
        self.where = where
        at = f" at iteration {iteration}" if iteration is not None else ""
        loop = f" in {where}" if where else ""
        super().__init__(
            f"non-finite values{loop}{at} in state leaves: "
            f"{', '.join(self.leaves)}")


class FitParked(RuntimeError):
    """A resilient fit stopped at a chunk boundary on request.

    Raised by :func:`run_resilient_loop` when the ambient
    :func:`park_scope` predicate returns true right after a checkpoint
    save — the fit's state is durably on disk, so re-invoking the same
    fit entry point with the same ``checkpoint_dir`` resumes under the
    same ``fit_id`` with cumulative wall-clock accounting.  This is the
    preemption primitive the jobs scheduler builds on; it is NOT an
    error in the fit itself.

    Attributes
    ----------
    step : int
        Iteration the checkpoint holds (where the resume will start).
    fit_id : str or None
        The fit's stable id (persisted in the checkpoint).
    name : str or None
        Loop label (``SRM.fit``, ...).
    """

    def __init__(self, step, fit_id=None, name=None):
        self.step = step
        self.fit_id = fit_id
        self.name = name
        loop = f"{name}: " if name else ""
        super().__init__(
            f"{loop}fit parked at iteration {step} "
            f"(fit_id={fit_id}); re-run with the same checkpoint_dir "
            f"to resume")


_park_local = threading.local()


@contextlib.contextmanager
def park_scope(should_park):
    """Make every :func:`run_resilient_loop` on this thread parkable.

    ``should_park`` is a zero-argument callable consulted exactly once
    per persisted chunk (right after the checkpoint save, and only when
    the loop has a ``checkpoint_dir`` — parking without a checkpoint
    would discard work).  When it returns true the loop finishes its
    progress stream with status ``"parked"`` and raises
    :class:`FitParked`.  Because the predicate fires once per chunk it
    doubles as the scheduler's chunk-grant meter: a closure counting
    its own invocations implements "run N chunks, then yield".

    Scopes nest; the innermost predicate wins and the previous one is
    restored on exit.  Predicate exceptions are swallowed (a broken
    scheduler must not kill a healthy fit).
    """
    prev = getattr(_park_local, "pred", None)
    _park_local.pred = should_park
    try:
        yield
    finally:
        _park_local.pred = prev


def _should_park():
    pred = getattr(_park_local, "pred", None)
    if pred is None:
        return False
    try:
        return bool(pred())
    except Exception:
        logger.exception("park predicate raised; ignoring")
        return False


def check_state(state, iteration=None, where=None, skip=(),
                nan_only=False):
    """Raise :class:`DivergenceError` if any floating leaf of ``state``
    (a flat dict of arrays) is non-finite.

    ``skip`` names leaves excluded from the check (e.g. log-likelihood
    histories that are NaN-padded by design); ``nan_only=True`` accepts
    infinities, for log-domain states where ``-inf`` is a legitimate
    zero probability.
    """
    bad = []
    for name, leaf in state.items():
        if name in skip:
            continue
        arr = np.asarray(leaf)
        if arr.dtype.kind != "f":
            continue
        if nan_only:
            ok = not np.any(np.isnan(arr))
        else:
            ok = bool(np.all(np.isfinite(arr)))
        if not ok:
            bad.append(name)
    if bad:
        raise DivergenceError(bad, iteration=iteration, where=where)


def _fingerprint_mismatch(saved, fingerprint):
    if saved is None:
        return True
    saved = np.asarray(saved, dtype=float).reshape(-1)
    fingerprint = np.asarray(fingerprint, dtype=float).reshape(-1)
    if saved.shape != fingerprint.shape:
        return True
    # atol=0: the default atol=1e-8 would equate any two near-zero
    # components (e.g. data sums of demeaned inputs), defeating the
    # mismatch guard entirely
    return not np.allclose(saved, fingerprint, rtol=1e-10, atol=0.0)


#: Checkpoint bookkeeping leaves the loop owns (stripped from the
#: state handed to ``run_chunk``): the data/config fingerprint, the
#: fit_id (uint8[16] of its hex digits), and [cumulative wall
#: seconds, cumulative chunk count] — the latter two so a resumed
#: fit continues the same progress stream with honest rate/ETA
#: accounting instead of restarting the clock from zero.
_CKPT_META = ("fingerprint", "fit_id", "fit_wall")


def _encode_fit_id(fit_id):
    return np.frombuffer(fit_id.encode("ascii"),
                         dtype=np.uint8).copy()


def _decode_fit_id(leaf):
    try:
        raw = bytes(np.asarray(leaf).astype(np.uint8).tolist())
        fit_id = raw.decode("ascii")
        int(fit_id, 16)  # trace-id shaped or bust
        return fit_id
    except (ValueError, UnicodeDecodeError):
        return None


def run_resilient_loop(run_chunk, init_state, n_iter, *,
                       checkpoint_dir=None, checkpoint_every=5,
                       fingerprint=None, template=None, max_rollbacks=2,
                       name="fit", guard_skip=(), guard_nan_only=False,
                       progress_objective=None,
                       progress_direction="min"):
    """Drive an iterative fit resiliently; returns ``(state, step)``.

    Parameters
    ----------
    run_chunk : callable ``(state, step, n_steps) -> (state, done)``
        Advance the fit ``n_steps`` iterations from ``state`` (a flat
        dict mapping leaf name to array).  ``done=True`` signals early
        convergence.  Must be deterministic in ``(state, step)`` so a
        rollback re-run and a resume reproduce the original iterates.
    init_state : dict
        Fresh-start state (ignored when a checkpoint is resumed).
        A ``"done"`` leaf, when present, is interpreted as the early-
        convergence flag across checkpoint round trips.
    n_iter : int
        Total iteration budget.
    checkpoint_dir, checkpoint_every
        When ``checkpoint_dir`` is set, state is persisted every
        ``checkpoint_every`` iterations and the latest checkpoint is
        resumed (after fingerprint validation).
    fingerprint : 1-D float array, optional
        Data/config digest stored with each checkpoint; resuming
        against a different digest raises ``ValueError`` instead of
        silently mixing runs.
    template : dict, optional
        Restore template (leaf name -> zeros of the right shape/dtype)
        for sharded orbax restores; ``None`` restores the raw tree.
    max_rollbacks : int, default 2
        Consecutive guard-triggered rollbacks tolerated before the
        :class:`DivergenceError` propagates.
    name : str
        Label for logs and errors.
    guard_skip, guard_nan_only
        Forwarded to :func:`check_state`.
    progress_objective : str or callable, optional
        Objective hint for the fit-progress tracker
        (:class:`brainiak_tpu.obs.progress.FitProgress`): a state
        leaf name (reduced with ``np.mean``) or ``state -> float``.
        Without it the fit still reports chunk cadence / ratio / ETA
        but no objective-trend telemetry.
    progress_direction : {"min", "max"}
        Whether ``progress_objective`` should decrease or increase
        as the fit converges (drives the divergence precursor).

    Every run owns a stable ``fit_id`` (persisted in the checkpoint,
    so a resume continues the same id) and emits one schema-v4
    ``progress`` record per chunk; on divergence abort the flight
    recorder dumps an incident snapshot
    (:func:`brainiak_tpu.obs.flight.dump`).
    """
    from ..utils.checkpoint import CheckpointManager

    if checkpoint_every < 1:
        raise ValueError(
            "checkpoint_every must be >= 1 (got {}); omit "
            "checkpoint_dir to disable checkpointing".format(
                checkpoint_every))
    mngr = None
    step = 0
    state = init_state
    saved_fit_id = None
    saved_wall, saved_chunks = 0.0, 0
    if checkpoint_dir is not None:
        mngr = CheckpointManager(checkpoint_dir)
        tpl = template
        if tpl is not None:
            meta = {"fit_id": np.zeros(16, dtype=np.uint8),
                    "fit_wall": np.zeros(2, dtype=float)}
            if fingerprint is not None:
                meta["fingerprint"] = np.zeros_like(
                    np.asarray(fingerprint, dtype=float))
            tpl = dict(tpl, **meta)
        saved_step, saved = mngr.restore(template=tpl)
        if saved is not None:
            if fingerprint is not None and _fingerprint_mismatch(
                    saved.get("fingerprint"), fingerprint):
                raise ValueError(
                    "Checkpoint in {} was written for different data "
                    "or model settings; use a fresh "
                    "checkpoint_dir".format(checkpoint_dir))
            if saved_step > n_iter:
                raise ValueError(
                    "Checkpoint is at iteration {} but n_iter={}; use "
                    "a fresh checkpoint_dir or raise n_iter".format(
                        saved_step, n_iter))
            if "fit_id" in saved:
                saved_fit_id = _decode_fit_id(saved["fit_id"])
            if "fit_wall" in saved:
                wall = np.asarray(saved["fit_wall"],
                                  dtype=float).reshape(-1)
                if wall.size >= 2 and np.all(np.isfinite(wall[:2])):
                    saved_wall = float(wall[0])
                    saved_chunks = int(wall[1])
            state = {k: v for k, v in saved.items()
                     if k not in _CKPT_META}
            step = saved_step
            logger.info("%s: resumed from checkpoint at iteration %d",
                        name, step)
            obs_sink.event("resume", estimator=name, step=step,
                           fit_id=saved_fit_id)
            obs_metrics.counter(
                "resume_total",
                help="checkpoint resumes").inc(estimator=name)
    progress = obs_progress.FitProgress(
        name, n_iter, fit_id=saved_fit_id,
        objective=progress_objective, direction=progress_direction,
        n_chunks=-(-int(n_iter) // int(checkpoint_every)) or None,
        wall0=saved_wall, chunks0=saved_chunks)

    done = bool(np.asarray(state.get("done", False)).reshape(-1)[0]) \
        if isinstance(state, dict) and "done" in state else False
    last_good = (step, state)
    rollbacks = 0
    while step < n_iter and not done:
        n_steps = min(checkpoint_every, n_iter - step)
        try:
            # run_chunk may itself raise DivergenceError from a
            # per-iteration check_state; it gets the same rollback.
            # The span is a no-op while obs is disabled (and never
            # introduces a device sync either way: run_chunk returns
            # host-checkpointable state by contract).  Memory
            # watermarks bracket the chunk: the delta of the device
            # high-water mark across the chunk becomes
            # hbm_peak_bytes{estimator=} (never a backend init, never
            # a sync — memory_stats is a host-side counter read).
            watermark = obs_profile.memory_watermark() \
                if obs_sink.enabled() else None
            t_chunk = time.perf_counter()
            with obs_spans.span(
                    "fit_chunk",
                    attrs={"estimator": name, "step": step,
                           "n_steps": n_steps,
                           "fit_id": progress.fit_id}):
                if obs_sanitize.enabled():
                    # the checkify lane (BRAINIAK_TPU_SANITIZE=1):
                    # a tripped NaN/div/OOB check inside a traceable
                    # chunk emits a typed ``sanitizer`` event and
                    # feeds the rollback machinery like any other
                    # divergence; step/n_steps stay static so chunk
                    # drivers may use them in Python control flow
                    sanitizer_error, (new_state, done) = \
                        obs_sanitize.call_checked(
                            run_chunk, (state, step, n_steps),
                            site=name, scope="resilient_loop",
                            static_argnums=(1, 2))
                    if sanitizer_error is not None:
                        raise DivergenceError(
                            ["sanitizer:" + sanitizer_error
                             .splitlines()[0].strip()],
                            iteration=step + n_steps, where=name)
                else:
                    new_state, done = run_chunk(state, step, n_steps)
            if watermark is not None:
                obs_profile.memory_watermark(estimator=name,
                                             before=watermark)
            new_state = faults.corrupt_state(new_state, step + n_steps,
                                             site=name)
            # progress observes the PRE-guard state: a non-finite or
            # trend-worsening objective fires the typed
            # divergence_precursor event strictly before the guard
            # below can trip (and before its rollback/abort events)
            progress.observe(new_state, step + n_steps, n_steps,
                             time.perf_counter() - t_chunk)
            check_state(new_state, iteration=step + n_steps, where=name,
                        skip=guard_skip, nan_only=guard_nan_only)
        except DivergenceError as exc:
            rollbacks += 1
            progress.note_rollback()
            if rollbacks > max_rollbacks:
                logger.error("%s: %s; %d consecutive rollbacks "
                             "exhausted", name, exc, max_rollbacks)
                obs_sink.event("divergence_abort", estimator=name,
                               step=last_good[0],
                               leaves=list(exc.leaves),
                               fit_id=progress.fit_id)
                progress.finish("diverged")
                obs_flight.dump(
                    "divergence_abort", fit_id=progress.fit_id,
                    state={"estimator": name, "step": last_good[0],
                           "failed_step": step + n_steps,
                           "leaves": list(exc.leaves),
                           "rollbacks": progress.rollbacks})
                raise
            logger.warning(
                "%s: %s; rolling back to iteration %d "
                "(rollback %d/%d)", name, exc, last_good[0], rollbacks,
                max_rollbacks)
            obs_sink.event("rollback", estimator=name,
                           from_step=step + n_steps,
                           to_step=last_good[0],
                           leaves=list(exc.leaves), attempt=rollbacks,
                           fit_id=progress.fit_id)
            obs_metrics.counter(
                "rollback_total",
                help="non-finite-guard rollbacks").inc(estimator=name)
            step, state = last_good
            done = False
            continue
        rollbacks = 0
        step += n_steps
        state = new_state
        last_good = (step, state)
        obs_metrics.counter(
            "fit_steps_total",
            help="iterations advanced by guarded fit loops").inc(
                n_steps, estimator=name)
        if mngr is not None:
            to_save = {k: np.asarray(v) for k, v in state.items()}
            if fingerprint is not None:
                to_save["fingerprint"] = np.asarray(fingerprint,
                                                    dtype=float)
            to_save["fit_id"] = _encode_fit_id(progress.fit_id)
            # host-side floats, not device state: no sync happens
            to_save["fit_wall"] = np.array(  # jaxlint: disable=JX002
                [progress.fit_wall_s, progress.chunk], dtype=float)
            t_save = time.perf_counter()
            mngr.save(step, to_save)
            dt_save = time.perf_counter() - t_save
            obs_metrics.histogram(
                "checkpoint_seconds", unit="s",
                help="checkpoint save wall time").observe(
                    dt_save, estimator=name)
            obs_sink.event("checkpoint", estimator=name, step=step,
                           seconds=dt_save, fit_id=progress.fit_id)
        faults.preempt_point(step, site=name)
        # park check: once per persisted chunk, checkpointed loops
        # only (the predicate fires after the save, so the raised
        # FitParked always has a durable resume point behind it);
        # a finished fit is never parked — it returns normally below
        if mngr is not None and step < n_iter and not done \
                and _should_park():
            obs_sink.event("parked", estimator=name, step=step,
                           fit_id=progress.fit_id)
            obs_metrics.counter(
                "fit_parked_total",
                help="resilient fits parked at a chunk boundary "
                     "by a park_scope predicate").inc(estimator=name)
            progress.finish("parked")
            raise FitParked(step, fit_id=progress.fit_id, name=name)
    progress.finish("converged" if done else "completed")
    return state, step
