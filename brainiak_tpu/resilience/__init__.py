"""Resilience subsystem: preemption-safe fits, retry, and guards.

Long TPU jobs get preempted, diverge, and hit transient I/O failures.
This package gives every iterative estimator the standard recovery
discipline of a production training stack:

- :mod:`~brainiak_tpu.resilience.retry` — exponential-backoff retry for
  transient failures (coordinator connect, NIfTI reads, checkpoint
  I/O);
- :mod:`~brainiak_tpu.resilience.guards` — non-finite-state guards with
  checkpoint rollback, and :func:`run_resilient_loop`, the chunked
  fit-loop driver every ``fit(..., checkpoint_dir=)`` runs under;
- :mod:`~brainiak_tpu.resilience.faults` — deterministic fault
  injection (``preempt`` / ``nan`` / ``io_error``) so the recovery
  paths are exercised in CI.

See ``docs/resilience.md`` for the full model.
"""

from . import faults  # noqa: F401
from .faults import (  # noqa: F401
    InjectedIOError,
    PreemptionError,
    inject,
)
from .guards import (  # noqa: F401
    DivergenceError,
    FitParked,
    check_state,
    park_scope,
    run_resilient_loop,
)
from .retry import retry  # noqa: F401

__all__ = [
    "DivergenceError",
    "FitParked",
    "InjectedIOError",
    "PreemptionError",
    "check_state",
    "faults",
    "inject",
    "park_scope",
    "retry",
    "run_resilient_loop",
]
