"""Generic retry with exponential backoff for transient failures.

Multi-hour multi-host jobs hit transient faults that single-process
NumPy code never sees: the DCN coordinator is not up yet when a worker
calls ``jax.distributed.initialize``, a shared-filesystem NIfTI read
times out, a checkpoint write races a preemption.  The reference's MPI
workloads simply die; here the I/O edges of the framework retry with
exponential backoff and structured logging, and give up with the
original exception once the budget is exhausted.

Wired into :func:`brainiak_tpu.parallel.mesh.initialize_distributed`
(coordinator connect), :func:`brainiak_tpu.nifti.load` (and through it
``io.load_images*``), and ``CheckpointManager.save``/``restore``.

With :mod:`brainiak_tpu.obs` enabled, each retry emits a ``retry``
event and a ``retry_total{site=...}`` increment, and exhausting the
budget emits ``retry_exhausted`` — so transient-fault churn is visible
in the trace instead of only in scrollback logs.
"""

import functools
import logging
import random
import time

from ..obs import metrics as obs_metrics
from ..obs import sink as obs_sink

logger = logging.getLogger(__name__)

__all__ = ["retry"]

# Test seam: monkeypatch to avoid real sleeping in unit tests.
_sleep = time.sleep


def retry(fn=None, *, retries=3, backoff=0.5, jitter=0.1,
          retriable=(OSError,), retry_if=None, name=None):
    """Retry ``fn`` on transient exceptions with exponential backoff.

    Usable bare (``@retry``), configured (``@retry(retries=5)``), or
    inline (``retry(fn, ...)``) — the last form returns the wrapped
    callable, it does not call it.

    Parameters
    ----------
    retries : int, default 3
        Additional attempts after the first failure (so up to
        ``retries + 1`` calls).
    backoff : float, default 0.5
        Base delay in seconds; attempt ``i`` (0-based) sleeps
        ``backoff * 2**i``, scaled by jitter.  ``0`` disables sleeping.
    jitter : float, default 0.1
        Each delay is multiplied by ``1 + uniform(0, jitter)`` so
        simultaneously-preempted hosts do not retry in lockstep.
    retriable : tuple of exception types, default ``(OSError,)``
        Only these are retried; anything else propagates immediately.
    retry_if : callable, optional
        Extra predicate over a type-matched exception; returning False
        propagates it immediately.  Lets a caller retry only the
        transient subset of a broad type (e.g. connection-shaped
        ``RuntimeError`` but not deterministic misconfiguration).
    name : str, optional
        Label used in log records (default: the function's name).
    """

    def decorate(func):
        label = name or getattr(func, "__name__", repr(func))

        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            for attempt in range(retries + 1):
                try:
                    return func(*args, **kwargs)
                except retriable as exc:
                    if retry_if is not None and not retry_if(exc):
                        raise
                    if attempt >= retries:
                        logger.error(
                            "retry[%s]: giving up after %d attempts "
                            "(%s: %s)", label, attempt + 1,
                            type(exc).__name__, exc)
                        obs_sink.event(
                            "retry_exhausted", site=label,
                            attempts=attempt + 1,
                            error=type(exc).__name__)
                        from ..obs import flight
                        flight.dump(
                            "retry_exhausted",
                            state={"site": label,
                                   "attempts": attempt + 1,
                                   "error": f"{type(exc).__name__}: "
                                            f"{exc}"})
                        raise
                    delay = backoff * (2.0 ** attempt)
                    if jitter:
                        delay *= 1.0 + random.random() * jitter
                    logger.warning(
                        "retry[%s]: attempt %d/%d failed (%s: %s); "
                        "retrying in %.2fs", label, attempt + 1,
                        retries + 1, type(exc).__name__, exc, delay)
                    obs_sink.event(
                        "retry", site=label, attempt=attempt + 1,
                        error=type(exc).__name__, delay_s=delay)
                    obs_metrics.counter(
                        "retry_total",
                        help="transient-failure retries").inc(
                            site=label)
                    if delay > 0:
                        _sleep(delay)
            raise AssertionError("unreachable")  # pragma: no cover

        return wrapper

    if fn is not None:
        return decorate(fn)
    return decorate
