"""Deterministic fault injection for resilience tests.

Long TPU jobs die in three characteristic ways: the scheduler preempts
the host, an iterative state diverges to NaN/Inf, and shared-filesystem
I/O fails transiently.  This module injects each of those — at an exact,
reproducible point — so the recovery paths (checkpoint resume, guard
rollback, retry) are exercised by fast CI tests instead of being claimed
and never run.  No sleeps, subprocesses, or real preemption involved.

Usage (context manager)::

    from brainiak_tpu.resilience import faults

    with faults.inject("preempt", at_step=4):
        model.fit(X, checkpoint_dir=d)   # raises PreemptionError at
                                         # the first checkpoint >= 4
    model.fit(X, checkpoint_dir=d)       # resumes from the checkpoint

Usage (environment)::

    BRAINIAK_TPU_FAULT="preempt@4" python train.py

Kinds
-----
``"preempt"``
    :func:`preempt_point` raises :class:`PreemptionError` at the first
    guarded-loop step ``>= at_step`` — *after* that step's checkpoint
    was persisted, which is the recoverable half of real preemption
    (the unrecoverable half, dying mid-save, is covered by the
    checkpoint writer's atomic-rename discipline).
``"nan"``
    :func:`corrupt_state` poisons one leaf of the loop state at the
    first step ``>= at_step``, exercising the non-finite guard's
    rollback policy (:mod:`brainiak_tpu.resilience.guards`).
``"io_error"``
    :func:`io_point` raises :class:`InjectedIOError` (an ``OSError``)
    from inside retry-wrapped I/O (NIfTI reads, checkpoint save or
    restore), exercising :func:`brainiak_tpu.resilience.retry.retry`.
    Here ``at_step`` counts I/O calls to let through first.
``"replica_crash"``
    :func:`crash_point` raises :class:`ReplicaCrashError` from inside
    the serving loop (:class:`~brainiak_tpu.serve.service.
    ServeService` calls it once per loop iteration, with ``step`` =
    the iteration count) — the loop thread dies WITHOUT resolving its
    queued tickets, which is exactly what a preempted replica host
    looks like to the fleet.  The
    :class:`~brainiak_tpu.serve.federation.fleet.FleetSupervisor`
    failover path is the recovery under test.
``"slow_replica"``
    :func:`slow_point` returns a stall duration (``delay_s``, default
    0.05 s, settable via ``leaf=``) the serving loop sleeps between
    ticks while the fault is armed — a replica that is alive but not
    making progress, the gray-failure half of replica death.  The
    supervisor's ``degraded`` hysteresis is the consumer.

Every fault fires ``times`` times (default 1) and is inert afterwards,
so a retry or rollback that re-runs the failed operation succeeds —
the "transient failure" contract.
"""

import logging
import os
from contextlib import contextmanager

import numpy as np

from ..obs import sink as obs_sink

logger = logging.getLogger(__name__)

__all__ = [
    "FAULT_ENV_VAR",
    "InjectedIOError",
    "PreemptionError",
    "ReplicaCrashError",
    "corrupt_state",
    "crash_point",
    "inject",
    "io_point",
    "preempt_point",
    "slow_point",
]

FAULT_ENV_VAR = "BRAINIAK_TPU_FAULT"

KINDS = ("preempt", "nan", "io_error", "replica_crash",
         "slow_replica")

#: Stall per loop iteration while a ``slow_replica`` fault with no
#: explicit ``leaf=`` duration is armed.
DEFAULT_SLOW_REPLICA_S = 0.05


class PreemptionError(RuntimeError):
    """Injected preemption: the fit process was 'killed' at a step."""


class InjectedIOError(OSError):
    """Injected transient I/O failure (retriable)."""


class ReplicaCrashError(RuntimeError):
    """Injected replica death: the serving loop thread was 'killed'
    mid-run, stranding its queued work (the federation failover
    path's trigger)."""


class _Fault:
    def __init__(self, kind, at_step=0, times=1, leaf=None,
                 target=None):
        if kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r}; expected one of {KINDS}")
        self.kind = kind
        self.at_step = int(at_step)
        self.times = int(times)
        self.leaf = leaf
        self.target = target  # replica name for the serve kinds
        self.fired = 0
        self.seen = 0  # io_error: calls observed so far

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"_Fault({self.kind!r}, at_step={self.at_step}, "
                f"times={self.times}, fired={self.fired})")


# Innermost-wins stack of active context-manager faults, plus at most
# one env-var fault (parsed once per distinct spec so it fires once per
# process, like a real environmental failure).
_active = []
_env_fault = None
_env_spec_seen = None


@contextmanager
def inject(kind, at_step=0, times=1, leaf=None, target=None):
    """Activate a fault for the dynamic extent of the ``with`` block.

    Yields the fault record; ``fault.fired`` afterwards tells a test
    whether the fault actually triggered.  ``target`` scopes the
    serve kinds (``replica_crash``/``slow_replica``) to one named
    replica — the chaos soak kills a SPECIFIC replica while the
    rest of the fleet keeps serving (None hits whichever loop
    iterates first)."""
    fault = _Fault(kind, at_step=at_step, times=times, leaf=leaf,
                   target=target)
    _active.append(fault)
    try:
        yield fault
    finally:
        _active.remove(fault)


def _from_env():
    """Parse ``BRAINIAK_TPU_FAULT="kind@step[xtimes]"`` lazily, once per
    distinct spec value."""
    global _env_fault, _env_spec_seen
    spec = os.environ.get(FAULT_ENV_VAR)
    if not spec:
        return None
    if spec != _env_spec_seen:
        _env_spec_seen = spec
        kind, _, rest = spec.partition("@")
        step_s, _, times_s = rest.partition("x")
        try:
            _env_fault = _Fault(kind.strip(),
                                at_step=int(step_s or 0),
                                times=int(times_s or 1))
        except ValueError:
            logger.warning("ignoring malformed %s=%r (expected "
                           "'kind@step[xtimes]')", FAULT_ENV_VAR, spec)
            _env_fault = None
    return _env_fault


def _match(kind, where=None):
    for fault in reversed(_active):
        if fault.kind == kind and fault.fired < fault.times and \
                fault.target in (None, where):
            return fault
    env = _from_env()
    if env is not None and env.kind == kind and env.fired < env.times:
        return env
    return None


def preempt_point(step, site="fit"):
    """Hook called by guarded fit loops after persisting ``step``'s
    checkpoint; raises :class:`PreemptionError` when a ``"preempt"``
    fault has reached its trigger step."""
    fault = _match("preempt")
    if fault is not None and step >= fault.at_step:
        fault.fired += 1
        obs_sink.event("fault", kind="preempt", site=site, step=step)
        raise PreemptionError(
            f"injected preemption in {site} at step {step}")


def corrupt_state(state, step, site="fit"):
    """Hook called by guarded fit loops on each new chunk state; returns
    the state with one leaf poisoned with NaN when a ``"nan"`` fault has
    reached its trigger step.  ``state`` is a flat dict of arrays; the
    poisoned leaf is ``fault.leaf`` or the first floating-point leaf."""
    fault = _match("nan")
    if fault is None or step < fault.at_step:
        return state
    name = fault.leaf
    if name is None:
        for key, leaf in state.items():
            if np.asarray(leaf).dtype.kind == "f":
                name = key
                break
    if name is None or name not in state:
        logger.warning("nan fault at step %d: no such leaf %r", step,
                       fault.leaf)
        return state
    fault.fired += 1
    logger.info("injecting NaN into leaf %r of %s at step %d", name,
                site, step)
    obs_sink.event("fault", kind="nan", site=site, step=step,
                   leaf=name)
    poisoned = np.array(np.asarray(state[name]), dtype=float, copy=True)
    poisoned.reshape(-1)[0] = np.nan
    out = dict(state)
    out[name] = poisoned
    return out


def crash_point(step, site="serve", name=None):
    """Hook called once per serving-loop iteration (lock-free — the
    loop calls it BEFORE acquiring any lock, so an injected death
    never strands a held lock); raises :class:`ReplicaCrashError`
    when a ``"replica_crash"`` fault targeting ``name`` (or any
    replica) has reached its trigger step."""
    fault = _match("replica_crash", where=name)
    if fault is not None and step >= fault.at_step:
        fault.fired += 1
        obs_sink.event("fault", kind="replica_crash", site=site,
                       step=step, replica=name)
        raise ReplicaCrashError(
            f"injected replica crash in {site} at step {step}")


def slow_point(step, site="serve", name=None):
    """Hook called once per serving-loop iteration; returns the
    seconds the loop should stall (0.0 when no ``"slow_replica"``
    fault targeting ``name`` — or any replica — is armed or its
    trigger step is not reached).  The fault's ``leaf=`` carries an
    explicit stall duration; default
    :data:`DEFAULT_SLOW_REPLICA_S`.  Unlike the raise-style kinds a
    slow replica degrades EVERY iteration while armed, so each
    returned stall consumes one of the fault's ``times``."""
    fault = _match("slow_replica", where=name)
    if fault is None or step < fault.at_step:
        return 0.0
    fault.fired += 1
    delay = (float(fault.leaf) if fault.leaf is not None
             else DEFAULT_SLOW_REPLICA_S)
    obs_sink.event("fault", kind="slow_replica", site=site,
                   step=step, delay_s=delay)
    return delay


def io_point(path="", site="io"):
    """Hook called at the top of retry-wrapped I/O operations; raises
    :class:`InjectedIOError` while an ``"io_error"`` fault is armed.
    ``at_step`` counts calls to let through before firing."""
    fault = _match("io_error")
    if fault is None:
        return
    fault.seen += 1
    if fault.seen > fault.at_step:
        fault.fired += 1
        obs_sink.event("fault", kind="io_error", site=site,
                       path=str(path))
        raise InjectedIOError(
            f"injected io_error in {site} for {path!r}")
