"""jaxlint command line: ``python -m brainiak_tpu.analysis``.

Exit status 0 when no finding survives pragma + baseline
suppression, 1 otherwise, 2 for configuration errors.  ``--format
json`` emits one machine-readable object (the same shape
``tools/run_checks.py --format=json`` uses) for CI consumption;
``--format sarif`` emits a SARIF 2.1.0 log CI hosts render as
inline annotations.
"""

import argparse
import json
import os
import sys

from .baseline import Baseline, BaselineError
from .config import load_config
from .core import analyze_paths, iter_python_files, SKIP_DIRS
from .interproc import INTERPROC_RULES
from .lockrules import LOCK_RULES
from .meshrules import MESH_RULES
from .rules import JAXLINT_RULES
from .sarif import to_sarif

#: The project-wide (interprocedural / mesh / lock) rule families —
#: everything beyond the per-file JX001-JX006 set.
DEEP_RULES = INTERPROC_RULES + MESH_RULES + LOCK_RULES

#: Every selectable rule, file and project alike.
ALL_RULES = JAXLINT_RULES + DEEP_RULES


def _selected_rules(select):
    by_code = {r.code: r for r in ALL_RULES}
    unknown = [c for c in select if c not in by_code]
    if unknown:
        raise SystemExit(
            f"jaxlint: unknown rule code(s): {', '.join(unknown)}")
    return [by_code[c] for c in select]


def _filter_excluded(paths, repo_root, exclude):
    """Drop files under any excluded repo-relative prefix."""
    if not exclude:
        for p in paths:
            yield p
        return
    prefixes = tuple(e.rstrip("/") + "/" for e in exclude)
    for p in paths:
        rel = os.path.relpath(p, repo_root).replace(os.sep, "/")
        if not (rel + "/").startswith(prefixes) \
                and not rel.startswith(prefixes):
            yield p


def run(paths, repo_root, select, baseline_path=None, exclude=()):
    """Programmatic entry; returns (findings, stale, n_files)."""
    rules = _selected_rules(select)
    baseline = (Baseline.load(baseline_path)
                if baseline_path else None)
    files = list(_filter_excluded(
        iter_python_files(paths, SKIP_DIRS), repo_root, exclude))
    return analyze_paths(files, repo_root, rules, baseline=baseline)


def build_parser():
    parser = argparse.ArgumentParser(
        prog="jaxlint",
        description="TPU-correctness static analysis for JAX code "
                    "(file rules JX001-JX006, interprocedural "
                    "JX010-JX012, mesh/collective JX101-JX103, "
                    "lock-discipline JX201-JX205; see "
                    "docs/static_analysis.md)")
    parser.add_argument(
        "paths", nargs="*",
        help="files/dirs to analyze (default: [tool.jaxlint] "
             "include, else brainiak_tpu/)")
    parser.add_argument("--format",
                        choices=("text", "json", "sarif"),
                        default="text")
    parser.add_argument(
        "--select",
        help="comma-separated rule codes (default: config select)")
    parser.add_argument(
        "--baseline",
        help="baseline JSON path (default: config baseline)")
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline (report grandfathered findings)")
    parser.add_argument(
        "--write-baseline", metavar="PATH",
        help="write current findings as a baseline template "
             "(reasons set to TODO) and exit 0")
    parser.add_argument("--list-rules", action="store_true",
                        help="list rule codes and exit")
    parser.add_argument(
        "--ir", action="store_true",
        help="run the jaxlint-IR audit (JP301-JP305): trace every "
             "registered jitted-program builder at its canonical "
             "abstract signature and rule-check the actual IR; "
             "requires jax (pins a forced multi-device CPU backend "
             "when jax is not yet configured)")
    return parser


def _setup_ir_env():
    """Pin the audit backend BEFORE jax first imports: CPU, 8 forced
    host devices (so collective programs trace against a real mesh).
    A caller that already imported/configured jax wins."""
    if "jax" in sys.modules:
        return
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()


def run_ir(paths, repo_root, select=None, baseline_path=None):
    """Programmatic jaxlint-IR entry; returns an AuditReport."""
    from . import ir
    _setup_ir_env()
    baseline = (Baseline.load(baseline_path)
                if baseline_path else None)
    return ir.run_audit(paths, repo_root, select=select,
                        baseline=baseline)


def _main_ir(args, config):
    from . import ir

    select = (tuple(c.strip() for c in args.select.split(","))
              if args.select else ir.DEFAULT_SELECT)
    by_code = {r.code: r for r in ir.IR_RULES}
    unknown = [c for c in select if c not in by_code]
    if unknown:
        print(f"jaxlint: unknown IR rule code(s): "
              f"{', '.join(unknown)}", file=sys.stderr)
        return 2
    paths = args.paths or config.include_paths()
    baseline_path = None
    if not args.no_baseline and not args.write_baseline:
        baseline_path = (
            os.path.abspath(args.baseline) if args.baseline
            else config.baseline_path())
    try:
        report = run_ir(paths, config.repo_root, select=select,
                        baseline_path=baseline_path)
    except BaselineError as exc:
        print(f"jaxlint: {exc}", file=sys.stderr)
        return 2
    if args.write_baseline:
        with open(args.write_baseline, "w", encoding="utf-8") as fh:
            fh.write(Baseline.render(report.findings))
        print(f"jaxlint: wrote {len(report.findings)} baseline "
              f"entries to {args.write_baseline}")
        return 0
    if args.format == "sarif":
        print(json.dumps(to_sarif(
            report.findings,
            {c: by_code[c] for c in select}), indent=2))
    elif args.format == "json":
        payload = report.to_dict()
        payload["ok"] = not report.findings
        print(json.dumps(payload, indent=2))
    else:
        for finding in report.findings:
            print(finding)
        for site, reason in sorted(report.skipped.items()):
            print(f"skip: {site}: {reason}")
        for entry in report.stale:
            print(f"warning: stale baseline entry "
                  f"{entry['rule']} {entry['path']} "
                  f"({entry['reason']}) matches nothing; delete it")
        status = "OK" if not report.findings else \
            f"{len(report.findings)} finding(s)"
        print(f"jaxlint-ir: {status}; traced "
              f"{len(report.traced)}/{len(report.sites)} builder "
              f"sites (coverage {report.coverage:.0%}) in "
              f"{report.seconds:.1f}s")
    return 1 if report.findings else 0


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.list_rules:
        from .ir import IR_RULES
        for rule in (*ALL_RULES, *IR_RULES):
            print(f"{rule.code}  {rule.name}: "
                  f"{(rule.__doc__ or '').splitlines()[0]}")
        return 0
    config = load_config()
    if args.ir:
        return _main_ir(args, config)
    select = (tuple(c.strip() for c in args.select.split(","))
              if args.select else config.select)
    paths = args.paths or config.include_paths()
    baseline_path = None
    if not args.no_baseline and not args.write_baseline:
        baseline_path = (
            os.path.abspath(args.baseline) if args.baseline
            else config.baseline_path())
    try:
        findings, stale, n = run(
            paths, config.repo_root, select,
            baseline_path=baseline_path, exclude=config.exclude)
    except BaselineError as exc:
        print(f"jaxlint: {exc}", file=sys.stderr)
        return 2
    if args.write_baseline:
        with open(args.write_baseline, "w",
                  encoding="utf-8") as fh:
            fh.write(Baseline.render(findings))
        print(f"jaxlint: wrote {len(findings)} baseline entries "
              f"to {args.write_baseline}")
        return 0
    if args.format == "sarif":
        rules_by_code = {r.code: r for r in ALL_RULES}
        print(json.dumps(to_sarif(
            findings,
            {c: rules_by_code[c] for c in select
             if c in rules_by_code}), indent=2))
    elif args.format == "json":
        print(json.dumps({
            "ok": not findings,
            "files": n,
            "rules": list(select),
            "findings": [f.to_dict() for f in findings],
            "stale_baseline": stale,
        }, indent=2))
    else:
        for finding in findings:
            print(finding)
        for entry in stale:
            print(f"warning: stale baseline entry "
                  f"{entry['rule']} {entry['path']} "
                  f"({entry['reason']}) matches nothing; delete it")
        status = "OK" if not findings else \
            f"{len(findings)} finding(s)"
        print(f"jaxlint: {status} over {n} files "
              f"({len(select)} rules)")
    return 1 if findings else 0


if __name__ == "__main__":   # pragma: no cover
    sys.exit(main())
