"""jaxlint rules JX001-JX006: TPU-readiness invariants.

Each rule is a small plugin over the shared :class:`.core.FileContext`
(one parse per file, N rules).  The rule set encodes the classic
JAX/TPU hazards that silently destroy performance or correctness:
uncached retracing, host-device synchronisation inside hot loops,
float64 leaks, NumPy RNG / PRNG-key reuse under jit, Python control
flow on traced values, and missing static-argument declarations.
"""

import ast
import re

from .core import _STATIC_ATTRS, FileRule, register

# calls that force a host-device round trip
_HOST_SYNC_CALLS = {
    "numpy.asarray": "np.asarray",
    "numpy.array": "np.array",
    "jax.device_get": "jax.device_get",
}
_HOST_SYNC_METHODS = {"item", "block_until_ready"}

# ``for``-loop headers that look like training/EM epochs; counting
# loops (``for ... in range(...)``) additionally match chunked-fit
# vocabulary (block/chunk/batch) -- those are the per-block dispatch
# loops where a stray host sync serializes the device queue
_EPOCH_RE = re.compile(
    r"epoch|n_iter|max_iter|num_iter|iteration|n_steps|n_epochs",
    re.IGNORECASE)
_CHUNK_RE = re.compile(
    r"epoch|itera|n_iter|max_iter|num_iter|n_steps|block|chunk"
    r"|batch", re.IGNORECASE)

# jax.random functions that legitimately consume a key many times
_KEY_MGMT = {"split", "fold_in", "PRNGKey", "key", "key_data",
             "wrap_key_data", "clone"}

_CACHE_DECOS = {"functools.lru_cache", "functools.cache",
                "lru_cache", "cache",
                # the obs retrace-counting lru_cache wrapper
                # (brainiak_tpu.obs.runtime.counted_cache) — resolved
                # under its common import spellings, incl. the
                # package-level re-export (brainiak_tpu.obs.*); asname
                # aliases canonicalize through ctx.resolve already
                "counted_cache", "obs.runtime.counted_cache",
                "brainiak_tpu.obs.runtime.counted_cache",
                "obs.counted_cache", "brainiak_tpu.obs.counted_cache",
                "obs_runtime.counted_cache",
                "runtime.counted_cache",
                # the serve bucket-program cache (a counted_cache
                # under serve's site convention,
                # brainiak_tpu.serve.engine.program_cache): jit
                # construction inside a builder it decorates is
                # cached by definition
                "program_cache", "engine.program_cache",
                "serve.engine.program_cache",
                "brainiak_tpu.serve.engine.program_cache",
                "serve.program_cache",
                "brainiak_tpu.serve.program_cache",
                # program_cache now LIVES in serve.batching (the
                # cache key IS the bucket); engine re-exports it,
                # so both module spellings stay recognized
                "batching.program_cache",
                "serve.batching.program_cache",
                "brainiak_tpu.serve.batching.program_cache"}


def _loop_ancestor(ctx, node):
    for anc in ctx.ancestors(node):
        if isinstance(anc, (ast.For, ast.AsyncFor, ast.While)):
            return anc
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return None
    return None


def _fn_ancestor(ctx, node):
    return ctx.enclosing(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda))


def _is_cached(ctx, fn):
    """True when ``fn`` (or an enclosing def) is lru_cache-decorated."""
    cur = fn
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in cur.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if ctx.resolve(target) in _CACHE_DECOS:
                    return True
        cur = _fn_ancestor(ctx, cur)
    return False


def _walk_skip_nested(body):
    """Walk statements without descending into nested defs/lambdas."""
    stack = list(body) if isinstance(body, list) else [body]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


@register
class JitPerCall(FileRule):
    """JX001: ``jax.jit`` constructed where it retraces every call."""

    code = "JX001"
    name = "jit-per-call"

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and ctx.resolve(node.func) == "jax.jit"):
                continue
            if ctx.in_decorator(node):
                continue
            parent = ctx.parent(node)
            if isinstance(parent, ast.Call) and parent.func is node:
                yield ctx.finding(
                    self, node,
                    "jax.jit(...)(...) wrapped and immediately "
                    "called: the traced function is discarded and "
                    "every call retraces; bind the jitted callable "
                    "once (module scope or lru_cache)")
                continue
            loop = _loop_ancestor(ctx, node)
            if loop is not None:
                yield ctx.finding(
                    self, node,
                    "jax.jit constructed inside a loop: each "
                    "iteration builds a fresh callable and retraces; "
                    "hoist the jit out of the loop")
                continue
            fn = _fn_ancestor(ctx, node)
            if fn is not None and not _is_cached(ctx, fn):
                where = getattr(fn, "name", "<lambda>")
                yield ctx.finding(
                    self, node,
                    f"jax.jit constructed inside function "
                    f"'{where}': every call builds a fresh callable "
                    "and retraces; hoist to module scope or cache "
                    "the wrapper (functools.lru_cache)")


def _local_defs(ctx):
    defs = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)
        elif (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Lambda)):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    defs.setdefault(tgt.id, node.value)
    return defs


def _epochish(header, counting):
    """Whether a loop header reads as an epoch/chunk hot loop."""
    if counting:
        return bool(_CHUNK_RE.search(header))
    return bool(_EPOCH_RE.search(header))


def iter_hot_scopes(ctx, local_defs=None):
    """Yield ``(walk_nodes, why, scope_node)`` for every hot scope
    in a file: callees handed to ``run_resilient_loop`` /
    ``lax.scan`` / ``lax.fori_loop`` / ``lax.while_loop``, Python
    ``for``-loops and ``while``-loops whose headers name epochs or
    chunks, and comprehensions whose generators do (the former JX002
    blind spot).  Shared by JX002 and the interprocedural JX010.
    """
    if local_defs is None:
        local_defs = _local_defs(ctx)

    def resolve_callee(arg):
        if isinstance(arg, ast.Lambda):
            return arg
        if isinstance(arg, ast.Name):
            return local_defs.get(arg.id)
        return None

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            target = ctx.resolve(node.func) or ""
            short = target.rsplit(".", 1)[-1]
            callee_args = ()
            if short == "run_resilient_loop" and node.args:
                callee_args = (node.args[0],)
                why = "the run_resilient_loop chunk body"
            elif target == "jax.lax.scan" and node.args:
                callee_args = (node.args[0],)
                why = "a lax.scan body"
            elif (target == "jax.lax.fori_loop"
                    and len(node.args) >= 3):
                callee_args = (node.args[2],)
                why = "a lax.fori_loop body"
            elif (target == "jax.lax.while_loop"
                    and len(node.args) >= 2):
                callee_args = node.args[:2]
                why = "a lax.while_loop cond/body"
            for arg in callee_args:
                callee = resolve_callee(arg)
                if callee is not None:
                    body = (callee.body
                            if not isinstance(callee, ast.Lambda)
                            else [callee.body])
                    yield body, why, callee
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            header = ast.dump(node.target) + ast.dump(node.iter)
            counting = (isinstance(node.iter, ast.Call)
                        and isinstance(node.iter.func, ast.Name)
                        and node.iter.func.id == "range")
            if _epochish(header, counting):
                why = ("an epoch/chunk-style Python for-loop"
                       if counting
                       else "an epoch-style Python for-loop")
                yield node.body, why, node
        elif isinstance(node, ast.While):
            if _EPOCH_RE.search(ast.dump(node.test)):
                yield node.body, "an epoch-style while-loop", node
        elif isinstance(node, (ast.ListComp, ast.SetComp,
                               ast.DictComp, ast.GeneratorExp)):
            for gen in node.generators:
                header = ast.dump(gen.target) + ast.dump(gen.iter)
                counting = (isinstance(gen.iter, ast.Call)
                            and isinstance(gen.iter.func, ast.Name)
                            and gen.iter.func.id == "range")
                if not _epochish(header, counting):
                    continue
                parts = ([node.key, node.value]
                         if isinstance(node, ast.DictComp)
                         else [node.elt])
                parts += [i for g in node.generators
                          for i in g.ifs]
                # the first generator's iterable evaluates once;
                # later generators re-evaluate per outer element
                parts += [g.iter for g in node.generators[1:]]
                yield (parts,
                       "an epoch/chunk-style comprehension", node)
                break


@register
class HostSyncInLoop(FileRule):
    """JX002: host-device sync inside a hot loop body."""

    code = "JX002"
    name = "host-sync-in-loop"

    def check(self, ctx):
        local_defs = _local_defs(ctx)
        seen = set()
        for body, why, _scope in iter_hot_scopes(ctx, local_defs):
            for node in _walk_skip_nested(body):
                hit = self._host_sync(ctx, node)
                if hit is None or id(node) in seen:
                    continue
                seen.add(id(node))
                yield ctx.finding(
                    self, node,
                    f"host-device sync `{hit}` inside {why}: forces "
                    "a device round trip every iteration; move it "
                    "out of the hot loop (or fetch once after)")

    @staticmethod
    def _local_defs(ctx):
        return _local_defs(ctx)

    @staticmethod
    def _host_sync(ctx, node):
        if not isinstance(node, ast.Call):
            return None
        target = ctx.resolve(node.func)
        if target in _HOST_SYNC_CALLS:
            return _HOST_SYNC_CALLS[target]
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in _HOST_SYNC_METHODS):
            return f".{node.func.attr}()"
        if (isinstance(node.func, ast.Name)
                and node.func.id == "float"
                and node.func.id not in ctx.aliases
                and len(node.args) == 1
                and not isinstance(node.args[0], ast.Constant)):
            return "float(...)"
        return None


@register
class Float64Leak(FileRule):
    """JX003: float64 literal in device code without x64 guard."""

    code = "JX003"
    name = "float64-leak"

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            hit = self._f64(ctx, node)
            if hit and not self._guarded(ctx, node):
                yield ctx.finding(
                    self, node,
                    f"{hit} in device code: TPUs run float32/bf16 "
                    "and jax silently downcasts unless "
                    "jax_enable_x64 is set; use float32 or guard "
                    "with an explicit enable_x64 check")

    def _f64(self, ctx, node):
        in_jit = self._in_jitted(ctx, node)
        if isinstance(node, ast.Attribute) and node.attr == "float64":
            target = ctx.resolve(node) or ""
            if target == "jax.numpy.float64":
                return "jnp.float64"
            if target == "numpy.float64" and (
                    in_jit or self._in_jax_call(ctx, node)):
                return "np.float64"
        if (isinstance(node, ast.Constant)
                and node.value == "float64"):
            call = ctx.enclosing(node, ast.Call)
            if call is None:
                return None
            if (isinstance(call.func, ast.Attribute)
                    and call.func.attr == "astype" and in_jit):
                return ".astype('float64')"
            target = ctx.resolve(call.func) or ""
            if target.startswith(("jax.", "jax_")) or in_jit:
                return "dtype='float64'"
        return None

    @staticmethod
    def _in_jitted(ctx, node):
        for anc in ctx.ancestors(node):
            if anc in ctx.jitted:
                return True
        return False

    @staticmethod
    def _in_jax_call(ctx, node):
        call = ctx.enclosing(node, ast.Call)
        while call is not None:
            target = ctx.resolve(call.func) or ""
            if target.startswith("jax."):
                return True
            call = ctx.enclosing(call, ast.Call)
        return False

    @staticmethod
    def _guarded(ctx, node):
        if "enable_x64" in ctx.src_line(node.lineno):
            return True
        for anc in ctx.ancestors(node):
            if isinstance(anc, (ast.If, ast.IfExp)):
                test = ast.dump(anc.test)
                if "enable_x64" in test or "x64" in test:
                    return True
        return False


@register
class RngHazard(FileRule):
    """JX004: NumPy RNG, or PRNG key reuse, in a jitted function."""

    code = "JX004"
    name = "rng-hazard"

    def check(self, ctx):
        for fn, _ in ctx.jitted.items():
            yield from self._np_random(ctx, fn)
            yield from self._key_reuse(ctx, fn)

    def _np_random(self, ctx, fn):
        for node in _walk_skip_nested(fn.body):
            if not isinstance(node, ast.Call):
                continue
            target = ctx.resolve(node.func) or ""
            if target.startswith("numpy.random."):
                yield ctx.finding(
                    self, node,
                    f"`{target}` inside jitted '{fn.name}': NumPy "
                    "RNG runs at trace time on the host, so every "
                    "execution replays the SAME draw; thread a "
                    "jax.random key instead")

    def _key_reuse(self, ctx, fn):
        uses = {}      # key name -> [call nodes]
        managed = set()    # keys handed to split/fold_in
        stores = {}        # name -> number of rebindings
        for node in _walk_skip_nested(fn.body):
            if isinstance(node, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                for tgt in targets:
                    for sub in ast.walk(tgt):
                        if isinstance(sub, ast.Name):
                            stores[sub.id] = \
                                stores.get(sub.id, 0) + 1
            if not isinstance(node, ast.Call):
                continue
            target = ctx.resolve(node.func) or ""
            if not target.startswith("jax.random."):
                continue
            short = target.rsplit(".", 1)[-1]
            if not node.args or not isinstance(node.args[0],
                                               ast.Name):
                continue
            key = node.args[0].id
            if short in _KEY_MGMT:
                managed.add(key)
            else:
                uses.setdefault(key, []).append(node)
        for key, calls in sorted(uses.items()):
            # a name bound at most once (a parameter, or a single
            # PRNGKey/split result) that feeds >= 2 samplers without
            # ever being split is the canonical reuse bug; names
            # rebound between uses (key rotation) are exempt
            if (len(calls) >= 2 and key not in managed
                    and stores.get(key, 0) <= 1):
                yield ctx.finding(
                    self, calls[1],
                    f"PRNG key `{key}` consumed by "
                    f"{len(calls)} jax.random calls in "
                    f"'{fn.name}' without a split: the draws are "
                    "IDENTICAL, not independent; "
                    "jax.random.split the key first")


@register
class TracedBranch(FileRule):
    """JX005: Python ``if``/``while`` on a traced parameter."""

    code = "JX005"
    name = "traced-branch"

    def check(self, ctx):
        for fn, statics in ctx.jitted.items():
            if isinstance(fn, ast.Lambda):
                continue
            params = set(ctx.fn_params(fn)) - statics - {"self"}
            for node in _walk_skip_nested(fn.body):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                name = self._traced_name(ctx, node.test, params)
                if name is None:
                    continue
                kind = ("if" if isinstance(node, ast.If)
                        else "while")
                yield ctx.finding(
                    self, node,
                    f"Python `{kind}` on traced parameter "
                    f"`{name}` of jitted '{fn.name}': trace-time "
                    "branching raises TracerBoolConversionError or "
                    "bakes in one path; use lax.cond/lax.select, "
                    "or declare the argument static")

    @staticmethod
    def _traced_name(ctx, test, params):
        if (isinstance(test, ast.Compare)
                and all(isinstance(op, (ast.Is, ast.IsNot))
                        for op in test.ops)):
            return None  # `x is None` checks are static
        for node in ast.walk(test):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "isinstance"):
                return None
        for node in ast.walk(test):
            if (isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in params):
                parent = ctx.parent(node)
                if (isinstance(parent, ast.Attribute)
                        and parent.attr in _STATIC_ATTRS):
                    continue  # static metadata access
                return node.id
        return None


@register
class MissingStatic(FileRule):
    """JX006: traced param used where a Python int is required."""

    code = "JX006"
    name = "missing-static"

    def check(self, ctx):
        for fn, statics in ctx.jitted.items():
            if isinstance(fn, ast.Lambda):
                continue
            params = set(ctx.fn_params(fn)) - statics - {"self"}
            for node in _walk_skip_nested(fn.body):
                if not isinstance(node, ast.Call):
                    continue
                kind = self._int_sink(ctx, node)
                if kind is None:
                    continue
                for arg in self._int_args(node):
                    if (isinstance(arg, ast.Name)
                            and arg.id in params):
                        yield ctx.finding(
                            self, node,
                            f"traced parameter `{arg.id}` passed "
                            f"to `{kind}` in jitted '{fn.name}': "
                            "needs a concrete Python int at trace "
                            "time; declare it in static_argnums/"
                            "static_argnames")

    @staticmethod
    def _int_sink(ctx, node):
        if (isinstance(node.func, ast.Name)
                and node.func.id == "range"
                and node.func.id not in ctx.aliases):
            return "range"
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "reshape":
            return "reshape"
        if ctx.resolve(node.func) == "jax.numpy.reshape":
            return "jnp.reshape"
        return None

    @staticmethod
    def _int_args(node):
        for arg in node.args:
            if isinstance(arg, ast.Tuple):
                yield from arg.elts
            else:
                yield arg


JAXLINT_RULES = [JitPerCall, HostSyncInLoop, Float64Leak,
                 RngHazard, TracedBranch, MissingStatic]
