"""SARIF 2.1.0 output for jaxlint and the gate registry.

SARIF (Static Analysis Results Interchange Format) is what CI hosts
(GitHub code scanning, Azure, Gitea) ingest to render findings as
inline PR annotations.  :func:`to_sarif` maps the analyzer's
findings to one minimal-but-valid ``sarif-2.1.0`` log: a single run,
one ``tool.driver`` rule entry per distinct rule code, one result
per finding with a physical location (repo-relative URI +
1-based ``startLine``).
"""

__all__ = ["SARIF_SCHEMA", "SARIF_VERSION", "to_sarif"]

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")

#: Codes that describe CI/gate plumbing failures rather than code
#: defects map to SARIF level "error"; lint findings are "warning".
_ERROR_PREFIXES = ("CHK0", "OBS", "REG", "SRV", "DLA", "ENC",
                   "EXT", "JPR")


def _level(code):
    return ("error" if code.startswith(_ERROR_PREFIXES)
            else "warning")


def _rule_entry(code, rule_cls):
    entry = {"id": code, "name": code}
    if rule_cls is not None:
        doc = (rule_cls.__doc__ or "").strip().splitlines()
        entry["name"] = getattr(rule_cls, "name", "") or code
        if doc:
            entry["shortDescription"] = {"text": doc[0]}
    return entry


def to_sarif(findings, rules_by_code=None, tool_name="jaxlint",
             tool_version="2.0"):
    """One SARIF log dict for ``findings``.

    ``rules_by_code`` maps rule codes to rule classes (for
    descriptions); codes present only in findings still get a
    minimal rule entry, so the log is self-contained for any gate.
    """
    rules_by_code = dict(rules_by_code or {})
    codes = sorted({f.code for f in findings}
                   | set(rules_by_code))
    driver = {
        "name": tool_name,
        "informationUri": ("https://github.com/brainiak/brainiak"
                           "/blob/master/docs/static_analysis.md"),
        "version": tool_version,
        "rules": [_rule_entry(code, rules_by_code.get(code))
                  for code in codes],
    }
    results = []
    for finding in findings:
        results.append({
            "ruleId": finding.code,
            "level": _level(finding.code),
            "message": {"text": finding.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": finding.path,
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {"startLine": max(finding.line, 1)},
                },
            }],
        })
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": driver},
            "columnKind": "utf16CodeUnits",
            "originalUriBaseIds": {"SRCROOT": {"uri": "./"}},
            "results": results,
        }],
    }
