"""jaxlint configuration: the ``[tool.jaxlint]`` pyproject section.

This build environment is Python 3.10 without :mod:`tomllib`, so a
deliberately small TOML-subset reader handles the one section we own:
string values, string lists (possibly multi-line), ints and booleans.
When :mod:`tomllib` is importable it is used instead.
"""

import ast
import os
import re

__all__ = ["JaxlintConfig", "load_config", "find_pyproject"]

_DEFAULT_SELECT = ("JX001", "JX002", "JX003", "JX004", "JX005",
                   "JX006")
_DEFAULT_INCLUDE = ("brainiak_tpu",)
_DEFAULT_EXCLUDE = ()


class JaxlintConfig:
    """Resolved analyzer configuration."""

    def __init__(self, repo_root, select=_DEFAULT_SELECT,
                 include=_DEFAULT_INCLUDE, exclude=_DEFAULT_EXCLUDE,
                 baseline=None):
        self.repo_root = repo_root
        self.select = tuple(select)
        self.include = tuple(include)
        self.exclude = tuple(exclude)
        self.baseline = baseline   # repo-relative path or None

    def include_paths(self):
        return [os.path.join(self.repo_root, p)
                for p in self.include]

    def baseline_path(self):
        if not self.baseline:
            return None
        return os.path.join(self.repo_root, self.baseline)


def find_pyproject(start):
    """Nearest pyproject.toml at/above ``start``, else None."""
    cur = os.path.abspath(start)
    if os.path.isfile(cur):
        cur = os.path.dirname(cur)
    while True:
        cand = os.path.join(cur, "pyproject.toml")
        if os.path.isfile(cand):
            return cand
        parent = os.path.dirname(cur)
        if parent == cur:
            return None
        cur = parent


def _section_lines(text, section):
    """Raw lines of one ``[section]`` table, [] when absent."""
    lines = []
    in_section = False
    for line in text.splitlines():
        stripped = line.strip()
        if stripped.startswith("["):
            in_section = stripped == f"[{section}]"
            continue
        if in_section:
            lines.append(line)
    return lines


def _parse_section(lines):
    """``key = value`` pairs from a TOML-subset table body."""
    out = {}
    buf = ""
    key = None
    for line in lines:
        stripped = line.split("#", 1)[0].rstrip() \
            if not line.lstrip().startswith("#") else ""
        if not stripped.strip():
            continue
        if key is None:
            m = re.match(r"\s*([A-Za-z0-9_-]+)\s*=\s*(.*)", stripped)
            if not m:
                continue
            key, buf = m.group(1), m.group(2)
        else:
            buf += " " + stripped.strip()
        if buf.count("[") > buf.count("]"):
            continue    # multi-line array, keep accumulating
        out[key] = _coerce(buf.strip())
        key, buf = None, ""
    return out


def _coerce(raw):
    if raw == "true":
        return True
    if raw == "false":
        return False
    try:
        return ast.literal_eval(raw)
    except (ValueError, SyntaxError):
        return raw.strip("\"'")


def _load_table(pyproject_path):
    try:
        import tomllib
        with open(pyproject_path, "rb") as fh:
            data = tomllib.load(fh)
        return data.get("tool", {}).get("jaxlint", {})
    except ImportError:
        with open(pyproject_path, encoding="utf-8") as fh:
            text = fh.read()
        return _parse_section(_section_lines(text, "tool.jaxlint"))


def load_config(repo_root=None, pyproject_path=None):
    """Build a :class:`JaxlintConfig` from ``[tool.jaxlint]``.

    Missing file or section yields the defaults (all JX rules over
    ``brainiak_tpu/`` with no baseline).
    """
    if pyproject_path is None:
        pyproject_path = find_pyproject(repo_root or os.getcwd())
    if repo_root is None:
        repo_root = (os.path.dirname(pyproject_path)
                     if pyproject_path else os.getcwd())
    table = {}
    if pyproject_path and os.path.isfile(pyproject_path):
        table = _load_table(pyproject_path)
    return JaxlintConfig(
        repo_root,
        select=tuple(table.get("select", _DEFAULT_SELECT)),
        include=tuple(table.get("include", _DEFAULT_INCLUDE)),
        exclude=tuple(table.get("exclude", _DEFAULT_EXCLUDE)),
        baseline=table.get("baseline"))
