"""Interprocedural dataflow rules JX010-JX012.

The original JX001/JX002/JX004 see one function at a time; these
three follow the :mod:`.graph` call graph, so a hazard hidden behind
a helper — even one in another module — is reported at the call
site where it bites:

- **JX010** — a call inside a hot loop to a function that
  (transitively, must-execute) performs a definite device sync, or
  that directly performs an ambiguous host conversion;
- **JX011** — a call inside any loop to a function that constructs
  an uncached ``jax.jit`` per call (the JX001 hazard, observed from
  the looping caller's side);
- **JX012** — a PRNG key fed to two or more key-consuming calls
  without a split, where consumption happens through helper
  functions (the JX004 hazard across function boundaries).
"""

import ast

from .core import ProjectRule, register
from .rules import (
    HostSyncInLoop,
    _KEY_MGMT,
    _walk_skip_nested,
    iter_hot_scopes,
)
from .summaries import project_summaries

__all__ = ["TransitiveHostSync", "TransitiveJitInLoop",
           "CrossFunctionKeyReuse", "INTERPROC_RULES"]


def _finding(rule, ctx, node, message):
    return ctx.finding(rule, node, message)


def _uses_jax(ctx):
    """Whether a module imports jax (directly or via jax.numpy)."""
    return any(canon == "jax" or canon.startswith("jax.")
               for canon in ctx.aliases.values())


@register
class TransitiveHostSync(ProjectRule):
    """JX010: hot-loop call to a helper that host-syncs."""

    code = "JX010"
    name = "transitive-host-sync"

    def check(self, project):
        summaries = project_summaries(project)
        for ctx in project.contexts.values():
            seen = set()
            for body, why, scope in iter_hot_scopes(ctx):
                direct_lines = {
                    n.lineno for n in _walk_skip_nested(body)
                    if HostSyncInLoop._host_sync(ctx, n)}
                for node in _walk_skip_nested(body):
                    if not isinstance(node, ast.Call) \
                            or id(node) in seen \
                            or node.lineno in direct_lines:
                        continue
                    enclosing = project.enclosing_function(ctx,
                                                           node)
                    targets = project.resolve_call(ctx, node,
                                                   enclosing)
                    if len(targets) != 1:
                        continue
                    target = targets[0]
                    if enclosing is not None \
                            and target.qualname == \
                            enclosing.qualname:
                        continue  # recursion, not a helper
                    summary = summaries.get(target.qualname)
                    if summary is None:
                        continue
                    if not _uses_jax(target.ctx):
                        # a module that never imports jax has no
                        # device arrays: its np.asarray/.item()
                        # calls are host bookkeeping, not syncs
                        continue
                    hit = self._classify(summary)
                    if hit is None:
                        continue
                    seen.add(id(node))
                    yield _finding(
                        self, ctx, node,
                        f"call to '{target.name}' "
                        f"({target.relpath}) inside {why} "
                        f"host-syncs every iteration: {hit}; "
                        "hoist the sync out of the hot loop or "
                        "restructure the helper")

    @staticmethod
    def _classify(summary):
        if summary.sync_witness is not None:
            return summary.sync_witness
        for node, label, cond in summary.host_convs:
            if not cond:
                return (f"{label} at {summary.info.relpath}:"
                        f"{node.lineno}")
        return None


@register
class TransitiveJitInLoop(ProjectRule):
    """JX011: loop call to a builder that jits per call."""

    code = "JX011"
    name = "transitive-jit-in-loop"

    def check(self, project):
        summaries = project_summaries(project)
        for summary in summaries.values():
            ctx = summary.info.ctx
            for node, targets, _cond in summary.calls:
                if len(targets) != 1:
                    continue
                callee = summaries.get(targets[0].qualname)
                if callee is None \
                        or callee.builds_jit_line is None:
                    continue
                if not self._in_loop(ctx, node,
                                     summary.info.node):
                    continue
                yield _finding(
                    self, ctx, node,
                    f"call to '{targets[0].name}' inside a loop: "
                    "it constructs a fresh jax.jit per call "
                    f"({targets[0].relpath}:"
                    f"{callee.builds_jit_line}), so every "
                    "iteration retraces; hoist the call or cache "
                    "the builder (functools.lru_cache / "
                    "counted_cache)")

    @staticmethod
    def _in_loop(ctx, node, fn_node):
        cur = ctx.parent(node)
        while cur is not None and cur is not fn_node:
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While,
                                ast.ListComp, ast.SetComp,
                                ast.DictComp, ast.GeneratorExp)):
                return True
            if isinstance(cur, (ast.FunctionDef,
                                ast.AsyncFunctionDef, ast.Lambda)):
                return False
            cur = ctx.parent(cur)
        return False


@register
class CrossFunctionKeyReuse(ProjectRule):
    """JX012: PRNG key reuse across function boundaries."""

    code = "JX012"
    name = "cross-function-key-reuse"

    def check(self, project):
        summaries = project_summaries(project)
        for summary in summaries.values():
            yield from self._check_fn(project, summaries, summary)

    def _check_fn(self, project, summaries, summary):
        ctx = summary.info.ctx
        fn = summary.info.node
        stores = {}
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                for tgt in targets:
                    for sub in ast.walk(tgt):
                        if isinstance(sub, ast.Name):
                            stores[sub.id] = \
                                stores.get(sub.id, 0) + 1
        consumed = {}    # key name -> [(call node, via-helper name)]
        managed = set()
        for node, targets, _cond in summary.calls:
            target = ctx.resolve(node.func) or ""
            short = target.rsplit(".", 1)[-1]
            if target.startswith("jax.random."):
                if not node.args or not isinstance(node.args[0],
                                                   ast.Name):
                    continue
                key = node.args[0].id
                if short in _KEY_MGMT:
                    managed.add(key)
                else:
                    consumed.setdefault(key, []).append(
                        (node, None))
                continue
            if len(targets) != 1:
                continue
            callee = summaries.get(targets[0].qualname)
            if callee is None or not callee.key_params:
                continue
            for key in self._keys_into(node, callee):
                consumed.setdefault(key, []).append(
                    (node, targets[0].name))
        for key, calls in sorted(consumed.items()):
            helpers = sorted({via for _, via in calls
                              if via is not None})
            if not helpers:
                continue  # all-direct reuse is JX004's domain
            if len(calls) < 2 or key in managed \
                    or stores.get(key, 0) > 1:
                continue
            node = calls[1][0]
            yield _finding(
                self, ctx, node,
                f"PRNG key `{key}` consumed by {len(calls)} "
                f"calls in '{summary.info.name}' — including "
                f"helper(s) {', '.join(helpers)} which sample "
                "from it — without a split: the draws are "
                "IDENTICAL, not independent; jax.random.split "
                "the key first")

    @staticmethod
    def _keys_into(node, callee):
        """Caller names passed into the callee's key-consuming
        parameters at this call site."""
        callee_pos = [a.arg for a in
                      (callee.info.node.args.posonlyargs
                       + callee.info.node.args.args)]
        skip = 1 if callee_pos[:1] == ["self"] else 0
        out = []
        for i, arg in enumerate(node.args):
            if isinstance(arg, ast.Name) \
                    and i + skip < len(callee_pos) \
                    and callee_pos[i + skip] in callee.key_params:
                out.append(arg.id)
        for kw in node.keywords:
            if isinstance(kw.value, ast.Name) \
                    and kw.arg in callee.key_params:
                out.append(kw.value.id)
        return out


INTERPROC_RULES = [TransitiveHostSync, TransitiveJitInLoop,
                   CrossFunctionKeyReuse]
