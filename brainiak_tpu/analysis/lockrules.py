"""Lock-discipline race detector JX201-JX205 for the serve loop.

PR 8 introduced the repo's first real threading (the
:class:`~brainiak_tpu.serve.service.ServeService` loop, ticket
futures, eviction callbacks) with no tooling able to prove its lock
discipline.  These rules implement the ``# guarded-by:`` convention:

- annotate a mutable attribute where it is created::

      self._pending = {}   # guarded-by: _engine_lock

- annotate a helper whose callers must hold a lock (trusted like
  clang's ``REQUIRES()`` — and *verified* at every statically
  visible call site)::

      def _deliver_many(self, name, records):  # requires-lock: _engine_lock

The analyzer discovers every ``threading.Lock``/``RLock``/
``Condition`` attribute, computes the lock set held at each
statement — ``with self._lock:`` blocks plus the **entry lock set**
propagated through the call graph (the intersection of the locks
held at every statically visible call site; functions that escape as
callbacks or thread targets start from the empty set) — and reports:

- **JX201** — read/write of a ``guarded-by`` field without holding
  its lock;
- **JX202** — inconsistent lock-acquisition order (a cycle in the
  acquired-while-holding graph; re-acquiring a non-reentrant
  ``Lock`` is the one-lock case);
- **JX203** — a blocking call (``.poll()``, ``.result()``,
  ``.join()``, ``.wait()`` on foreign objects, file I/O,
  ``time.sleep``) made while holding a lock;
- **JX204** — a call site that does not hold a callee's declared
  ``# requires-lock:``;
- **JX205** — annotation hygiene: ``guarded-by``/``requires-lock``
  naming a lock the class/module does not define.
"""

import ast
import re

from .core import ProjectRule, register
from .graph import body_nodes
from .summaries import project_summaries

__all__ = ["UnguardedAttribute", "LockOrderInversion",
           "BlockingCallUnderLock", "RequiresLockViolation",
           "UnknownLockAnnotation", "LOCK_RULES"]

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][\w.]*)")
_REQUIRES_RE = re.compile(r"#\s*requires-lock:\s*([A-Za-z_][\w.]*)")

_LOCK_CTORS = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "threading.Condition": "condition",
    "threading.Semaphore": "lock",
    "threading.BoundedSemaphore": "lock",
}

#: Reentrant kinds: re-acquisition is legal, not a self-deadlock
#: (``Condition()`` wraps an RLock by default).
_REENTRANT = {"rlock", "condition"}

_BLOCKING_CALLS = {
    "time.sleep": "time.sleep",
    "subprocess.run": "subprocess.run",
    "subprocess.call": "subprocess.call",
    "subprocess.check_call": "subprocess.check_call",
    "subprocess.check_output": "subprocess.check_output",
    "subprocess.Popen": "subprocess.Popen",
    "os.system": "os.system",
    "io.open": "io.open",
    "numpy.load": "np.load",
    "numpy.save": "np.save",
    "numpy.savez": "np.savez",
    "numpy.savez_compressed": "np.savez_compressed",
}
_BLOCKING_METHODS = {"result", "join", "wait", "wait_for", "poll"}


def _fmt(lock):
    module, cls, attr = lock
    return f"{cls}.{attr}" if cls else attr


def _fmt_set(locks):
    return ", ".join(sorted(_fmt(lk) for lk in locks)) or "none"


class LockModel:
    """Everything JX201-JX205 share, built once per run."""

    def __init__(self):
        self.locks = {}           # (module, cls|None, attr) -> kind
        self.guarded_attr = {}    # (module, cls, field) -> (lock, ln)
        self.guarded_global = {}  # (module, name) -> (lock, ln)
        self.requires = {}        # qualname -> set of lock ids
        self.ann_errors = []      # (ctx, lineno, message)
        self.entry = {}           # qualname -> frozenset of lock ids
        self.node_locks = {}      # qualname -> {id(node): frozenset}
        self.acquire_sites = {}   # qualname -> [(node, lock, held)]
        self.acquires_trans = {}  # qualname -> set of lock ids
        self.locked_modules = set()

    def lock_for_name(self, module, cls, name):
        """Resolve an annotation's lock name to a lock id."""
        name = name[5:] if name.startswith("self.") else name
        if "." in name:
            owner, attr = name.rsplit(".", 1)
            cands = [lk for lk in self.locks
                     if lk[1] == owner and lk[2] == attr]
            same = [lk for lk in cands if lk[0] == module]
            if len(same) == 1:
                return same[0]
            if len(cands) == 1:
                return cands[0]
            return None
        if cls is not None and (module, cls, name) in self.locks:
            return (module, cls, name)
        if (module, None, name) in self.locks:
            return (module, None, name)
        return None


def _stmt_lines(node):
    return range(node.lineno,
                 getattr(node, "end_lineno", node.lineno) + 1)


def _comment_on(ctx, node, regex):
    for lineno in _stmt_lines(node):
        m = regex.search(ctx.src_line(lineno))
        if m:
            return m.group(1), lineno
    return None


def _header_lines(node):
    first = min([node.lineno]
                + [d.lineno for d in node.decorator_list])
    last = node.body[0].lineno - 1 if node.body else node.lineno
    return range(first, max(last, node.lineno) + 1)


def _scan_definitions(project, model):
    for ctx in project.contexts.values():
        module = ctx.module
        for stmt in ctx.tree.body:
            self_assign = (isinstance(stmt, ast.Assign)
                           and len(stmt.targets) == 1
                           and isinstance(stmt.targets[0],
                                          ast.Name))
            if not self_assign:
                continue
            name = stmt.targets[0].id
            kind = _ctor_kind(ctx, stmt.value)
            if kind:
                model.locks[(module, None, name)] = kind
            hit = _comment_on(ctx, stmt, _GUARDED_RE)
            if hit:
                model.guarded_global[(module, name)] = hit
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for sub in ast.walk(node):
                target = None
                if isinstance(sub, ast.Assign) and sub.targets:
                    target = sub.targets[0]
                elif isinstance(sub, (ast.AnnAssign,
                                      ast.AugAssign)):
                    target = sub.target
                if not (isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"):
                    continue
                field = target.attr
                value = getattr(sub, "value", None)
                kind = _ctor_kind(ctx, value)
                if kind:
                    model.locks[(module, node.name, field)] = kind
                hit = _comment_on(ctx, sub, _GUARDED_RE)
                if hit:
                    key = (module, node.name, field)
                    model.guarded_attr.setdefault(key, hit)


def _ctor_kind(ctx, value):
    if not isinstance(value, ast.Call):
        return None
    return _LOCK_CTORS.get(ctx.resolve(value.func) or "")


def _resolve_annotations(project, model):
    """guarded-by/requires-lock names -> lock ids (JX205 on miss)."""
    for key, (name, lineno) in list(model.guarded_attr.items()):
        module, cls, field = key
        lock = model.lock_for_name(module, cls, name)
        if lock is None:
            model.ann_errors.append((
                project.modules.get(module), lineno,
                f"guarded-by names unknown lock `{name}` for "
                f"field `{field}` (class {cls} defines no such "
                "threading.Lock/RLock/Condition attribute)"))
            del model.guarded_attr[key]
        else:
            model.guarded_attr[key] = (lock, lineno)
    for key, (name, lineno) in list(model.guarded_global.items()):
        module, field = key
        lock = model.lock_for_name(module, None, name)
        if lock is None:
            model.ann_errors.append((
                project.modules.get(module), lineno,
                f"guarded-by names unknown lock `{name}` for "
                f"module global `{field}`"))
            del model.guarded_global[key]
        else:
            model.guarded_global[key] = (lock, lineno)
    for info in project.iter_functions():
        found = set()
        for lineno in _header_lines(info.node):
            m = _REQUIRES_RE.search(info.ctx.src_line(lineno))
            if not m:
                continue
            lock = model.lock_for_name(info.module, info.cls,
                                       m.group(1))
            if lock is None:
                model.ann_errors.append((
                    info.ctx, lineno,
                    f"requires-lock names unknown lock "
                    f"`{m.group(1)}` on '{info.name}'"))
            else:
                found.add(lock)
        if found:
            model.requires[info.qualname] = found


def _with_locks(model, info, node):
    """Lock ids acquired by one ``with`` item context expr."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self" and info.cls is not None):
        key = (info.module, info.cls, node.attr)
        if key in model.locks:
            return key
    if isinstance(node, ast.Name):
        key = (info.module, None, node.id)
        if key in model.locks:
            return key
    return None


def _walk_locksets(model, info):
    """Per-node held-set map + acquisition sites for one function."""
    held_map = {}
    sites = []

    def walk(node, held):
        held_map[id(node)] = held
        if isinstance(node, (ast.With, ast.AsyncWith)):
            # items acquire LEFT TO RIGHT: `with a, b:` holds a
            # while acquiring b — the same order edge as nesting
            inner = held
            for item in node.items:
                walk(item.context_expr, inner)
                if item.optional_vars is not None:
                    walk(item.optional_vars, inner)
                lock = _with_locks(model, info,
                                   item.context_expr)
                if lock is not None:
                    sites.append((item.context_expr, lock, inner))
                    inner = frozenset(inner | {lock})
            for stmt in node.body:
                walk(stmt, inner)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            walk(child, held)

    for stmt in info.node.body:
        walk(stmt, frozenset())
    return held_map, sites


def _escaped_functions(project, summaries):
    escaped = set()
    for summary in summaries.values():
        escaped |= summary.refs
    for ctx in project.contexts.values():
        for stmt in ctx.tree.body:
            if isinstance(stmt, (ast.FunctionDef,
                                 ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            for node in ast.walk(stmt):
                if isinstance(node, (ast.Name, ast.Attribute)):
                    for info in project.resolve_callable(ctx,
                                                         node):
                        escaped.add(info.qualname)
    return escaped


def build_lock_model(project):
    model = LockModel()
    _scan_definitions(project, model)
    model.locked_modules = {lk[0] for lk in model.locks}
    _resolve_annotations(project, model)
    summaries = project_summaries(project)
    for info in project.iter_functions():
        if info.module not in model.locked_modules:
            continue
        held_map, sites = _walk_locksets(model, info)
        model.node_locks[info.qualname] = held_map
        model.acquire_sites[info.qualname] = sites
    # transitive acquired-locks (may-analysis, for order edges)
    acquires = {q: {lk for _, lk, _ in s}
                for q, s in model.acquire_sites.items()}
    changed = True
    rounds = 0
    while changed and rounds < 12:
        changed = False
        rounds += 1
        for qual, summary in summaries.items():
            mine = acquires.setdefault(qual, set())
            before = len(mine)
            for _node, targets, _cond in summary.calls:
                for target in targets:
                    mine |= acquires.get(target.qualname, set())
            if len(mine) != before:
                changed = True
    model.acquires_trans = acquires
    _compute_entries(project, model, summaries)
    return model


def _compute_entries(project, model, summaries):
    """Entry lock sets: intersection over statically visible call
    sites, ∅ for escaped functions, plus trusted requires-lock."""
    universe = frozenset(model.locks)
    escaped = _escaped_functions(project, summaries)
    call_sites = {}
    for qual, summary in summaries.items():
        for node, targets, _cond in summary.calls:
            for target in targets:
                call_sites.setdefault(target.qualname, []).append(
                    (qual, node))
    entry = {q: universe for q in summaries}

    def lockset_at(caller, node):
        base = entry.get(caller, frozenset())
        withs = model.node_locks.get(caller, {}).get(
            id(node), frozenset())
        return base | withs

    for _ in range(20):
        changed = False
        for qual in summaries:
            if qual in escaped or qual not in call_sites:
                base = frozenset()
            else:
                base = universe
                for caller, node in call_sites[qual]:
                    base &= lockset_at(caller, node)
            eff = base | model.requires.get(qual, frozenset())
            if eff != entry[qual]:
                entry[qual] = frozenset(eff)
                changed = True
        if not changed:
            break
    model.entry = entry


def lock_model(project):
    return project.cache("lock_model", build_lock_model)


def _held_at(model, qual, node):
    return (model.entry.get(qual, frozenset())
            | model.node_locks.get(qual, {}).get(id(node),
                                                 frozenset()))


def _access_kind(ctx, node):
    """read vs write, seeing through subscript stores
    (``self._pending[k] = v`` writes the container)."""
    if isinstance(node.ctx, (ast.Store, ast.Del)):
        return "write"
    parent = ctx.parent(node)
    if isinstance(parent, ast.Subscript) and isinstance(
            parent.ctx, (ast.Store, ast.Del)):
        return "write"
    return "read"


@register
class UnguardedAttribute(ProjectRule):
    """JX201: guarded-by field accessed without its lock."""

    code = "JX201"
    name = "unguarded-attribute"

    def check(self, project):
        model = lock_model(project)
        if not model.guarded_attr and not model.guarded_global:
            return
        for info in project.iter_functions():
            if info.module not in model.locked_modules:
                continue
            if info.name == "__init__":
                continue  # construction precedes sharing
            yield from self._check_attrs(model, info)
            yield from self._check_globals(model, info)

    def _check_attrs(self, model, info):
        if info.cls is None:
            return
        ctx = info.ctx
        seen = set()
        for node in body_nodes(info):
            if not (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"):
                continue
            key = (info.module, info.cls, node.attr)
            hit = model.guarded_attr.get(key)
            if hit is None:
                continue
            lock, _ = hit
            held = _held_at(model, info.qualname, node)
            if lock in held:
                continue
            mark = (node.lineno, node.attr)
            if mark in seen:
                continue
            seen.add(mark)
            access = _access_kind(ctx, node)
            yield ctx.finding(
                self, node,
                f"{access} of `self.{node.attr}` (guarded-by "
                f"{_fmt(lock)}) in '{info.name}' without holding "
                f"it (held: {_fmt_set(held)}); wrap the access in "
                f"`with self.{lock[2]}:` or annotate the method "
                f"`# requires-lock: {lock[2]}`")

    def _check_globals(self, model, info):
        ctx = info.ctx
        fields = {name for (mod, name) in model.guarded_global
                  if mod == info.module}
        if not fields:
            return
        fn = info.node
        params = {a.arg for a in (fn.args.posonlyargs
                                  + fn.args.args
                                  + fn.args.kwonlyargs)}
        globals_decl = set()
        local_stores = set()
        for node in body_nodes(info):
            if isinstance(node, ast.Global):
                globals_decl |= set(node.names)
            elif isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Store):
                local_stores.add(node.id)
        seen = set()
        for node in body_nodes(info):
            if not (isinstance(node, ast.Name)
                    and node.id in fields):
                continue
            name = node.id
            if name in params or (name in local_stores
                                  and name not in globals_decl):
                continue  # shadowed local
            lock, _ = model.guarded_global[(info.module, name)]
            held = _held_at(model, info.qualname, node)
            if lock in held:
                continue
            mark = (node.lineno, name)
            if mark in seen:
                continue
            seen.add(mark)
            access = _access_kind(ctx, node)
            yield ctx.finding(
                self, node,
                f"{access} of module global `{name}` (guarded-by "
                f"{_fmt(lock)}) in '{info.name}' without holding "
                f"it (held: {_fmt_set(held)})")


@register
class LockOrderInversion(ProjectRule):
    """JX202: cyclic acquired-while-holding order (deadlock)."""

    code = "JX202"
    name = "lock-order-inversion"

    def check(self, project):
        model = lock_model(project)
        summaries = project_summaries(project)
        edges = {}   # (A, B) -> (ctx, node)
        for qual, sites in model.acquire_sites.items():
            info = summaries[qual].info if qual in summaries \
                else None
            if info is None:
                continue
            entry = model.entry.get(qual, frozenset())
            for node, lock, held_before in sites:
                held = entry | held_before
                if lock in held:
                    kind = model.locks.get(lock, "lock")
                    if kind not in _REENTRANT:
                        yield info.ctx.finding(
                            self, node,
                            f"re-acquisition of non-reentrant "
                            f"Lock {_fmt(lock)} while already "
                            "holding it: self-deadlock; use an "
                            "RLock or split the locked region")
                    continue
                for prior in held:
                    edges.setdefault((prior, lock),
                                     (info.ctx, node))
        for qual, summary in summaries.items():
            entry = model.entry.get(qual, frozenset())
            node_locks = model.node_locks.get(qual, {})
            for node, targets, _cond in summary.calls:
                held = entry | node_locks.get(id(node),
                                              frozenset())
                if not held:
                    continue
                for target in targets:
                    acq = model.acquires_trans.get(
                        target.qualname, set())
                    for lock in acq - held:
                        for prior in held:
                            edges.setdefault(
                                (prior, lock),
                                (summary.info.ctx, node))
        yield from self._report_cycles(edges)

    def _report_cycles(self, edges):
        adj = {}
        for (a, b) in edges:
            adj.setdefault(a, set()).add(b)

        def reaches(src, dst):
            seen = set()
            stack = [src]
            while stack:
                cur = stack.pop()
                if cur == dst:
                    return True
                if cur in seen:
                    continue
                seen.add(cur)
                stack.extend(adj.get(cur, ()))
            return False

        reported = set()
        for (a, b), (ctx, node) in sorted(
                edges.items(),
                key=lambda kv: (kv[1][0].relpath,
                                kv[1][1].lineno)):
            if not reaches(b, a):
                continue
            pair = frozenset((a, b))
            if pair in reported:
                continue
            reported.add(pair)
            yield ctx.finding(
                self, node,
                f"lock order inversion: {_fmt(b)} is acquired "
                f"while holding {_fmt(a)} here, but elsewhere "
                f"{_fmt(a)} is (transitively) acquired while "
                f"holding {_fmt(b)} — a potential deadlock; pick "
                "ONE acquisition order and enforce it")


@register
class BlockingCallUnderLock(ProjectRule):
    """JX203: blocking call made while holding a lock."""

    code = "JX203"
    name = "blocking-call-under-lock"

    def check(self, project):
        model = lock_model(project)
        summaries = project_summaries(project)
        for qual, summary in summaries.items():
            if summary.info.module not in model.locked_modules:
                continue
            ctx = summary.info.ctx
            entry = model.entry.get(qual, frozenset())
            node_locks = model.node_locks.get(qual, {})
            for node, _targets, _cond in summary.calls:
                held = entry | node_locks.get(id(node),
                                              frozenset())
                if not held:
                    continue
                label = self._blocking(model, summary.info, ctx,
                                       node, held)
                if label is None:
                    continue
                yield ctx.finding(
                    self, node,
                    f"blocking call `{label}` while holding "
                    f"{_fmt_set(held)}: every other thread "
                    "contending for the lock stalls behind this "
                    "I/O/wait; move it outside the locked region "
                    "or document via the baseline why the lock "
                    "must cover it")

    @staticmethod
    def _blocking(model, info, ctx, node, held):
        target = ctx.resolve(node.func) or ""
        if target in _BLOCKING_CALLS:
            return _BLOCKING_CALLS[target]
        if (isinstance(node.func, ast.Name)
                and node.func.id == "open"
                and node.func.id not in ctx.aliases):
            return "open(...)"
        if not isinstance(node.func, ast.Attribute):
            return None
        method = node.func.attr
        if method not in _BLOCKING_METHODS:
            return None
        receiver = node.func.value
        if method in ("wait", "wait_for"):
            lock = _with_locks(model, info, receiver)
            if lock is not None and lock in held:
                return None  # waiting the held condition: the idiom
        if method == "join" and isinstance(
                receiver, (ast.Constant, ast.JoinedStr)):
            return None  # str.join, not thread join
        return f".{method}()"


@register
class RequiresLockViolation(ProjectRule):
    """JX204: call site missing a callee's requires-lock."""

    code = "JX204"
    name = "requires-lock-violation"

    def check(self, project):
        model = lock_model(project)
        if not model.requires:
            return
        summaries = project_summaries(project)
        for qual, summary in summaries.items():
            ctx = summary.info.ctx
            for node, targets, _cond in summary.calls:
                if len(targets) != 1:
                    continue
                required = model.requires.get(
                    targets[0].qualname)
                if not required:
                    continue
                held = _held_at(model, qual, node)
                missing = required - held
                if not missing:
                    continue
                yield ctx.finding(
                    self, node,
                    f"call to '{targets[0].name}' which declares "
                    f"`# requires-lock: "
                    f"{_fmt_set(missing)}` without holding it "
                    f"(held: {_fmt_set(held)})")


@register
class UnknownLockAnnotation(ProjectRule):
    """JX205: guarded-by/requires-lock names an unknown lock."""

    code = "JX205"
    name = "unknown-lock-annotation"

    def check(self, project):
        model = lock_model(project)
        for ctx, lineno, message in model.ann_errors:
            if ctx is None:
                continue
            yield ctx.finding(self, lineno, message)


LOCK_RULES = [UnguardedAttribute, LockOrderInversion,
              BlockingCallUnderLock, RequiresLockViolation,
              UnknownLockAnnotation]
