"""Per-function dataflow summaries over the project call graph.

Each project function gets one :class:`FunctionSummary`: the calls it
makes (with their resolved project targets), the functions it merely
*references* (escape analysis for the lock rules), its host-sync
behavior, whether it constructs an uncached ``jax.jit`` per call,
which parameters it consumes as PRNG keys, and the collectives it
issues.  The interprocedural rules (:mod:`.interproc`,
:mod:`.meshrules`, :mod:`.lockrules`) are thin queries over these.

Transitive host-sync propagation is a **must-execute** analysis: a
sync only enters a function's summary when it executes on every call
(not nested under ``if``/``try``/``while``/``except``), and only
propagates through unconditional, unambiguously-resolved call sites.
That keeps JX010 actionable — ``obs`` spans whose
``block_until_ready`` is gated behind ``if enabled()`` do not taint
every instrumented caller.  Definite device syncs
(``.block_until_ready``/``.item()``/``jax.device_get``) propagate to
any depth; ambiguous host conversions (``np.asarray``/``np.array``/
``float``) count only one call away from the hot loop, where they
are still clearly attributable.
"""

import ast

from .graph import body_nodes
from .rules import _KEY_MGMT

__all__ = ["FunctionSummary", "build_summaries", "project_summaries"]

#: Device syncs that force the host to wait for the device queue no
#: matter what the operand is.
DEFINITE_SYNC_CALLS = {
    "jax.device_get": "jax.device_get",
    "jax.block_until_ready": "jax.block_until_ready",
}
DEFINITE_SYNC_METHODS = {"item", "block_until_ready"}

#: Host conversions that sync IF the operand lives on device — only
#: propagated one level (see module docstring).
HOST_CONV_CALLS = {
    "numpy.asarray": "np.asarray",
    "numpy.array": "np.array",
}

#: ``jax.lax`` collectives that take a mesh-axis name.
COLLECTIVE_OPS = {
    "psum", "pmean", "pmax", "pmin", "ppermute", "pshuffle",
    "all_gather", "all_to_all", "psum_scatter", "axis_index",
}

_CONDITIONAL = (ast.If, ast.IfExp, ast.While, ast.ExceptHandler,
                ast.Assert, ast.comprehension)


class FunctionSummary:
    """Everything the project rules need to know about one
    function without re-walking its body."""

    __slots__ = ("info", "calls", "refs", "definite_syncs",
                 "host_convs", "sync_witness", "builds_jit_line",
                 "key_params", "collectives")

    def __init__(self, info):
        self.info = info
        #: [(call node, (FunctionInfo, ...), conditional)]
        self.calls = []
        #: qualnames referenced without being called (escapes)
        self.refs = set()
        #: [(node, label, conditional)] — definite device syncs
        self.definite_syncs = []
        #: [(node, label, conditional)] — ambiguous host conversions
        self.host_convs = []
        #: human-readable witness chain once a definite sync is
        #: reachable must-execute (None until proven)
        self.sync_witness = None
        #: line of an uncached ``jax.jit`` construction, else None
        self.builds_jit_line = None
        #: parameter names this function consumes as PRNG keys
        self.key_params = set()
        #: [(node, op short name, axis expression or None)]
        self.collectives = []


def _conditional_nodes(fn_node):
    """ids of nodes that may not execute on a given call (nested
    anywhere under a conditional construct) — the must-execute
    filter.  Conservative in the under-reporting direction: ``if``
    tests and ``try`` bodies count as conditional too."""
    out = set()
    stack = [(n, False) for n in fn_node.body]
    while stack:
        node, cond = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        if cond:
            out.add(id(node))
        here = cond or isinstance(node, _CONDITIONAL) \
            or isinstance(node, ast.Try)
        for child in ast.iter_child_nodes(node):
            stack.append((child, here))
    return out


def _direct_sync(ctx, node):
    """(label, definite) when ``node`` is a host-sync call."""
    if not isinstance(node, ast.Call):
        return None
    target = ctx.resolve(node.func)
    if target in DEFINITE_SYNC_CALLS:
        return DEFINITE_SYNC_CALLS[target], True
    if (isinstance(node.func, ast.Attribute)
            and node.func.attr in DEFINITE_SYNC_METHODS):
        return f".{node.func.attr}()", True
    if target in HOST_CONV_CALLS:
        return HOST_CONV_CALLS[target], False
    if (isinstance(node.func, ast.Name)
            and node.func.id == "float"
            and node.func.id not in ctx.aliases
            and len(node.args) == 1
            and not isinstance(node.args[0], ast.Constant)):
        return "float(...)", False
    return None


def _is_cached_builder(info):
    """True when the function (or an enclosing def) carries a known
    program-cache decorator — its jit construction is memoized."""
    from .rules import _is_cached
    return _is_cached(info.ctx, info.node)


def _collect(project, info):
    summary = FunctionSummary(info)
    ctx = info.ctx
    conditional = _conditional_nodes(info.node)
    params = [a.arg for a in (info.node.args.posonlyargs
                              + info.node.args.args
                              + info.node.args.kwonlyargs)]
    call_func_ids = set()
    for node in body_nodes(info):
        if isinstance(node, ast.Call):
            call_func_ids.add(id(node.func))
            cond = id(node) in conditional
            hit = _direct_sync(ctx, node)
            if hit is not None:
                label, definite = hit
                bucket = (summary.definite_syncs if definite
                          else summary.host_convs)
                bucket.append((node, label, cond))
            target = ctx.resolve(node.func) or ""
            short = target.rsplit(".", 1)[-1]
            if (target.startswith("jax.lax.")
                    and short in COLLECTIVE_OPS):
                summary.collectives.append(
                    (node, short, _axis_arg(node, short)))
            if target == "jax.jit" \
                    and not ctx.in_decorator(node) \
                    and summary.builds_jit_line is None \
                    and not _is_cached_builder(info):
                summary.builds_jit_line = node.lineno
            if (target.startswith("jax.random.")
                    and short not in _KEY_MGMT
                    and node.args
                    and isinstance(node.args[0], ast.Name)
                    and node.args[0].id in params):
                summary.key_params.add(node.args[0].id)
            targets = tuple(project.resolve_call(ctx, node, info))
            summary.calls.append((node, targets, cond))
    # escape analysis: project functions referenced outside a direct
    # call position (callbacks, thread targets, functools wrappers)
    for node in body_nodes(info):
        if id(node) in call_func_ids:
            continue
        if isinstance(node, (ast.Name, ast.Attribute)) \
                and isinstance(getattr(node, "ctx", None), ast.Load):
            parent = ctx.parent(node)
            if isinstance(parent, ast.Attribute):
                continue  # inner part of a dotted chain
            for target in project.resolve_callable(ctx, node, info):
                summary.refs.add(target.qualname)
    return summary


def _axis_arg(node, op):
    """The axis-name argument expression of a collective call."""
    for kw in node.keywords:
        if kw.arg == "axis_name":
            return kw.value
    pos = 0 if op == "axis_index" else 1
    if len(node.args) > pos:
        return node.args[pos]
    return None


def _propagate_key_params(summaries, by_qual):
    """A parameter forwarded (positionally or by name) to a callee's
    key-consuming parameter is itself key-consuming."""
    changed = True
    rounds = 0
    while changed and rounds < 8:
        changed = False
        rounds += 1
        for summary in summaries:
            fn = summary.info.node
            params = [a.arg for a in (fn.args.posonlyargs
                                      + fn.args.args)]
            for node, targets, _ in summary.calls:
                if len(targets) != 1:
                    continue
                callee = by_qual.get(targets[0].qualname)
                if callee is None or not callee.key_params:
                    continue
                callee_pos = [
                    a.arg for a in
                    (callee.info.node.args.posonlyargs
                     + callee.info.node.args.args)]
                skip = 1 if callee_pos[:1] == ["self"] else 0
                for i, arg in enumerate(node.args):
                    if not isinstance(arg, ast.Name) \
                            or arg.id not in params:
                        continue
                    if i + skip < len(callee_pos) and \
                            callee_pos[i + skip] in \
                            callee.key_params:
                        if arg.id not in summary.key_params:
                            summary.key_params.add(arg.id)
                            changed = True
                for kw in node.keywords:
                    if isinstance(kw.value, ast.Name) \
                            and kw.value.id in params \
                            and kw.arg in callee.key_params \
                            and kw.value.id not in \
                            summary.key_params:
                        summary.key_params.add(kw.value.id)
                        changed = True


def _propagate_syncs(summaries, by_qual):
    """Must-execute transitive closure of definite device syncs."""
    for summary in summaries:
        for node, label, cond in summary.definite_syncs:
            if not cond:
                summary.sync_witness = (
                    f"{label} at {summary.info.relpath}:"
                    f"{node.lineno}")
                break
    changed = True
    rounds = 0
    while changed and rounds < 12:
        changed = False
        rounds += 1
        for summary in summaries:
            if summary.sync_witness is not None:
                continue
            for node, targets, cond in summary.calls:
                if cond or len(targets) != 1:
                    continue
                callee = by_qual.get(targets[0].qualname)
                if callee is None or callee.sync_witness is None:
                    continue
                summary.sync_witness = (
                    f"{callee.info.name} -> "
                    f"{callee.sync_witness}")
                changed = True
                break


def build_summaries(project):
    """``{qualname: FunctionSummary}`` for every project function."""
    by_qual = {}
    for info in project.iter_functions():
        by_qual[info.qualname] = _collect(project, info)
    summaries = list(by_qual.values())
    _propagate_key_params(summaries, by_qual)
    _propagate_syncs(summaries, by_qual)
    return by_qual


def project_summaries(project):
    """The per-run memoized summary table."""
    return project.cache("summaries", build_summaries)
