"""Abstract tracing of registered program builders (jaxlint-IR).

The static tiers (:mod:`..rules`, :mod:`..interproc`) reason about
source; this tier reasons about the **actual IR**: every
:func:`~brainiak_tpu.obs.runtime.counted_cache` builder that attached
a canonical-signature factory is built at its canonical key and traced
with ``jax.make_jaxpr`` at abstract (``ShapeDtypeStruct``) arguments —
no data, no device math, just the jaxprs XLA would compile.  One
:class:`SiteTrace` per spec summarizes everything the JP3xx rules
need as plain Python (dtype strings, primitive names, axis names,
donation/aliasing booleans), so :mod:`.rules` never imports jax.

Tracing conventions (the audit child pins these):

* 64-bit mode ON — a hidden ``np.float64`` constant then shows up as
  a genuine ``float64`` aval instead of being silently truncated
  (JP301's whole signal; Python floats stay weakly typed, so
  f32-input programs remain f32 unless something strongly promotes);
* 8 forced CPU host devices — collective programs trace against a
  real mesh, so axis names resolve (or demonstrably don't: JP304);
* compilation happens only when donation is at stake (JP302) — the
  aliasing table is a property of the *executable*, not the jaxpr.
"""

import warnings
from dataclasses import dataclass

__all__ = ["SiteTrace", "trace_spec"]

#: jaxpr primitives that are cross-device collectives; their axis
#: params must name axes of the mesh the spec traced against.
_COLLECTIVE_PRIMS = {
    "psum", "psum2", "pmax", "pmin", "pmean", "ppermute",
    "pbroadcast", "all_gather", "all_to_all", "reduce_scatter",
    "axis_index", "pgather", "pdot", "pswapaxes",
}

#: param keys that carry collective axis names.
_AXIS_PARAM_KEYS = ("axes", "axis_name", "axis")

#: dtypes whose appearance in a <=32-bit program is a promotion leak.
_WIDE_DTYPES = {"float64", "complex128"}


@dataclass
class SiteTrace:
    """One builder traced at one canonical spec — plain-Python facts.

    ``jaxpr`` keeps the ClosedJaxpr for debugging, but every field a
    rule reads is a string/tuple/bool so the rule layer stays
    jax-free.
    """

    site: str
    label: str
    key: tuple
    spec: dict
    input_dtypes: tuple = ()          # flattened arg aval dtypes
    jaxpr: object = None              # ClosedJaxpr, or None on error
    error: str = None                 # trace failure (str(exc))
    error_type: str = None
    wide_eqns: tuple = ()             # (primitive, dtype) f64/c128 outs
    callback_prims: tuple = ()        # callback primitives seen
    collectives: tuple = ()           # (primitive, axis-name tuple)
    mesh_axes: tuple = ()             # axes of the spec's trace mesh
    donate_expected: tuple = ()       # spec["donate"] argnums
    donated_declared: bool = False    # any donated_invars in the IR
    aliased: bool = None              # executable aliasing non-empty
    compile_error: str = None
    float_keys: tuple = ()            # float-valued key params (JP305)
    array_keys: tuple = ()            # unhashable-ish key params

    @property
    def axis_error(self):
        """Trace failed on an unresolvable collective axis (JP304)."""
        return bool(self.error) and "unbound axis name" in self.error

    @property
    def traced(self):
        """Whether this spec produced auditable IR: a jaxpr, or the
        one failure mode that IS a finding (unbound axis)."""
        return self.jaxpr is not None or self.axis_error


def _sub_jaxprs(params):
    """Jaxprs nested in an eqn's params (pjit/scan/while/cond...)."""
    stack = list(params.values())
    while stack:
        val = stack.pop()
        if isinstance(val, (list, tuple)):
            stack.extend(val)
        elif hasattr(val, "jaxpr") and hasattr(val, "consts"):
            yield val.jaxpr                       # ClosedJaxpr
        elif hasattr(val, "eqns") and hasattr(val, "invars"):
            yield val                             # raw Jaxpr


def iter_eqns(jaxpr):
    """Every eqn of ``jaxpr`` and its nested sub-jaxprs, once."""
    stack, seen = [jaxpr], set()
    while stack:
        j = stack.pop()
        if id(j) in seen:
            continue
        seen.add(id(j))
        for eqn in j.eqns:
            yield eqn
            stack.extend(_sub_jaxprs(eqn.params))


def _axis_names(value):
    """Flatten an axis param value into a tuple of name strings."""
    if isinstance(value, str):
        return (value,)
    if isinstance(value, (list, tuple, frozenset, set)):
        out = []
        for v in value:
            out.extend(_axis_names(v))
        return tuple(out)
    return ()


def _key_surface(record, key):
    """(float_keys, array_keys): cache-key params whose VALUES vary
    continuously (floats) or are array-shaped — both mint unbounded
    program-cache entries from what should be a finite bucket space.
    Param names come from the builder's own signature; names the
    site declared via ``float_keys_ok`` are intentional constants."""
    import inspect

    import numpy as np

    try:
        params = list(inspect.signature(record["fn"]).parameters)
    except (TypeError, ValueError):   # builtins, odd callables
        params = []
    ok = set(record.get("float_keys_ok") or ())
    float_keys, array_keys = [], []
    for i, value in enumerate(tuple(key)):
        name = params[i] if i < len(params) else f"arg{i}"
        if isinstance(value, (np.ndarray, list, dict, set)):
            array_keys.append(name)
        elif isinstance(value, (float, np.floating)) \
                and not isinstance(value, bool) and name not in ok:
            float_keys.append(name)
    return tuple(float_keys), tuple(array_keys)


def _summarize(jaxpr_closed):
    """(wide_eqns, callback_prims, collectives) from a ClosedJaxpr."""
    wide, callbacks, collectives = [], [], []
    for eqn in iter_eqns(jaxpr_closed.jaxpr):
        prim = eqn.primitive.name
        if "callback" in prim or prim in ("outside_call",
                                          "host_callback_call"):
            callbacks.append(prim)
        if prim in _COLLECTIVE_PRIMS:
            axes = []
            for k in _AXIS_PARAM_KEYS:
                if k in eqn.params:
                    axes.extend(_axis_names(eqn.params[k]))
            collectives.append((prim, tuple(axes)))
        for var in eqn.outvars:
            dt = str(getattr(getattr(var, "aval", None), "dtype", ""))
            if dt in _WIDE_DTYPES:
                wide.append((prim, dt))
    return tuple(wide), tuple(callbacks), tuple(collectives)


def _declared_donation(jaxpr_closed):
    """Whether any nested pjit declares donated invars in the IR."""
    for eqn in iter_eqns(jaxpr_closed.jaxpr):
        donated = eqn.params.get("donated_invars")
        if donated and any(donated):
            return True
    return False


def _executable_aliases(prog, args, kwargs):
    """Whether the compiled executable's input/output aliasing table
    is non-empty — the ground truth donation either survived to
    (``input_output_alias`` in the optimized HLO) or was dropped
    from (CPU: XLA warns and strips it)."""
    with warnings.catch_warnings():
        # CPU's "Some donated buffers were not usable" is exactly the
        # condition being measured, not a problem with measuring it
        warnings.simplefilter("ignore")
        compiled = prog.lower(*args, **kwargs).compile()
    text = compiled.as_text() or ""
    return "input_output_alias" in text


def trace_spec(record, spec):
    """Trace one builder at one canonical spec → :class:`SiteTrace`.

    Never raises: build/trace failures land in ``error`` (the
    coverage report's skip reasons and JP304's unbound-axis signal),
    compile failures in ``compile_error``.
    """
    import jax

    site = record["site"]
    key = tuple(spec.get("key", ()))
    kwargs = dict(spec.get("kwargs") or {})
    args = tuple(spec.get("args", ()))
    mesh = spec.get("mesh")
    float_keys, array_keys = _key_surface(record, key)
    trace = SiteTrace(
        site=site,
        label=str(spec.get("label") or ""),
        key=key,
        spec=spec,
        mesh_axes=tuple(mesh.axis_names) if mesh is not None else (),
        donate_expected=tuple(spec.get("donate") or ()),
        float_keys=float_keys,
        array_keys=array_keys,
    )
    trace.input_dtypes = tuple(
        str(leaf.dtype) for leaf in jax.tree_util.tree_leaves(args)
        if hasattr(leaf, "dtype"))
    try:
        prog = record["wrapper"](*key)
        fn = (lambda *a: prog(*a, **kwargs)) if kwargs else prog
        closed = jax.make_jaxpr(fn)(*args)
    except Exception as exc:
        trace.error = str(exc)
        trace.error_type = type(exc).__name__
        return trace
    trace.jaxpr = closed
    trace.wide_eqns, trace.callback_prims, trace.collectives = \
        _summarize(closed)
    trace.donated_declared = _declared_donation(closed)
    if trace.donate_expected or trace.donated_declared:
        # aliasing is an executable property — compile, but only
        # when donation is actually at stake (compiles dominate the
        # audit's wall clock)
        try:
            trace.aliased = _executable_aliases(prog, args, kwargs)
        except Exception as exc:
            trace.compile_error = str(exc)
    return trace
