"""jaxlint-IR auditor: enumerate, trace, and rule-check every
registered jitted-program builder.

The enumeration is **mechanical**, not curated:

1. the source tree is AST-scanned for ``counted_cache`` /
   ``program_cache`` decorated builders (the same decorator
   spellings jaxlint's JX001 recognizes) — this static census is the
   coverage DENOMINATOR, robust to modules that fail to import;
2. every census module is imported, which registers its builders in
   :func:`~brainiak_tpu.obs.runtime.builder_registry`;
3. each registered site's canonical-signature factory runs, and each
   spec it yields is traced (:mod:`.trace`) under the audit
   configuration (x64 on, forced multi-device CPU);
4. the JP3xx rules run over each trace; findings anchor at the
   builder's ``def`` line in its source file, where line pragmas and
   the shared baseline apply.

A site that cannot be audited is never silently dropped: it appears
in the coverage report with a reason (module import failed, no
canonical signature, factory failed, trace failed).  The coverage
contract (the JPR001 gate enforces >= 90%) keeps the mechanical
sweep honest — new builders must ship signatures or show up red.
"""

import ast
import importlib
import time
from dataclasses import dataclass, field

from ..core import Finding, build_context, iter_python_files
from .rules import DEFAULT_SELECT, IR_RULES

__all__ = ["AuditReport", "enumerate_static_sites", "run_audit"]


@dataclass
class AuditReport:
    """One jaxlint-IR run: findings + the coverage ledger."""

    findings: list = field(default_factory=list)
    stale: list = field(default_factory=list)
    #: static census: site -> {path, line, module, qualname}
    sites: dict = field(default_factory=dict)
    #: sites that produced auditable IR (>=1 jaxpr or axis-error)
    traced: list = field(default_factory=list)
    #: site -> reason for every census site NOT traced
    skipped: dict = field(default_factory=dict)
    seconds: float = 0.0
    select: tuple = ()

    @property
    def coverage(self):
        """Traced fraction of the static census (1.0 when empty)."""
        return (len(self.traced) / len(self.sites)) if self.sites \
            else 1.0

    def to_dict(self):
        return {
            "sites": len(self.sites),
            "traced": sorted(self.traced),
            "skipped": [{"site": s, "reason": r}
                        for s, r in sorted(self.skipped.items())],
            "coverage": round(self.coverage, 4),
            "seconds": round(self.seconds, 3),
            "rules": list(self.select),
            "findings": [f.to_dict() for f in self.findings],
            "stale_baseline": list(self.stale),
        }


def enumerate_static_sites(paths, repo_root):
    """AST census of cache-decorated builder sites under ``paths``.

    Returns ``{site: {path, line, module, qualname}}`` — every
    function decorated with a recognized caching decorator
    (:data:`..rules._CACHE_DECOS`) whose first argument is a string
    literal site name.  Site-less ``lru_cache`` uses are not program
    builders and are excluded by construction.
    """
    from ..rules import _CACHE_DECOS

    sites = {}
    for path in iter_python_files(paths):
        ctx = build_context(path, repo_root)
        if ctx.tree is None:
            continue
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                if not (isinstance(dec, ast.Call) and dec.args):
                    continue
                if ctx.resolve(dec.func) not in _CACHE_DECOS:
                    continue
                first = dec.args[0]
                if not (isinstance(first, ast.Constant)
                        and isinstance(first.value, str)):
                    continue
                sites[first.value] = {
                    "path": ctx.relpath,
                    "line": node.lineno,
                    "module": ctx.module,
                    "qualname": node.name,
                }
    return sites


def _import_census_modules(sites):
    """Import every census module; returns {module: error-or-None}."""
    status = {}
    for mod in sorted({info["module"] for info in sites.values()}):
        try:
            importlib.import_module(mod)
            status[mod] = None
        except Exception as exc:
            status[mod] = f"{type(exc).__name__}: {exc}"
    return status


def _first_reason(traces):
    for t in traces:
        if t.error:
            return f"trace failed ({t.error_type}): {t.error}"
    return "trace produced no IR"


def run_audit(paths, repo_root, select=None, baseline=None):
    """Run the full IR audit; returns an :class:`AuditReport`.

    Requires jax importable; the caller pins the environment
    (``JAX_PLATFORMS=cpu``, forced host device count) before this
    runs — the CLI's ``--ir`` mode and the ``jaxlint-ir`` gate both
    do.  64-bit mode is enabled for the duration of the audit (and
    restored) so promotion leaks are visible rather than truncated.
    """
    import jax

    from brainiak_tpu.obs.runtime import builder_registry

    t0 = time.monotonic()
    select = tuple(select) if select else DEFAULT_SELECT
    rules = [r() for r in IR_RULES if r.code in select]
    report = AuditReport(select=select)
    report.sites = enumerate_static_sites(paths, repo_root)
    import_status = _import_census_modules(report.sites)

    contexts = {}

    def ctx_for(info):
        rel = info["path"]
        if rel not in contexts:
            import os
            contexts[rel] = build_context(
                os.path.join(repo_root, rel), repo_root)
        return contexts[rel]

    raw = []
    x64_before = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", True)
    try:
        registry = builder_registry()
        for site, info in sorted(report.sites.items()):
            import_error = import_status.get(info["module"])
            if import_error:
                report.skipped[site] = (
                    f"module import failed: {import_error}")
                continue
            record = registry.get(site)
            if record is None:
                report.skipped[site] = (
                    "module imported but site never registered "
                    "(decorator not executed?)")
                continue
            factory = record.get("signature")
            if factory is None:
                report.skipped[site] = (
                    "no canonical signature registered "
                    "(trace_signature missing)")
                continue
            try:
                specs = list(factory())
            except Exception as exc:
                report.skipped[site] = (
                    f"signature factory failed "
                    f"({type(exc).__name__}): {exc}")
                continue
            if not specs:
                report.skipped[site] = (
                    "signature factory returned no specs")
                continue
            from .trace import trace_spec
            traces = [trace_spec(record, spec) for spec in specs]
            if not any(t.traced for t in traces):
                report.skipped[site] = _first_reason(traces)
                continue
            report.traced.append(site)
            for trace in traces:
                for rule in rules:
                    for message in rule.check(trace):
                        raw.append((rule, info, message))
    finally:
        jax.config.update("jax_enable_x64", x64_before)

    seen = set()
    findings = []
    for rule, info, message in raw:
        ctx = ctx_for(info)
        finding = Finding(info["path"], info["line"], rule.code,
                          message, ctx.src_line(info["line"]))
        ident = (finding.code, finding.path, finding.line, message)
        if ident in seen:
            continue  # multi-spec sites repeat spec-free findings
        seen.add(ident)
        if not ctx.suppressed(finding, rule.pragma):
            findings.append(finding)

    if baseline is not None:
        findings, stale = baseline.filter(findings)
        # the shared baseline also carries JX entries for the static
        # gates; only entries for the rules THIS audit ran can be
        # judged stale here
        report.stale = [e for e in stale if e.get("rule") in select]
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    report.findings = findings
    report.seconds = time.monotonic() - t0
    return report
