"""jaxlint-IR rules JP301-JP305: checks over traced program IR.

Where the JX rules pattern-match source, these rules read the
:class:`~brainiak_tpu.analysis.ir.trace.SiteTrace` facts distilled
from ``jax.make_jaxpr`` of each registered builder at its canonical
abstract signature — what XLA would actually compile, not what the
source looks like.  Each rule yields plain message strings; the
auditor (:mod:`.audit`) anchors them as findings at the builder's
``def`` line, where the normal ``# jaxlint: disable=JPxxx`` pragma
and baseline machinery apply.

This module must stay importable without jax (``tools/run_checks.py``
imports the analysis package on hosts that never trace).
"""

from ..core import register

__all__ = ["IRRule", "IR_RULES", "DEFAULT_SELECT"]


class IRRule:
    """Base class: one check over one traced builder spec."""

    code = ""
    name = ""
    gate = "jaxlint-ir"
    pragma = "jaxlint"

    def check(self, trace):  # pragma: no cover - interface
        raise NotImplementedError


def _spec_tag(trace):
    return f" [{trace.label}]" if trace.label else ""


@register
class DtypePromotionLeak(IRRule):
    """JP301: 64-bit values inside a program traced at <=32-bit
    inputs."""

    code = "JP301"
    name = "ir-dtype-promotion-leak"

    def check(self, trace):
        if trace.jaxpr is None or not trace.wide_eqns:
            return
        if any(d in ("float64", "complex128")
               for d in trace.input_dtypes):
            return  # legitimately a 64-bit program
        prim, dtype = trace.wide_eqns[0]
        in_set = "/".join(sorted(set(trace.input_dtypes))) or "scalar"
        yield (f"{trace.site}{_spec_tag(trace)}: {dtype} values "
               f"appear in a program whose inputs are {in_set} "
               f"(first widening primitive: {prim}) — a strongly "
               "typed 64-bit constant (np.float64 scalar, "
               "dtype-less np array) promotes the chain; on TPU "
               "this silently truncates instead, so the fit runs "
               "different math per backend")


@register
class DegenerateDonation(IRRule):
    """JP302: donation declared or expected, but the executable
    aliases nothing."""

    code = "JP302"
    name = "ir-degenerate-donation"

    def check(self, trace):
        if trace.jaxpr is None:
            return
        if trace.donated_declared:
            if trace.aliased is False:
                yield (f"{trace.site}{_spec_tag(trace)}: program "
                       "declares donated arguments but the compiled "
                       "executable's aliasing table is empty — XLA "
                       "dropped the donation (unusable layout or "
                       "backend), so the buffer double-buffers "
                       "anyway and the caller must still not reuse "
                       "it")
        elif trace.donate_expected:
            argnums = ",".join(str(i) for i in trace.donate_expected)
            yield (f"{trace.site}{_spec_tag(trace)}: family expects "
                   f"the batch buffer (argnums {argnums}) to be "
                   "donated but the built program declares no "
                   "donation — on HBM-bound serving paths the "
                   "padded batch double-buffers")


@register
class HostCallbackInProgram(IRRule):
    """JP303: host callback primitive inside a hot jitted program."""

    code = "JP303"
    name = "ir-host-callback"

    def check(self, trace):
        if trace.jaxpr is None:
            return
        for prim in sorted(set(trace.callback_prims)):
            yield (f"{trace.site}{_spec_tag(trace)}: {prim} "
                   "primitive inside the jitted program — every "
                   "dispatch pays a host round-trip, serializing "
                   "the device queue from inside the hottest path")


@register
class CollectiveAxisMismatch(IRRule):
    """JP304: collective axes that don't resolve against the trace
    mesh."""

    code = "JP304"
    name = "ir-collective-axis"

    def check(self, trace):
        if trace.axis_error:
            yield (f"{trace.site}{_spec_tag(trace)}: trace failed "
                   f"with '{trace.error}' — the program names a "
                   "collective axis no enclosing mesh binds")
            return
        if trace.jaxpr is None:
            return
        mesh_axes = set(trace.mesh_axes)
        for prim, axes in trace.collectives:
            unknown = [a for a in axes if a not in mesh_axes]
            if not unknown:
                continue
            if not mesh_axes:
                yield (f"{trace.site}{_spec_tag(trace)}: {prim} "
                       f"over axis {'/'.join(unknown)} but the "
                       "canonical spec provides no trace mesh — "
                       "the signature cannot validate the "
                       "collective it contains")
            else:
                yield (f"{trace.site}{_spec_tag(trace)}: {prim} "
                       f"over axis {'/'.join(unknown)}, not an axis "
                       f"of the trace mesh "
                       f"({', '.join(sorted(mesh_axes))}) — the "
                       "program can only run under a differently "
                       "named mesh than its own signature declares")


@register
class RetraceSurface(IRRule):
    """JP305: array-valued or continuously-varying builder cache
    keys."""

    code = "JP305"
    name = "ir-retrace-surface"

    def check(self, trace):
        for name in trace.array_keys:
            yield (f"{trace.site}: builder cache key parameter "
                   f"'{name}' is array/container-valued — unhashable "
                   "or unbounded as an lru key; every distinct value "
                   "mints a fresh compiled program")
        for name in trace.float_keys:
            yield (f"{trace.site}: builder cache key parameter "
                   f"'{name}' carries a float — a continuously "
                   "varying value makes the program cache unbounded "
                   "(one compile per distinct float); declare it in "
                   "float_keys_ok if it is a fixed per-model "
                   "constant")


IR_RULES = (DtypePromotionLeak, DegenerateDonation,
            HostCallbackInProgram, CollectiveAxisMismatch,
            RetraceSurface)

DEFAULT_SELECT = tuple(r.code for r in IR_RULES)
