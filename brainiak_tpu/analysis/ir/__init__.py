"""jaxlint-IR: the traced-IR analysis tier (rules JP301-JP305).

The third analysis tier.  Tier 1 (:mod:`..rules`) pattern-matches
single files; tier 2 (:mod:`..interproc` and friends) reasons over
the project call graph; this tier builds every registered
jitted-program builder at a canonical abstract signature and runs
rules over the **actual jaxpr/executable** — dtype promotion leaks
(JP301), degenerate donation (JP302), host callbacks in hot programs
(JP303), collective-axis validity against a real mesh (JP304), and
retrace-surface hygiene of the builder cache keys (JP305).

Entry points: :func:`run_audit` (programmatic),
``python -m brainiak_tpu.analysis.cli --ir`` (CLI), and the
``jaxlint-ir`` gate of ``tools/run_checks.py`` (CI).  Importing this
package is jax-free; only :func:`run_audit` needs a working jax.
"""

from .audit import AuditReport, enumerate_static_sites, run_audit
from .rules import DEFAULT_SELECT, IR_RULES, IRRule

__all__ = [
    "AuditReport",
    "DEFAULT_SELECT",
    "IRRule",
    "IR_RULES",
    "enumerate_static_sites",
    "run_audit",
]
