"""jaxlint: AST-based TPU-correctness static analysis.

Rule-plugin analyzer enforcing the TPU-readiness invariants the
north-star depends on.  v1 file rules (JX001-JX006): no per-call
retracing, no host-device syncs in hot loops, no float64 leaks,
disciplined PRNG handling, no Python branching on traced values,
and explicit static arguments.  v2 project rules run over a shared
call-graph model (:mod:`.graph`/:mod:`.summaries`): interprocedural
dataflow (JX010-JX012), mesh/collective axis checking
(JX101-JX103), and the serve-loop lock-discipline race detector
(JX201-JX205).  v3 (:mod:`.ir`, rules JP301-JP305) leaves the AST
entirely: every registered jitted-program builder is traced at a
canonical abstract signature and the rules run over the actual
jaxpr/executable — dtype promotion, donation, host callbacks,
collective axes, retrace surface.  Run it standalone
(``python -m brainiak_tpu.analysis``, ``--ir`` for the traced
tier, ``--format sarif`` for CI annotation hosts) or through the
``jaxlint`` / ``jaxlint-deep`` / ``jaxlint-ir`` gates of
``python -m tools.run_checks``.
"""

from .baseline import Baseline, BaselineError  # noqa: F401
from .config import JaxlintConfig, load_config  # noqa: F401
from .core import (  # noqa: F401
    FileContext,
    FileRule,
    Finding,
    ProjectRule,
    RepoRule,
    analyze_file,
    analyze_paths,
    iter_python_files,
    register,
)
from .rules import JAXLINT_RULES  # noqa: F401
from .cli import ALL_RULES, DEEP_RULES  # noqa: F401
from .ir import IR_RULES, run_audit  # noqa: F401
from .sarif import to_sarif  # noqa: F401
