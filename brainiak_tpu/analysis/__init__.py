"""jaxlint: AST-based TPU-correctness static analysis (JX001-JX006).

Rule-plugin analyzer enforcing the TPU-readiness invariants the
north-star depends on: no per-call retracing, no host-device syncs in
hot loops, no float64 leaks, disciplined PRNG handling, no Python
branching on traced values, and explicit static arguments.  Run it
standalone (``python -m brainiak_tpu.analysis``) or as the jaxlint
gate of ``python -m tools.run_checks --only=jaxlint``.
"""

from .baseline import Baseline, BaselineError  # noqa: F401
from .config import JaxlintConfig, load_config  # noqa: F401
from .core import (  # noqa: F401
    FileContext,
    FileRule,
    Finding,
    RepoRule,
    analyze_file,
    analyze_paths,
    iter_python_files,
    register,
)
from .rules import JAXLINT_RULES  # noqa: F401
