"""Project-wide semantic model: modules, functions, call graph.

PR 2's jaxlint sees one file at a time; the JX01x/JX1xx/JX2xx
families need to see the program.  :class:`ProjectContext` is the
shared model every :class:`~.core.ProjectRule` runs over — built
once per analyzer run from the SAME :class:`~.core.FileContext`
parses the file rules already used (no second parse):

- a **module map** (dotted module name -> context, with relative
  imports canonicalized against each file's package);
- a **function index** covering nested defs and methods, with the
  innermost-enclosing-function query rules anchor findings with;
- **call resolution** from a call site to the project functions it
  may invoke: local defs, module-level functions, ``self.`` methods,
  alias-expanded cross-module dotted names, ``functools.partial``
  unwrapping, and a unique-method-name fallback for attribute calls
  whose receiver type is statically unknown;
- a **string-constant table** so axis names like
  ``DEFAULT_VOXEL_AXIS`` resolve to their literal values across
  modules (the mesh rules verify against values, not spellings).

Per-function dataflow summaries live in :mod:`.summaries`; rule
families cache their derived models through :meth:`ProjectContext.
cache` so e.g. the lock model is computed once for JX201-JX205.
"""

import ast

__all__ = ["FunctionInfo", "ProjectContext", "body_nodes"]


class FunctionInfo:
    """One function/method definition in the project."""

    __slots__ = ("qualname", "name", "node", "ctx", "module", "cls",
                 "parent", "scope")

    def __init__(self, qualname, name, node, ctx, cls, parent,
                 scope):
        self.qualname = qualname   # "module:Outer.inner"
        self.name = name
        self.node = node
        self.ctx = ctx
        self.module = ctx.module
        self.cls = cls             # innermost class name or None
        self.parent = parent       # enclosing FunctionInfo or None
        self.scope = scope         # tuple of enclosing def/class names

    @property
    def relpath(self):
        return self.ctx.relpath

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"<fn {self.qualname}>"


def body_nodes(info):
    """Every AST node belonging to ``info``'s own body, in source
    (pre-)order — consumers anchor findings to the FIRST offending
    site, so ordering is part of the contract.  Nested
    function/class bodies are excluded (they are separate
    :class:`FunctionInfo` scopes); lambdas are treated as part of
    the enclosing function."""
    stack = list(reversed(info.node.body))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield node
        stack.extend(reversed(list(ast.iter_child_nodes(node))))


class ProjectContext:
    """The project model shared by every project rule in one run."""

    def __init__(self, contexts):
        # parse failures already produced CHK001; skip their trees
        self.contexts = {rel: ctx for rel, ctx in contexts.items()
                         if ctx.tree is not None}
        self.modules = {}          # module name -> FileContext
        self.functions = {}        # qualname -> FunctionInfo
        self._top = {}             # (module, name) -> FunctionInfo
        self._methods = {}         # (class, method) -> [FunctionInfo]
        self._by_method_name = {}  # method name -> [FunctionInfo]
        self._by_node = {}         # id(def node) -> FunctionInfo
        self._locals = {}          # (id(parent node), name) -> info
        self._constants = {}       # (module, NAME) -> str value
        self._const_by_name = {}   # NAME -> set of str values
        self._cache = {}
        for ctx in self.contexts.values():
            self.modules[ctx.module] = ctx
        for ctx in self.contexts.values():
            self._index_module(ctx)

    def cache(self, key, builder):
        """Memoize an expensive derived model (lock model, summaries,
        mesh declarations) across the project rules of one run."""
        if key not in self._cache:
            self._cache[key] = builder(self)
        return self._cache[key]

    # -- indexing ----------------------------------------------------

    def _index_module(self, ctx):
        module = ctx.module
        for stmt in ctx.tree.body:
            if (isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Constant)
                    and isinstance(stmt.value.value, str)):
                name = stmt.targets[0].id
                self._constants[(module, name)] = stmt.value.value
                self._const_by_name.setdefault(name, set()).add(
                    stmt.value.value)
        self._walk_defs(ctx, ctx.tree, (), None, None)

    def _walk_defs(self, ctx, node, scope, cls, parent):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                inner = scope + (child.name,)
                qual = f"{ctx.module}:{'.'.join(inner)}"
                info = FunctionInfo(qual, child.name, child, ctx,
                                    cls, parent, scope)
                # first definition wins on (rare) duplicate names
                self.functions.setdefault(qual, info)
                self._by_node[id(child)] = info
                if parent is None and cls is None:
                    self._top.setdefault((ctx.module, child.name),
                                         info)
                if cls is not None and parent is None:
                    self._methods.setdefault(
                        (cls, child.name), []).append(info)
                    self._by_method_name.setdefault(
                        child.name, []).append(info)
                if parent is not None:
                    self._locals[(id(parent.node), child.name)] = \
                        info
                self._walk_defs(ctx, child, inner, cls, info)
            elif isinstance(child, ast.ClassDef):
                self._walk_defs(ctx, child, scope + (child.name,),
                                child.name, None)
            else:
                self._walk_defs(ctx, child, scope, cls, parent)

    # -- queries -----------------------------------------------------

    def function_for_node(self, node):
        """The :class:`FunctionInfo` whose def node is ``node``."""
        return self._by_node.get(id(node))

    def enclosing_function(self, ctx, node):
        """Innermost indexed function containing ``node``."""
        cur = node
        while cur is not None:
            info = self._by_node.get(id(cur))
            if info is not None:
                return info
            cur = ctx.parent(cur)
        return None

    def iter_functions(self):
        return self.functions.values()

    def methods_named(self, name):
        return self._by_method_name.get(name, [])

    def module_function(self, module, name):
        return self._top.get((module, name))

    # -- call resolution ---------------------------------------------

    def resolve_call(self, ctx, call, enclosing=None):
        """Project functions a call site may invoke (possibly [])."""
        return self.resolve_callable(ctx, call.func, enclosing)

    def resolve_callable(self, ctx, node, enclosing=None, _depth=0):
        """Project functions a callable *expression* denotes.

        Handles bare names (local defs, module functions, imported
        names), ``self.method``, dotted cross-module attributes, and
        ``functools.partial(f, ...)`` unwrapping.  Attribute calls
        on statically-unknown receivers resolve to nothing — a
        deliberate precision choice: a unique-method-name guess
        turns every ``d.get(...)`` into a call edge to whatever
        class happens to define ``get``.
        """
        if _depth > 4:
            return []
        if isinstance(node, ast.Call):
            target = ctx.resolve(node.func) or ""
            if target.rsplit(".", 1)[-1] == "partial" and node.args:
                return self.resolve_callable(
                    ctx, node.args[0], enclosing, _depth + 1)
            return []
        if isinstance(node, ast.Name):
            cur = enclosing
            while cur is not None:
                local = self._locals.get((id(cur.node), node.id))
                if local is not None:
                    return [local]
                cur = cur.parent
            top = self._top.get((ctx.module, node.id))
            if top is not None:
                return [top]
            dotted = ctx.aliases.get(node.id)
            if dotted:
                return self._resolve_dotted(dotted, _depth)
            return []
        if isinstance(node, ast.Attribute):
            if (isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and enclosing is not None
                    and enclosing.cls is not None):
                cands = self._methods.get(
                    (enclosing.cls, node.attr), [])
                same = [c for c in cands
                        if c.module == enclosing.module]
                return same or cands
            dotted = ctx.resolve(node)
            if dotted:
                return self._resolve_dotted(dotted, _depth)
            return []
        return []

    def _resolve_dotted(self, dotted, _depth=0):
        if _depth > 4:
            return []
        parts = dotted.split(".")
        for i in range(len(parts) - 1, 0, -1):
            module = ".".join(parts[:i])
            ctx = self.modules.get(module)
            if ctx is None:
                continue
            rest = parts[i:]
            if len(rest) == 1:
                info = self._top.get((module, rest[0]))
                if info is not None:
                    return [info]
                # package re-export: chase the __init__ alias
                target = ctx.aliases.get(rest[0])
                if target and target != dotted:
                    return self._resolve_dotted(target, _depth + 1)
                return []
            if len(rest) == 2:
                cands = [f for f in self._methods.get(
                             (rest[0], rest[1]), [])
                         if f.module == module]
                if cands:
                    return cands
                target = ctx.aliases.get(rest[0])
                if target and target != dotted:
                    return self._resolve_dotted(
                        f"{target}.{rest[1]}", _depth + 1)
            return []
        return []

    # -- constant / axis-name resolution -----------------------------

    def param_default(self, fn_node, name):
        """The default-value expression of parameter ``name``."""
        args = fn_node.args
        pos = args.posonlyargs + args.args
        n_def = len(args.defaults)
        for arg, dflt in zip(pos[len(pos) - n_def:], args.defaults):
            if arg.arg == name:
                return dflt
        for arg, dflt in zip(args.kwonlyargs, args.kw_defaults):
            if arg.arg == name and dflt is not None:
                return dflt
        return None

    def literal_strings(self, ctx, node, enclosing=None, _depth=0):
        """The set of literal strings an expression denotes, or None
        when any part is statically unresolvable (rules then skip —
        they flag only provable mismatches)."""
        if node is None or _depth > 5:
            return None
        if isinstance(node, ast.Constant):
            return ({node.value}
                    if isinstance(node.value, str) else None)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            out = set()
            for elt in node.elts:
                sub = self.literal_strings(ctx, elt, enclosing,
                                           _depth + 1)
                if sub is None:
                    return None
                out |= sub
            return out
        if isinstance(node, ast.Name):
            if enclosing is not None:
                bound, resolved = self._local_binding(
                    ctx, node.id, enclosing, _depth)
                if bound:
                    return resolved
            const = self._constants.get((ctx.module, node.id))
            if const is not None:
                return {const}
            dotted = ctx.aliases.get(node.id)
            if dotted and "." in dotted:
                mod, name = dotted.rsplit(".", 1)
                const = self._constants.get((mod, name))
                if const is not None:
                    return {const}
            vals = self._const_by_name.get(node.id)
            if vals is not None and len(vals) == 1:
                return set(vals)
            return None
        if isinstance(node, ast.Attribute):
            dotted = ctx.resolve(node)
            if dotted and "." in dotted:
                mod, name = dotted.rsplit(".", 1)
                const = self._constants.get((mod, name))
                if const is not None:
                    return {const}
            return None
        return None

    def _local_binding(self, ctx, name, enclosing, _depth):
        """Resolve a name bound inside a function: a parameter's
        default (callers may override — still the declared intent
        the rule verifies) or a single local literal assignment.
        Returns ``(bound, values)``: a locally-bound name stops the
        module-scope fallback even when its value is unresolvable (a
        parameter must not be confused with a same-named module
        constant it shadows)."""
        fn = enclosing.node
        params = {a.arg for a in (fn.args.posonlyargs + fn.args.args
                                  + fn.args.kwonlyargs)}
        if name in params:
            return True, self.literal_strings(
                ctx, self.param_default(fn, name), enclosing.parent,
                _depth + 1)
        assigns = []
        for node in body_nodes(enclosing):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    for sub in ast.walk(tgt):
                        if isinstance(sub, ast.Name) \
                                and sub.id == name:
                            assigns.append(
                                node.value
                                if isinstance(tgt, ast.Name)
                                else None)
        if len(assigns) == 1 and assigns[0] is not None:
            return True, self.literal_strings(
                ctx, assigns[0], enclosing, _depth + 1)
        if assigns:
            return True, None
        return False, None
