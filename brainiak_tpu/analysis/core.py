"""jaxlint core: findings, per-file context, rule registry, engine.

The analyzer is a rule-plugin system: each rule is a small class
registered with :func:`register`; the engine parses every file ONCE
into a :class:`FileContext` and hands the same context to every
enabled rule, so adding a rule never adds a parse.  Suppression is
layered:

* line pragma  -- ``# jaxlint: disable=JX001[,JX002]`` (JX rules) or
  the conventional ``# noqa`` (style gates, ``pragma = "noqa"``);
  for jaxlint pragmas a multi-line simple statement is one logical
  line (a pragma on any of its physical lines suppresses), and a
  function/class header is one unit (decorator lines and the ``def``
  line suppress each other's findings);
* baseline     -- a repo-level JSON file of grandfathered findings,
  each with a written justification (:mod:`.baseline`).

Rules come in three kinds: :class:`FileRule` (runs once per parsed
file), :class:`ProjectRule` (runs once over the whole analyzed file
set through the shared semantic model of :mod:`.graph` -- the
interprocedural JX01x / mesh JX1xx / lock JX2xx families), and
:class:`RepoRule` (runs once per repo walk -- used by the
``tools/run_checks.py`` gates that need cross-file state).
"""

import ast
import os
import re
from dataclasses import dataclass

__all__ = [
    "Finding", "FileContext", "FileRule", "ProjectRule", "RepoRule",
    "register", "all_rules", "rules_for_gate", "analyze_file",
    "analyze_context", "analyze_paths", "build_context",
    "run_project_rules", "iter_python_files", "SKIP_DIRS",
]

SKIP_DIRS = {
    ".git", "__pycache__", ".claude", "build", "dist",
    ".pytest_cache", "node_modules", ".venv", "venv", ".tox",
    ".eggs", ".ruff_cache", ".mypy_cache",
}

_PRAGMA_RE = re.compile(
    r"#\s*jaxlint:\s*disable=([A-Za-z0-9_,\s]+)")

# attributes of a traced array that are static at trace time, so
# branching on them is legitimate Python control flow under jit
_STATIC_ATTRS = {"ndim", "shape", "dtype", "size", "aval"}


@dataclass(frozen=True)
class Finding:
    """One analyzer finding, machine-readable."""

    path: str          # repo-relative, POSIX separators
    line: int
    code: str          # e.g. "JX001"
    message: str
    snippet: str = ""  # stripped source line (baseline fingerprint)

    def key(self):
        """Line-number-free identity used by baseline matching."""
        return (self.code, self.path, self.snippet)

    def to_dict(self):
        return {"path": self.path, "line": self.line,
                "code": self.code, "message": self.message,
                "snippet": self.snippet}

    def __str__(self):
        return (f"{self.path}:{self.line}: {self.code} "
                f"{self.message}")


class FileContext:
    """One parsed file: source, AST, parents, import aliases, jit map.

    Shared across every rule so the file is read and parsed exactly
    once per analyzer run.
    """

    def __init__(self, path, relpath, source):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.module = self._module_name(self.relpath)
        self.source = source
        self.lines = source.splitlines()
        self.tree = None
        self.parse_error = None
        try:
            self.tree = ast.parse(source, filename=path)
            compile(source, path, "exec")
        except SyntaxError as exc:
            self.tree = None
            self.parse_error = exc
        self._parents = {}
        self._decorator_nodes = set()
        self._pragma_extents = {}   # line -> tuple of sibling lines
        self.aliases = {}
        self.jitted = {}   # FunctionDef -> set of static param names
        if self.tree is not None:
            self._index()

    # -- indexing ----------------------------------------------------

    @staticmethod
    def _module_name(relpath):
        """Dotted module name from the repo-relative path
        (``brainiak_tpu/serve/aot.py`` -> ``brainiak_tpu.serve.aot``,
        package ``__init__.py`` -> the package itself)."""
        parts = relpath[:-3].split("/") if relpath.endswith(".py") \
            else relpath.split("/")
        if parts and parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(p for p in parts if p) or "__main__"

    def _package_parts(self):
        parts = self.module.split(".")
        if self.relpath.endswith("/__init__.py"):
            return parts
        return parts[:-1]

    def _canonical_from(self, node):
        """Absolute dotted module for an ``ImportFrom``, resolving
        relative imports against this file's package."""
        if not node.level:
            return node.module or ""
        base = self._package_parts()
        base = base[:len(base) - (node.level - 1)] if node.level > 1 \
            else base
        parts = list(base)
        if node.module:
            parts.append(node.module)
        return ".".join(parts)

    def _index(self):
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                for dec in node.decorator_list:
                    for sub in ast.walk(dec):
                        self._decorator_nodes.add(id(sub))
                self._index_header_extent(node)
            elif self._is_simple_stmt(node):
                self._index_stmt_extent(node)
            if isinstance(node, ast.Import):
                for alias in node.names:
                    bound = alias.asname or alias.name.split(".")[0]
                    canon = (alias.name if alias.asname
                             else alias.name.split(".")[0])
                    self.aliases[bound] = canon
            elif isinstance(node, ast.ImportFrom):
                mod = self._canonical_from(node)
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    bound = alias.asname or alias.name
                    self.aliases[bound] = (f"{mod}.{alias.name}"
                                           if mod else alias.name)
        self._collect_jitted()

    @staticmethod
    def _is_simple_stmt(node):
        return isinstance(node, (
            ast.Assign, ast.AnnAssign, ast.AugAssign, ast.Expr,
            ast.Return, ast.Raise, ast.Assert, ast.Delete,
            ast.Import, ast.ImportFrom, ast.Global, ast.Nonlocal))

    def _index_stmt_extent(self, node):
        """A multi-line simple statement is ONE logical line for
        jaxlint pragmas (flake8 noqa semantics)."""
        end = getattr(node, "end_lineno", node.lineno)
        if end <= node.lineno:
            return
        span = tuple(range(node.lineno, end + 1))
        for line in span:
            self._pragma_extents.setdefault(line, span)

    def _index_header_extent(self, node):
        """Decorator lines + the ``def``/``class`` header line form
        one pragma unit: a pragma on the decorator line suppresses a
        finding anchored to the def line and vice versa."""
        first = min([node.lineno]
                    + [d.lineno for d in node.decorator_list])
        last = node.body[0].lineno - 1 if node.body else node.lineno
        if last < node.lineno:
            last = node.lineno
        span = tuple(range(first, last + 1))
        if len(span) <= 1:
            return
        for line in span:
            self._pragma_extents[line] = span

    def _collect_jitted(self):
        defs = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                defs.setdefault(node.name, node)
                statics = self._decorator_statics(node)
                if statics is not None:
                    self.jitted[node] = statics
        # wrap-site pattern: ``f_jit = jax.jit(f, static_...)``
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and self.resolve(node.value.func) == "jax.jit"
                    and node.value.args
                    and isinstance(node.value.args[0], ast.Name)):
                continue
            fn = defs.get(node.value.args[0].id)
            if fn is not None and fn not in self.jitted:
                self.jitted[fn] = self._static_names(
                    fn, node.value.keywords)

    def _decorator_statics(self, fn):
        """Static param names if ``fn`` is jit-decorated, else None."""
        for dec in fn.decorator_list:
            if self.resolve(dec) == "jax.jit":
                return set()
            if isinstance(dec, ast.Call):
                target = self.resolve(dec.func)
                if target == "jax.jit":
                    return self._static_names(fn, dec.keywords)
                if (target == "functools.partial" and dec.args
                        and self.resolve(dec.args[0]) == "jax.jit"):
                    return self._static_names(fn, dec.keywords)
        return None

    def _static_names(self, fn, keywords):
        names = set()
        pos = fn.args.posonlyargs + fn.args.args
        for kw in keywords:
            if kw.arg == "static_argnames":
                for sub in ast.walk(kw.value):
                    if (isinstance(sub, ast.Constant)
                            and isinstance(sub.value, str)):
                        names.add(sub.value)
            elif kw.arg == "static_argnums":
                for sub in ast.walk(kw.value):
                    if (isinstance(sub, ast.Constant)
                            and isinstance(sub.value, int)
                            and 0 <= sub.value < len(pos)):
                        names.add(pos[sub.value].arg)
        return names

    # -- queries -----------------------------------------------------

    def parent(self, node):
        return self._parents.get(node)

    def ancestors(self, node):
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def enclosing(self, node, types):
        for anc in self.ancestors(node):
            if isinstance(anc, types):
                return anc
        return None

    def in_decorator(self, node):
        return id(node) in self._decorator_nodes

    def dotted(self, node):
        """``a.b.c`` parts of a Name/Attribute chain, else None."""
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        return list(reversed(parts))

    def resolve(self, node):
        """Canonical dotted name of an expression, alias-expanded.

        ``np.random.normal`` -> ``numpy.random.normal`` under
        ``import numpy as np``; unresolvable shapes return None.
        """
        parts = self.dotted(node)
        if not parts:
            return None
        root = self.aliases.get(parts[0], parts[0])
        return ".".join([root] + parts[1:])

    def src_line(self, lineno):
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule, node_or_line, message):
        lineno = (node_or_line if isinstance(node_or_line, int)
                  else node_or_line.lineno)
        return Finding(self.relpath, lineno, rule.code, message,
                       self.src_line(lineno))

    def fn_params(self, fn):
        return [a.arg for a in (fn.args.posonlyargs + fn.args.args
                                + fn.args.kwonlyargs)]

    # -- suppression -------------------------------------------------

    def suppressed(self, finding, pragma):
        if pragma == "noqa":
            # style gates keep exact-line noqa semantics (E501 is a
            # physical-line check; extending it would over-suppress)
            return "# noqa" in self.src_line(finding.line)
        for lineno in self._pragma_extents.get(finding.line,
                                              (finding.line,)):
            m = _PRAGMA_RE.search(self.src_line(lineno))
            if not m:
                continue
            codes = {c.strip() for c in m.group(1).split(",")}
            if finding.code in codes or "all" in codes:
                return True
        return False


class FileRule:
    """Base class: one check over one parsed file."""

    code = ""
    name = ""
    gate = "jaxlint"
    pragma = "jaxlint"     # "jaxlint" or "noqa" line suppression
    needs_tree = True

    def check(self, ctx):  # pragma: no cover - interface
        raise NotImplementedError


class ProjectRule:
    """Base class: one check over the whole analyzed file set.

    ``check`` receives a :class:`brainiak_tpu.analysis.graph.
    ProjectContext` (module map, call graph, per-function summaries)
    built once per run and shared by every project rule — the
    project-wide analog of :class:`FileRule`'s one-parse contract.
    Findings go through the same pragma + baseline suppression as
    file-rule findings.
    """

    code = ""
    name = ""
    gate = "jaxlint-deep"
    pragma = "jaxlint"

    def check(self, project):  # pragma: no cover - interface
        raise NotImplementedError


class RepoRule:
    """Base class: one check over the whole repository."""

    code = ""
    name = ""
    gate = "repo"
    pragma = "noqa"

    def check(self, repo_root):  # pragma: no cover - interface
        raise NotImplementedError


_REGISTRY = {}


def register(cls):
    """Class decorator adding a rule to the shared plugin registry."""
    if cls.code in _REGISTRY:
        raise ValueError(f"duplicate rule code {cls.code}")
    _REGISTRY[cls.code] = cls
    return cls


def all_rules():
    return dict(_REGISTRY)


def rules_for_gate(gate):
    return {c: r for c, r in _REGISTRY.items() if r.gate == gate}


def iter_python_files(paths, skip_dirs=SKIP_DIRS):
    """Yield .py files under ``paths`` (files pass through as-is)."""
    for base in paths:
        if os.path.isfile(base):
            if base.endswith(".py"):
                yield base
            continue
        for root, dirs, files in os.walk(base):
            dirs[:] = sorted(d for d in dirs if d not in skip_dirs)
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(root, f)


def build_context(path, repo_root):
    """Read + parse one file into a shared :class:`FileContext`."""
    relpath = os.path.relpath(path, repo_root)
    with open(path, encoding="utf-8") as fh:
        source = fh.read()
    return FileContext(path, relpath, source)


def analyze_context(ctx, rules):
    """Run file-rule instances over one built context.

    Parse failures yield a single CHK001 syntax finding; tree-needing
    rules are skipped for that file.
    """
    findings = []
    if ctx.parse_error is not None:
        exc = ctx.parse_error
        findings.append(Finding(
            ctx.relpath, exc.lineno or 1, "CHK001",
            f"syntax error: {exc.msg}",
            ctx.src_line(exc.lineno or 1)))
    for rule in rules:
        if rule.needs_tree and ctx.tree is None:
            continue
        for finding in rule.check(ctx):
            if not ctx.suppressed(finding, rule.pragma):
                findings.append(finding)
    return findings


def analyze_file(path, repo_root, rules):
    """Run ``rules`` (instances) over one file; returns findings."""
    return analyze_context(build_context(path, repo_root), rules)


def run_project_rules(contexts, rules):
    """Run :class:`ProjectRule` instances over already-built
    contexts (``{relpath: FileContext}``); pragma suppression is
    applied through each finding's own file context."""
    if not rules:
        return []
    from .graph import ProjectContext  # lazy: graph imports core
    project = ProjectContext(contexts)
    findings = []
    for rule in rules:
        for finding in rule.check(project):
            ctx = contexts.get(finding.path)
            if ctx is None or not ctx.suppressed(
                    finding, rule.pragma):
                findings.append(finding)
    return findings


def analyze_paths(paths, repo_root, rules, baseline=None):
    """Analyze every file under ``paths``.

    Files are parsed once into shared contexts; file rules run per
    file, project rules run once over the full context set, repo
    rules run last.  Returns ``(findings, stale_entries, n_files)``:
    findings that survived pragma + baseline suppression, baseline
    entries that matched nothing (candidates for deletion), and the
    file count.
    """
    instances = [r() if isinstance(r, type) else r for r in rules]
    file_rules = [r for r in instances if isinstance(r, FileRule)]
    project_rules = [r for r in instances
                     if isinstance(r, ProjectRule)]
    findings = []
    contexts = {}
    n = 0
    for path in iter_python_files(paths):
        n += 1
        ctx = build_context(path, repo_root)
        contexts[ctx.relpath] = ctx
        findings.extend(analyze_context(ctx, file_rules))
    findings.extend(run_project_rules(contexts, project_rules))
    for rule in instances:
        if isinstance(rule, RepoRule):
            findings.extend(rule.check(repo_root))
    if baseline is not None:
        findings, stale = baseline.filter(
            findings, codes={r.code for r in instances})
    else:
        stale = []
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings, stale, n
