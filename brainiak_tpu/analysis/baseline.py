"""jaxlint baseline: grandfathered findings with justifications.

A baseline entry suppresses exactly one finding identity
``(rule, path, snippet)`` -- the snippet is the stripped source line,
so entries survive unrelated line-number drift but die as soon as the
flagged code changes.  Every entry MUST carry a non-empty ``reason``;
a baseline without written justifications fails to load, so the file
cannot silently become a blanket suppression list.

Entries may live in the top-level ``entries`` list or grouped under
named ``sections`` (``{"sections": {"tools-and-bench": [...]}}``) --
sections are purely organizational (the tools/bench walk keeps its
intentional bench-harness syncs in its own section) and are
flattened into one suppression set at load time.
"""

import json
import os

__all__ = ["Baseline", "BaselineError"]


class BaselineError(ValueError):
    """Malformed baseline file (missing reason, bad schema...)."""


class Baseline:
    """Repo-level suppression list loaded from JSON."""

    REQUIRED = ("rule", "path", "snippet", "reason")

    def __init__(self, entries=(), path=None):
        self.path = path
        self.entries = list(entries)
        self._index = {}
        for i, entry in enumerate(self.entries):
            for field in self.REQUIRED:
                if not str(entry.get(field, "")).strip():
                    raise BaselineError(
                        f"baseline entry #{i} missing non-empty "
                        f"'{field}' (every grandfathered finding "
                        "needs a written justification): "
                        f"{json.dumps(entry)}")
            key = (entry["rule"], entry["path"],
                   entry["snippet"].strip())
            self._index[key] = entry

    @classmethod
    def load(cls, path):
        if not os.path.exists(path):
            return cls([], path=path)
        with open(path, encoding="utf-8") as fh:
            try:
                data = json.load(fh)
            except json.JSONDecodeError as exc:
                raise BaselineError(
                    f"{path}: not valid JSON ({exc})") from exc
        if isinstance(data, dict):
            entries = list(data.get("entries", []))
            sections = data.get("sections", {})
            if not isinstance(sections, dict):
                raise BaselineError(
                    f"{path}: 'sections' must map section names "
                    "to entry lists")
            for name in sorted(sections):
                block = sections[name]
                if not isinstance(block, list):
                    raise BaselineError(
                        f"{path}: section {name!r} must be a "
                        "list of entries")
                entries.extend(block)
        else:
            raise BaselineError(
                f"{path}: expected object with 'entries' list")
        return cls(entries, path=path)

    def filter(self, findings, codes=None):
        """Split findings into (kept, stale-baseline-entries).

        ``codes`` names the rule codes the caller actually ran: only
        entries for those rules can be judged stale (an entry for a
        rule family this run never executed always looks unmatched —
        e.g. the JP3xx traced-IR entries during an AST-only walk)."""
        used = set()
        kept = []
        for finding in findings:
            key = (finding.code, finding.path,
                   finding.snippet.strip())
            if key in self._index:
                used.add(key)
            else:
                kept.append(finding)
        stale = [entry for key, entry in self._index.items()
                 if key not in used
                 and (codes is None or key[0] in codes)]
        return kept, stale

    @staticmethod
    def render(findings, reason="TODO: justify or fix"):
        """Baseline JSON for ``findings`` (``--write-baseline``)."""
        entries = [{"rule": f.code, "path": f.path,
                    "snippet": f.snippet, "reason": reason}
                   for f in findings]
        return json.dumps({"version": 1, "entries": entries},
                          indent=2) + "\n"
