"""Mesh / collective axis rules JX101-JX103.

A wrong axis name in a ``psum``/``ppermute`` is a silent-wrong-answer
bug (the collective reduces over the wrong device group — or raises
only at run time on a pod you do not have in CI).  These rules build
one project-wide **axis model**:

- **declared mesh axes** — string literals (and constants resolving
  to strings, e.g. ``DEFAULT_VOXEL_AXIS``) in ``make_mesh(...)`` /
  ``jax.make_mesh(...)`` / ``jax.sharding.Mesh(...)`` axis-name
  arguments;
- **spec axes** — axis names appearing in ``shard_map`` /
  ``shard_vmap`` ``in_specs``/``out_specs``/``axis_names`` (and the
  ``axis_name=`` kwarg of sharded helpers);
- **shard-map scope** — the functions passed as ``shard_map`` bodies
  plus everything they (transitively) call or reference, and inline
  lambda bodies.

Checks (all skip when the needed fact is statically unresolvable —
they flag only provable mismatches):

- **JX101** — a collective whose resolved axis name is not a
  declared mesh/spec axis anywhere in the project;
- **JX102** — a collective issued outside any shard-map scope (it
  would raise ``NameError: unbound axis`` at trace time, or worse,
  silently run unpartitioned under eager evaluation);
- **JX103** — a ``PartitionSpec`` axis literal no mesh declares.
"""

import ast

from .core import ProjectRule, register
from .summaries import project_summaries

__all__ = ["UndeclaredCollectiveAxis", "CollectiveOutsideShardMap",
           "UndeclaredPartitionAxis", "MESH_RULES"]

_MESH_CALLS = {"make_mesh", "subject_voxel_mesh"}
_SHARD_CALLS = {"shard_map", "shard_vmap"}


class AxisModel:
    """Project-wide mesh/axis facts shared by JX101-JX103."""

    def __init__(self):
        self.mesh_axes = set()
        self.spec_axes = set()
        self.spec_sites = []     # (ctx, node, axis string)
        self.scope = set()       # qualnames inside shard-map scope
        self.inline_bodies = set()   # id() of lambda body nodes


def _collect_axis_strings(project, ctx, node, enclosing):
    """Every axis-name string statically visible in an expression:
    plain literals, resolvable constants, and the arguments of
    ``PartitionSpec(...)`` calls.  Partial results are fine here —
    this feeds the DECLARED set, where missing an unresolvable name
    only makes the checks more conservative."""
    out = set()
    if node is None:
        return out
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) \
                and isinstance(sub.value, str):
            out.add(sub.value)
        elif isinstance(sub, (ast.Name, ast.Attribute)):
            vals = project.literal_strings(ctx, sub, enclosing)
            if vals:
                out |= vals
    return out


def build_axis_model(project):
    summaries = project_summaries(project)
    model = AxisModel()
    seeds = set()
    for ctx in project.contexts.values():
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            target = ctx.resolve(node.func) or ""
            short = target.rsplit(".", 1)[-1]
            enclosing = project.enclosing_function(ctx, node)
            if short in _MESH_CALLS or target == "jax.make_mesh":
                arg = None
                for kw in node.keywords:
                    if kw.arg == "axis_names":
                        arg = kw.value
                if arg is None and node.args:
                    arg = (node.args[1]
                           if target == "jax.make_mesh"
                           and len(node.args) > 1
                           else node.args[0])
                vals = project.literal_strings(ctx, arg, enclosing)
                if vals is None:
                    vals = _collect_axis_strings(project, ctx, arg,
                                                 enclosing)
                model.mesh_axes |= vals
            elif short == "Mesh" and (
                    target in ("Mesh", "jax.sharding.Mesh")
                    or target.endswith(".sharding.Mesh")):
                arg = None
                for kw in node.keywords:
                    if kw.arg == "axis_names":
                        arg = kw.value
                if arg is None and len(node.args) > 1:
                    arg = node.args[1]
                model.mesh_axes |= _collect_axis_strings(
                    project, ctx, arg, enclosing)
            elif short in _SHARD_CALLS:
                self_args = list(node.args)
                for kw in node.keywords:
                    if kw.arg in ("in_specs", "out_specs",
                                  "axis_names", "axis_name"):
                        model.spec_axes |= _collect_axis_strings(
                            project, ctx, kw.value, enclosing)
                # positional layout: shard_map(f, mesh, in_specs,
                # out_specs) / shard_vmap(f, mesh, axis_name, n)
                for arg in self_args[2:4]:
                    model.spec_axes |= _collect_axis_strings(
                        project, ctx, arg, enclosing)
                if self_args:
                    body = self_args[0]
                    if isinstance(body, ast.Lambda):
                        for sub in ast.walk(body):
                            model.inline_bodies.add(id(sub))
                    else:
                        for info in project.resolve_callable(
                                ctx, body, enclosing):
                            seeds.add(info.qualname)
            elif short == "PartitionSpec":
                for arg in list(node.args) + [
                        kw.value for kw in node.keywords]:
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Constant) \
                                and isinstance(sub.value, str):
                            model.spec_sites.append(
                                (ctx, sub, sub.value))
                            model.spec_axes.add(sub.value)
    # transitive shard-map scope over calls AND bare references
    # (bodies are often handed to lax.scan / partial, not called)
    work = list(seeds)
    model.scope = set(seeds)
    while work:
        qual = work.pop()
        summary = summaries.get(qual)
        if summary is None:
            continue
        nexts = {t.qualname for _, targets, _ in summary.calls
                 for t in targets}
        nexts |= summary.refs
        for item in nexts:
            if item not in model.scope:
                model.scope.add(item)
                work.append(item)
    return model


def axis_model(project):
    return project.cache("axis_model", build_axis_model)


def _in_shard_scope(project, model, ctx, node):
    info = project.enclosing_function(ctx, node)
    while info is not None:
        if info.qualname in model.scope:
            return True
        info = info.parent
    cur = node
    while cur is not None:
        if id(cur) in model.inline_bodies:
            return True
        cur = ctx.parent(cur)
    return False


@register
class UndeclaredCollectiveAxis(ProjectRule):
    """JX101: collective over an axis no mesh/spec declares."""

    code = "JX101"
    name = "undeclared-collective-axis"

    def check(self, project):
        model = axis_model(project)
        declared = model.mesh_axes | model.spec_axes
        if not declared:
            return  # nothing declared anywhere: cannot verify
        summaries = project_summaries(project)
        for summary in summaries.values():
            ctx = summary.info.ctx
            for node, op, axis_node in summary.collectives:
                vals = project.literal_strings(
                    ctx, axis_node, summary.info)
                if not vals:
                    continue  # statically unresolvable: skip
                missing = sorted(v for v in vals
                                 if v not in declared)
                if missing:
                    yield ctx.finding(
                        self, node,
                        f"jax.lax.{op} over axis "
                        f"{', '.join(repr(m) for m in missing)}: "
                        "no mesh or shard_map spec in the project "
                        "declares that axis (declared: "
                        f"{', '.join(sorted(declared))}) — a wrong "
                        "axis name reduces over the wrong device "
                        "group")


@register
class CollectiveOutsideShardMap(ProjectRule):
    """JX102: collective outside any shard_map/shard_vmap scope."""

    code = "JX102"
    name = "collective-outside-shard-map"

    def check(self, project):
        model = axis_model(project)
        summaries = project_summaries(project)
        for summary in summaries.values():
            ctx = summary.info.ctx
            for node, op, _axis in summary.collectives:
                if _in_shard_scope(project, model, ctx, node):
                    continue
                yield ctx.finding(
                    self, node,
                    f"jax.lax.{op} outside any shard_map/"
                    "shard_vmap scope: named-axis collectives "
                    "need an enclosing manual-sharding region or "
                    "they fail at trace time (unbound axis); "
                    "wrap the computation in shard_map or route "
                    "it through ops.distla")


@register
class UndeclaredPartitionAxis(ProjectRule):
    """JX103: PartitionSpec axis literal no mesh declares."""

    code = "JX103"
    name = "undeclared-partition-axis"

    def check(self, project):
        model = axis_model(project)
        if not model.mesh_axes:
            return  # no statically-visible mesh: cannot verify
        for ctx, node, value in model.spec_sites:
            if value in model.mesh_axes:
                continue
            yield ctx.finding(
                self, node,
                f"PartitionSpec axis {value!r}: no mesh in the "
                "project declares that axis (meshes declare: "
                f"{', '.join(sorted(model.mesh_axes))}) — "
                "placement over an undeclared axis raises at "
                "device_put time on the pod, not in CPU tests")


MESH_RULES = [UndeclaredCollectiveAxis, CollectiveOutsideShardMap,
              UndeclaredPartitionAxis]
