"""Hierarchical trace spans with async-dispatch-aware stop.

A span times a named region of work.  Spans nest: each thread keeps a
stack of active spans, and a completed span's record carries its full
``path`` (``"fit/fit_chunk"``), so the report CLI can reconstruct the
per-process span tree.  Because jax dispatch is asynchronous, a wall
clock alone measures enqueue time, not compute — :func:`span` accepts
a ``sync`` pytree (or one set on the yielded frame) that is
``block_until_ready``-ed before the clock stops.

Obs-disabled (the default) every ``with span(...)`` is a no-op that
skips the sync entirely, so instrumented hot loops keep their
async-dispatch pipelining (the device queue is never drained for
telemetry nobody is collecting).

The legacy :func:`stage_timer`/:func:`stage_times` API from
``brainiak_tpu.utils.profiling`` lives here now (that module is a
shim); unlike :func:`span` it ALWAYS records into the in-process
stage registry (and always honors ``sync``), because existing callers
rely on reading :func:`stage_times` without configuring a sink.
"""

import contextlib
import functools
import logging
import threading
import time
from collections import defaultdict

from . import sink

logger = logging.getLogger(__name__)

__all__ = [
    "current_span",
    "reset_stage_times",
    "span",
    "stage_timer",
    "stage_times",
    "traced",
]

_registry_lock = threading.RLock()
_stage_times = defaultdict(list)
_local = threading.local()


def _stack():
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    return stack


class _Frame:
    """Mutable handle for an active span: set attributes or a late
    sync target from inside the ``with`` block."""

    __slots__ = ("name", "attrs", "sync")

    def __init__(self, name, attrs):
        self.name = name
        self.attrs = dict(attrs) if attrs else {}
        self.sync = None

    def set(self, key, value):
        self.attrs[key] = value
        return self


class _NullFrame:
    """Inert frame yielded when obs is disabled: attribute writes
    (``frame.sync = y``, per the documented late-sync pattern) are
    silently discarded — storing them would pin the last sync pytree
    in memory for nothing."""

    __slots__ = ()
    name = None
    attrs = None
    sync = None

    def set(self, key, value):
        return self

    def __setattr__(self, key, value):
        pass


_NULL = _NullFrame()


def current_span():
    """Name path of the innermost active span, or '' (this thread)."""
    return "/".join(f.name for f in _stack())


@contextlib.contextmanager
def span(name, sync=None, attrs=None):
    """Trace a named region; yields a frame for attrs / late sync.

    Parameters
    ----------
    name : str
        Span name; the emitted record's ``path`` prefixes it with the
        names of the enclosing active spans.
    sync : pytree of jax arrays, optional
        Blocked on (``jax.block_until_ready``) before the clock stops,
        so asynchronously dispatched device work is charged to this
        span instead of whichever later operation first touches the
        result.  Only honored while obs is enabled — a disabled span
        introduces no host sync.
    attrs : dict, optional
        Attributes stamped into the span record; the yielded frame's
        ``set(key, value)`` adds more from inside the block, and
        assigning ``frame.sync`` supplies a sync target computed
        inside the block.
    """
    if not sink.enabled():
        yield _NULL
        return
    frame = _Frame(name, attrs)
    stack = _stack()
    stack.append(frame)
    t0 = time.perf_counter()
    try:
        yield frame
    finally:
        # pop BEFORE syncing: a sync target whose computation failed
        # re-raises out of this block (recording a bogus unsynced
        # time would be worse), and a caller that catches and
        # continues (run_resilient_loop's rollback) must not inherit
        # a corrupted span stack / wrong paths
        path = "/".join(f.name for f in stack)
        if stack and stack[-1] is frame:
            stack.pop()
        else:  # pragma: no cover - unbalanced exit (generator abuse)
            try:
                stack.remove(frame)
            except ValueError:
                pass
        target = frame.sync if frame.sync is not None else sync
        if target is not None:
            _block_until_ready(target)
        dt = time.perf_counter() - t0
        # a fit_id attr is promoted to the top-level schema-v4 field
        # (same contract as sink.event) so fit_chunk spans join their
        # fit's progress stream
        fit_id = frame.attrs.pop("fit_id", None)
        sink.emit(sink.make_record(
            "span", name, path=path, dur_s=dt,
            attrs=frame.attrs or None, fit_id=fit_id))


def _block_until_ready(target):
    """Best-effort device sync: computation errors must propagate (a
    swallowed failure would record a bogus, unsynced time), but a
    missing jax never should."""
    import sys
    jax = sys.modules.get("jax")
    if jax is None:
        return
    jax.block_until_ready(target)


def traced(name=None, sync_result=False):
    """Decorator form of :func:`span`.

    ``@traced`` / ``@traced("label")`` wraps the function in a span
    (default label: the qualified name); ``sync_result=True``
    additionally blocks on the return value before the span closes,
    for functions returning asynchronously dispatched device arrays.
    """
    if callable(name):  # bare @traced
        fn, name = name, None
        return traced()(fn)

    def decorate(fn):
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not sink.enabled():
                return fn(*args, **kwargs)
            with span(label) as frame:
                out = fn(*args, **kwargs)
                if sync_result:
                    frame.sync = out
                return out

        return wrapper

    return decorate


# -- legacy stage-timer API (brainiak_tpu.utils.profiling shim) -------

@contextlib.contextmanager
def stage_timer(name, sync=None):
    """Time a pipeline stage; ``sync`` may be an array (or pytree) to
    block on before stopping the clock (remember: dispatch is async).

    Results accumulate in a process-wide registry readable with
    :func:`stage_times` (thread-safe).  Deprecated in favor of
    :func:`span` — kept as a working alias because it always records
    locally (no sink required) and always syncs, which :func:`span`
    deliberately does not do while obs is disabled.

    Non-nesting: the emitted span record is prefixed with the path of
    the enclosing :func:`span`\\ s, but a stage does NOT become a
    parent for spans opened inside its block (they attach to the
    nearest real span).  Code that needs hierarchy should use
    :func:`span`.
    """
    t0 = time.perf_counter()
    holder = {}
    try:
        yield holder
    finally:
        target = holder.get("sync", sync)
        if target is not None:
            _block_until_ready(target)
        dt = time.perf_counter() - t0
        with _registry_lock:
            _stage_times[name].append(dt)
        logger.debug("stage %s took %.3fs", name, dt)
        if sink.enabled():
            sink.emit(sink.make_record(
                "span", name, path=_span_path(name), dur_s=dt))


def _span_path(name):
    prefix = current_span()
    return f"{prefix}/{name}" if prefix else name


def stage_times():
    """Mapping of stage name -> list of durations (seconds)."""
    with _registry_lock:
        return {k: list(v) for k, v in _stage_times.items()}


def reset_stage_times():
    with _registry_lock:
        _stage_times.clear()
