"""OBS003 selfcheck: the fit-progress telemetry plane, end to end.

The ``obs-fit`` gate of ``tools/run_checks.py`` runs
:func:`selfcheck` in a CPU-pinned child process, driving a toy
chunked fit through :func:`~brainiak_tpu.resilience.guards.
run_resilient_loop` twice:

**Phase 1 — preemption parity.**  A checkpointed fit is preempted
mid-flight (:func:`brainiak_tpu.resilience.faults.inject`) and
rerun to completion.  The progress stream must show ONE ``fit_id``
across both processes' worth of records, strictly monotone chunk
indices spanning the resume point, and a cumulative
``fit_wall_s`` that keeps growing across the resume (the
wall-accounting carried in the checkpoint).

**Phase 2 — divergence incident.**  A NaN fault poisons the
objective leaf until the rollback budget exhausts.  The
``divergence_precursor`` event must be timestamped no later than
the first ``rollback`` (the tracker observes the chunk before the
guard trips), the abort must auto-dump exactly one flight-recorder
snapshot under ``$BRAINIAK_TPU_OBS_DIR/incidents`` whose manifest
names the aborting fit, and ``python -m brainiak_tpu.obs
postmortem`` must render that snapshot cleanly (exit 0, estimator
named).

Every record emitted along the way must validate against the
current sink schema (v4).  Prints one JSON verdict line; exit 0 on
success, 1 with the verdict naming what failed — the gate
classifies from the verdict, not from a traceback.
"""

import contextlib
import io
import json
import os
import tempfile

import numpy as np

__all__ = ["selfcheck"]


def _toy_chunk(state, step, n_steps):
    """Deterministic toy fit: the objective leaf decays toward 0."""
    # pure-numpy toy state; nothing here lives on a device
    new = {k: np.array(v, copy=True)  # jaxlint: disable=JX002
           for k, v in state.items()}
    new["obj"] = (100.0  # jaxlint: disable=JX002
                  / (1.0 + float(step + n_steps)) + 0.0 * new["obj"])
    return new, False


def _progress_records(mem, fit_id=None):
    return [r for r in mem.records if r["kind"] == "progress"
            and (fit_id is None or r["fit_id"] == fit_id)]


def _event_ts(mem, name):
    return [r["ts"] for r in mem.records
            if r["kind"] == "event" and r["name"] == name]


def selfcheck(n_iter=10, checkpoint_every=2):
    """Run the fit-progress check (see module docstring); returns
    the process exit code."""
    from ..resilience import faults
    from ..resilience.guards import DivergenceError, \
        run_resilient_loop
    from . import flight, postmortem, progress as obs_progress
    from . import sink as obs_sink

    verdict = {"ok": False, "n_iter": n_iter}
    tmp = tempfile.mkdtemp(prefix="obs-fitcheck-")
    # the incident auto-dump lands under $BRAINIAK_TPU_OBS_DIR; set
    # it before any record is emitted so the env-driven JSONL sink
    # and the flight recorder agree on the directory
    os.environ[obs_sink.OBS_DIR_ENV] = tmp
    os.environ["BRAINIAK_TPU_CHECKPOINT_NPZ"] = "1"
    mem = obs_sink.add_sink(obs_sink.MemorySink())
    try:
        init = {"obj": np.full(4, 100.0)}
        ckpt = os.path.join(tmp, "ckpt")

        # -- phase 1: preempt mid-fit, resume, check id parity ----
        try:
            with faults.inject("preempt", at_step=4):
                run_resilient_loop(
                    _toy_chunk, init, n_iter,
                    checkpoint_dir=ckpt,
                    checkpoint_every=checkpoint_every,
                    name="fitcheck", progress_objective="obj")
            verdict["error"] = "preemption fault never fired"
            raise SystemExit
        except faults.PreemptionError:
            pass
        run_resilient_loop(
            _toy_chunk, init, n_iter, checkpoint_dir=ckpt,
            checkpoint_every=checkpoint_every, name="fitcheck",
            progress_objective="obj")
        recs = _progress_records(mem)
        verdict["n_progress"] = len(recs)
        fit_ids = {r["fit_id"] for r in recs}
        verdict["fit_id_stable"] = len(fit_ids) == 1
        chunks = [r["chunk"] for r in recs]
        verdict["chunks"] = chunks
        verdict["chunks_monotone"] = (
            chunks == sorted(chunks) and len(set(chunks)) ==
            len(chunks) and
            len(chunks) == -(-n_iter // checkpoint_every))
        walls = [r["fit_wall_s"] for r in recs]
        verdict["wall_cumulative"] = all(
            b > a for a, b in zip(walls, walls[1:]))

        # -- phase 2: NaN divergence -> precursor, dump, postmortem
        mem.records.clear()
        obs_progress.clear_registry()
        flight.clear()
        aborted = False
        try:
            with faults.inject("nan", at_step=6, times=10,
                               leaf="obj"):
                run_resilient_loop(
                    _toy_chunk, init, n_iter,
                    checkpoint_every=checkpoint_every,
                    max_rollbacks=1, name="fitcheck",
                    progress_objective="obj")
        except DivergenceError:
            aborted = True
        verdict["aborted"] = aborted
        precursors = _event_ts(mem, "divergence_precursor")
        rollbacks = _event_ts(mem, "rollback")
        verdict["precursor_fired"] = bool(precursors)
        verdict["precursor_before_guard"] = bool(
            precursors and rollbacks
            and precursors[0] <= rollbacks[0])
        abort_fit = [r for r in mem.records
                     if r["kind"] == "event"
                     and r["name"] == "divergence_abort"]
        fit_id = abort_fit[0].get("fit_id") if abort_fit else None
        snapdir = os.path.join(tmp, "incidents")
        snaps = sorted(os.listdir(snapdir)) \
            if os.path.isdir(snapdir) else []
        verdict["n_snapshots"] = len(snaps)
        snapshot_ok = False
        postmortem_ok = False
        if len(snaps) == 1:
            path = os.path.join(snapdir, snaps[0])
            with open(os.path.join(path, "manifest.json"),
                      encoding="utf-8") as fh:
                manifest = json.load(fh)
            snapshot_ok = (
                manifest.get("trigger") == "divergence_abort"
                and fit_id is not None
                and manifest.get("fit_id") == fit_id)
            out = io.StringIO()
            with contextlib.redirect_stdout(out):
                rc = postmortem.main([path])
            postmortem_ok = rc == 0 and "fitcheck" in out.getvalue()
            verdict["postmortem_rc"] = rc
        verdict["snapshot_ok"] = snapshot_ok
        verdict["postmortem_ok"] = postmortem_ok

        # -- every record must be schema-clean --------------------
        schema_errors = []
        for rec in mem.records:
            schema_errors.extend(obs_sink.validate_record(rec))
        verdict["schema_errors"] = schema_errors[:5]

        verdict["ok"] = bool(
            verdict["fit_id_stable"] and verdict["chunks_monotone"]
            and verdict["wall_cumulative"] and aborted
            and verdict["precursor_before_guard"] and snapshot_ok
            and postmortem_ok and not schema_errors)
    except SystemExit:
        pass
    except Exception as exc:  # the gate wants a verdict, not a trace
        verdict["error"] = f"{type(exc).__name__}: {exc}"
    finally:
        obs_sink.remove_sink(mem)
        obs_sink.close_all()
    print(json.dumps(verdict))
    return 0 if verdict["ok"] else 1
