"""XLA cost profiling: FLOPs/bytes attribution for jitted programs.

PR 3 recorded *when* programs run (spans) and *how often* they
recompile (``retrace_total``); this module records *what they cost*.
:func:`profile_program` wraps a jitted program (or the program a
counted builder returns) so that — while profiling is active — the
first call per abstract input signature captures a schema-v2 ``cost``
record: XLA cost-analysis FLOPs / bytes-accessed / transcendentals,
the HLO module size, and (at the ``compiled`` level) the measured
compile wall time plus the executable's memory analysis.

Two levels, because the honest compile wall time is not free:

- ``lowered`` (default when profiling is on) — ``fn.lower(*args)``
  only: one extra trace, **no** extra XLA compile.  Cost analysis
  comes from the lowered (pre-optimization) HLO, which is exact for
  FLOPs of the written program.
- ``compiled`` — additionally ``lowered.compile()`` under a timer:
  post-optimization cost analysis, ``compile_s``, and memory
  analysis.  JAX's ahead-of-time compile does NOT warm the jit
  dispatch cache, so this level pays one extra compile per program
  signature; use it for dedicated profiling runs, not steady-state
  telemetry.

Activation: the ``BRAINIAK_TPU_OBS_PROFILE`` env var (``1``/
``lowered`` or ``compiled``) or the :func:`profiling` context
manager; records are only emitted while an obs sink is active.  Off
(the default), every wrapped program adds one attribute check per
call and nothing else.  Under an ambient trace (a wrapped program
called from inside another jitted function) the wrapper always
bypasses straight to the wrapped callable — tracers never reach
``lower``.

The graceful-degradation contract: a backend without
``cost_analysis()`` (or a program whose lowering fails) still yields
a ``cost`` record, with an ``unavailable`` marker naming what was
missing — downstream tooling sees the site exists rather than
silently losing it.

:func:`memory_watermark` is the companion runtime snapshot:
HBM high-water marks (``device.memory_stats``) and host peak RSS,
emitted as delta gauges around each ``fit_chunk`` in
:func:`brainiak_tpu.resilience.guards.run_resilient_loop`.
"""

import contextlib
import logging
import os
import sys
import threading
import time

from . import metrics, sink

logger = logging.getLogger(__name__)

__all__ = [
    "PEAK_FLOPS_ENV",
    "PROFILE_ENV",
    "ProfiledProgram",
    "memory_watermark",
    "profile_level",
    "profile_program",
    "profiling",
]

PROFILE_ENV = "BRAINIAK_TPU_OBS_PROFILE"
PEAK_FLOPS_ENV = "BRAINIAK_TPU_PEAK_FLOPS"

#: Nominal peak FLOP/s per platform for roofline ratios, matching the
#: ceilings ``benchmarks/tpu_mfu.py`` reports against (fp32 HIGHEST
#: dots run ~6 passes of the bf16 MXU).  Override with
#: ``BRAINIAK_TPU_PEAK_FLOPS`` (a float); unknown platforms get no
#: peak and the report simply omits the ratio.
PLATFORM_PEAK_FLOPS = {
    "tpu": 197e12 / 6.0,
}

_LEVELS = ("lowered", "compiled")

# module-level override (profiling() context / tests); None defers to
# the environment variable
_level_override = None


def profile_level():
    """Active profiling level: ``None`` (off), ``"lowered"``, or
    ``"compiled"``.  The :func:`profiling` override wins over the
    ``BRAINIAK_TPU_OBS_PROFILE`` environment variable (``0``/empty
    off, ``1`` = lowered)."""
    if _level_override is not None:
        return _level_override if _level_override in _LEVELS else None
    raw = os.environ.get(PROFILE_ENV, "").strip().lower()
    if raw in ("", "0", "off", "false"):
        return None
    if raw in ("compiled", "2"):
        return "compiled"
    return "lowered"


@contextlib.contextmanager
def profiling(level="lowered"):
    """Force cost profiling on (``"lowered"``/``"compiled"``) or off
    (``None``) for a block, regardless of the environment."""
    global _level_override
    if level is not None and level not in _LEVELS:
        raise ValueError(
            f"profiling level must be one of {_LEVELS} or None, "
            f"got {level!r}")
    prev = _level_override
    _level_override = level if level is not None else "off"
    try:
        yield
    finally:
        _level_override = prev


def _jax():
    """The already-imported jax module, or None — never import it
    (telemetry must not be the first thing to touch a wedged
    backend)."""
    return sys.modules.get("jax")


def _abstract_key(args, kwargs):
    """Hashable (treedef, leaf signatures) key for a call, or None
    when the call must not be profiled (tracer leaves — we are under
    an ambient trace — or unhashable static leaves).

    Scalar leaves: Python floats are keyed by TYPE, matching jit's
    weak-type cache (floats here are dynamic hyperparameters — RSRM's
    ``gamma`` — and keying them by value would pay one extra
    ``lower()`` trace plus a duplicate cost record per sweep point).
    Ints / bools / strings are keyed by VALUE: in this codebase they
    are static arguments (``n_steps``, ``features``, ``K``,
    ``weight_method``) that select a different program with different
    FLOPs; dynamic scalar ints (the ISC slab start index) arrive as
    jax arrays and take the shape/dtype path.
    """
    jax = _jax()
    if jax is None:
        return None
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    parts = []
    for leaf in leaves:
        if isinstance(leaf, jax.core.Tracer):
            return None
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            parts.append(("a", tuple(shape), str(dtype)))
        elif isinstance(leaf, float):
            parts.append(("f", type(leaf).__name__))
        else:
            try:
                hash(leaf)
            except TypeError:
                return None
            parts.append(("s", leaf))
    return (str(treedef), tuple(parts))


def _cost_analysis_dict(stage):
    """The cost-analysis mapping of a Lowered/Compiled stage, or None.
    Handles both API generations (dict vs. one-element list)."""
    try:
        ca = stage.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    return ca if isinstance(ca, dict) else None


def _peak_flops(backend):
    env = os.environ.get(PEAK_FLOPS_ENV)
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    return PLATFORM_PEAK_FLOPS.get(backend)


def _nonneg(value):
    """Cost-analysis value as a float field, or None (XLA reports -1
    for quantities it cannot attribute)."""
    try:
        value = float(value)
    except (TypeError, ValueError):
        return None
    return value if value >= 0.0 else None


class ProfiledProgram:
    """Callable proxy adding one-shot cost capture to a jitted program.

    Transparent by construction: every call is forwarded to the
    wrapped program unchanged (the ahead-of-time stages are used only
    for *analysis*, never for execution), so wrapping cannot alter
    numerics, sharding, or dispatch behavior.  Profiling state is
    per-proxy; builders cached with
    :func:`~brainiak_tpu.obs.runtime.counted_cache` therefore profile
    once per (mesh/config key, input signature).
    """

    def __init__(self, fn, site, span=None, estimator=None):
        self._fn = fn
        self.site = site
        self.span_hint = span
        self.estimator_hint = estimator
        self._seen = set()
        self._lock = threading.Lock()
        self.__wrapped__ = fn
        self.__name__ = getattr(fn, "__name__", site)

    def __repr__(self):
        return f"ProfiledProgram({self.site!r}, {self._fn!r})"

    def __call__(self, *args, **kwargs):
        level = profile_level()
        if level is not None and sink.enabled():
            try:
                self._maybe_profile(level, args, kwargs)
            except Exception:  # pragma: no cover - belt and braces
                logger.exception(
                    "cost profile of %s failed; continuing unprofiled",
                    self.site)
        return self._fn(*args, **kwargs)

    # expose the lru_cache surface when wrapping a cached builder's
    # program is composed the other way around (builder-level wrap)
    def __getattr__(self, name):
        return getattr(self.__dict__["_fn"], name)

    #: Distinct signatures profiled per program before capture stops
    #: — a bound on ``_seen`` growth (and on extra lowers) in
    #: long-lived sweep processes; real programs see a handful.
    MAX_SIGNATURES = 512

    def _maybe_profile(self, level, args, kwargs):
        key = _abstract_key(args, kwargs)
        if key is None:
            return
        with self._lock:
            if (level, key) in self._seen:
                return
            if len(self._seen) >= self.MAX_SIGNATURES:
                return
            # mark before the (slow) capture: a concurrent caller
            # must not profile the same signature twice
            self._seen.add((level, key))
        self._capture(level, args, kwargs)

    def _capture(self, level, args, kwargs):
        jax = _jax()
        backend = None
        if jax is not None:
            try:
                backend = jax.default_backend()
            except Exception:
                backend = None
        fields = {"site": self.site, "level": level,
                  "backend": backend, "span": self.span_hint,
                  "estimator": self.estimator_hint}
        try:
            self._analyze(level, args, kwargs, fields)
        except Exception as exc:
            # a lowering stage that raises outside the per-step
            # guards (Pallas/Mosaic backends have done this) must
            # degrade to a marked record, not lose the site
            logger.debug("cost analysis of %s failed: %s",
                         self.site, exc)
            fields.setdefault(
                "unavailable",
                f"profile-failed:{type(exc).__name__}")
        peak = _peak_flops(backend)
        if peak:
            fields["peak_flops"] = peak
        sink.emit(sink.make_record("cost", self.site, **{
            k: v for k, v in fields.items() if v is not None}))
        metrics.counter(
            "cost_profile_total",
            help="cost records captured per site").inc(site=self.site)

    def _analyze(self, level, args, kwargs, fields):
        lower = getattr(self._fn, "lower", None)
        lowered = None
        if lower is None:
            fields["unavailable"] = "not-lowerable"
        else:
            try:
                lowered = lower(*args, **kwargs)
            except Exception as exc:
                logger.debug("lowering %s for cost profile failed: %s",
                             self.site, exc)
                fields["unavailable"] = (
                    f"lower-failed:{type(exc).__name__}")
        compiled = None
        if lowered is not None:
            try:
                text = lowered.as_text()
                fields["hlo_bytes"] = len(text)
                fields["hlo_lines"] = text.count("\n") + 1
            except Exception:
                pass
            if level == "compiled":
                t0 = time.perf_counter()
                try:
                    compiled = lowered.compile()
                    fields["compile_s"] = time.perf_counter() - t0
                except Exception as exc:
                    logger.debug(
                        "AOT compile of %s for cost profile "
                        "failed: %s", self.site, exc)
                    fields["unavailable"] = (
                        f"compile-failed:{type(exc).__name__}")
            # post-optimization numbers when available, else the
            # lowered estimate — marked, so a record that SAYS
            # compiled never silently carries pre-optimization FLOPs
            ca = _cost_analysis_dict(compiled) if compiled is not None \
                else None
            if ca is None:
                if level == "compiled":
                    fields.setdefault("unavailable",
                                      "compiled-cost-analysis")
                ca = _cost_analysis_dict(lowered)
            if ca is None:
                fields.setdefault("unavailable", "cost_analysis")
            else:
                fields["flops"] = _nonneg(ca.get("flops"))
                fields["bytes_accessed"] = _nonneg(
                    ca.get("bytes accessed"))
                fields["transcendentals"] = _nonneg(
                    ca.get("transcendentals"))
                if fields["flops"] is None \
                        and fields["bytes_accessed"] is None:
                    # Pallas/Mosaic-lowered programs surface a cost
                    # dict with nothing attributable in it; mark the
                    # record so the report renders the site with
                    # span-only timing instead of dropping it
                    fields.setdefault("unavailable",
                                      "cost-analysis-empty")
            if compiled is not None:
                mem = self._memory_fields(compiled)
                if mem:
                    fields["attrs"] = mem

    @staticmethod
    def _memory_fields(compiled):
        try:
            mem = compiled.memory_analysis()
        except Exception:
            return None
        out = {}
        for attr in ("argument_size_in_bytes", "output_size_in_bytes",
                     "temp_size_in_bytes",
                     "generated_code_size_in_bytes"):
            val = getattr(mem, attr, None)
            if isinstance(val, int):
                out[attr.replace("_size_in_bytes", "_bytes")] = val
        return out or None


def profile_program(fn, site, span=None, estimator=None):
    """Wrap a jitted program in a :class:`ProfiledProgram`.

    Parameters
    ----------
    fn : callable
        A ``jax.jit``-ed callable (anything with ``.lower``); plain
        callables are tolerated and record ``unavailable``.
    site : str
        Attribution label, conventionally matching the builder's
        ``counted_cache`` site (``"fcma.sharded_gram"``) so retrace
        counts and cost records join on one key.
    span : str, optional
        Name of the span whose durations measure this program's
        execution (``"fcma.block"``); the report CLI joins cost and
        span records through it to compute achieved throughput.
    estimator : str, optional
        ``estimator`` span attribute to additionally require in that
        join, for programs that run under the shared ``fit_chunk``
        span (``"SRM.fit"``).
    """
    return ProfiledProgram(fn, site, span=span, estimator=estimator)


# -- memory watermarks ------------------------------------------------

def _device_peaks():
    """Max over local devices of (peak_bytes_in_use, bytes_in_use), or
    (None, None) when the backend exposes no memory stats (CPU) or is
    not yet initialized (``sink.backend_initialized``):
    ``jax.local_devices()`` would INITIALIZE the backend — a blocking
    first device touch on a wedged TPU tunnel — and a watermark read
    must never be the thing that first touches the device (a
    checkpointed fit can resume to completion without any device
    call)."""
    if not sink.backend_initialized():
        return None, None
    jax = _jax()
    peak = in_use = None
    try:
        devices = jax.local_devices()
    except Exception:
        return None, None
    for dev in devices:
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        if "peak_bytes_in_use" in stats:
            val = int(stats["peak_bytes_in_use"])
            peak = val if peak is None else max(peak, val)
        if "bytes_in_use" in stats:
            val = int(stats["bytes_in_use"])
            in_use = val if in_use is None else max(in_use, val)
    return peak, in_use


def _host_rss_bytes():
    try:
        import resource
        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    except Exception:  # pragma: no cover - non-unix
        return None
    # linux reports kilobytes (macOS bytes; both monotonic peaks)
    return int(rss) * (1 if sys.platform == "darwin" else 1024)


def memory_watermark(estimator=None, before=None):
    """Snapshot HBM / host-memory high-water marks.

    With no arguments, returns ``{"hbm_peak", "hbm_in_use",
    "host_rss"}`` (entries None where the backend has no stats) —
    cheap enough to take before every fit chunk.  With ``estimator``
    and a ``before`` snapshot, additionally sets the delta gauges:

    - ``hbm_peak_bytes{estimator=}`` — growth of the device
      high-water mark across the chunk (the first chunk of a fit is
      where the working set peaks; later chunks read ~0);
    - ``hbm_bytes_in_use{estimator=}`` — absolute live bytes after
      the chunk;
    - ``host_peak_rss_bytes{estimator=}`` — absolute host peak RSS.

    Never initializes a backend and never raises: on CPU (no
    ``memory_stats``) only the host gauge is set.
    """
    peak, in_use = _device_peaks()
    snap = {"hbm_peak": peak, "hbm_in_use": in_use,
            "host_rss": _host_rss_bytes()}
    if estimator is None:
        return snap
    if peak is not None:
        prev = (before or {}).get("hbm_peak") or 0
        metrics.gauge(
            "hbm_peak_bytes", unit="bytes",
            help="device high-water-mark growth per fit chunk").set(
                max(peak - prev, 0), estimator=estimator)
    if in_use is not None:
        metrics.gauge(
            "hbm_bytes_in_use", unit="bytes",
            help="live device bytes after a fit chunk").set(
                in_use, estimator=estimator)
    if snap["host_rss"] is not None:
        metrics.gauge(
            "host_peak_rss_bytes", unit="bytes",
            help="host peak RSS").set(
                snap["host_rss"], estimator=estimator)
    return snap
