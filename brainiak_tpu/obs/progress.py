"""Fit-progress and convergence telemetry for resilient fit loops.

Every chunked fit driven by
:func:`brainiak_tpu.resilience.guards.run_resilient_loop` owns a
:class:`FitProgress` tracker.  The tracker mints a stable ``fit_id``
(same idiom as trace ids; the loop persists it in the checkpoint so a
resumed fit continues the same id), and on every chunk:

- emits one schema-v4 ``progress`` record (fit_id, estimator, chunk
  i-of-N, step/epoch, objective value and delta, cumulative rollback
  count, chunk wall, EWMA iteration rate, ETA) to the sinks while obs
  is enabled — and ALWAYS into the flight-recorder ring
  (:mod:`brainiak_tpu.obs.flight`) and the in-process registry that
  feeds the ``/jobs`` endpoint;
- maintains convergence telemetry: a bounded objective-trace ring
  (the postmortem tail), plateau detection
  (:data:`PLATEAU_CHUNKS` consecutive chunks moving less than
  :data:`PLATEAU_RTOL` relative), and a divergence-precursor signal —
  a non-finite objective, or the EWMA of *worsening* objective deltas
  turning positive — that fires one typed ``divergence_precursor``
  event strictly BEFORE the loop's non-finite guard can trip (the
  loop observes the new state first, then guards it);
- keeps the ``fit_progress_ratio{fit_id,estimator}`` and
  ``fit_eta_seconds{fit_id,estimator}`` gauges current on
  ``/metrics``.

The zero-overhead contract matches spans: obs-disabled adds **zero
records and zero host syncs**.  The tracker's own work is plain host
arithmetic on state leaves that are host-checkpointable by the
resilient-loop contract (the guard ``np.asarray``'s the same leaves
right after), so no ``block_until_ready`` is ever introduced.

Objective extraction (``objective=`` hint): None (no objective
telemetry — cadence/ETA only), a state-leaf name (reduced with
``np.mean``, so one poisoned element makes the extracted value
non-finite and trips the precursor), or a callable
``state -> float``.  Extraction errors are swallowed — telemetry
must never break the fit.
"""

import contextlib
import math
import os
import threading
import time

import numpy as np

from . import flight, metrics, sink

__all__ = [
    "EWMA_ALPHA",
    "OBJECTIVE_RING",
    "PLATEAU_CHUNKS",
    "PLATEAU_RTOL",
    "FitProgress",
    "active_fits",
    "add_finish_listener",
    "clear_registry",
    "current_context",
    "fit_context",
    "new_fit_id",
    "remove_finish_listener",
]

#: Smoothing for the chunk-rate and objective-delta EWMAs.
EWMA_ALPHA = 0.3

#: Objective-trace ring length carried per fit (the postmortem tail).
OBJECTIVE_RING = 32

#: Consecutive chunks with relative objective movement below
#: :data:`PLATEAU_RTOL` before the ``plateau`` event fires.
PLATEAU_CHUNKS = 5
PLATEAU_RTOL = 1e-4

#: Deltas observed before the EWMA trend may fire the precursor (a
#: single noisy first step must not cry divergence).
_TREND_WARMUP = 3

#: Finished fits retained in the registry for ``/jobs`` history.
_MAX_FINISHED = 32


def new_fit_id():
    """Mint a fit id: 16 hex chars, the trace-id idiom."""
    return os.urandom(8).hex()


def _finite_or_none(value):
    """Non-finite telemetry values are OMITTED from records, not
    serialized: ``json.dumps`` would write a bare ``NaN`` token,
    breaking every strict-JSON consumer of the sink files and the
    chrome-trace export (the precursor ``reason`` already names the
    non-finite objective)."""
    if value is None or not math.isfinite(value):
        return None
    return value


# -- ambient fit context (job/tenant attribution) ---------------------

_context_local = threading.local()


def current_context():
    """The ambient fit-context attrs for this thread (``{}`` outside
    any :func:`fit_context`)."""
    return dict(getattr(_context_local, "attrs", None) or {})


@contextlib.contextmanager
def fit_context(**attrs):
    """Attribute every fit started on this thread to ``attrs``.

    The jobs scheduler wraps each fit invocation in
    ``fit_context(job_id=..., tenant=...)``; :class:`FitProgress`
    captures the ambient attrs at construction and carries them on
    every progress record (``attrs``), every fit event, and every
    registry snapshot — so ``/jobs``, ``obs watch`` and ``obs
    postmortem`` can join a fit back to the job that scheduled it.
    ``None`` values are dropped; scopes nest (inner keys shadow
    outer ones) and restore on exit.
    """
    prev = getattr(_context_local, "attrs", None)
    merged = dict(prev or {})
    merged.update({k: v for k, v in attrs.items() if v is not None})
    _context_local.attrs = merged
    try:
        yield
    finally:
        _context_local.attrs = prev


# -- finish listeners (job-record feedback) ---------------------------

_listeners_lock = threading.Lock()
_finish_listeners = []  # guarded-by: _listeners_lock


def add_finish_listener(fn):
    """Register ``fn(snapshot)`` to run whenever any fit finishes.

    The snapshot is the final registry dict (fit_id, estimator,
    terminal ``status`` — ``converged``/``completed``/``diverged``/
    ``parked`` — plus any :func:`fit_context` attrs such as
    ``job_id``/``tenant``).  Listener exceptions are swallowed:
    telemetry must never break the fit.  Listeners run on the fit
    thread.
    """
    with _listeners_lock:
        if fn not in _finish_listeners:
            _finish_listeners.append(fn)


def remove_finish_listener(fn):
    """Unregister a :func:`add_finish_listener` callback (no-op if
    absent)."""
    with _listeners_lock:
        if fn in _finish_listeners:
            _finish_listeners.remove(fn)


def _notify_finish(snapshot):
    with _listeners_lock:
        listeners = list(_finish_listeners)
    for fn in listeners:
        try:
            fn(dict(snapshot))
        except Exception:
            pass


# -- in-process registry (feeds /jobs and the watch CLI) --------------

_registry_lock = threading.Lock()
_registry = {}   # guarded-by: _registry_lock (fit_id -> snapshot)
_order = []      # guarded-by: _registry_lock (fit_id insertion order)


def _publish(snapshot):
    fit_id = snapshot["fit_id"]
    with _registry_lock:
        if fit_id not in _registry:
            _order.append(fit_id)
        _registry[fit_id] = snapshot
        finished = [f for f in _order
                    if _registry[f]["status"] != "running"]
        for stale in finished[:-_MAX_FINISHED]:
            _order.remove(stale)
            del _registry[stale]


def active_fits():
    """Snapshots of every registered fit, oldest first — running
    fits plus the :data:`_MAX_FINISHED` most recent finished ones
    (each a plain JSON-serializable dict; the ``/jobs`` payload)."""
    with _registry_lock:
        return [dict(_registry[f]) for f in _order]


def clear_registry():
    """Drop every registered fit (test isolation)."""
    with _registry_lock:
        _registry.clear()
        del _order[:]


class FitProgress:
    """Per-fit progress/convergence tracker (one fit thread writes;
    readers see snapshots through :func:`active_fits`).

    Parameters
    ----------
    estimator : str
        The loop label (``SRM.fit``, ``stats``, ...).
    n_iter : int
        Total iteration budget of the fit.
    fit_id : str, optional
        Resume an existing id (from a checkpoint); default mints one.
    objective, direction
        Objective hint (see module docstring) and whether it should
        ``"min"``imize or ``"max"``imize.
    n_chunks : int, optional
        Planned chunk count (ceil(n_iter / checkpoint_every)).
    wall0, chunks0 : float, int
        Cumulative fit wall seconds / chunk count carried over from a
        resumed checkpoint, so post-resume rate and ETA estimates
        account for the work the previous process already did.
    """

    def __init__(self, estimator, n_iter, *, fit_id=None,
                 objective=None, direction="min", n_chunks=None,
                 wall0=0.0, chunks0=0):
        if direction not in ("min", "max"):
            raise ValueError(
                f"direction must be 'min' or 'max', got {direction!r}")
        self.estimator = estimator
        self.n_iter = max(int(n_iter), 0)
        self.fit_id = fit_id or new_fit_id()
        self.objective_spec = objective
        self.direction = direction
        self.n_chunks = int(n_chunks) if n_chunks else None
        self.context = current_context()
        self.chunk = int(chunks0)       # monotone observation count
        self.fit_wall_s = float(wall0)
        self.rollbacks = 0
        self.status = "running"
        self.objectives = []            # (step, value) tail, bounded
        self.last_objective = None
        self.rate = None                # EWMA iterations / second
        self.eta_s = None
        self.ratio = 0.0
        self.plateaued = False
        self.precursor_fired = False
        self._worsen_ewma = None
        self._n_deltas = 0
        self._plateau_run = 0

    # -- telemetry fan-out (sink when enabled; flight/registry always)

    def _emit_record(self, rec):
        # sink.emit already mirrors into the flight ring; tap it
        # directly ONLY when sinks are off, or every record would
        # land in incident snapshots twice
        if sink.enabled():
            sink.emit(rec)
        else:
            flight.record(rec)

    def _event(self, name, **attrs):
        merged = dict(self.context, **attrs)
        rec = sink.make_record("event", name, attrs=merged or None,
                               fit_id=self.fit_id)
        self._emit_record(rec)
        return rec

    # -- objective extraction -----------------------------------------

    def _extract(self, state):
        spec = self.objective_spec
        if spec is None:
            return None
        try:
            if callable(spec):
                value = spec(state)
            else:
                leaf = state[spec]
                arr = np.asarray(leaf, dtype=float)
                if arr.size == 0:
                    return None
                # mean: one poisoned element -> non-finite extract
                value = np.mean(arr)
            return None if value is None else float(value)
        except Exception:
            return None

    # -- the per-chunk observation ------------------------------------

    def note_rollback(self):
        """Count one guard-triggered rollback against this fit."""
        self.rollbacks += 1

    def observe(self, state, step, n_steps, chunk_s):
        """Record one completed chunk: ``state`` is the chunk's output
        (pre-guard), ``step`` the iteration it reached, ``n_steps``
        the iterations it advanced, ``chunk_s`` its wall seconds.
        Returns the progress record dict.

        Called by the loop BEFORE the non-finite guard, so the
        divergence precursor (non-finite or trend-worsening
        objective) is timestamped before any rollback/abort event.
        """
        self.chunk += 1
        self.fit_wall_s += float(chunk_s)
        denom = max(float(chunk_s), 1e-9)
        sample_rate = n_steps / denom
        self.rate = sample_rate if self.rate is None else \
            EWMA_ALPHA * sample_rate + (1 - EWMA_ALPHA) * self.rate
        self.ratio = min(step / self.n_iter, 1.0) \
            if self.n_iter else 1.0
        remaining = max(self.n_iter - step, 0)
        self.eta_s = remaining / self.rate if self.rate and \
            self.rate > 0 else None

        value = self._extract(state)
        delta = None
        precursor = None
        if value is not None:
            if not math.isfinite(value):
                precursor = "non_finite_objective"
            elif self.last_objective is not None:
                delta = value - self.last_objective
                worsening = delta if self.direction == "min" \
                    else -delta
                self._worsen_ewma = worsening \
                    if self._worsen_ewma is None else \
                    EWMA_ALPHA * worsening \
                    + (1 - EWMA_ALPHA) * self._worsen_ewma
                self._n_deltas += 1
                if self._n_deltas >= _TREND_WARMUP \
                        and self._worsen_ewma > 0:
                    precursor = "worsening_trend"
                scale = max(abs(value), abs(self.last_objective), 1.0)
                if abs(delta) <= PLATEAU_RTOL * scale:
                    self._plateau_run += 1
                else:
                    self._plateau_run = 0
            if math.isfinite(value):
                self.last_objective = value
                self.objectives.append((int(step), value))
                del self.objectives[:-OBJECTIVE_RING]

        if precursor and not self.precursor_fired:
            self.precursor_fired = True
            self._event(
                "divergence_precursor", estimator=self.estimator,
                chunk=self.chunk, step=int(step), reason=precursor,
                objective=_finite_or_none(value),
                ewma_worsening=_finite_or_none(self._worsen_ewma))
        if not self.plateaued and self._plateau_run >= PLATEAU_CHUNKS:
            self.plateaued = True
            self._event("plateau", estimator=self.estimator,
                        chunk=self.chunk, step=int(step),
                        objective=value, window=PLATEAU_CHUNKS,
                        rtol=PLATEAU_RTOL)

        rec = sink.make_record(
            "progress", "fit_progress", fit_id=self.fit_id,
            estimator=self.estimator, chunk=self.chunk,
            n_chunks=self.n_chunks, step=int(step),
            n_iter=self.n_iter, ratio=float(self.ratio),
            objective=_finite_or_none(value),
            delta=_finite_or_none(delta), rollbacks=self.rollbacks,
            chunk_s=float(chunk_s), fit_wall_s=self.fit_wall_s,
            rate=self.rate, eta_s=self.eta_s,
            plateaued=self.plateaued or None,
            attrs=self.context or None)
        self._emit_record(rec)
        # gauges update the in-process registry regardless (host-only
        # work); they emit metric records only while obs is enabled
        metrics.gauge(
            "fit_progress_ratio",
            help="fraction of the iteration budget a resilient fit "
                 "has completed").set(
                self.ratio, fit_id=self.fit_id,
                estimator=self.estimator)
        if self.eta_s is not None:
            metrics.gauge(
                "fit_eta_seconds", unit="s",
                help="EWMA-rate estimate of seconds until a "
                     "resilient fit completes").set(
                    self.eta_s, fit_id=self.fit_id,
                    estimator=self.estimator)
        self._publish_snapshot(rec["ts"], int(step))
        return rec

    def finish(self, status):
        """Mark the fit finished (``converged`` / ``completed`` /
        ``diverged`` / ``parked``), emit the ``fit_finished`` event,
        publish the final registry snapshot, and notify any
        :func:`add_finish_listener` callbacks with it — the hook the
        jobs scheduler uses to fold the fit outcome back into the
        owning job record (never a zombie "running" entry)."""
        self.status = status
        self._event("fit_finished", estimator=self.estimator,
                    status=status, chunk=self.chunk,
                    rollbacks=self.rollbacks,
                    fit_wall_s=self.fit_wall_s)
        snap = self._publish_snapshot(time.time(),
                                      self.objectives[-1][0]
                                      if self.objectives else None)
        _notify_finish(snap)

    def _publish_snapshot(self, ts, step):
        snap = dict(self.context)
        snap.update({
            "fit_id": self.fit_id,
            "estimator": self.estimator,
            "status": self.status,
            "chunk": self.chunk,
            "n_chunks": self.n_chunks,
            "step": step,
            "n_iter": self.n_iter,
            "ratio": self.ratio,
            "objective": self.last_objective,
            "rollbacks": self.rollbacks,
            "rate": self.rate,
            "eta_s": self.eta_s,
            "fit_wall_s": self.fit_wall_s,
            "plateaued": self.plateaued,
            "precursor": self.precursor_fired,
            "objective_tail": [v for _, v in self.objectives[-5:]],
            "ts": ts,
        })
        _publish(snap)
        return snap
