"""JAX-level collectors: retrace counting, device memory, topology.

Nothing here imports jax at module scope, and nothing initializes a
backend that the calling code has not already initialized — on a
wedged TPU tunnel even backend init hangs, and telemetry must never
be the first thing to touch the device (docs/performance.md
operational rules).

- :func:`counted_cache` — ``functools.lru_cache`` with a cache-miss
  hook: wraps the repo's jitted-program *builders* (the lru-cached
  functions that construct jit/shard_map programs per mesh/shape key)
  so every cache miss — i.e. every fresh trace+compile of that
  program — increments ``retrace_total{site=...}``.  jaxlint's JX001
  recognizes it as a caching decorator.  Every decorated builder
  additionally registers itself in the process-global
  :func:`builder_registry` — the enumeration surface the jaxlint-IR
  auditor (:mod:`brainiak_tpu.analysis.ir`) traces at canonical
  abstract signatures; :func:`trace_signature` attaches a site's
  canonical-signature factory after the fact.
- :func:`device_memory_snapshot` — per-device ``memory_stats()``
  gauges plus one ``device_memory`` event.
- :func:`topology_event` — backend/process/device (and optionally
  mesh axes) capture, emitted by ``parallel.mesh.make_mesh`` for
  every mesh a run builds.
- :func:`install_compile_listener` — best-effort ``jax.monitoring``
  hook recording XLA compile durations into ``jax_compile_seconds``.
- :func:`device_trace` — ``jax.profiler`` wrapper (TensorBoard
  traces), moved here from ``utils.profiling``.
"""

import contextlib
import functools
import sys

from . import metrics, sink

__all__ = [
    "builder_registry",
    "counted_cache",
    "device_memory_snapshot",
    "device_trace",
    "install_compile_listener",
    "topology_event",
    "trace_signature",
]

#: Process-global registry of every ``counted_cache``-decorated
#: program builder: ``site -> record``.  Each record holds the
#: wrapper, the raw builder, its module/qualname, the lru bound, and
#: (when the site attached one) the canonical-signature factory the
#: IR auditor traces it with.  Plain dicts, no jax — registration
#: must stay importable on a host that never touches a device.
_BUILDER_REGISTRY = {}


def builder_registry():
    """Snapshot of the registered program-builder sites
    (``{site: record}``); records are shared, the mapping is a
    copy."""
    return dict(_BUILDER_REGISTRY)


def trace_signature(site, float_keys_ok=()):
    """Attach a canonical-signature factory to a registered builder.

    ``factory`` is a zero-argument callable returning a list of
    trace specs — plain dicts with keys ``key`` (the positional
    builder arguments), ``args`` (abstract arrays for calling the
    built program), and optionally ``kwargs`` (static call kwargs),
    ``mesh`` (the trace mesh, for collective-axis validation),
    ``donate`` (argnums the family expects the executable to alias),
    and ``label``.  The factory runs only inside the IR auditor's
    trace child, so it may import jax and build meshes freely; the
    decorated module stays jax-import-free at registration time.

    ``float_keys_ok`` names builder parameters that legitimately
    carry float values in the cache key (a per-model constant, not a
    per-request value) — JP305 skips them.
    """

    def attach(factory):
        record = _BUILDER_REGISTRY.get(site)
        if record is None:  # decoration order bug: fail loudly
            raise KeyError(f"trace_signature({site!r}): no "
                           "counted_cache builder registered under "
                           "that site")
        record["signature"] = factory
        record["float_keys_ok"] = tuple(float_keys_ok)
        return factory

    return attach


def counted_cache(site, maxsize=None, signature=None,
                  float_keys_ok=()):
    """An ``lru_cache`` whose misses count as retraces.

    Use on jitted-program builders: a miss means the builder ran,
    which means a fresh trace + XLA compile for a new (mesh, shape,
    config) key.  The count surfaces as ``retrace_total{site=...}``;
    an unexpectedly growing site is the runtime confirmation of the
    static retrace hazards jaxlint JX001 hunts for.

    The wrapper keeps ``cache_info``/``cache_clear`` so call sites
    and tests can inspect and reset it like a plain ``lru_cache``,
    and registers the builder in :func:`builder_registry` so the
    jaxlint-IR auditor can enumerate every program family
    mechanically.  ``signature`` (or a later
    :func:`trace_signature`) attaches the canonical-signature
    factory the auditor traces the site with; a site without one
    shows up in the auditor's coverage report as skipped.
    """

    def decorate(fn):
        cached = functools.lru_cache(maxsize=maxsize)(fn)

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            # telemetry only: a racing concurrent miss may be
            # attributed once; the lru_cache itself stays exact
            misses = cached.cache_info().misses
            out = cached(*args, **kwargs)
            if cached.cache_info().misses > misses:
                metrics.counter(
                    "retrace_total",
                    help="program-builder cache misses "
                         "(fresh trace+compile)").inc(site=site)
            return out

        wrapper.cache_info = cached.cache_info
        wrapper.cache_clear = cached.cache_clear
        wrapper.__wrapped__ = fn
        # re-registration (module reload, test fixtures) replaces
        # the record: latest decoration wins, matching lru behavior
        _BUILDER_REGISTRY[site] = {
            "site": site,
            "wrapper": wrapper,
            "fn": fn,
            "module": getattr(fn, "__module__", None),
            "qualname": getattr(fn, "__qualname__",
                                getattr(fn, "__name__", site)),
            "maxsize": maxsize,
            "signature": signature,
            "float_keys_ok": tuple(float_keys_ok),
        }
        return wrapper

    return decorate


def _jax():
    """The already-imported jax module, or None — never import it."""
    return sys.modules.get("jax")


def device_memory_snapshot(emit=True):
    """Per-device memory stats as a list of dicts.

    Sets ``device_bytes_in_use{device=...}`` gauges and (when ``emit``)
    an aggregate ``device_memory`` event.  Returns ``[]`` when jax is
    not imported or the backend exposes no ``memory_stats`` (CPU).
    """
    jax = _jax()
    if jax is None:
        return []
    out = []
    for dev in jax.devices():
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        entry = {"device": dev.id, "platform": dev.platform}
        for key in ("bytes_in_use", "peak_bytes_in_use",
                    "bytes_limit"):
            if key in stats:
                entry[key] = int(stats[key])
        out.append(entry)
        if "bytes_in_use" in entry:
            metrics.gauge(
                "device_bytes_in_use", unit="bytes").set(
                    entry["bytes_in_use"], device=str(dev.id))
    if emit and out and sink.enabled():
        sink.emit(sink.make_record(
            "event", "device_memory", attrs={"devices": out}))
    return out


def topology_event(mesh=None):
    """Emit a ``topology`` event (backend, processes, devices, mesh
    axes) and return its attrs; None when obs is disabled or jax is
    not imported."""
    if not sink.enabled():
        return None
    jax = _jax()
    if jax is None:
        return None
    try:
        attrs = {"backend": jax.default_backend(),
                 "process_index": int(jax.process_index()),
                 "process_count": int(jax.process_count()),
                 "device_count": int(jax.device_count()),
                 "local_device_count":
                     int(jax.local_device_count())}
    except Exception:  # backend init failed mid-flight
        return None
    if mesh is not None:
        attrs["mesh_axes"] = {str(name): int(size) for name, size
                              in zip(mesh.axis_names,
                                     mesh.devices.shape)}
    sink.emit(sink.make_record("event", "topology", attrs=attrs))
    return attrs


_compile_listener_installed = False


def install_compile_listener():
    """Record XLA compile durations via ``jax.monitoring`` (if this
    jax version exposes duration listeners).  Observations land in the
    ``jax_compile_seconds`` histogram labeled by the monitoring event
    name.  Returns True when installed (idempotent)."""
    global _compile_listener_installed
    if _compile_listener_installed:
        return True
    jax = _jax()
    if jax is None:
        return False
    try:
        from jax import monitoring
        register = monitoring.register_event_duration_secs_listener
    except (ImportError, AttributeError):
        return False

    def _listen(event, duration, **kwargs):
        if "compil" not in event:
            return
        try:
            metrics.histogram(
                "jax_compile_seconds", unit="s").observe(
                    float(duration), event=event)
        except Exception:  # telemetry must never break compilation
            pass

    try:
        register(_listen)
    except Exception:
        return False
    _compile_listener_installed = True
    return True


@contextlib.contextmanager
def device_trace(log_dir):
    """Capture a jax.profiler trace (TensorBoard-viewable) around a
    block of device work."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
