"""Noise-aware bench regression gating over the BENCH_* trajectory.

``python -m brainiak_tpu.obs regress --history PATH [...]`` reads the
repo's accumulated bench records (``BENCH_r*.json`` round files, the
committed ``tools/bench_fixture/`` history, directories, JSONL — any
mix), separates them into **tiers**, and decides whether the newest
record of each tier is a regression against that tier's own history:

- **tier separation** — a ``cpu_fallback`` record is never compared
  against an on-chip baseline (the r05 record is ~10x below the last
  on-chip rate for reasons that have nothing to do with the code);
  the tier comes from the record's ``tier`` field, falling back to
  the legacy metric-name marker (``_CPU_FALLBACK_``) for pre-tier
  history;
- **noise awareness** — the baseline is the *median* of the tier's
  history, so one outlier round cannot poison the verdict, and the
  pass bar is a *relative* threshold (default: the fresh value must
  reach ``0.7 x`` median for higher-is-better metrics);
- **min history** — with fewer than ``--min-history`` (default 2)
  prior records a tier is reported ``insufficient_history`` and does
  not gate; a brand-new tier must not fail CI on its first record;
- **per-metric direction** — throughput metrics are
  higher-is-better (the default), but the service tier also gates
  p99 latency and padding waste, where HIGHER is the regression: a
  record carrying ``"direction": "lower_is_better"`` flips the
  comparison (the fresh value must stay under ``median /
  threshold``), so a doubled p99 fails CI the right way round
  instead of reading as a 2x "improvement".

The fresh sample is ``--fresh FILE`` (or ``-`` for stdin, i.e. piped
straight from ``python bench.py``); without it, the newest history
record of each tier gates against the records before it — the mode
the ``regress`` gate of ``tools/run_checks.py`` runs on the committed
fixture.  ``--only TIER[,TIER]`` restricts gating to the named tier
families (``--only distla`` covers ``distla`` and
``distla_cpu_fallback``).  The verdict is machine-readable
(``--format=json``) and the exit status is the gate: 0 pass,
1 regression (the offending metric is named in the message), 2 no
usable records (or nothing in the ``--only`` selection).

Record trust: every candidate must pass
:func:`brainiak_tpu.obs.report.validate_bench_record` (which checks
the v2 ``schema_version``/``git_commit`` provenance stamps when
present); invalid records are skipped and reported, never compared.
"""

import argparse
import glob
import json
import os
import sys

from .report import validate_bench_record

__all__ = ["DEFAULT_MIN_HISTORY", "DEFAULT_THRESHOLD", "evaluate",
           "load_bench_records", "main", "tier_of", "tier_selected"]

DEFAULT_THRESHOLD = 0.7
DEFAULT_MIN_HISTORY = 2

#: Legacy marker bench.py appended to the metric name before the
#: ``tier`` field existed (rounds r01-r04).
_LEGACY_CPU_MARKER = "_CPU_FALLBACK_tpu_unresponsive"


def tier_of(rec):
    """The comparison tier of a bench record (``tier`` field, legacy
    metric-name marker, else ``"unknown"``)."""
    tier = rec.get("tier")
    if isinstance(tier, str) and tier:
        return tier
    if _LEGACY_CPU_MARKER.strip("_") in str(rec.get("metric", "")):
        return "cpu_fallback"
    return "unknown"


def _base_metric(rec):
    """Metric family with the legacy tier marker stripped, so one
    tier's records group together across the schema generations."""
    return str(rec.get("metric", "")).replace(_LEGACY_CPU_MARKER, "")


def _normalize_legacy(rec):
    """Backfill the ``tier`` field on pre-tier rounds (r01-r04 carry
    the tier only as a metric-name marker) so the validator accepts
    the repo's real history; records with neither stay invalid."""
    if "tier" not in rec and \
            _LEGACY_CPU_MARKER in str(rec.get("metric", "")):
        rec = dict(rec, tier="cpu_fallback")
    return rec


def _candidate_docs(doc):
    """Bench-record candidates inside one parsed JSON document: the
    document itself, a round-file wrapper's ``parsed`` payload, or a
    list of either."""
    if isinstance(doc, list):
        for item in doc:
            yield from _candidate_docs(item)
    elif isinstance(doc, dict):
        if "parsed" in doc and isinstance(doc["parsed"], dict):
            yield doc["parsed"]
        else:
            yield doc


def _expand(paths):
    out = []
    for path in paths:
        if os.path.isdir(path):
            out.extend(sorted(
                p for p in glob.glob(os.path.join(path, "*"))
                if p.endswith((".json", ".jsonl"))))
        else:
            out.append(path)
    return out


def _parse_text(text, label, order_start=0):
    """Validated bench records out of one blob of JSON text (single
    document, JSONL, or concatenated lines) — the one code path both
    files and ``--fresh -`` stdin go through, legacy-tier backfill
    included.  Returns ``(records, skipped)``."""
    records = []
    skipped = []
    order = order_start
    docs = []
    try:
        docs.append(json.loads(text))
    except ValueError:
        # JSONL / concatenated documents: one per non-empty line
        for lineno, line in enumerate(text.splitlines(), 1):
            line = line.strip()
            if not line:
                continue
            try:
                docs.append(json.loads(line))
            except ValueError:
                skipped.append(f"{label}:{lineno}: bad JSON")
    for doc in docs:
        for cand in _candidate_docs(doc):
            cand = _normalize_legacy(cand)
            bad = validate_bench_record(cand)
            if bad:
                skipped.append(f"{label}: {'; '.join(bad)}")
                continue
            rec = dict(cand)
            rec["source"] = label
            rec["order"] = order
            order += 1
            records.append(rec)
    return records, skipped


def load_bench_records(paths):
    """Parse + validate bench records from files/directories.

    Returns ``(records, skipped)``: records are
    ``{"source", "order", **bench record}`` dicts in chronological
    order (file name order, then line order — round files sort by
    name), skipped are ``"source: reason"`` strings for anything that
    failed :func:`validate_bench_record`.
    """
    records = []
    skipped = []
    for path in _expand(paths):
        try:
            with open(path, encoding="utf-8") as fh:
                text = fh.read()
        except OSError as exc:
            skipped.append(f"{path}: unreadable ({exc})")
            continue
        recs, skips = _parse_text(text, os.path.basename(path),
                                  order_start=len(records))
        records.extend(recs)
        skipped.extend(skips)
    return records, skipped


def tier_selected(tier, only):
    """Whether ``tier`` is covered by an ``--only`` family selector:
    exact match or a ``_``-separated extension, so ``distla`` selects
    both ``distla`` and ``distla_cpu_fallback`` — one family, two
    backends — without ever conflating unrelated tiers."""
    if only is None:
        return True
    return any(tier == fam or tier.startswith(fam + "_")
               for fam in only)


def evaluate(history, fresh=None, threshold=DEFAULT_THRESHOLD,
             min_history=DEFAULT_MIN_HISTORY, only=None):
    """Regression checks per (metric family, tier) group.

    ``history``/``fresh`` are record lists from
    :func:`load_bench_records`; with ``fresh=None`` each group's
    chronologically newest history record is the sample under test.
    ``only`` restricts gating to the named tier families
    (:func:`tier_selected`).  Returns ``{"verdict": "pass"|"fail"|
    "skip", "checks": [...]}`` where each check carries the group's
    key, values, ratio, direction, and a ``status`` of ``ok`` /
    ``regression`` / ``insufficient_history``.  Higher values are
    better unless the sample record is stamped ``"direction":
    "lower_is_better"`` (latency/padding metrics), in which case the
    value must stay below ``baseline / threshold``.
    """
    groups = {}
    for rec in history:
        if not tier_selected(tier_of(rec), only):
            continue
        groups.setdefault((_base_metric(rec), tier_of(rec)),
                          []).append(rec)
    fresh_by_group = {}
    if fresh:
        for rec in fresh:
            if not tier_selected(tier_of(rec), only):
                continue
            fresh_by_group.setdefault(
                (_base_metric(rec), tier_of(rec)), []).append(rec)
    # an explicit fresh run gates ONLY the tiers it produced (a
    # cpu_fallback round must not re-litigate the whole_brain
    # history); self-gating mode covers every tier in the history
    keys = sorted(fresh_by_group) if fresh \
        else sorted(groups)
    checks = []
    for key in keys:
        metric, tier = key
        past = sorted(groups.get(key, []),
                      key=lambda r: r["order"])
        if key in fresh_by_group:
            sample = fresh_by_group[key][-1]
        elif past:
            sample = past.pop()  # newest history record gates
        else:
            continue
        direction = str(sample.get("direction")
                        or "higher_is_better")
        check = {"metric": metric, "tier": tier,
                 "value": float(sample["value"]),
                 "unit": sample.get("unit"),
                 "source": sample.get("source"),
                 "n_history": len(past),
                 "direction": direction,
                 "threshold": threshold}
        if len(past) < min_history:
            check["status"] = "insufficient_history"
        else:
            values = sorted(float(r["value"]) for r in past)
            mid = len(values) // 2
            baseline = values[mid] if len(values) % 2 \
                else 0.5 * (values[mid - 1] + values[mid])
            # zero baseline: a zero fresh value matches it (ratio
            # 1.0 passes either direction); any positive value is
            # infinitely above — an improvement for higher-is-
            # better, a regression for lower-is-better (a tier
            # whose p99/padding history is legitimately 0.0 must
            # not fail forever on staying at 0.0)
            if baseline:
                ratio = float(sample["value"]) / baseline
            else:
                ratio = float("inf") if float(sample["value"]) > 0 \
                    else 1.0
            check["baseline_median"] = baseline
            check["ratio"] = ratio
            if direction == "lower_is_better":
                # the mirrored bar: a latency/padding value may
                # grow to baseline/threshold before it regresses
                bad = ratio > 1.0 / threshold
            else:
                bad = ratio < threshold
            check["status"] = "regression" if bad else "ok"
        checks.append(check)
    if not checks:
        verdict = "skip"
    elif any(c["status"] == "regression" for c in checks):
        verdict = "fail"
    else:
        verdict = "pass"
    return {"verdict": verdict, "checks": checks}


def _render_text(result, skipped):
    lines = []
    for check in result["checks"]:
        status = check["status"]
        head = (f"{check['metric']} [tier {check['tier']}] "
                f"value={check['value']:.6g}")
        if status == "insufficient_history":
            lines.append(
                f"SKIP {head} ({check['n_history']} prior record(s); "
                "not enough history to gate)")
            continue
        detail = (f"{check['ratio']:.2f}x of median baseline "
                  f"{check['baseline_median']:.6g} over "
                  f"{check['n_history']} record(s), threshold "
                  f"{check['threshold']:.2f}")
        if check.get("direction") == "lower_is_better":
            detail += " (lower is better)"
        if status == "regression":
            lines.append(f"FAIL {head}: regression — {detail}")
        else:
            lines.append(f"OK   {head}: {detail}")
    for note in skipped:
        lines.append(f"note: skipped {note}")
    lines.append(f"verdict: {result['verdict']}")
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m brainiak_tpu.obs regress",
        description="bench regression gate over BENCH_* history "
                    "(docs/observability.md)")
    parser.add_argument(
        "--history", nargs="+", required=True, metavar="PATH",
        help="bench history: files, directories, round wrappers, "
             "JSONL")
    parser.add_argument(
        "--fresh", metavar="FILE",
        help="record under test (a bench.py JSON line; '-' = stdin); "
             "default: the newest history record per tier")
    parser.add_argument("--threshold", type=float,
                        default=DEFAULT_THRESHOLD,
                        help="min fresh/baseline ratio "
                             "(default %(default)s)")
    parser.add_argument("--min-history", type=int,
                        default=DEFAULT_MIN_HISTORY,
                        help="prior records required before gating "
                             "(default %(default)s)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument(
        "--only", metavar="TIER[,TIER...]",
        help="gate only these tier families (a family selects its "
             "backend variants too: 'distla' covers distla and "
             "distla_cpu_fallback)")
    args = parser.parse_args(argv)
    if not 0.0 < args.threshold <= 1.0:
        parser.error("--threshold must be in (0, 1]")
    only = ([t.strip() for t in args.only.split(",") if t.strip()]
            if args.only else None)

    history, skipped = load_bench_records(args.history)
    fresh = None
    if args.fresh:
        if args.fresh == "-":
            fresh, extra = _parse_text(sys.stdin.read(), "stdin",
                                       order_start=10 ** 9)
        else:
            fresh, extra = load_bench_records([args.fresh])
        skipped.extend(extra)
        if not fresh:
            print("obs regress: no valid fresh record",
                  file=sys.stderr)
            return 2
    if not history and not fresh:
        print("obs regress: no usable bench records",
              file=sys.stderr)
        return 2

    result = evaluate(history, fresh, threshold=args.threshold,
                      min_history=args.min_history, only=only)
    if only and not result["checks"]:
        print("obs regress: no records in tier(s) "
              + ", ".join(only), file=sys.stderr)
        return 2
    if args.format == "json":
        result["skipped"] = skipped
        print(json.dumps(result, indent=2))
    else:
        print(_render_text(result, skipped))
    if result["verdict"] == "fail":
        bad = [c for c in result["checks"]
               if c["status"] == "regression"]
        print("obs regress: regression in "
              + ", ".join(f"{c['metric']} [tier {c['tier']}]"
                          for c in bad),
              file=sys.stderr)
        return 1
    return 0 if result["verdict"] in ("pass", "skip") else 1


if __name__ == "__main__":  # pragma: no cover - module smoke entry
    sys.exit(main())
