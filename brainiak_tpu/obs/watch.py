"""Live terminal view of active fits and recent incidents.

``python -m brainiak_tpu.obs watch [--url URL | --dir DIR]`` polls a
fit-progress source and renders a table per refresh:

- ``--url`` scrapes a :class:`~brainiak_tpu.obs.http.TelemetryServer`
  ``/jobs`` endpoint (a live process's in-memory registry);
- ``--dir`` tails the ``progress`` records of an obs JSONL directory
  (default: ``$BRAINIAK_TPU_OBS_DIR``) — the cross-process view, and
  the only one that works after the fit process exited;

plus the ``incidents/`` snapshots under the watched directory (or
``$BRAINIAK_TPU_OBS_DIR``), newest first.  ``--once`` renders a
single frame and exits (tests and scripting); otherwise the view
refreshes every ``--interval`` seconds until interrupted.

This module imports neither jax nor numpy — a watch terminal must
never be the process that first touches a wedged device.
"""

import argparse
import glob
import json
import os
import sys
import time
import urllib.request

from .sink import OBS_DIR_ENV

__all__ = ["fits_from_dir", "fits_from_url", "main",
           "payload_from_url", "render_frame"]

BAR_WIDTH = 20


def payload_from_url(url, timeout=5.0):
    """The full ``/jobs`` payload dict (``fits`` always; a live
    scheduler adds ``scheduler`` — see
    :mod:`brainiak_tpu.jobs.scheduler`)."""
    if not url.rstrip("/").endswith("/jobs"):
        url = url.rstrip("/") + "/jobs"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.load(resp)


def fits_from_url(url, timeout=5.0):
    """Fit snapshots from a ``/jobs`` endpoint (``url`` may name the
    server root or the ``/jobs`` path)."""
    return list(payload_from_url(url, timeout).get("fits", []))


def fits_from_dir(directory):
    """Fit snapshots reconstructed from the ``progress`` records of
    every ``*.jsonl`` file under ``directory`` (last record per
    fit_id wins, by record timestamp)."""
    fits = {}
    for path in sorted(glob.glob(os.path.join(directory,
                                              "*.jsonl"))):
        try:
            fh = open(path, encoding="utf-8")
        except OSError:
            continue
        with fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("kind") == "progress":
                    cur = fits.get(rec.get("fit_id"))
                    if cur is None or float(rec.get("ts", 0)) >= \
                            float(cur.get("ts", 0)):
                        fits[rec["fit_id"]] = rec
                elif rec.get("kind") == "event" \
                        and rec.get("name") == "fit_finished" \
                        and rec.get("fit_id") in fits:
                    status = (rec.get("attrs") or {}).get("status")
                    if status:
                        fits[rec["fit_id"]] = dict(
                            fits[rec["fit_id"]], status=status)
    return [fits[k] for k in sorted(fits)]


def recent_incidents(directory, limit=5):
    """The newest incident-snapshot manifests under
    ``directory/incidents`` (or ``directory`` itself when it already
    is the incidents dir), newest first."""
    if not directory:
        return []
    roots = [os.path.join(directory, "incidents"), directory]
    manifests = []
    for root in roots:
        manifests = sorted(
            glob.glob(os.path.join(root, "*", "manifest.json")),
            key=os.path.getmtime, reverse=True)
        if manifests:
            break
    out = []
    for path in manifests[:limit]:
        try:
            with open(path, encoding="utf-8") as fh:
                manifest = json.load(fh)
        except (OSError, ValueError):
            continue
        manifest["_path"] = os.path.dirname(path)
        out.append(manifest)
    return out


def _bar(ratio):
    try:
        ratio = min(max(float(ratio), 0.0), 1.0)
    except (TypeError, ValueError):
        ratio = 0.0
    full = int(round(ratio * BAR_WIDTH))
    return "[" + "#" * full + "-" * (BAR_WIDTH - full) + "]"


def _fmt_eta(eta):
    if eta is None:
        return "-"
    eta = float(eta)
    if eta >= 3600:
        return f"{eta / 3600:.1f}h"
    if eta >= 60:
        return f"{eta / 60:.1f}m"
    return f"{eta:.0f}s"


def render_frame(fits, incidents=(), now=None, scheduler=None):
    """One text frame: the fit table, the scheduler's job table
    (when a live scheduler feeds the ``/jobs`` payload), and recent
    incidents."""
    now = time.time() if now is None else now
    when = time.strftime("%H:%M:%S", time.localtime(now))
    lines = [f"obs watch  {when}  ({len(fits)} fit(s))"]
    has_jobs = any(fit.get("tenant") or fit.get("job_id")
                   for fit in fits)
    if fits:
        tenant_head = f" {'tenant':10s}" if has_jobs else ""
        lines.append(
            f"  {'fit_id':16s} {'estimator':20s}{tenant_head} "
            f"{'progress':{BAR_WIDTH + 2}s} {'step':>12s} "
            f"{'objective':>12s} {'eta':>7s} {'rb':>3s}  status")
    for fit in fits:
        step = f"{fit.get('step', '?')}/{fit.get('n_iter', '?')}"
        objective = fit.get("objective")
        objective = "-" if objective is None else f"{objective:.5g}"
        status = fit.get("status", "running")
        tenant_col = f" {str(fit.get('tenant') or '-')[:10]:10s}" \
            if has_jobs else ""
        lines.append(
            f"  {str(fit.get('fit_id', '?')):16s} "
            f"{str(fit.get('estimator', '?'))[:20]:20s}"
            f"{tenant_col} "
            f"{_bar(fit.get('ratio'))} {step:>12s} "
            f"{objective:>12s} {_fmt_eta(fit.get('eta_s')):>7s} "
            f"{fit.get('rollbacks', 0):>3} "
            f" {status}")
    if not fits:
        lines.append("  (no fits reported yet)")
    if scheduler:
        jobs = scheduler.get("jobs", [])
        tenants = scheduler.get("tenants", {})
        counts = scheduler.get("counts", {})
        state_summary = " ".join(
            f"{state}={counts[state]}" for state in sorted(counts))
        pressure = " [serving pressure]" \
            if scheduler.get("pressure") else ""
        lines.append("")
        lines.append(
            f"scheduler  slots={scheduler.get('slots', '?')}"
            f"{pressure}  {state_summary}")
        if jobs:
            lines.append(
                f"  {'job_id':16s} {'tenant':10s} {'kind':16s} "
                f"{'pri':>3s} {'state':9s} {'chunks':>6s} "
                f"{'preempt':>7s} {'deficit':>8s}")
        for job in jobs:
            deficit = tenants.get(job.get("tenant"), {}) \
                .get("deficit")
            deficit = "-" if deficit is None else f"{deficit:.2f}"
            lines.append(
                f"  {str(job.get('job_id', '?'))[:16]:16s} "
                f"{str(job.get('tenant', '?'))[:10]:10s} "
                f"{str(job.get('kind', '?'))[:16]:16s} "
                f"{job.get('priority', 0):>3} "
                f"{str(job.get('state', '?')):9s} "
                f"{job.get('chunks', 0):>6.0f} "
                f"{job.get('n_preemptions', 0):>7} "
                f"{deficit:>8s}")
    if incidents:
        lines.append("")
        lines.append("recent incidents:")
        for manifest in incidents:
            ts = manifest.get("ts")
            when = time.strftime("%H:%M:%S", time.localtime(ts)) \
                if ts else "?"
            fit_id = manifest.get("fit_id") or "-"
            lines.append(
                f"  {when}  {manifest.get('trigger', '?'):18s} "
                f"fit={fit_id}  {manifest['_path']}")
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m brainiak_tpu.obs watch",
        description="live terminal view of active fits "
                    "(docs/observability.md)")
    source = parser.add_mutually_exclusive_group()
    source.add_argument(
        "--url", help="TelemetryServer base URL (or its /jobs path)")
    source.add_argument(
        "--dir", dest="directory",
        help=f"obs JSONL directory (default: ${OBS_DIR_ENV})")
    parser.add_argument("--interval", type=float, default=2.0,
                        help="refresh period in seconds")
    parser.add_argument("--once", action="store_true",
                        help="render one frame and exit")
    args = parser.parse_args(argv)

    directory = args.directory
    if args.url is None and directory is None:
        directory = os.environ.get(OBS_DIR_ENV)
        if not directory:
            parser.error(
                f"give --url or --dir (or set ${OBS_DIR_ENV})")
    while True:
        scheduler = None
        try:
            if args.url:
                payload = payload_from_url(args.url)
                fits = list(payload.get("fits", []))
                scheduler = payload.get("scheduler")
            else:
                fits = fits_from_dir(directory)
        except OSError as exc:
            print(f"obs watch: source unreachable ({exc})",
                  file=sys.stderr)
            if args.once:
                return 1
            fits = []
        incidents = recent_incidents(
            directory or os.environ.get(OBS_DIR_ENV) or "")
        print(render_frame(fits, incidents, scheduler=scheduler))
        if args.once:
            return 0
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:  # pragma: no cover - interactive
            return 0
        print()


if __name__ == "__main__":  # pragma: no cover - module smoke entry
    sys.exit(main())
