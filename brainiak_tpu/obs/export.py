"""Export obs JSONL traces to a Chrome-trace (Perfetto) timeline.

``python -m brainiak_tpu.obs export --format=chrome-trace PATH ...``
converts one or more per-rank JSONL sinks (files or directories of
``*.jsonl``) into a single JSON document loadable by
``chrome://tracing`` and https://ui.perfetto.dev:

- each **rank** becomes a process lane (``pid`` = rank, named
  ``rank N`` via metadata events);
- **span** records become complete duration events (``ph="X"``);
  spans from one thread nest by containment, reconstructing the span
  tree visually (records carry no thread id, so concurrent same-rank
  threads share a lane);
- **event** and **cost** records become instant events (``ph="i"``,
  process-scoped) carrying their attrs;
- **metric** records become counter tracks (``ph="C"``): counters
  plot their running sum, gauges and histogram observations plot the
  raw value;
- **progress** records (schema v4 fit telemetry from
  :mod:`brainiak_tpu.obs.progress`) become two counter tracks per
  fit in that rank's lane — the completion ratio and, when the fit
  reports one, the objective trace — so a diverging fit's objective
  blow-up lines up visually with its span/rollback timeline;
- **traced** spans (schema v3 ``trace_id``/``span_id``/``parent_id``
  from :mod:`brainiak_tpu.obs.trace`) additionally become Chrome
  flow events (``ph="s"/"t"/"f"``, one flow per trace id): each
  request's submit→enqueue→dispatch→deliver chain renders as arrows
  across span slices — and across *rank lanes*, because the flow
  timestamps go through the same clock-skew merge, so a request
  submitted by one process and served by another draws as one
  connected flow.

Cross-rank clock skew: per-rank wall clocks need not agree (the
JSONL ``ts`` is host ``time.time()``).  The merge anchors on each
rank's first ``topology`` event — emitted by ``make_mesh`` on every
process of a collective mesh build, i.e. at (close to) the same true
instant — and shifts each rank so those anchors coincide with the
reference rank's.  Ranks without a topology event are passed through
unshifted.  Timestamps are exported relative to the earliest
adjusted event, in microseconds (the Chrome trace unit).

This module imports neither jax nor numpy — exports run anywhere.
"""

import argparse
import json
import math
import sys

from .report import iter_jsonl_paths, load_records

__all__ = ["chrome_trace", "main", "rank_offsets",
           "validate_chrome_trace"]

#: ``ph`` values the exporter emits; :func:`validate_chrome_trace`
#: accepts exactly these ("s"/"t"/"f" are the flow-event phases
#: traced requests render as).
_PHASES = ("X", "i", "C", "M", "s", "t", "f")


def rank_offsets(records):
    """Per-rank clock offsets (seconds to SUBTRACT from ``ts``).

    The reference is the lowest rank that has a ``topology`` event;
    every other anchored rank is shifted so its first topology event
    lands at the reference's instant.  ``{}`` when fewer than two
    ranks are anchored (nothing to reconcile).
    """
    anchors = {}
    for rec in records:
        if rec["kind"] == "event" and rec["name"] == "topology":
            anchors.setdefault(rec["rank"], float(rec["ts"]))
    if len(anchors) < 2:
        return {}
    ref_rank = min(anchors)
    ref_ts = anchors[ref_rank]
    return {rank: ts - ref_ts for rank, ts in anchors.items()}


def _counter_value(state, rec):
    """The value a metric record plots: running per-(rank,name,labels)
    sum for counters, the raw sample otherwise."""
    value = float(rec["value"])
    if rec.get("mtype") != "counter":
        return value
    key = (rec["rank"], rec["name"],
           tuple(sorted((rec.get("labels") or {}).items())))
    state[key] = state.get(key, 0.0) + value
    return state[key]


def _metric_name(rec):
    labels = rec.get("labels") or {}
    if not labels:
        return rec["name"]
    inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return f"{rec['name']}{{{inner}}}"


def chrome_trace(records):
    """Build the Chrome-trace document for validated obs records."""
    offsets = rank_offsets(records)

    def adjusted(rec):
        return float(rec["ts"]) - offsets.get(rec["rank"], 0.0)

    # earliest adjusted instant (span records' ts is their END time)
    t0 = None
    for rec in records:
        start = adjusted(rec)
        if rec["kind"] == "span":
            start -= float(rec["dur_s"])
        t0 = start if t0 is None else min(t0, start)
    t0 = t0 or 0.0

    def us(seconds):
        return round((seconds - t0) * 1e6, 3)

    events = []
    ranks = sorted({rec["rank"] for rec in records})
    for rank in ranks:
        events.append({"ph": "M", "name": "process_name",
                       "pid": rank, "tid": 0,
                       "args": {"name": f"rank {rank}"}})
        events.append({"ph": "M", "name": "process_sort_index",
                       "pid": rank, "tid": 0,
                       "args": {"sort_index": rank}})
    counter_state = {}
    flows = {}  # trace_id -> [(start_s, rank, span name)]
    for rec in records:
        kind = rec["kind"]
        end = adjusted(rec)
        if kind == "span":
            dur = float(rec["dur_s"])
            args = dict(rec.get("attrs") or {}, path=rec["path"])
            for key in ("trace_id", "span_id", "parent_id"):
                if rec.get(key):
                    args[key] = rec[key]
            if rec.get("trace_id"):
                # causal order is END time (a delivery span STARTS
                # near submit — its latency covers the whole
                # chain); the flow timestamp sits just inside the
                # slice's end so the viewer binds the arrow to the
                # right slice AND the steps stay monotone in time
                flows.setdefault(rec["trace_id"], []).append(
                    (end, end - dur * 1e-3, rec["rank"],
                     rec["name"]))
            events.append({
                "ph": "X", "name": rec["name"], "cat": "span",
                "ts": us(end - dur), "dur": round(dur * 1e6, 3),
                "pid": rec["rank"], "tid": 0,
                "args": args,
            })
        elif kind == "metric":
            events.append({
                "ph": "C", "name": _metric_name(rec),
                "ts": us(end), "pid": rec["rank"], "tid": 0,
                "args": {"value": _counter_value(counter_state, rec)},
            })
        elif kind == "progress":
            # one ratio track per fit (+ an objective track when the
            # fit reports one), named so every chunk of a fit lands
            # on the same counter in that rank's lane
            fit = f"{rec['estimator']}:{rec['fit_id']}"
            events.append({
                "ph": "C", "name": f"fit_progress {fit}",
                "ts": us(end), "pid": rec["rank"], "tid": 0,
                "args": {"ratio": float(rec["ratio"])},
            })
            objective = rec.get("objective")
            if objective is not None \
                    and math.isfinite(float(objective)):
                # a NaN/Inf objective has no plottable value (and
                # would not round-trip as JSON) — the precursor
                # event in the same lane marks the blow-up instant
                events.append({
                    "ph": "C", "name": f"fit_objective {fit}",
                    "ts": us(end), "pid": rec["rank"], "tid": 0,
                    "args": {"objective": float(objective)},
                })
        else:  # event / cost
            args = dict(rec.get("attrs") or {})
            if kind == "cost":
                args.update({k: rec[k] for k in
                             ("site", "level", "flops",
                              "bytes_accessed", "compile_s")
                             if k in rec})
            events.append({
                "ph": "i", "name": rec["name"], "cat": kind,
                "s": "p", "ts": us(end), "pid": rec["rank"],
                "tid": 0, "args": args,
            })
    # traced requests: one flow per trace id, stepping through its
    # spans in start order — the viewer draws arrows between the
    # slices the flow timestamps land in, across rank lanes
    for trace_id, steps in flows.items():
        if len(steps) < 2:  # no arrow to draw
            continue
        steps.sort()
        prev_ts = None
        for i, (end, inside, rank, name) in enumerate(steps):
            ph = "s" if i == 0 else (
                "f" if i == len(steps) - 1 else "t")
            # keep the step sequence strictly monotone even when
            # two chain spans END microseconds apart (delivery is
            # recorded right after dispatch): any instant inside
            # the slice binds, and a later span's slice always
            # covers its predecessor's end
            ts = inside if prev_ts is None \
                else min(end, max(inside, prev_ts + 1e-6))
            prev_ts = ts
            ev = {"ph": ph, "id": trace_id, "name": "trace",
                  "cat": "trace", "ts": us(ts), "pid": rank,
                  "tid": 0, "args": {"step": name}}
            if ph == "f":
                ev["bp"] = "e"  # bind to the enclosing slice
            events.append(ev)
    # stable viewer ordering: X events must be opened in start order
    # for nesting; metadata first
    events.sort(key=lambda e: (e["ph"] != "M", e.get("ts", 0.0)))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "generator": "brainiak_tpu.obs.export",
            "clock_offsets_s": {str(r): round(off, 6)
                                for r, off in offsets.items()},
        },
    }


def validate_chrome_trace(doc):
    """Return schema-violation strings for a Chrome-trace document
    (empty = valid).  Checks the keys the Chrome/Perfetto loaders
    require: a ``traceEvents`` list whose entries carry ``ph``/
    ``name``/``pid`` (+ ``ts`` for non-metadata, ``dur`` for complete
    events), with numeric non-negative timestamps."""
    errors = []
    if not isinstance(doc, dict):
        return ["document is not an object"]
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents missing or not a list"]
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if ph not in _PHASES:
            errors.append(f"{where}: ph={ph!r} (expected one of "
                          f"{_PHASES})")
            continue
        for key in ("name", "pid"):
            if key not in ev:
                errors.append(f"{where}: missing {key!r}")
        if ph != "M":
            ts = ev.get("ts")
            if not isinstance(ts, (int, float)) \
                    or isinstance(ts, bool) or ts < 0:
                errors.append(
                    f"{where}: ts={ts!r} (expected a number >= 0)")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) \
                    or isinstance(dur, bool) or dur < 0:
                errors.append(
                    f"{where}: dur={dur!r} (expected a number >= 0)")
        if ph in ("s", "t", "f") and not ev.get("id"):
            errors.append(
                f"{where}: flow event missing its 'id' (the trace "
                "id binding the arrow's endpoints)")
    return errors


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m brainiak_tpu.obs export",
        description="export obs JSONL traces to a viewer timeline "
                    "(docs/observability.md)")
    parser.add_argument(
        "paths", nargs="+", metavar="PATH",
        help="JSONL files or directories of *.jsonl")
    parser.add_argument("--format", choices=("chrome-trace",),
                        default="chrome-trace")
    parser.add_argument(
        "-o", "--output", metavar="FILE", default=None,
        help="write the trace JSON here (default: stdout)")
    args = parser.parse_args(argv)

    files = iter_jsonl_paths(args.paths)
    if not files:
        print(f"obs export: no .jsonl files under {args.paths}",
              file=sys.stderr)
        return 1
    records, errors = load_records(files)
    for err in errors:
        print(f"obs export: schema violation: {err}",
              file=sys.stderr)
    if not records:
        print("obs export: no valid records to export",
              file=sys.stderr)
        return 1
    doc = chrome_trace(records)
    payload = json.dumps(doc, indent=2)
    if args.output:
        with open(args.output, "w", encoding="utf-8") as fh:
            fh.write(payload + "\n")
        print(f"obs export: wrote {len(doc['traceEvents'])} events "
              f"({len(records)} records) to {args.output}",
              file=sys.stderr)
    else:
        print(payload)
    return 1 if errors else 0
