"""Flight recorder: an always-on ring of recent telemetry records.

A six-hour fit that dies at 03:00 leaves nothing behind unless
something was already recording when it died.  This module keeps a
bounded in-memory ring (:data:`FLIGHT_RECORDS_ENV` cap, default
:data:`DEFAULT_CAPACITY`) of the most recent spans / events / metrics
/ progress records — fed by every :func:`brainiak_tpu.obs.sink.emit`
and, independently of any sink, by the fit-progress tracker
(:mod:`brainiak_tpu.obs.progress`) — so the moments before an
incident are always reconstructable.

Appends are O(1) under the module lock and never block the emitting
thread on I/O: the ring is pure memory until :func:`dump` is called.
``dump()`` writes an incident snapshot — ``records.jsonl`` plus a
``manifest.json`` naming the trigger, the implicated ``fit_id`` /
``trace_id``, and the caller's last-known state — into (first match
wins) an explicit directory argument, ``$BRAINIAK_TPU_OBS_FLIGHT_DIR``,
or ``$BRAINIAK_TPU_OBS_DIR/incidents``; with none of those set it is
a silent no-op (the ring still records, snapshots have nowhere to
land).  Automatic dump triggers live at the failure edges of the
framework: ``divergence_abort`` (resilience/guards), sanitizer trips
(obs/sanitize), ``retry_exhausted`` (resilience/retry), SLO violation
transitions (obs/slo), and replica death (serve/federation/fleet).

``python -m brainiak_tpu.obs postmortem <snapshot>`` renders a
snapshot (:mod:`brainiak_tpu.obs.postmortem`).
"""

import json
import logging
import os
import threading
import time
from collections import deque

logger = logging.getLogger(__name__)

__all__ = [
    "DEFAULT_CAPACITY",
    "FLIGHT_DIR_ENV",
    "FLIGHT_RECORDS_ENV",
    "capacity",
    "clear",
    "dump",
    "last_dump",
    "record",
    "records",
]

#: Ring capacity when ``BRAINIAK_TPU_OBS_FLIGHT_RECORDS`` is unset.
DEFAULT_CAPACITY = 512

FLIGHT_RECORDS_ENV = "BRAINIAK_TPU_OBS_FLIGHT_RECORDS"
FLIGHT_DIR_ENV = "BRAINIAK_TPU_OBS_FLIGHT_DIR"

_lock = threading.Lock()
_ring = None       # guarded-by: _lock (deque, maxlen = capacity)
_ring_cap = None   # guarded-by: _lock (capacity _ring was built with)
_seq = 0           # guarded-by: _lock (snapshot filename uniquifier)
_last_dump = None  # guarded-by: _lock ((path, ts, fit_id, trigger))


def capacity():
    """The configured ring capacity (env override, else default)."""
    env = os.environ.get(FLIGHT_RECORDS_ENV)
    if env:
        try:
            cap = int(env)
            if cap > 0:
                return cap
        except ValueError:
            pass
        logger.warning("ignoring invalid %s=%r", FLIGHT_RECORDS_ENV,
                       env)
    return DEFAULT_CAPACITY


def _ring_locked():
    """The ring, (re)built at the current capacity.  Callers hold
    ``_lock``."""
    global _ring, _ring_cap
    cap = capacity()
    if _ring is None or _ring_cap != cap:
        old = list(_ring) if _ring is not None else []
        _ring = deque(old[-cap:], maxlen=cap)
        _ring_cap = cap
    return _ring


def record(rec):
    """Append one record dict to the ring (O(1); oldest evicted)."""
    with _lock:
        _ring_locked().append(rec)


def records():
    """A snapshot list of the ring's records, oldest first."""
    with _lock:
        return list(_ring_locked())


def clear():
    """Empty the ring (test isolation)."""
    with _lock:
        if _ring is not None:
            _ring.clear()


def _resolve_dir(directory):
    if directory:
        return directory
    env = os.environ.get(FLIGHT_DIR_ENV)
    if env:
        return env
    obs_dir = os.environ.get("BRAINIAK_TPU_OBS_DIR")
    if obs_dir:
        return os.path.join(obs_dir, "incidents")
    return None


def dump(trigger, *, fit_id=None, trace_id=None, state=None,
         directory=None):
    """Write an incident snapshot of the ring; returns its path.

    ``trigger`` names the incident kind (``divergence_abort``,
    ``sanitizer``, ``retry_exhausted``, ``slo_violation``,
    ``replica_death``, ...); ``fit_id``/``trace_id`` implicate the
    fit or request; ``state`` is the caller's last-known state — a
    small JSON-serializable dict (failing step, leaves, site) stamped
    into the manifest verbatim.  With no resolvable target directory
    (see module docstring) returns None without writing anything.
    Dump failures are logged and swallowed: the flight recorder must
    never turn an incident into a second incident.
    """
    global _seq
    target = _resolve_dir(directory)
    if target is None:
        return None
    with _lock:
        ring = list(_ring_locked())
        cap = _ring_cap
        _seq += 1
        seq = _seq
    now = time.time()
    name = "incident-{}-{}-{}-{}".format(
        trigger, int(now * 1000), os.getpid(), seq)
    path = os.path.join(target, name)
    try:
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "records.jsonl"), "w",
                  encoding="utf-8") as fh:
            for rec in ring:
                fh.write(json.dumps(rec, default=_json_default) + "\n")
        manifest = {
            "trigger": trigger,
            "ts": now,
            "fit_id": fit_id,
            "trace_id": trace_id,
            "n_records": len(ring),
            "capacity": cap,
            "state": state or {},
        }
        with open(os.path.join(path, "manifest.json"), "w",
                  encoding="utf-8") as fh:
            json.dump(manifest, fh, indent=2, default=_json_default)
    except OSError as exc:
        logger.warning("flight dump for %s failed: %s", trigger, exc)
        return None
    global _last_dump
    with _lock:
        _last_dump = (path, now, fit_id, trigger)
    from . import sink
    if sink.enabled():
        sink.event("flight_dump", trigger=trigger, path=path,
                   n_records=len(ring), fit_id=fit_id)
    logger.info("flight recorder dumped %d records to %s (trigger: "
                "%s)", len(ring), path, trigger)
    return path


def last_dump(fit_id=None, since=None):
    """The most recent snapshot written by :func:`dump` (in this
    process) as ``{"path", "ts", "fit_id", "trigger"}``, or None.

    ``fit_id`` restricts the answer to a snapshot implicating that
    fit; ``since`` (epoch seconds) to one written at/after that time.
    The jobs scheduler uses this to attach the incident snapshot of a
    diverged / retry-exhausted fit to the failed job's record.
    """
    with _lock:
        hit = _last_dump
    if hit is None:
        return None
    path, ts, hit_fit, trigger = hit
    if fit_id is not None and hit_fit is not None and hit_fit != fit_id:
        return None
    if since is not None and ts < since:
        return None
    return {"path": path, "ts": ts, "fit_id": hit_fit,
            "trigger": trigger}


def _json_default(obj):
    for attr in ("tolist", "item"):
        fn = getattr(obj, attr, None)
        if callable(fn):
            try:
                return fn()
            except Exception:
                continue
    return repr(obj)
