"""OBS002 selfcheck: the live telemetry plane, end to end.

The ``obs-live`` gate of ``tools/run_checks.py`` runs
:func:`selfcheck` in a CPU-pinned child process: drive a tiny
in-process :class:`~brainiak_tpu.serve.service.ServeService` (demo
SRM, a handful of mixed-shape requests) with SLO tracking attached
and the exposition endpoint on an **ephemeral** port, then scrape
``/metrics`` + ``/healthz`` + ``/readyz`` over real HTTP and verify:

- the scrape parses with the minimal in-repo Prometheus parser
  (:func:`brainiak_tpu.obs.http.parse_prometheus_text`) with zero
  errors;
- the required ``serve_*`` and ``slo_*`` families are present
  (:data:`REQUIRED_SERIES`);
- the scraped ``serve_requests_total{outcome="ok"}`` agrees with
  the service summary's ``n_ok`` (the exposition and the JSON
  summary must tell one story);
- ``/healthz`` answers 200 and ``/readyz`` reports ready with a
  resident model.

Prints one JSON verdict line; exit 0 on success, 1 with the verdict
naming what failed — the gate classifies from the verdict, not from
a traceback.
"""

import json
import urllib.request

__all__ = ["REQUIRED_SERIES", "selfcheck"]

#: Metric families a healthy live scrape must expose (the series the
#: ROADMAP item 3 router and the SLO dashboards read).
REQUIRED_SERIES = (
    "serve_requests_total",
    "serve_request_seconds",
    "serve_queue_depth",
    "serve_service_ingress_depth",
    "slo_burn_rate",
    "slo_error_budget_remaining",
)


def _get(port, path, timeout=10.0):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}",
            timeout=timeout) as resp:
        return resp.status, resp.read().decode("utf-8")


def selfcheck(n_requests=12):
    """Run the live-plane check (see module docstring); returns the
    process exit code."""
    from ..serve import BucketPolicy, ModelResidency
    from ..serve.__main__ import (build_demo_model,
                                  build_mixed_requests)
    from ..serve.service import ServeService
    from . import sink as obs_sink
    from .http import parse_prometheus_text
    from .slo import Objective

    verdict = {"ok": False, "missing": [], "parse_errors": [],
               "n_requested": n_requests}
    mem = obs_sink.add_sink(obs_sink.MemorySink())
    try:
        model = build_demo_model(n_subjects=2, voxels=24,
                                 samples=20, features=4, n_iter=2)
        requests = build_mixed_requests(model, n_requests)
        residency = ModelResidency(
            budget_bytes=1 << 30,
            policy=BucketPolicy(max_batch=8, max_wait_s=0.01))
        residency.register("demo", model=model)
        svc = ServeService(
            residency, default_model="demo", http_port=0,
            slos=[Objective.latency("p99_latency", quantile=0.99,
                                    threshold_s=30.0),
                  Objective.error_rate("availability",
                                       max_error_rate=0.01)],
        ).start()
        try:
            tickets = svc.submit_many(requests)
            for ticket in tickets:
                ticket.result(timeout=120.0)
            port = svc.summary().get("http_port")
            verdict["http_port"] = port
            status, text = _get(port, "/metrics")
            verdict["metrics_status"] = status
            families, errors = parse_prometheus_text(text)
            verdict["parse_errors"] = errors
            verdict["n_families"] = len(families)
            verdict["missing"] = [name for name in REQUIRED_SERIES
                                  if name not in families]
            # the exposition and the JSON summary must agree on
            # requests served
            scraped_ok = sum(
                value for fam in ("serve_requests_total",)
                for name, labels, value in
                families.get(fam, {"samples": []})["samples"]
                if labels.get("outcome") == "ok")
            health_status, health_body = _get(port, "/healthz")
            verdict["healthz_ok"] = (
                health_status == 200
                and health_body.strip() == "ok")
            ready_status, ready_body = _get(port, "/readyz")
            verdict["readyz_status"] = ready_status
            verdict["readyz_ready"] = bool(
                json.loads(ready_body).get("ready"))
        finally:
            summary = svc.shutdown()
        verdict["n_ok"] = summary["n_ok"]
        verdict["scraped_ok"] = scraped_ok
        verdict["counts_agree"] = \
            int(scraped_ok) == summary["n_ok"] == n_requests
        verdict["ok"] = bool(
            verdict["metrics_status"] == 200
            and not verdict["parse_errors"]
            and not verdict["missing"]
            and verdict["healthz_ok"]
            and verdict["readyz_ready"]
            and verdict["counts_agree"])
    except Exception as exc:  # the gate wants a verdict, not a trace
        verdict["error"] = f"{type(exc).__name__}: {exc}"
    finally:
        obs_sink.remove_sink(mem)
    print(json.dumps(verdict))
    return 0 if verdict["ok"] else 1
