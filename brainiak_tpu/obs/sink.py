"""Observability sinks: where span/event/metric records go.

Records are flat JSON-serializable dicts with a versioned schema
(:data:`SCHEMA_VERSION`, :func:`validate_record`).  Two sinks exist:

- :class:`JsonlSink` — one JSON-lines file per host process
  (``obs-<rank>.jsonl``), append-mode, flushed on every write batch
  and closed atexit.  Activated automatically when the
  ``BRAINIAK_TPU_OBS_DIR`` environment variable names a directory.
- :class:`MemorySink` — an in-process record list for tests and for
  :mod:`bench`'s stage breakdown.

The module-level dispatch (:func:`emit`) fans a record out to every
active sink.  **Disabled is the default**: with no sink registered and
no ``BRAINIAK_TPU_OBS_DIR``, :func:`enabled` is False and every
instrumentation site in the framework short-circuits to a no-op —
in particular no ``block_until_ready`` host syncs are introduced in
instrumented hot loops (acceptance-tested in
``tests/obs/test_integration.py`` and linted by jaxlint JX002).
"""

import atexit
import contextlib
import io
import json
import logging
import os
import sys
import threading
import time

logger = logging.getLogger(__name__)

__all__ = [
    "ACCEPTED_VERSIONS",
    "OBS_DIR_ENV",
    "OBS_MAX_MB_ENV",
    "OBS_RANK_ENV",
    "SCHEMA_VERSION",
    "JsonlSink",
    "MemorySink",
    "add_sink",
    "all_sinks",
    "backend_initialized",
    "close_all",
    "emit",
    "enabled",
    "event",
    "make_record",
    "process_rank",
    "remove_sink",
    "suspended",
    "validate_record",
]

OBS_DIR_ENV = "BRAINIAK_TPU_OBS_DIR"
OBS_RANK_ENV = "BRAINIAK_TPU_OBS_RANK"

#: Version stamped into every record as ``"v"``.  Bump on any
#: backwards-incompatible change to the keys below; the report CLI and
#: the ``obs`` gate of ``tools/run_checks.py`` reject records whose
#: version or shape they do not understand.  v2 (PR 4) added the
#: ``cost`` kind (XLA cost-analysis attribution, see
#: :mod:`brainiak_tpu.obs.profile`); v3 (PR 12) added the optional
#: request-tracing fields ``trace_id``/``span_id``/``parent_id`` on
#: span and event records (:mod:`brainiak_tpu.obs.trace`).  v4
#: (PR 19) added the ``progress`` kind (per-chunk fit progress /
#: convergence telemetry, :mod:`brainiak_tpu.obs.progress`) and the
#: optional ``fit_id`` field on span and event records so a fit's
#: spans/events join its progress stream.  v1–v3 records remain
#: valid, so pre-existing traces keep loading.
SCHEMA_VERSION = 4
ACCEPTED_VERSIONS = (1, 2, 3, 4)

KINDS = ("span", "event", "metric", "cost", "progress")
METRIC_TYPES = ("counter", "gauge", "histogram")

OBS_MAX_MB_ENV = "BRAINIAK_TPU_OBS_MAX_MB"

# backend-derived process rank, cached once resolvable (see
# process_rank: a process's rank never changes after distributed init)
_cached_rank = None

_NUM = (int, float)
_REQUIRED = {
    "span": {"dur_s": _NUM, "path": str},
    "event": {},
    "metric": {"mtype": str, "value": _NUM},
    "cost": {"site": str},
    # progress (schema v4): one record per resilient-loop chunk
    # (obs.progress) — the fit_id joins a fit's records across
    # process restarts (it rides in the checkpoint)
    "progress": {"fit_id": str, "estimator": str, "chunk": _NUM,
                 "step": _NUM, "ratio": _NUM},
}
_OPTIONAL = {
    # trace_id/span_id/parent_id (schema v3): request-scoped tracing
    # (obs.trace) — a span/event may belong to one request's
    # end-to-end trace, with parent_id naming the causally-preceding
    # span so the export CLI reconstructs per-request flows;
    # fit_id (schema v4): the owning fit's progress stream
    "span": {"attrs": dict, "trace_id": str, "span_id": str,
             "parent_id": str, "fit_id": str},
    "event": {"attrs": dict, "trace_id": str, "span_id": str,
              "parent_id": str, "fit_id": str},
    "metric": {"labels": dict, "unit": str},
    # cost: FLOPs/bytes may be absent (backend without cost_analysis
    # reports `unavailable` instead); span/estimator are join hints
    # for the report CLI's roofline computation
    "cost": {"flops": _NUM, "bytes_accessed": _NUM,
             "transcendentals": _NUM, "compile_s": _NUM,
             "hlo_bytes": int, "hlo_lines": int, "peak_flops": _NUM,
             "level": str, "backend": str, "span": str,
             "estimator": str, "unavailable": str, "attrs": dict},
    # objective / ETA telemetry may be absent: a fit without a
    # progress_objective hint still reports chunk cadence and ratio
    "progress": {"n_chunks": _NUM, "n_iter": _NUM, "epoch": _NUM,
                 "objective": _NUM, "delta": _NUM, "rollbacks": _NUM,
                 "chunk_s": _NUM, "fit_wall_s": _NUM, "rate": _NUM,
                 "eta_s": _NUM, "plateaued": bool, "attrs": dict},
}


def validate_record(rec):
    """Return a list of schema-violation strings (empty = valid).

    Checked: the common envelope (``v``/``kind``/``ts``/``rank``/
    ``name``), kind-specific required keys with their types, optional
    keys with their types, and that no unknown keys are present.
    """
    errors = []
    if not isinstance(rec, dict):
        return ["record is not an object"]
    v = rec.get("v")
    if v not in ACCEPTED_VERSIONS:
        errors.append(f"v={v!r} (expected one of {ACCEPTED_VERSIONS})")
    kind = rec.get("kind")
    if kind not in KINDS:
        errors.append(f"kind={kind!r} (expected one of {KINDS})")
        return errors
    if kind == "cost" and isinstance(v, int) and v < 2:
        errors.append("cost records require schema v>=2")
    if kind == "progress" and isinstance(v, int) and v < 4:
        errors.append("progress records require schema v>=4")
    if not isinstance(rec.get("ts"), (int, float)):
        errors.append("ts missing or not a number")
    if not isinstance(rec.get("rank"), int):
        errors.append("rank missing or not an int")
    if not isinstance(rec.get("name"), str) or not rec.get("name"):
        errors.append("name missing or empty")
    required = _REQUIRED[kind]
    optional = _OPTIONAL[kind]
    for key, typ in required.items():
        val = rec.get(key)
        if not isinstance(val, typ) or isinstance(val, bool):
            errors.append(f"{kind}.{key}={val!r} (expected {typ})")
    for key, typ in optional.items():
        if key in rec and not isinstance(rec[key], typ):
            errors.append(
                f"{kind}.{key}={rec[key]!r} (expected {typ})")
    if kind == "metric" and rec.get("mtype") not in METRIC_TYPES:
        errors.append(f"metric.mtype={rec.get('mtype')!r} "
                      f"(expected one of {METRIC_TYPES})")
    known = {"v", "kind", "ts", "rank", "name"}
    known.update(required)
    known.update(optional)
    unknown = sorted(set(rec) - known)
    if unknown:
        errors.append(f"unknown key(s): {', '.join(unknown)}")
    return errors


def process_rank():
    """This process's rank for record attribution and sink filenames.

    ``BRAINIAK_TPU_OBS_RANK`` wins; otherwise ``jax.process_index()``
    — but ONLY when a jax backend is already initialized (checked via
    the xla_bridge backend registry without touching it): obs never
    imports jax and never initializes a backend, because on a wedged
    TPU tunnel backend init hangs and telemetry must not be the
    thing that first touches the device.  Records emitted before
    distributed init therefore report rank 0; :class:`JsonlSink`
    re-resolves its filename per write, so post-init records land in
    the correct per-rank file.
    """
    global _cached_rank
    env = os.environ.get(OBS_RANK_ENV)
    if env is not None:
        try:
            return int(env)
        except ValueError:
            pass
    if _cached_rank is not None:
        # immutable after distributed init — skip the per-record
        # probe cost on instrumented hot paths
        return _cached_rank
    # jax.process_index() itself would INITIALIZE the backend (a
    # blocking first device touch); the bridge registry is populated
    # only after real initialization
    if not backend_initialized():
        return 0
    jax = sys.modules.get("jax")
    try:
        _cached_rank = int(jax.process_index())
    except Exception:  # backend unreachable mid-teardown
        return 0
    return _cached_rank


def backend_initialized():
    """True when a jax backend is already initialized, checked via
    the xla_bridge registry WITHOUT touching it — the load-bearing
    "telemetry must never be the first device touch" probe shared by
    :func:`process_rank` and
    :func:`brainiak_tpu.obs.profile.memory_watermark` (on a wedged
    TPU tunnel, backend init blocks)."""
    if sys.modules.get("jax") is None:
        return False
    bridge = sys.modules.get("jax._src.xla_bridge")
    return bool(bridge is not None
                and getattr(bridge, "_backends", None))


def make_record(kind, name, **fields):
    """Build a :data:`SCHEMA_VERSION` record envelope around
    ``fields``."""
    rec = {"v": SCHEMA_VERSION, "kind": kind, "ts": time.time(),
           "rank": process_rank(), "name": name}
    rec.update({k: v for k, v in fields.items() if v is not None})
    return rec


class MemorySink:
    """In-process sink: records accumulate in ``self.records``."""

    def __init__(self):
        self._lock = threading.Lock()
        self.records = []   # guarded-by: _lock

    def write(self, record):
        with self._lock:
            self.records.append(record)

    def flush(self):
        pass

    def close(self):
        pass

    def clear(self):
        with self._lock:
            self.records.clear()


class JsonlSink:
    """Append records to ``<directory>/obs-<rank>.jsonl``.

    One file per host process (rank-suffixed) so multi-process runs
    never interleave writes; the report CLI aggregates the directory.
    The file opens lazily, flushes after every record (a crash must
    not lose the trace that explains it), and closes atexit through
    :func:`close_all`.  The rank is re-resolved per write: records
    emitted before ``jax.distributed`` initialization (when every
    process still reports rank 0) go to ``obs-0.jsonl``, and once the
    backend is up the sink reopens under the process's real rank —
    so steady-state records never interleave across hosts.

    ``max_mb`` (default: the ``BRAINIAK_TPU_OBS_MAX_MB`` env var)
    caps the bytes this sink will write across all its rank files: a
    multi-day fit with per-chunk spans must not fill the disk.  On
    reaching the cap the sink writes ONE ``obs_truncated`` event (so
    the trace records its own incompleteness) and drops every later
    record — but keeps COUNTING them: :meth:`close` stamps one final
    ``obs_dropped`` event carrying ``dropped_total`` (the one record
    allowed past the cap), so ``obs report`` can state exactly how
    incomplete a truncated trace is instead of implying the run went
    quiet.  The in-process metric registry keeps aggregating
    regardless.
    """

    def __init__(self, directory, rank=None, max_mb=None):
        self.directory = directory
        self._rank = rank
        self._lock = threading.Lock()
        self._fh = None          # guarded-by: _lock
        self._open_path = None   # guarded-by: _lock
        if max_mb is None:
            env = os.environ.get(OBS_MAX_MB_ENV)
            try:
                max_mb = float(env) if env else None
            except ValueError:
                logger.warning("ignoring non-numeric %s=%r",
                               OBS_MAX_MB_ENV, env)
                max_mb = None
        self.max_bytes = None if not max_mb or max_mb <= 0 \
            else int(max_mb * 1024 * 1024)
        self._written = 0        # guarded-by: _lock
        self._truncated = False  # guarded-by: _lock
        self._dropped = 0        # guarded-by: _lock
        self._drop_stamped = False  # guarded-by: _lock

    @property
    def dropped_total(self):
        """Records dropped after the ``max_mb`` cap hit (0 while the
        sink is under the cap)."""
        with self._lock:
            return self._dropped

    @property
    def path(self):
        rank = self._rank if self._rank is not None else process_rank()
        return os.path.join(self.directory, f"obs-{rank}.jsonl")

    def _ensure_open(self):
        path = self.path
        if self._fh is None or self._open_path != path:
            if self._fh is not None:
                self._fh.close()
            os.makedirs(self.directory, exist_ok=True)
            self._fh = io.open(path, "a", encoding="utf-8")
            self._open_path = path
        return self._fh

    def write(self, record):
        with self._lock:
            if self._truncated:
                self._dropped += 1
                return
            line = json.dumps(record, default=_json_default) + "\n"
            if self.max_bytes is not None \
                    and self._written + len(line) > self.max_bytes:
                self._truncated = True
                # the record whose write tripped the cap is dropped
                # too (the marker takes its slot)
                self._dropped += 1
                line = json.dumps(make_record(
                    "event", "obs_truncated",
                    attrs={"limit_mb":
                           self.max_bytes / (1024 * 1024),
                           "written_bytes": self._written}),
                    default=_json_default) + "\n"
                logger.warning(
                    "obs sink reached %s cap (%.1f MB); dropping "
                    "further records", OBS_MAX_MB_ENV,
                    self.max_bytes / (1024 * 1024))
            fh = self._ensure_open()
            fh.write(line)
            fh.flush()
            self._written += len(line)

    def flush(self):
        with self._lock:
            if self._fh is not None:
                self._fh.flush()

    def close(self):
        with self._lock:
            # a truncated sink owes the trace its own drop count:
            # ONE final event past the cap (stamped once even across
            # repeated close() calls), so `obs report` renders
            # dropped_total instead of implying the run went quiet
            if self._truncated and self._dropped \
                    and not self._drop_stamped:
                self._drop_stamped = True
                line = json.dumps(make_record(
                    "event", "obs_dropped",
                    attrs={"dropped_total": self._dropped}),
                    default=_json_default) + "\n"
                try:
                    fh = self._ensure_open()
                    fh.write(line)
                    fh.flush()
                except OSError:  # disk full is how we got here
                    pass
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def _json_default(obj):
    """Serialize numpy scalars/arrays that leak into span attrs."""
    for attr in ("tolist", "item"):
        fn = getattr(obj, attr, None)
        if callable(fn):
            try:
                return fn()
            except Exception:
                continue
    return repr(obj)


# -- module-level dispatch --------------------------------------------

_sinks = []          # guarded-by: _lock
_env_sink = None     # guarded-by: _lock
_env_dir_seen = None  # guarded-by: _lock
# env sink disabled after a write failure
_env_broken = False  # guarded-by: _lock
# nesting depth of active suspended() blocks (see below)
_suspend_depth = 0   # guarded-by: _lock
_lock = threading.Lock()


@contextlib.contextmanager
def suspended():
    """Temporarily force :func:`enabled` False (nests; thread-wide).

    The obs-off reference lane for overhead measurement: the bench's
    ``service_obs_overhead_ratio`` drives the same workload once
    with telemetry live and once under this block, without tearing
    down (and thereby closing) the registered sinks.  Instrumented
    sites see plain disabled behavior — no records, no syncs."""
    global _suspend_depth
    with _lock:
        _suspend_depth += 1
    try:
        yield
    finally:
        with _lock:
            _suspend_depth -= 1


def add_sink(sink):
    """Register ``sink`` to receive every emitted record; returns it."""
    with _lock:
        _sinks.append(sink)
    return sink


def remove_sink(sink):
    """Unregister (and close) a sink added with :func:`add_sink`."""
    with _lock:
        if sink in _sinks:
            _sinks.remove(sink)
    sink.close()


def _configure_from_env():
    """Keep the env-var-driven JSONL sink in step with the current
    value of ``BRAINIAK_TPU_OBS_DIR`` (tests monkeypatch it)."""
    global _env_sink, _env_dir_seen, _env_broken
    directory = os.environ.get(OBS_DIR_ENV) or None
    if directory == _env_dir_seen:
        return
    with _lock:
        if directory == _env_dir_seen:
            return
        if _env_sink is not None:
            _env_sink.close()
        _env_sink = JsonlSink(directory) if directory else None
        _env_dir_seen = directory
        _env_broken = False  # a NEW dir gets a fresh chance


def enabled():
    """True when at least one sink is (or will be) active.

    This is the gate every instrumentation site checks first; it costs
    one list check plus one environ lookup, and instrumented code paths
    do no timing, no attribute building, and — critically — no
    ``block_until_ready`` when it returns False.

    An env-configured sink that was disabled by a write failure turns
    this False again, so instrumentation stops paying for records
    nobody can receive; pointing the env var at a DIFFERENT directory
    re-enables (it gets a fresh sink).  An active :func:`suspended`
    block wins over everything.
    """
    if _suspend_depth:
        return False
    if _sinks:
        return True
    directory = os.environ.get(OBS_DIR_ENV)
    if not directory:
        return False
    return not _env_broken or directory != _env_dir_seen


def all_sinks():
    """The currently-active sinks (explicit + env-configured);
    empty under an active :func:`suspended` block."""
    _configure_from_env()
    with _lock:
        if _suspend_depth:
            return []
        sinks = list(_sinks)
        if _env_sink is not None:
            sinks.append(_env_sink)
    return sinks


def emit(record):
    """Dispatch ``record`` to every active sink; returns the record.

    Telemetry must never break the instrumented application: a sink
    whose write raises (unwritable ``BRAINIAK_TPU_OBS_DIR``, disk
    full) is logged once and DISABLED for the rest of the process
    instead of propagating into the fit/retry/fetch call that
    happened to emit the record.

    Every emitted record is additionally mirrored into the
    flight-recorder ring (:mod:`brainiak_tpu.obs.flight`) so an
    incident snapshot carries the records that led up to it.
    """
    from . import flight
    flight.record(record)
    for sink in all_sinks():
        try:
            sink.write(record)
        except Exception as exc:
            logger.warning(
                "obs sink %s failed (%s: %s); disabling it",
                type(sink).__name__, type(exc).__name__, exc)
            _disable_sink(sink)
    return record


def _disable_sink(sink):
    global _env_sink, _env_broken
    with _lock:
        if sink in _sinks:
            _sinks.remove(sink)
        if sink is _env_sink:
            # keep _env_dir_seen so the broken dir is not re-created
            # on the next emit, and mark it broken so enabled()
            # reverts to False (instrumentation stops paying)
            _env_sink = None
            _env_broken = True
    try:
        sink.close()
    except Exception:
        pass


def event(name, **attrs):
    """Emit an ``event`` record (no-op while obs is disabled).

    The one-liner instrumentation sites use: attribute values must be
    JSON-serializable (numpy scalars are coerced).  A ``fit_id``
    attribute is promoted to the record's top-level schema-v4 field so
    the event joins that fit's progress stream."""
    if not enabled():
        return None
    fit_id = attrs.pop("fit_id", None)
    return emit(make_record("event", name, attrs=attrs or None,
                            fit_id=fit_id))


def close_all():
    """Flush and close every sink (registered atexit)."""
    global _env_sink, _env_dir_seen, _env_broken
    with _lock:
        sinks = list(_sinks)
        if _env_sink is not None:
            sinks.append(_env_sink)
        _env_sink = None
        _env_dir_seen = None
        _env_broken = False
        del _sinks[:]
    for sink in sinks:
        try:
            sink.close()
        except Exception:  # never let telemetry mask an exit path
            pass


atexit.register(close_all)
