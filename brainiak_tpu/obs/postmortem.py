"""Render flight-recorder incident snapshots.

``python -m brainiak_tpu.obs postmortem <snapshot>`` reads a snapshot
written by :func:`brainiak_tpu.obs.flight.dump` — a directory holding
``manifest.json`` + ``records.jsonl`` (either file also accepted
directly) — and renders the incident for a human: the trigger and
implicated fit/trace, the failing chunk and site from the manifest's
last-known state, each fit's objective tail (the last few values
before the lights went out), and a timeline of the final records in
the ring.  Exit 0 on a rendered snapshot, 1 on an unreadable or
malformed one.

This module imports neither jax nor numpy — postmortems run anywhere
(a laptop reading a snapshot scp'd off the pod).
"""

import argparse
import json
import os
import sys
import time

__all__ = ["load_snapshot", "main", "render"]

#: Timeline rows rendered from the tail of the ring.
TIMELINE_TAIL = 20

#: Objective values shown per fit (the convergence tail).
OBJECTIVE_TAIL = 5


def load_snapshot(path):
    """Read ``(manifest, records)`` from a snapshot directory (or
    either of its files); raises ``ValueError`` on malformed input,
    ``OSError`` on unreadable paths."""
    if os.path.isdir(path):
        manifest_path = os.path.join(path, "manifest.json")
        records_path = os.path.join(path, "records.jsonl")
    elif path.endswith("manifest.json"):
        manifest_path = path
        records_path = os.path.join(os.path.dirname(path),
                                    "records.jsonl")
    else:
        records_path = path
        manifest_path = os.path.join(os.path.dirname(path),
                                     "manifest.json")
    manifest = {}
    if os.path.exists(manifest_path):
        with open(manifest_path, encoding="utf-8") as fh:
            manifest = json.load(fh)
        if not isinstance(manifest, dict):
            raise ValueError(
                f"{manifest_path}: manifest is not an object")
    records = []
    with open(records_path, encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError as exc:
                raise ValueError(
                    f"{records_path}:{lineno}: bad JSON ({exc})")
    return manifest, records


def _fmt_ts(ts, t0):
    try:
        return f"+{float(ts) - t0:8.3f}s"
    except (TypeError, ValueError):
        return " " * 10


def _job_for_fit(records, fit_id):
    """Job attribution (``job_id``/``tenant``/...) for ``fit_id``,
    read from the newest ring record carrying scheduler context —
    progress records and events both ride ``attrs`` (see
    :func:`brainiak_tpu.obs.progress.fit_context`); ``None`` when
    the fit was not a scheduled job."""
    found = None
    for rec in records:
        if rec.get("fit_id") != fit_id:
            continue
        attrs = rec.get("attrs") or {}
        if attrs.get("job_id"):
            found = attrs
    return found


def _describe(rec):
    kind = rec.get("kind")
    name = rec.get("name", "?")
    if kind == "progress":
        parts = [f"chunk {rec.get('chunk')}",
                 f"step {rec.get('step')}/{rec.get('n_iter', '?')}"]
        if rec.get("objective") is not None:
            parts.append(f"objective={rec['objective']:.6g}")
        return f"progress  {rec.get('estimator')}: " \
               + ", ".join(parts)
    if kind == "span":
        return f"span      {rec.get('path', name)} " \
               f"({rec.get('dur_s', 0):.4f}s)"
    if kind == "event":
        attrs = rec.get("attrs") or {}
        keys = ("estimator", "site", "step", "reason", "leaves",
                "slo", "replica", "error", "status", "job_id",
                "tenant")
        detail = ", ".join(f"{k}={attrs[k]}" for k in keys
                           if k in attrs)
        return f"event     {name}" + (f" [{detail}]" if detail
                                      else "")
    if kind == "metric":
        return f"metric    {name} = {rec.get('value')}"
    return f"{kind or '?':9s} {name}"


def render(manifest, records):
    """Human-readable postmortem text for a loaded snapshot."""
    lines = ["flight-recorder postmortem"]
    trigger = manifest.get("trigger", "unknown")
    ts = manifest.get("ts")
    when = time.strftime("%Y-%m-%d %H:%M:%S",
                         time.localtime(ts)) if ts else "?"
    lines.append(f"  trigger: {trigger}  at {when}")
    if manifest.get("fit_id"):
        lines.append(f"  fit_id: {manifest['fit_id']}")
        job = _job_for_fit(records, manifest["fit_id"])
        if job:
            # the snapshot's fit belongs to a scheduled job: name
            # the tenant + job so the on-call pages the right owner
            lines.append(
                f"  implicated job: tenant={job.get('tenant', '?')}"
                f"  job_id={job.get('job_id', '?')}")
    if manifest.get("trace_id"):
        lines.append(f"  trace_id: {manifest['trace_id']}")
    state = manifest.get("state") or {}
    for key in sorted(state):
        lines.append(f"  {key}: {state[key]}")
    lines.append(f"  ring: {len(records)} record(s)"
                 + (f" (capacity {manifest['capacity']})"
                    if manifest.get("capacity") else ""))

    # per-fit objective tails + failing chunk, from the ring's
    # progress stream (newest records win)
    fits = {}
    for rec in records:
        if rec.get("kind") != "progress":
            continue
        cur = fits.setdefault(rec.get("fit_id"), {
            "estimator": rec.get("estimator"),
            "objectives": [], "last": rec})
        cur["last"] = rec
        if rec.get("objective") is not None:
            cur["objectives"].append(
                (rec.get("step"), rec["objective"]))
    for fit_id, cur in fits.items():
        last = cur["last"]
        lines.append("")
        marker = "  <-- implicated" \
            if fit_id == manifest.get("fit_id") else ""
        attrs = last.get("attrs") or {}
        job = ""
        if attrs.get("job_id"):
            job = (f" (job {attrs['job_id']}, "
                   f"tenant {attrs.get('tenant', '?')})")
        lines.append(f"fit {fit_id} [{cur['estimator']}]{job}"
                     f"{marker}")
        lines.append(
            f"  last chunk: {last.get('chunk')}"
            f" (step {last.get('step')}/{last.get('n_iter', '?')},"
            f" rollbacks {last.get('rollbacks', 0)})")
        tail = cur["objectives"][-OBJECTIVE_TAIL:]
        if tail:
            lines.append("  objective tail: " + ", ".join(
                f"{v:.6g}@{s}" for s, v in tail))

    events = [r for r in records if r.get("kind") == "event"
              and r.get("name") in (
                  "divergence_precursor", "rollback",
                  "divergence_abort", "sanitizer", "fault",
                  "retry_exhausted", "slo_violation",
                  "replica_dead", "fit_finished")]
    if events:
        lines.append("")
        lines.append("incident events:")
        t0 = float(records[0].get("ts", 0.0)) if records else 0.0
        for rec in events:
            lines.append(f"  {_fmt_ts(rec.get('ts'), t0)}  "
                         + _describe(rec))

    lines.append("")
    lines.append(f"timeline (last {TIMELINE_TAIL} records):")
    t0 = float(records[0].get("ts", 0.0)) if records else 0.0
    for rec in records[-TIMELINE_TAIL:]:
        lines.append(f"  {_fmt_ts(rec.get('ts'), t0)}  "
                     + _describe(rec))
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m brainiak_tpu.obs postmortem",
        description="render a flight-recorder incident snapshot "
                    "(docs/observability.md)")
    parser.add_argument(
        "snapshot",
        help="snapshot directory written by the flight recorder "
             "(or its manifest.json / records.jsonl)")
    args = parser.parse_args(argv)
    try:
        manifest, records = load_snapshot(args.snapshot)
    except (OSError, ValueError) as exc:
        print(f"obs postmortem: {exc}", file=sys.stderr)
        return 1
    print(render(manifest, records))
    return 0


if __name__ == "__main__":  # pragma: no cover - module smoke entry
    sys.exit(main())
