"""Runtime sanitizer: the checkify lane of the jaxlint-IR tier.

``BRAINIAK_TPU_SANITIZE=1`` routes the repo's two hot dispatch
paths — :func:`~brainiak_tpu.resilience.guards.run_resilient_loop`
chunk programs and the serve engine's bucket programs — through
``jax.experimental.checkify`` with the NaN / division / out-of-bounds
error sets.  A tripped check surfaces as one typed ``sanitizer`` obs
event whose ``codes`` attribute cross-references the static JP3xx
rule family (:mod:`brainiak_tpu.analysis.ir`) auditing the same
program: the dynamic lane for what the IR pass proves statically.

Off (the default), every caller takes its original call path
untouched — zero extra syncs, zero extra records.  On, each checked
call pays one ``err.get()`` host read; the mode is a debugging lane,
not a serving configuration.

Not every chunk callable is checkifiable: ``run_resilient_loop``
accepts host-side chunk drivers (NumPy state juggling, checkpoint
IO) that cannot trace.  The first failed trace marks the site
unsanitizable (one ``sanitizer_skip`` event), and subsequent calls
run unwrapped — the sanitizer instruments pure chunks and stays out
of the way of impure ones.
"""

import os
import threading

from . import metrics, sink

__all__ = ["call_checked", "enabled", "reset"]

_ENV = "BRAINIAK_TPU_SANITIZE"

#: checkify error-set names the sanitizer enables (resolved lazily:
#: this module must import without jax).
_ERROR_SETS = ("float_checks", "index_checks", "div_checks")

#: What each dynamic check is the runtime half of: NaN/div trips are
#: the numeric-discipline lane (JP301's dtype/promotion audit traces
#: the same programs), OOB trips are the retrace/key-surface lane
#: (JP305 audits the shapes those indices were traced at).
_CHECK_CODES = ("JP301", "JP305")

_lock = threading.Lock()
_checked = {}        # id(fn) -> (fn, checked callable)
_unsanitizable = {}  # site -> first failure reason


def enabled():
    """Whether the sanitizer lane is on (``BRAINIAK_TPU_SANITIZE=1``)."""
    return os.environ.get(_ENV, "").strip() == "1"


def reset():
    """Drop memoized checked programs and skip markers (tests)."""
    with _lock:
        _checked.clear()
        _unsanitizable.clear()


def _errors():
    from jax.experimental import checkify

    sets = None
    for name in _ERROR_SETS:
        got = getattr(checkify, name, None)
        if got is None:
            continue
        sets = got if sets is None else sets | got
    return sets


def _checked_for(fn, static_argnums):
    """The memoized jitted-checkify wrapper for ``fn``."""
    import jax
    from jax.experimental import checkify

    key = (id(fn), static_argnums)
    with _lock:
        hit = _checked.get(key)
        if hit is not None and hit[0] is fn:
            return hit[1]
    # one jit per distinct (fn, static_argnums), memoized in
    # _checked above for process lifetime
    checked = jax.jit(  # jaxlint: disable=JX001
        checkify.checkify(fn, errors=_errors()),
        static_argnums=static_argnums)
    with _lock:
        _checked[key] = (fn, checked)
    return checked


def _emit(name, **attrs):
    if sink.enabled():
        sink.emit(sink.make_record("event", name, attrs=attrs))


def call_checked(fn, args, site, scope, codes=_CHECK_CODES,
                 static_argnums=()):
    """Run ``fn(*args)`` under checkify; returns ``(error, out)``.

    ``error`` is the checkify message string when a NaN / division /
    out-of-bounds check tripped (also emitted as a typed
    ``sanitizer`` obs event carrying ``site``, ``scope``, and the
    cross-referenced static rule ``codes``), else None.
    ``static_argnums`` marks positions that must stay concrete under
    the trace (the resilient loop's ``step``/``n_steps``, which
    chunk drivers use in Python control flow).  A function that
    cannot trace (host-side chunk drivers) is marked unsanitizable
    on first failure and runs unwrapped from then on, returning
    ``(None, out)`` like the disabled path.
    """
    reason = _unsanitizable.get(site)
    if reason is not None:
        return None, fn(*args)
    try:
        err, out = _checked_for(fn, tuple(static_argnums))(*args)
    except Exception as exc:
        # tracing failed (host code in the chunk) — remember, note
        # once, and fall back to the unwrapped call so the sanitizer
        # never changes what runs
        with _lock:
            _unsanitizable[site] = str(exc)
        _emit("sanitizer_skip", site=site, scope=scope,
              reason=f"{type(exc).__name__}: {exc}")
        return None, fn(*args)
    message = err.get()  # the lane's one deliberate host sync
    if message:
        _emit("sanitizer", site=site, scope=scope, error=message,
              codes=list(codes))
        metrics.counter(
            "sanitizer_errors_total",
            help="checkify errors caught by the sanitizer "
                 "lane").inc(site=site, scope=scope)
        from . import flight
        flight.dump("sanitizer",
                    state={"site": site, "scope": scope,
                           "error": message.splitlines()[0].strip(),
                           "codes": list(codes)})
        return message, out
    return None, out
