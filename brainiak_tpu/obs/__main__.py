"""``python -m brainiak_tpu.obs`` — the obs CLI (report command)."""

import sys

from .report import main

if __name__ == "__main__":
    sys.exit(main())
