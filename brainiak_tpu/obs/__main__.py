"""``python -m brainiak_tpu.obs`` — the obs CLI.

Subcommands: ``report`` (aggregate summaries,
:mod:`~brainiak_tpu.obs.report`), ``export`` (Chrome-trace timeline,
:mod:`~brainiak_tpu.obs.export`), ``regress`` (bench regression
gate, :mod:`~brainiak_tpu.obs.regress`), ``postmortem`` (render a
flight-recorder incident snapshot,
:mod:`~brainiak_tpu.obs.postmortem`), ``watch`` (live fit-progress
terminal view, :mod:`~brainiak_tpu.obs.watch`).
"""

import sys


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    command = argv[0] if argv else None
    if command == "export":
        from .export import main as sub
        return sub(argv[1:])
    if command == "regress":
        from .regress import main as sub
        return sub(argv[1:])
    if command == "postmortem":
        from .postmortem import main as sub
        return sub(argv[1:])
    if command == "watch":
        from .watch import main as sub
        return sub(argv[1:])
    # report.main owns the legacy parser (including the error message
    # for an unknown/missing subcommand)
    from .report import main as sub
    return sub(argv)


if __name__ == "__main__":
    sys.exit(main())
