"""Mergeable bounded-relative-error quantile sketches.

The live telemetry plane needs percentiles three ways the PR 8
sorted-deque could not deliver: in O(1) memory for a week-long
service process, in O(1) time under the service tick lock (the
deque sort was O(n log n) per ``summary()`` call), and **mergeable**
— a router combining N replicas' latency distributions must be able
to compute the pooled p99 from N compact summaries without shipping
raw samples (raw percentiles are famously non-mergeable: the mean of
two p99s is not the pooled p99).

:class:`QuantileSketch` is a DDSketch-style log-bucketed sketch
(Masson, Rim & Lee, VLDB 2019): values are mapped to geometrically
sized buckets ``gamma^k`` with ``gamma = (1 + alpha) / (1 - alpha)``,
so any reported quantile is within **relative** error ``alpha`` of an
exact rank statistic — ``|q_est - q_true| <= alpha * q_true`` — at
any scale from microseconds to hours, with no prior min/max hints.

Guarantees (tested in ``tests/obs/test_sketch.py``):

- ``observe`` is O(1) (a log, a dict increment);
- memory is bounded by ``max_buckets`` (oldest = smallest buckets
  collapse together, preserving the error bound for the upper
  quantiles serving cares about);
- ``merge`` is **exact**: ``a.merge(b)`` yields the same sketch as
  observing both streams into one (bucket-wise addition), so pooled
  replica quantiles carry the same ``alpha`` bound as local ones;
- ``to_dict``/``from_dict`` round-trip through JSON, so sketches
  travel in records/summaries between processes.

Thread-safety: none here, by design — every holder already
synchronizes (``ServeService`` under its tick lock,
:class:`~brainiak_tpu.obs.metrics.Histogram` under its metric lock);
an internal lock would double-lock the hot path.
"""

import math

__all__ = ["DEFAULT_MAX_BUCKETS", "DEFAULT_RELATIVE_ACCURACY",
           "QuantileSketch"]

#: Default relative accuracy ``alpha``: a reported p99 of 100 ms is
#: within +-1 ms of the exact rank statistic.
DEFAULT_RELATIVE_ACCURACY = 0.01

#: Default bucket-count bound.  At alpha=0.01 (gamma~1.0202) 2048
#: buckets span ~17 orders of magnitude — microseconds to weeks —
#: before any collapse happens.
DEFAULT_MAX_BUCKETS = 2048


class QuantileSketch:
    """DDSketch-style mergeable quantile summary.

    Parameters
    ----------
    relative_accuracy : float
        The ``alpha`` bound: quantile answers are within
        ``alpha * true_value`` of exact.  Must be in (0, 1).
    max_buckets : int
        Memory bound: when the positive store would exceed this many
        buckets, the smallest buckets collapse into one.  Upper
        quantiles keep their error bound; collapsed low quantiles
        degrade toward the collapse boundary (the right trade for
        latency telemetry, where the tail is the product).
    """

    __slots__ = ("relative_accuracy", "max_buckets", "_gamma",
                 "_log_gamma", "_buckets", "_neg_buckets",
                 "_zero_count", "count", "sum", "min", "max")

    def __init__(self, relative_accuracy=DEFAULT_RELATIVE_ACCURACY,
                 max_buckets=DEFAULT_MAX_BUCKETS):
        if not 0.0 < relative_accuracy < 1.0:
            raise ValueError(
                "relative_accuracy must be in (0, 1), got "
                f"{relative_accuracy}")
        if max_buckets < 2:
            raise ValueError(
                f"max_buckets must be >= 2, got {max_buckets}")
        self.relative_accuracy = float(relative_accuracy)
        self.max_buckets = int(max_buckets)
        self._gamma = (1.0 + relative_accuracy) \
            / (1.0 - relative_accuracy)
        self._log_gamma = math.log(self._gamma)
        self._buckets = {}      # key -> count (positive values)
        self._neg_buckets = {}  # key -> count (negative magnitudes)
        self._zero_count = 0
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    # -- ingest -------------------------------------------------------

    def _key(self, magnitude):
        # ceil(log_gamma(x)): every value in (gamma^(k-1), gamma^k]
        # shares bucket k, whose representative midpoint is within
        # alpha relative error of any member
        return int(math.ceil(math.log(magnitude) / self._log_gamma))

    def observe(self, value):
        """Add one observation (O(1)); non-finite values raise."""
        value = float(value)
        if not math.isfinite(value):
            raise ValueError(
                f"sketch observations must be finite, got {value}")
        self.count += 1
        self.sum += value
        self.min = value if self.min is None \
            else min(self.min, value)
        self.max = value if self.max is None \
            else max(self.max, value)
        if value == 0.0:
            self._zero_count += 1
            return
        store = self._buckets if value > 0 else self._neg_buckets
        key = self._key(abs(value))
        store[key] = store.get(key, 0) + 1
        if len(store) > self.max_buckets:
            self._collapse(store)

    def _collapse(self, store):
        """Fold the smallest buckets together until the bound holds
        (DDSketch's collapsing strategy: the tail quantiles keep
        their guarantee; the collapsed low end reports the collapse
        boundary)."""
        keys = sorted(store)
        while len(store) > self.max_buckets:
            lowest = keys.pop(0)
            store[keys[0]] = store.get(keys[0], 0) \
                + store.pop(lowest)

    # -- query --------------------------------------------------------

    def _bucket_value(self, key):
        # midpoint of (gamma^(k-1), gamma^k] in the geometric sense:
        # 2*gamma^k/(gamma+1) keeps relative error <= alpha for every
        # member of the bucket
        return 2.0 * math.pow(self._gamma, key) / (self._gamma + 1.0)

    def quantile(self, q):
        """The ``q``-quantile (q in [0, 1]) within relative error
        ``relative_accuracy``; None on an empty sketch."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        if self.count == 0:
            return None
        # nearest-rank (0-based, rounded) — the same convention the
        # serve summary's sorted-list percentile used, so the sketch
        # swap-in is sample-for-sample comparable at small n too
        rank = int(round(q * (self.count - 1)))
        seen = 0
        # ascending value order: negatives (largest magnitude first),
        # zeros, positives
        for key in sorted(self._neg_buckets, reverse=True):
            seen += self._neg_buckets[key]
            if seen > rank:
                return -self._bucket_value(key)
        seen += self._zero_count
        if seen > rank:
            return 0.0
        for key in sorted(self._buckets):
            seen += self._buckets[key]
            if seen > rank:
                return self._bucket_value(key)
        # numerical edge: q == 1.0 with float rank round-off
        return self.max

    def quantiles(self, qs):
        """[quantile(q) for q in qs] in one pass-friendly call."""
        return [self.quantile(q) for q in qs]

    # -- merge / serialization ---------------------------------------

    def merge(self, other):
        """Fold ``other`` into this sketch **exactly** (bucket-wise
        addition): the result is indistinguishable from having
        observed both streams locally, so pooled replica quantiles
        keep the single-sketch error bound.  The accuracies must
        match (merging across gammas has no exact form).  Returns
        self."""
        if not isinstance(other, QuantileSketch):
            raise TypeError(
                f"cannot merge {type(other).__name__} into a "
                "QuantileSketch")
        if other.relative_accuracy != self.relative_accuracy:
            raise ValueError(
                "cannot merge sketches with different relative "
                f"accuracies ({self.relative_accuracy} vs "
                f"{other.relative_accuracy})")
        for key, n in other._buckets.items():
            self._buckets[key] = self._buckets.get(key, 0) + n
        for key, n in other._neg_buckets.items():
            self._neg_buckets[key] = \
                self._neg_buckets.get(key, 0) + n
        self._zero_count += other._zero_count
        self.count += other.count
        self.sum += other.sum
        for attr in ("min", "max"):
            theirs = getattr(other, attr)
            if theirs is None:
                continue
            mine = getattr(self, attr)
            pick = theirs if mine is None else (
                min(mine, theirs) if attr == "min"
                else max(mine, theirs))
            setattr(self, attr, pick)
        if len(self._buckets) > self.max_buckets:
            self._collapse(self._buckets)
        if len(self._neg_buckets) > self.max_buckets:
            self._collapse(self._neg_buckets)
        return self

    def to_dict(self):
        """JSON-serializable form (:meth:`from_dict` round-trips):
        the cross-process wire format replica summaries travel in."""
        return {
            "relative_accuracy": self.relative_accuracy,
            "max_buckets": self.max_buckets,
            "buckets": {str(k): v
                        for k, v in sorted(self._buckets.items())},
            "neg_buckets": {
                str(k): v
                for k, v in sorted(self._neg_buckets.items())},
            "zero_count": self._zero_count,
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
        }

    @classmethod
    def from_dict(cls, data):
        """Rebuild a sketch from :meth:`to_dict` output."""
        sketch = cls(
            relative_accuracy=data["relative_accuracy"],
            max_buckets=data.get("max_buckets",
                                 DEFAULT_MAX_BUCKETS))
        sketch._buckets = {int(k): int(v)
                           for k, v in data["buckets"].items()}
        sketch._neg_buckets = {
            int(k): int(v)
            for k, v in data.get("neg_buckets", {}).items()}
        sketch._zero_count = int(data.get("zero_count", 0))
        sketch.count = int(data["count"])
        sketch.sum = float(data["sum"])
        sketch.min = data.get("min")
        sketch.max = data.get("max")
        return sketch

    def __repr__(self):
        return (f"QuantileSketch(count={self.count}, "
                f"alpha={self.relative_accuracy}, "
                f"buckets={len(self._buckets)})")
