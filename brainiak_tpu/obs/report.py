"""Aggregate obs JSONL traces into per-stage / per-estimator summaries.

``python -m brainiak_tpu.obs report [PATH ...]`` reads one or more
JSONL files (or directories of ``*.jsonl``; default: the directory in
``BRAINIAK_TPU_OBS_DIR``), validates every record against the obs
schema (:func:`brainiak_tpu.obs.sink.validate_record` — any violation
fails the run, which is what the ``obs`` gate of
``tools/run_checks.py`` relies on), and renders:

- **spans** grouped by path (and ``estimator`` attr when present):
  count / total / mean / max seconds;
- **events** grouped by name: count;
- **metrics** aggregated by (name, labels): counters sum their
  increments, gauges keep the last set value, histograms summarize
  count/sum/min/max/mean;
- **cost** profiles (schema v2, :mod:`brainiak_tpu.obs.profile`): one
  row per captured program signature, joined to the span durations
  named by the record's ``span``/``estimator`` hints to derive
  achieved FLOP/s and — when the record carries a platform peak — the
  roofline ratio (achieved / peak; 1.0 would be a compute-bound
  program running at the hardware ceiling);
- **fits** (schema v4, :mod:`brainiak_tpu.obs.progress`): one row per
  ``fit_id`` — estimator, chunks done, last step / iteration budget,
  rollbacks, ETA at the last record, and a converged / diverged /
  interrupted verdict (diverged when the trace carries that fit's
  ``divergence_abort`` event; converged when its last record reached
  the budget or a plateau).

``--top N`` additionally lists the N slowest individual spans per
estimator, so a trace is triageable without exporting to a viewer.
``--format=json`` prints the same structure as one JSON document.
This module imports neither jax nor numpy — reports run anywhere.
"""

import argparse
import glob
import json
import os
import sys

from .sink import OBS_DIR_ENV, validate_record

__all__ = ["aggregate", "git_commit_stamp", "iter_jsonl_paths",
           "load_records", "main", "render_text", "top_spans",
           "validate_bench_record"]

#: Keys a bench.py result record must carry (satellite: BENCH_*.json
#: drift fails CI instead of confusing the next round).
BENCH_REQUIRED = ("metric", "value", "unit", "vs_baseline", "tier")
BENCH_STAGE_KEYS = ("data_gen_s", "warm_s", "steady_s")

#: Version ``bench.py`` stamps into its JSON line as
#: ``schema_version`` (v2 added the stamp itself plus ``git_commit``,
#: so ``regress.py`` can pin a record to the code that produced it).
#: Absent on pre-v2 history; when present it must be an int no newer
#: than this.
BENCH_SCHEMA_VERSION = 2


def validate_bench_record(rec):
    """Schema check for the bench JSON line; returns error strings.

    Requires the headline keys (metric/value/unit/vs_baseline/tier)
    and, when present, a ``stages`` dict holding the per-stage time
    breakdown (data-gen / compile+warm / steady-state seconds), an
    int ``schema_version`` (<= :data:`BENCH_SCHEMA_VERSION`) and a
    string ``git_commit`` — the provenance stamps ``regress.py``
    trusts.  An optional ``direction`` must be ``higher_is_better``
    or ``lower_is_better`` (how ``regress.py`` orients the gate for
    latency/padding metrics).
    """
    errors = []
    if not isinstance(rec, dict):
        return ["bench record is not an object"]
    for key in BENCH_REQUIRED:
        if key not in rec:
            errors.append(f"missing key {key!r}")
    if "metric" in rec and not isinstance(rec["metric"], str):
        errors.append("metric is not a string")
    for key in ("value", "vs_baseline"):
        if key in rec and (not isinstance(rec[key], (int, float))
                           or isinstance(rec[key], bool)):
            errors.append(f"{key} is not a number")
    if "unit" in rec and not isinstance(rec["unit"], str):
        errors.append("unit is not a string")
    if "tier" in rec and not isinstance(rec["tier"], str):
        errors.append("tier is not a string")
    sv = rec.get("schema_version")
    if sv is not None:
        if not isinstance(sv, int) or isinstance(sv, bool):
            errors.append(f"schema_version={sv!r} (expected an int)")
        elif sv > BENCH_SCHEMA_VERSION:
            errors.append(
                f"schema_version={sv} is newer than supported "
                f"({BENCH_SCHEMA_VERSION})")
    commit = rec.get("git_commit")
    if commit is not None and (not isinstance(commit, str)
                               or not commit):
        errors.append(f"git_commit={commit!r} (expected a non-empty "
                      "string)")
    direction = rec.get("direction")
    if direction is not None and direction not in (
            "higher_is_better", "lower_is_better"):
        errors.append(
            f"direction={direction!r} (expected higher_is_better "
            "or lower_is_better)")
    stages = rec.get("stages")
    if stages is not None:
        if not isinstance(stages, dict):
            errors.append("stages is not an object")
        else:
            for key in BENCH_STAGE_KEYS:
                val = stages.get(key)
                if not isinstance(val, (int, float)) \
                        or isinstance(val, bool):
                    errors.append(
                        f"stages.{key}={val!r} (expected a number)")
    return errors


def git_commit_stamp(path=None):
    """Short commit hash of the checkout containing ``path``
    (default: this package's own checkout, so it works from any
    cwd), or None — the provenance stamp bench records carry so
    :mod:`~brainiak_tpu.obs.regress` can pin a record to the code
    that produced it.  Shared by ``bench.py`` and the serve CLI
    (one implementation, consistently-stamped records)."""
    import subprocess
    if path is None:
        path = os.path.dirname(os.path.abspath(__file__))
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=path,
            capture_output=True, text=True, timeout=10)
    except (OSError, subprocess.TimeoutExpired):
        return None
    commit = out.stdout.strip()
    return commit if out.returncode == 0 and commit else None


def iter_jsonl_paths(paths):
    """Expand files/directories into a sorted list of .jsonl files."""
    out = []
    for path in paths:
        if os.path.isdir(path):
            out.extend(sorted(
                glob.glob(os.path.join(path, "*.jsonl"))))
        else:
            out.append(path)
    return out


def load_records(paths):
    """Parse + validate records; returns ``(records, errors)`` where
    errors are ``"file:line: problem"`` strings."""
    records = []
    errors = []
    for path in iter_jsonl_paths(paths):
        try:
            fh = open(path, encoding="utf-8")
        except OSError as exc:
            errors.append(f"{path}: unreadable ({exc})")
            continue
        with fh:
            for lineno, line in enumerate(fh, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError as exc:
                    errors.append(f"{path}:{lineno}: bad JSON ({exc})")
                    continue
                bad = validate_record(rec)
                if bad:
                    errors.append(
                        f"{path}:{lineno}: {'; '.join(bad)}")
                    continue
                records.append(rec)
    return records, errors


def _labels_id(labels):
    return ",".join(f"{k}={v}" for k, v in sorted(labels.items()))


_COST_KEYS = ("site", "level", "backend", "flops", "bytes_accessed",
              "transcendentals", "compile_s", "hlo_bytes",
              "hlo_lines", "peak_flops", "span", "estimator",
              "unavailable")


def _roofline(cost_rows, span_rows):
    """Join cost rows to span aggregates through their ``span`` /
    ``estimator`` hints, deriving achieved FLOP/s and the roofline
    ratio in place.

    The joined span's count approximates executions of the profiled
    program (the span may include slicing/host overhead, so the
    achieved number is a floor); rows without a hint, a match, or a
    FLOPs figure simply stay unannotated.  When SEVERAL cost rows of
    one site share a join target (a checkpointed fit compiles both a
    full and a remainder chunk program, and their executions cannot
    be apportioned between the shared ``fit_chunk`` spans), the whole
    group stays unannotated too — charging each program's FLOPs to
    every span would overstate throughput and break the floor
    semantics documented in docs/performance.md.
    """
    # group on every hinted row (not just those with FLOPs): a
    # degraded row sharing the join target still makes the span
    # totals unapportionable, for timing and throughput alike
    joins = {}
    for row in cost_rows:
        if row.get("span"):
            key = (row["site"], row["span"], row.get("estimator"))
            joins[key] = joins.get(key, 0) + 1
    for row in cost_rows:
        hint = row.get("span")
        if not hint:
            continue
        if joins[(row["site"], hint, row.get("estimator"))] > 1:
            continue
        count = 0
        total_s = 0.0
        for srow in span_rows:
            if srow["path"].split("/")[-1] != hint:
                continue
            if row.get("estimator") and \
                    srow["estimator"] != row["estimator"]:
                continue
            count += srow["count"]
            total_s += srow["total_s"]
        if not count or total_s <= 0.0:
            continue
        # span-only timing is attached even without a FLOPs figure:
        # a Pallas-lowered program degrades to an ``unavailable``
        # cost record, and its site must still render with measured
        # wall time rather than dropping out of the section
        row["span_count"] = count
        row["span_total_s"] = total_s
        flops = row.get("flops")
        if not flops:
            continue
        achieved = flops * count / total_s
        row["achieved_flops_per_s"] = achieved
        peak = row.get("peak_flops")
        if peak:
            row["roofline_ratio"] = achieved / peak


def top_spans(records, n):
    """The ``n`` slowest individual span records per estimator.

    Returns ``[{"estimator", "spans": [{path, dur_s, ts, rank}]}]``
    sorted by each group's slowest span descending; spans without an
    ``estimator`` attr group under ``None``.
    """
    groups = {}
    for rec in records:
        if rec["kind"] != "span":
            continue
        attrs = rec.get("attrs") or {}
        est = attrs.get("estimator")
        groups.setdefault(
            str(est) if est is not None else None, []).append(rec)
    out = []
    for est, recs in groups.items():
        recs.sort(key=lambda r: -float(r["dur_s"]))
        out.append({
            "estimator": est,
            "spans": [{"path": r["path"],
                       "dur_s": float(r["dur_s"]),
                       "ts": float(r["ts"]),
                       "rank": int(r["rank"])}
                      for r in recs[:n]],
        })
    out.sort(key=lambda g: -g["spans"][0]["dur_s"])
    return out


def aggregate(records):
    """Summary dict over validated records (see module docstring)."""
    spans = {}
    events = {}
    metrics = {}
    costs = []
    fits = {}
    aborted = set()   # fit_ids with a divergence_abort event
    precursor = set()  # fit_ids with a divergence_precursor event
    finished = {}     # fit_id -> fit_finished status attr
    dropped = 0
    for rec in records:
        kind = rec["kind"]
        if kind == "event" and rec.get("fit_id"):
            if rec["name"] == "divergence_abort":
                aborted.add(rec["fit_id"])
            elif rec["name"] == "divergence_precursor":
                precursor.add(rec["fit_id"])
            elif rec["name"] == "fit_finished":
                status = (rec.get("attrs") or {}).get("status")
                if isinstance(status, str):
                    finished[rec["fit_id"]] = status
        if kind == "event" and rec["name"] == "obs_dropped":
            # the truncated sink's close-time drop count: surface it
            # as a headline so a capped trace reads as incomplete,
            # not quiet (summed across rank files)
            attrs = rec.get("attrs") or {}
            try:
                dropped += int(attrs.get("dropped_total", 0))
            except (TypeError, ValueError):
                pass
        if kind == "cost":
            row = {k: rec[k] for k in _COST_KEYS if k in rec}
            row["rank"] = rec["rank"]
            costs.append(row)
        elif kind == "span":
            attrs = rec.get("attrs") or {}
            key = (rec["path"], str(attrs.get("estimator", "")))
            cur = spans.setdefault(
                key, {"path": key[0], "estimator": key[1] or None,
                      "count": 0, "total_s": 0.0, "max_s": 0.0})
            cur["count"] += 1
            cur["total_s"] += float(rec["dur_s"])
            cur["max_s"] = max(cur["max_s"], float(rec["dur_s"]))
        elif kind == "event":
            events[rec["name"]] = events.get(rec["name"], 0) + 1
        elif kind == "progress":
            cur = fits.setdefault(rec["fit_id"], {
                "fit_id": rec["fit_id"],
                "estimator": rec["estimator"],
                "chunks": 0, "step": 0, "n_iter": None,
                "ratio": 0.0, "rollbacks": 0, "objective": None,
                "eta_s": None, "plateaued": False,
                "_last_ts": None})
            cur["chunks"] = max(cur["chunks"], int(rec["chunk"]))
            try:
                cur["rollbacks"] = max(cur["rollbacks"],
                                       int(rec.get("rollbacks", 0)))
            except (TypeError, ValueError):
                pass
            # fields "at the last record" follow the record
            # timestamp, not file-read order (multi-rank traces)
            ts = float(rec["ts"])
            if cur["_last_ts"] is None or ts >= cur["_last_ts"]:
                cur["_last_ts"] = ts
                cur["step"] = int(rec["step"])
                cur["ratio"] = float(rec["ratio"])
                if rec.get("n_iter") is not None:
                    cur["n_iter"] = int(rec["n_iter"])
                cur["objective"] = rec.get("objective")
                cur["eta_s"] = rec.get("eta_s")
                cur["plateaued"] = bool(rec.get("plateaued", False))
        else:  # metric
            labels = rec.get("labels") or {}
            key = (rec["name"], rec["mtype"], _labels_id(labels))
            cur = metrics.get(key)
            if cur is None:
                cur = metrics[key] = {
                    "name": rec["name"], "mtype": rec["mtype"],
                    "labels": labels, "unit": rec.get("unit"),
                    "count": 0, "sum": 0.0, "min": None,
                    "max": None, "last": None, "_last_ts": None}
            value = float(rec["value"])
            cur["count"] += 1
            cur["sum"] += value
            cur["min"] = value if cur["min"] is None \
                else min(cur["min"], value)
            cur["max"] = value if cur["max"] is None \
                else max(cur["max"], value)
            # "last" is by record timestamp, not file-read order —
            # multi-rank traces are read in filename order, which is
            # unrelated to wall time
            ts = float(rec["ts"])
            if cur["_last_ts"] is None or ts >= cur["_last_ts"]:
                cur["last"] = value
                cur["_last_ts"] = ts
    span_rows = []
    for cur in spans.values():
        cur["mean_s"] = cur["total_s"] / cur["count"]
        span_rows.append(cur)
    span_rows.sort(key=lambda r: -r["total_s"])
    metric_rows = []
    for cur in metrics.values():
        del cur["_last_ts"]
        if cur["mtype"] == "counter":
            cur["value"] = cur["sum"]
        elif cur["mtype"] == "gauge":
            cur["value"] = cur["last"]
        else:
            cur["value"] = {"count": cur["count"], "sum": cur["sum"],
                            "min": cur["min"], "max": cur["max"],
                            "mean": cur["sum"] / cur["count"]}
        metric_rows.append(cur)
    metric_rows.sort(key=lambda r: (r["name"],
                                    _labels_id(r["labels"])))
    costs.sort(key=lambda r: (r["site"], r.get("level") or ""))
    _roofline(costs, span_rows)
    fit_rows = []
    for cur in fits.values():
        del cur["_last_ts"]
        if cur["fit_id"] in aborted \
                or finished.get(cur["fit_id"]) == "diverged":
            cur["verdict"] = "diverged"
        elif cur["fit_id"] in finished:
            cur["verdict"] = "converged"
        elif cur["ratio"] >= 1.0 or cur["plateaued"]:
            cur["verdict"] = "converged"
        elif cur["fit_id"] in precursor:
            cur["verdict"] = "diverging"
        else:
            cur["verdict"] = "interrupted"
        fit_rows.append(cur)
    fit_rows.sort(key=lambda r: (r["estimator"], r["fit_id"]))
    return {
        "n_records": len(records),
        "dropped_records": dropped,
        "spans": span_rows,
        "events": [{"name": name, "count": count}
                   for name, count in sorted(events.items())],
        "metrics": metric_rows,
        "cost": costs,
        "fits": fit_rows,
    }


def _fmt_s(value):
    return f"{value:9.4f}"


def _fmt_quantity(value):
    return "-" if value is None else f"{value:.4g}"


def render_text(summary):
    """Human-readable tables for the aggregate summary."""
    lines = [f"records: {summary['n_records']}"]
    if summary.get("dropped_records"):
        lines.append(
            f"WARNING: {summary['dropped_records']} record(s) "
            "dropped after the BRAINIAK_TPU_OBS_MAX_MB cap — this "
            "trace is incomplete")
    if summary.get("top_spans"):
        lines.append("")
        lines.append(f"slowest spans (top {summary['top_n']} per "
                     "estimator):")
        for group in summary["top_spans"]:
            label = group["estimator"] or "(no estimator)"
            lines.append(f"  {label}:")
            for row in group["spans"]:
                lines.append(f"    {_fmt_s(row['dur_s'])}s  "
                             f"rank {row['rank']}  {row['path']}")
    if summary["spans"]:
        lines.append("")
        lines.append("spans (by path):")
        lines.append(f"  {'count':>6} {'total_s':>9} {'mean_s':>9} "
                     f"{'max_s':>9}  path")
        for row in summary["spans"]:
            label = row["path"]
            if row["estimator"]:
                label += f"  [{row['estimator']}]"
            lines.append(
                f"  {row['count']:>6} {_fmt_s(row['total_s'])} "
                f"{_fmt_s(row['mean_s'])} {_fmt_s(row['max_s'])}  "
                f"{label}")
    if summary["events"]:
        lines.append("")
        lines.append("events:")
        for row in summary["events"]:
            lines.append(f"  {row['count']:>6}  {row['name']}")
    if summary.get("fits"):
        lines.append("")
        lines.append("fits:")
        for row in summary["fits"]:
            budget = row["n_iter"] if row["n_iter"] is not None \
                else "?"
            parts = [f"chunks={row['chunks']}",
                     f"step={row['step']}/{budget}",
                     f"rollbacks={row['rollbacks']}"]
            if row["objective"] is not None:
                parts.append(
                    f"objective={_fmt_quantity(row['objective'])}")
            if row["eta_s"] is not None:
                parts.append(f"eta={row['eta_s']:.1f}s")
            lines.append(
                f"  {row['fit_id']}  [{row['estimator']}] "
                + " ".join(parts) + f"  -> {row['verdict']}")
    if summary.get("cost"):
        lines.append("")
        lines.append("cost profiles:")
        for row in summary["cost"]:
            parts = [f"flops={_fmt_quantity(row.get('flops'))}",
                     f"bytes={_fmt_quantity(row.get('bytes_accessed'))}"]
            if row.get("compile_s") is not None:
                parts.append(f"compile_s={row['compile_s']:.3f}")
            if row.get("achieved_flops_per_s") is not None:
                parts.append(
                    "achieved="
                    f"{row['achieved_flops_per_s'] / 1e9:.3g} GFLOP/s")
            if row.get("roofline_ratio") is not None:
                parts.append(
                    f"roofline={row['roofline_ratio']:.2%}")
            if row.get("unavailable"):
                parts.append(f"unavailable={row['unavailable']}")
                if row.get("span_total_s") is not None:
                    # span-only timing for sites whose cost analysis
                    # degraded (Pallas-lowered programs)
                    parts.append(
                        f"span={row['span_total_s']:.4f}s"
                        f"/{row['span_count']}x")
            lines.append(f"  {row['site']} "
                         f"[{row.get('level') or '?'}] "
                         + " ".join(parts))
    if summary["metrics"]:
        lines.append("")
        lines.append("metrics:")
        for row in summary["metrics"]:
            label = row["name"]
            if row["labels"]:
                label += "{" + _labels_id(row["labels"]) + "}"
            value = row["value"]
            if isinstance(value, dict):
                value = (f"count={value['count']} "
                         f"sum={value['sum']:.4g} "
                         f"mean={value['mean']:.4g} "
                         f"min={value['min']:.4g} "
                         f"max={value['max']:.4g}")
            else:
                value = f"{value:.6g}"
            unit = f" {row['unit']}" if row["unit"] else ""
            lines.append(f"  {label} = {value}{unit} "
                         f"[{row['mtype']}]")
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m brainiak_tpu.obs",
        description="obs trace tools (docs/observability.md); the "
                    "export and regress subcommands live in "
                    "brainiak_tpu.obs.export / .regress")
    sub = parser.add_subparsers(dest="command", required=True)
    rep = sub.add_parser(
        "report", help="aggregate JSONL traces into a summary")
    rep.add_argument(
        "paths", nargs="*", metavar="PATH",
        help="JSONL files or directories of *.jsonl "
             f"(default: ${OBS_DIR_ENV})")
    rep.add_argument("--format", choices=("text", "json"),
                     default="text")
    rep.add_argument(
        "--top", type=int, default=0, metavar="N",
        help="also list the N slowest individual spans per estimator")
    args = parser.parse_args(argv)

    paths = args.paths
    if not paths:
        env_dir = os.environ.get(OBS_DIR_ENV)
        if not env_dir:
            parser.error(
                f"no PATH given and ${OBS_DIR_ENV} is not set")
        paths = [env_dir]
    files = iter_jsonl_paths(paths)
    if not files:
        print(f"obs report: no .jsonl files under {paths}",
              file=sys.stderr)
        return 1
    # pass the expanded file list (not `paths`): one glob, and the
    # emptiness check above cannot disagree with what gets loaded
    records, errors = load_records(files)
    for err in errors:
        print(f"obs report: schema violation: {err}",
              file=sys.stderr)
    summary = aggregate(records)
    if args.top > 0:
        summary["top_n"] = args.top
        summary["top_spans"] = top_spans(records, args.top)
    if args.format == "json":
        summary["schema_errors"] = errors
        print(json.dumps(summary, indent=2))
    else:
        print(render_text(summary))
        if errors:
            print(f"obs report: {len(errors)} schema violation(s)",
                  file=sys.stderr)
    return 1 if errors else 0


if __name__ == "__main__":  # pragma: no cover - module smoke entry
    sys.exit(main())
