"""Typed metric registry: counters, gauges, histograms with labels.

Prometheus-shaped but dependency-free: a metric has a name, an
optional unit, and per-label-set values; updates are thread-safe and
always reflected in the in-process registry (:func:`collect`), and —
when obs is enabled — every update additionally emits a ``metric``
record to the sinks, so the JSONL trace carries the raw increments /
sets / observations for offline aggregation by the report CLI.

Naming convention (followed by the framework's built-in metrics):
``*_total`` for counters (``fit_steps_total{estimator=SRM}``,
``retrace_total{site=...}``, ``rollback_total``), ``*_seconds`` for
time histograms (``checkpoint_seconds``).
"""

import threading

from . import sink
from .sketch import QuantileSketch

__all__ = [
    "HISTOGRAM_QUANTILES",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "collect",
    "counter",
    "default_registry",
    "gauge",
    "histogram",
    "reset",
]

#: Quantiles histograms surface in :func:`collect` summaries and the
#: ``/metrics`` exposition (:mod:`brainiak_tpu.obs.http`).
HISTOGRAM_QUANTILES = (0.5, 0.9, 0.99)


def _label_key(labels):
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class _Metric:
    """Base: name, unit, per-label-set values behind one lock."""

    mtype = None

    def __init__(self, name, help="", unit=None):
        self.name = name
        self.help = help
        self.unit = unit
        self._values = {}
        self._lock = threading.Lock()

    def _emit(self, value, labels):
        if sink.enabled():
            sink.emit(sink.make_record(
                "metric", self.name, mtype=self.mtype,
                value=float(value),
                labels={k: str(v) for k, v in labels.items()} or None,
                unit=self.unit))

    def labelsets(self):
        with self._lock:
            return [dict(key) for key in self._values]

    def samples(self):
        """[(labels dict, value)] — histograms yield summary dicts."""
        with self._lock:
            return [(dict(key), value if not isinstance(value, dict)
                     else dict(value))
                    for key, value in self._values.items()]


class Counter(_Metric):
    """Monotonically increasing count; emitted records carry the
    increment (the report CLI sums them)."""

    mtype = "counter"

    def inc(self, amount=1, **labels):
        if amount < 0:
            raise ValueError(
                f"counter {self.name} increment must be >= 0, "
                f"got {amount}")
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) \
                + float(amount)
        self._emit(amount, labels)

    def value(self, **labels):
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)


class Gauge(_Metric):
    """Point-in-time value; emitted records carry the set value (the
    report CLI keeps the last)."""

    mtype = "gauge"

    def set(self, value, **labels):
        key = _label_key(labels)
        with self._lock:
            self._values[key] = float(value)
        self._emit(value, labels)

    def value(self, **labels):
        with self._lock:
            return self._values.get(_label_key(labels))


class Histogram(_Metric):
    """Streaming summary (count/sum/min/max + sketch quantiles) per
    label set; emitted records carry each observation.

    Every label set is additionally backed by a mergeable
    :class:`~brainiak_tpu.obs.sketch.QuantileSketch`, so
    :meth:`summary`/:func:`collect` report real p50/p90/p99 values
    (:data:`HISTOGRAM_QUANTILES`, bounded relative error) instead of
    only the min/max envelope, and :meth:`sketch` hands a **copy**
    out for cross-replica merging."""

    mtype = "histogram"

    def __init__(self, name, help="", unit=None):
        super().__init__(name, help=help, unit=unit)
        # accessed under the base _Metric._lock, like _values (the
        # lock-rule annotation lives with locks declared in the
        # same class; the base holds this one)
        self._sketches = {}

    def observe(self, value, **labels):
        value = float(value)
        key = _label_key(labels)
        with self._lock:
            cur = self._values.get(key)
            if cur is None:
                self._values[key] = {"count": 1, "sum": value,
                                     "min": value, "max": value}
                self._sketches[key] = QuantileSketch()
            else:
                cur["count"] += 1
                cur["sum"] += value
                cur["min"] = min(cur["min"], value)
                cur["max"] = max(cur["max"], value)
            self._sketches[key].observe(value)
        self._emit(value, labels)

    def summary(self, **labels):
        with self._lock:
            cur = self._values.get(_label_key(labels))
            if not cur:
                return None
            out = dict(cur)
            out.update(self._quantile_fields(_label_key(labels)))
            return out

    def _quantile_fields(self, key):
        # callers hold the base _Metric._lock
        sk = self._sketches.get(key)
        if sk is None:
            return {}
        return {f"p{int(q * 100)}": sk.quantile(q)
                for q in HISTOGRAM_QUANTILES}

    def quantile(self, q, **labels):
        """The ``q``-quantile of one label set's observations (None
        before the first observation)."""
        with self._lock:
            sk = self._sketches.get(_label_key(labels))
            return sk.quantile(q) if sk is not None else None

    def sketch(self, **labels):
        """A **copy** of one label set's sketch (None before the
        first observation) — safe to merge/serialize without racing
        :meth:`observe`."""
        with self._lock:
            sk = self._sketches.get(_label_key(labels))
            return QuantileSketch.from_dict(sk.to_dict()) \
                if sk is not None else None

    def samples(self):
        """[(labels dict, summary dict incl. sketch quantiles)]."""
        with self._lock:
            return [(dict(key),
                     dict(value, **self._quantile_fields(key)))
                    for key, value in self._values.items()]


class MetricsRegistry:
    """Get-or-create registry; re-registering a name with a different
    metric type is an error (a counter silently shadowed by a gauge
    would corrupt every report)."""

    def __init__(self):
        self._metrics = {}
        self._lock = threading.Lock()

    def _get(self, cls, name, help, unit):
        with self._lock:
            metric = self._metrics.get(name)
            if metric is None:
                metric = self._metrics[name] = cls(name, help=help,
                                                   unit=unit)
            elif type(metric) is not cls:
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{metric.mtype}, not {cls.mtype}")
            return metric

    def counter(self, name, help="", unit=None):
        return self._get(Counter, name, help, unit)

    def gauge(self, name, help="", unit=None):
        return self._get(Gauge, name, help, unit)

    def histogram(self, name, help="", unit=None):
        return self._get(Histogram, name, help, unit)

    def collect(self):
        """Flat samples: [{name, mtype, unit, labels, value}] sorted
        by name then labels (histogram value is a summary dict)."""
        with self._lock:
            metrics = list(self._metrics.values())
        out = []
        for metric in metrics:
            for labels, value in metric.samples():
                out.append({"name": metric.name,
                            "mtype": metric.mtype,
                            "unit": metric.unit,
                            "help": metric.help,
                            "labels": labels,
                            "value": value})
        out.sort(key=lambda s: (s["name"], sorted(s["labels"].items())))
        return out

    def reset(self):
        """Drop every metric (registrations included) — test isolation."""
        with self._lock:
            self._metrics.clear()


default_registry = MetricsRegistry()


def counter(name, help="", unit=None):
    """Get-or-create a :class:`Counter` in the default registry."""
    return default_registry.counter(name, help=help, unit=unit)


def gauge(name, help="", unit=None):
    """Get-or-create a :class:`Gauge` in the default registry."""
    return default_registry.gauge(name, help=help, unit=unit)


def histogram(name, help="", unit=None):
    """Get-or-create a :class:`Histogram` in the default registry."""
    return default_registry.histogram(name, help=help, unit=unit)


def collect():
    """Samples of the default registry (see ``MetricsRegistry.collect``)."""
    return default_registry.collect()


def reset():
    """Reset the default registry."""
    return default_registry.reset()
