"""Declarative SLOs with multi-window burn-rate tracking.

The serving tiers promise latencies (ROADMAP item 4's per-TR budget
is a hard one); promises need an evaluator that runs *while the
service does*, not a post-hoc log query.  This module is the
standard SRE construction (error budgets + multi-window burn rates,
Beyer et al., "The Site Reliability Workbook" ch. 5) on top of the
obs primitives:

- an :class:`Objective` declares what fraction of requests must be
  *good* (``target``, e.g. 0.999) and what good means — delivered
  ok, and (for latency objectives) within ``latency_threshold_s``.
  "p99 under 500 ms" is declared as
  ``Objective.latency("p99", quantile=0.99, threshold_s=0.5)``:
  99% of requests must finish inside the threshold, the
  budget-burn formulation of a quantile target;
- a **burn rate** is budget consumption speed: observed bad
  fraction / allowed bad fraction over a window.  Burn 1.0 spends
  exactly the budget over the budget window; burn 14.4 exhausts a
  30-day budget in ~2 days;
- a :class:`BurnRule` pairs a long and a short window with a factor
  (defaults: the workbook's 1h/5m @ 14.4 and 6h/30m @ 6).  A
  violation fires only when **both** windows burn past the factor —
  the long window provides significance, the short window confirms
  the problem is still live (so a recovered blip stops alerting
  immediately);
- an :class:`SLOTracker` ingests per-request outcomes (O(1), into
  time-sliced counters), evaluates the rules, emits
  ``slo_violation`` events to the sink on each transition into
  violation, and keeps ``slo_burn_rate{slo=,window=}`` /
  ``slo_error_budget_remaining{slo=}`` gauges fresh in the metric
  registry — which is exactly what ``/metrics``
  (:mod:`brainiak_tpu.obs.http`) exposes.

:class:`~brainiak_tpu.serve.service.ServeService` accepts
``slos=[...]`` and feeds every delivered record through its tracker
on the service thread; the tracker carries its own lock, so
dashboards may also evaluate it directly.
"""

import dataclasses
import threading
import time
from typing import Optional

from . import metrics as obs_metrics
from . import sink as obs_sink

__all__ = ["DEFAULT_BURN_RULES", "BurnRule", "Objective",
           "SLOTracker"]


@dataclasses.dataclass(frozen=True)
class BurnRule:
    """One multi-window burn-rate alert rule: fire when the error
    budget burns at ``factor``x or faster over BOTH the long and the
    short window."""

    long_s: float
    short_s: float
    factor: float

    def label(self):
        return f"{self.long_s:g}s/{self.short_s:g}s"


#: The SRE-workbook default pairing (scaled to a 30-day budget):
#: page-worthy fast burn (1h/5m at 14.4x) and slow burn (6h/30m at
#: 6x).
DEFAULT_BURN_RULES = (
    BurnRule(long_s=3600.0, short_s=300.0, factor=14.4),
    BurnRule(long_s=21600.0, short_s=1800.0, factor=6.0),
)


@dataclasses.dataclass(frozen=True)
class Objective:
    """One service-level objective: ``target`` fraction of requests
    must be good.  A request is *bad* when its record is an error,
    or — with ``latency_threshold_s`` set — when it was served
    slower than the threshold."""

    name: str
    target: float = 0.999
    latency_threshold_s: Optional[float] = None
    description: str = ""

    def __post_init__(self):
        if not 0.0 < self.target < 1.0:
            raise ValueError(
                f"objective {self.name!r}: target must be in "
                f"(0, 1), got {self.target}")

    @classmethod
    def latency(cls, name, quantile=0.99, threshold_s=1.0,
                description=""):
        """A latency quantile target — "p<quantile> must stay under
        ``threshold_s``" — expressed in budget form: ``quantile`` of
        requests must finish inside the threshold."""
        return cls(name=name, target=float(quantile),
                   latency_threshold_s=float(threshold_s),
                   description=description
                   or f"p{quantile * 100:g} latency <= "
                      f"{threshold_s}s")

    @classmethod
    def error_rate(cls, name, max_error_rate=0.001,
                   description=""):
        """An availability target: at most ``max_error_rate`` of
        requests may fail."""
        return cls(name=name, target=1.0 - float(max_error_rate),
                   description=description
                   or f"error rate <= {max_error_rate:g}")

    def is_bad(self, ok, latency_s):
        if not ok:
            return True
        return (self.latency_threshold_s is not None
                and latency_s is not None
                and latency_s > self.latency_threshold_s)

    def budget(self):
        """Allowed bad fraction (the error budget's rate form)."""
        return 1.0 - self.target


class _WindowCounts:
    """Time-sliced good/bad counters for one objective: O(1) ingest
    into the current slice, windowed sums by summing the few dozen
    live slices.  Slice width is sized from the shortest rule
    window, memory is bounded by the longest."""

    __slots__ = ("slice_s", "max_age_s", "slices")

    def __init__(self, slice_s, max_age_s):
        self.slice_s = float(slice_s)
        self.max_age_s = float(max_age_s)
        self.slices = []  # [[slice_start, good, bad], ...] ascending

    def add(self, now, good, bad):
        start = now - (now % self.slice_s)
        if self.slices and self.slices[-1][0] == start:
            self.slices[-1][1] += good
            self.slices[-1][2] += bad
        else:
            self.slices.append([start, good, bad])
            self.prune(now)

    def prune(self, now):
        cutoff = now - self.max_age_s - self.slice_s
        while self.slices and self.slices[0][0] < cutoff:
            self.slices.pop(0)

    def window(self, now, window_s):
        """(good, bad) over the trailing ``window_s``."""
        cutoff = now - window_s
        good = bad = 0
        for start, g, b in reversed(self.slices):
            if start + self.slice_s <= cutoff:
                break
            good += g
            bad += b
        return good, bad


class SLOTracker:
    """Ingest request outcomes, evaluate burn rules, surface budget
    state (see module docstring).

    Parameters
    ----------
    objectives : iterable of :class:`Objective`
    burn_rules : iterable of :class:`BurnRule`
        Default :data:`DEFAULT_BURN_RULES`; tests pass short windows
        with a fake ``clock``.
    clock : callable
        Monotonic time source (default ``time.monotonic``).
    min_window_count : int
        A window with fewer total events than this is never judged
        (early traffic must not page at the first error).
    gauge_interval_s : float
        Minimum spacing between ``slo_*`` gauge refreshes: the
        service evaluates every working tick (milliseconds apart),
        and each gauge set also writes a sink record while obs is
        enabled — violation *detection* stays per-evaluate, the
        gauge/record fan-out is throttled to this cadence (and
        always refreshed on a violation transition).
    """

    def __init__(self, objectives, burn_rules=DEFAULT_BURN_RULES,
                 clock=time.monotonic, min_window_count=10,
                 gauge_interval_s=1.0):
        self.objectives = list(objectives)
        if not self.objectives:
            raise ValueError("SLOTracker needs >= 1 objective")
        names = [o.name for o in self.objectives]
        if len(set(names)) != len(names):
            raise ValueError(
                f"duplicate objective names: {sorted(names)}")
        self.burn_rules = tuple(burn_rules)
        if not self.burn_rules:
            raise ValueError("SLOTracker needs >= 1 burn rule")
        self.clock = clock
        self.min_window_count = int(min_window_count)
        shortest = min(r.short_s for r in self.burn_rules)
        longest = max(r.long_s for r in self.burn_rules)
        self._lock = threading.Lock()
        self._counts = {
            o.name: _WindowCounts(max(shortest / 10.0, 1e-6),
                                  longest)
            for o in self.objectives}  # guarded-by: _lock
        # rule-keyed set of currently-violating (objective, rule)
        # pairs: violations emit on the transition INTO violation
        self._active = set()       # guarded-by: _lock
        self._n_violations = 0     # guarded-by: _lock
        self.gauge_interval_s = float(gauge_interval_s)
        self._last_gauge = None    # guarded-by: _lock

    # -- ingest -------------------------------------------------------

    def record(self, ok, latency_s=None, n=1):
        """Account ``n`` requests with one outcome (O(1) per
        objective)."""
        now = self.clock()
        with self._lock:
            for objective in self.objectives:
                bad = objective.is_bad(bool(ok), latency_s)
                self._counts[objective.name].add(
                    now, 0 if bad else n, n if bad else 0)

    # -- evaluation ---------------------------------------------------

    def evaluate(self, now=None):
        """Evaluate every objective against every burn rule; update
        the ``slo_*`` gauges; emit one ``slo_violation`` event per
        (objective, rule) transition into violation.  Returns the
        per-objective state dict (also served by
        ``ServeService.summary()[\"slo\"]``)."""
        if now is None:
            now = self.clock()
        out = {}
        events = []
        with self._lock:
            for objective in self.objectives:
                counts = self._counts[objective.name]
                counts.prune(now)
                budget = objective.budget()
                state = {"target": objective.target,
                         "description": objective.description,
                         "windows": {}, "violating": False}
                longest = max(r.long_s for r in self.burn_rules)
                for rule in self.burn_rules:
                    burns = {}
                    judged = True
                    for window_s in (rule.long_s, rule.short_s):
                        good, bad = counts.window(now, window_s)
                        total = good + bad
                        ratio = (bad / total) if total else 0.0
                        burn = ratio / budget
                        burns[window_s] = burn
                        state["windows"][f"{window_s:g}s"] = {
                            "total": total, "bad": bad,
                            "bad_ratio": ratio, "burn_rate": burn}
                        if total < self.min_window_count:
                            judged = False
                    violating = judged and all(
                        b >= rule.factor for b in burns.values())
                    key = (objective.name, rule)
                    if violating:
                        state["violating"] = True
                        if key not in self._active:
                            self._active.add(key)
                            self._n_violations += 1
                            events.append((objective, rule, burns))
                    else:
                        self._active.discard(key)
                # budget remaining over the longest configured
                # window: 1 - consumed fraction, floored at 0
                good, bad = counts.window(now, longest)
                total = good + bad
                ratio = (bad / total) if total else 0.0
                state["error_budget_remaining"] = max(
                    0.0, 1.0 - ratio / budget)
                state["n_requests"] = total
                out[objective.name] = state
            n_violations = self._n_violations
            refresh_gauges = (
                events
                or self._last_gauge is None
                or now - self._last_gauge >= self.gauge_interval_s)
            if refresh_gauges:
                self._last_gauge = now
        # telemetry outside the lock (sink writes are file I/O)
        for name, state in (out.items() if refresh_gauges else ()):
            obs_metrics.gauge(
                "slo_error_budget_remaining",
                help="fraction of the error budget left over the "
                     "longest burn window").set(
                    state["error_budget_remaining"], slo=name)
            for window, wstate in state["windows"].items():
                obs_metrics.gauge(
                    "slo_burn_rate",
                    help="error-budget burn rate (1.0 = spending "
                         "exactly the budget)").set(
                        wstate["burn_rate"], slo=name,
                        window=window)
        for objective, rule, burns in events:
            obs_metrics.counter(
                "slo_violations_total",
                help="burn-rule violations (transitions into "
                     "violation)").inc(slo=objective.name)
            obs_sink.event(
                "slo_violation", slo=objective.name,
                target=objective.target,
                rule=rule.label(), factor=rule.factor,
                burn_rates={f"{w:g}s": round(b, 4)
                            for w, b in burns.items()})
            # a violation TRANSITION is an incident: snapshot the
            # flight ring so the requests that burned the budget are
            # preserved (re-entering violation re-dumps; steady-state
            # violation does not)
            from . import flight
            flight.dump(
                "slo_violation",
                state={"slo": objective.name, "rule": rule.label(),
                       "factor": rule.factor,
                       "burn_rates": {f"{w:g}s": round(b, 4)
                                      for w, b in burns.items()}})
        return {"objectives": out, "n_violations": n_violations}

    def summary(self):
        """:meth:`evaluate` at the current clock — the service
        summary hook."""
        return self.evaluate()
