"""Live telemetry exposition: ``/metrics``, ``/healthz``, ``/readyz``.

The PR 3/4 obs stack is post-hoc (JSONL read after the run); an
always-on service needs its state scrapeable **while it runs** — a
router places requests by live queue depth, an operator watches SLO
burn on a dashboard, an orchestrator gates traffic on readiness.
:class:`TelemetryServer` is that plane with zero new dependencies:
one ``http.server`` daemon thread serving

- ``/metrics`` — the in-process registry
  (:func:`brainiak_tpu.obs.metrics.collect`) in Prometheus text
  exposition format (version 0.0.4): counters and gauges verbatim,
  histograms as summaries with real ``quantile=""`` series from
  their mergeable sketches plus ``_sum``/``_count``;
- ``/healthz`` — liveness: a 200 means the process (and this daemon
  thread) is alive;
- ``/readyz`` — readiness: delegates to an injectable callback
  (:class:`~brainiak_tpu.serve.service.ServeService` wires its
  residency + AOT warm state here) and answers 200 or 503 with a
  JSON detail body either way;
- ``/jobs`` — the active-fit registry
  (:func:`brainiak_tpu.obs.progress.active_fits`) as JSON: every
  running (and recently finished) resilient fit with its progress
  ratio, ETA, objective trend, and rollback count — the live view
  ``python -m brainiak_tpu.obs watch`` polls.  When a jobs
  scheduler (:mod:`brainiak_tpu.jobs.scheduler`) is live in the
  process the payload additionally carries ``scheduler`` — queue /
  running / parked job records and per-tenant fair-share usage
  (detected via ``sys.modules``: a serve-only process pays no
  import).

A process may also attach a **control** callback (``control=``) —
the jobs scheduler wires job submission here — which enables POST:
``POST /jobs/submit`` (body: the npz job codec,
:func:`brainiak_tpu.jobs.spec.encode_jobs`) and
``POST /jobs/cancel?job_id=<id>``, each answered with a JSON verdict.
Without a control callback every POST is 405 — the plane stays
read-only by default.

Opt-in: nothing listens unless a port is given — programmatically,
via ``serve service --http-port``, or through the
``BRAINIAK_TPU_OBS_HTTP_PORT`` environment variable
(:func:`maybe_start_from_env`).  Port 0 binds an ephemeral port
(read it back from :attr:`TelemetryServer.port` — the CI gate and
the tests do).  The handler threads only *read* (the registry and
the readiness callback synchronize internally), so exposition never
blocks the serving loop.

:func:`parse_prometheus_text` is the minimal in-repo parser the
OBS002 gate and the tests validate the exposition with — no
prometheus client library needed.
"""

import json
import logging
import os
import re
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import metrics as obs_metrics

logger = logging.getLogger(__name__)

__all__ = [
    "HTTP_HOST_ENV",
    "HTTP_PORT_ENV",
    "TelemetryServer",
    "maybe_start_from_env",
    "parse_prometheus_text",
    "render_prometheus",
]

HTTP_PORT_ENV = "BRAINIAK_TPU_OBS_HTTP_PORT"
HTTP_HOST_ENV = "BRAINIAK_TPU_OBS_HTTP_HOST"

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"\s+(?P<value>\S+)\s*$")
_LABEL_RE = re.compile(
    r'(?P<key>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<value>(?:[^"\\]|\\.)*)"')


def _escape_help(text):
    return text.replace("\\", r"\\").replace("\n", r"\n")


def _escape_label(value):
    return (str(value).replace("\\", r"\\")
            .replace('"', r'\"').replace("\n", r"\n"))


_UNESCAPE_RE = re.compile(r"\\(.)")


def _unescape_label(value):
    # single left-to-right scan: sequential str.replace would
    # mis-read the tail of an escaped backslash ('\\\\n' is
    # backslash + literal n, not backslash + newline)
    return _UNESCAPE_RE.sub(
        lambda m: "\n" if m.group(1) == "n" else m.group(1), value)


def _label_str(labels):
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt_value(value):
    value = float(value)
    if value != value:
        return "NaN"
    if value in (float("inf"), float("-inf")):
        return "+Inf" if value > 0 else "-Inf"
    return repr(value)


def render_prometheus(samples=None):
    """Prometheus text exposition (format 0.0.4) for registry
    samples (default: the live default registry).

    Counters/gauges render one line per label set; histograms render
    as the ``summary`` type — their sketch quantiles
    (:data:`~brainiak_tpu.obs.metrics.HISTOGRAM_QUANTILES`) as
    ``name{quantile="0.99"}`` series plus ``name_sum`` /
    ``name_count`` — because the sketch gives real bounded-error
    percentiles, not pre-binned bucket counts.
    """
    if samples is None:
        samples = obs_metrics.collect()
    by_name = {}
    for sample in samples:
        by_name.setdefault(sample["name"], []).append(sample)
    lines = []
    for name in sorted(by_name):
        group = by_name[name]
        if not _NAME_RE.match(name):
            logger.warning("skipping non-prometheus metric name %r",
                           name)
            continue
        mtype = group[0]["mtype"]
        help_text = group[0].get("help") or ""
        if help_text:
            lines.append(f"# HELP {name} {_escape_help(help_text)}")
        lines.append(
            f"# TYPE {name} "
            f"{'summary' if mtype == 'histogram' else mtype}")
        for sample in group:
            labels = sample["labels"]
            value = sample["value"]
            if mtype != "histogram":
                lines.append(f"{name}{_label_str(labels)} "
                             f"{_fmt_value(value)}")
                continue
            for q in obs_metrics.HISTOGRAM_QUANTILES:
                quant = value.get(f"p{int(q * 100)}")
                if quant is None:
                    continue
                qlabels = dict(labels, quantile=f"{q:g}")
                lines.append(f"{name}{_label_str(qlabels)} "
                             f"{_fmt_value(quant)}")
            lines.append(f"{name}_sum{_label_str(labels)} "
                         f"{_fmt_value(value['sum'])}")
            lines.append(f"{name}_count{_label_str(labels)} "
                         f"{_fmt_value(value['count'])}")
    return "\n".join(lines) + "\n"


def parse_prometheus_text(text):
    """Minimal Prometheus text-format parser: returns
    ``(families, errors)`` where ``families`` maps each metric
    family name to ``{"type": str, "help": str, "samples":
    [(sample_name, labels_dict, float_value)]}`` and ``errors`` is a
    list of ``"line N: problem"`` strings (empty = the document is
    well-formed).  This is the in-repo validator the OBS002 CI gate
    scrapes ``/metrics`` through — samples must parse, carry float
    values, and belong to a declared family (``_sum``/``_count``
    suffixes fold into their summary family)."""
    families = {}
    errors = []
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split(None, 3)
            if len(parts) != 4:
                errors.append(f"line {lineno}: malformed TYPE line")
                continue
            _, _, name, mtype = parts
            if mtype not in ("counter", "gauge", "summary",
                            "histogram", "untyped"):
                errors.append(
                    f"line {lineno}: unknown metric type {mtype!r}")
                continue
            families.setdefault(
                name, {"type": mtype, "help": "", "samples": []})[
                    "type"] = mtype
            continue
        if line.startswith("# HELP "):
            parts = line.split(None, 3)
            if len(parts) < 3:
                errors.append(f"line {lineno}: malformed HELP line")
                continue
            name = parts[2]
            families.setdefault(
                name, {"type": "untyped", "help": "",
                       "samples": []})["help"] = \
                parts[3] if len(parts) > 3 else ""
            continue
        if line.startswith("#"):
            continue  # plain comment
        m = _SAMPLE_RE.match(line)
        if not m:
            errors.append(f"line {lineno}: unparseable sample "
                          f"{line!r}")
            continue
        name = m.group("name")
        try:
            value = float(m.group("value"))
        except ValueError:
            errors.append(
                f"line {lineno}: non-numeric value "
                f"{m.group('value')!r}")
            continue
        labels = {lm.group("key"): _unescape_label(lm.group("value"))
                  for lm in _LABEL_RE.finditer(
                      m.group("labels") or "")}
        family = name
        for suffix in ("_sum", "_count", "_bucket"):
            if name.endswith(suffix) \
                    and name[:-len(suffix)] in families:
                family = name[:-len(suffix)]
                break
        if family not in families:
            errors.append(
                f"line {lineno}: sample {name!r} has no TYPE/HELP "
                "family declaration")
            continue
        families[family]["samples"].append((name, labels, value))
    for name, fam in families.items():
        if not fam["samples"]:
            errors.append(f"family {name!r} declared but has no "
                          "samples")
    return families, errors


def _scheduler_state():
    """Live jobs-scheduler state for the ``/jobs`` payload, or None.

    Gated on ``sys.modules``: a process that never imported the jobs
    scheduler (a serve-only replica, say) answers ``/jobs`` exactly
    as before — no import is triggered from the exposition path."""
    mod = sys.modules.get("brainiak_tpu.jobs.scheduler")
    if mod is None:
        return None
    try:
        return mod.scheduler_state()
    except Exception:
        logger.exception("scheduler state for /jobs failed")
        return None


class TelemetryServer:
    """The opt-in exposition daemon (see module docstring).

    Parameters
    ----------
    port : int
        TCP port; 0 binds an ephemeral port (read
        :attr:`port` after :meth:`start`).
    host : str
        Bind address.  Default ``127.0.0.1`` — the endpoint is
        unauthenticated, so wide exposure is an explicit choice:
        pass ``host=""`` (or set ``BRAINIAK_TPU_OBS_HTTP_HOST=""``
        for the env-driven path) to bind all interfaces for a real
        scraper.
    readiness : callable, optional
        Zero-arg callable returning ``(ok, detail_dict)``; drives
        ``/readyz`` (200/503 + JSON detail).  Without one,
        ``/readyz`` mirrors liveness.
    registry : :class:`~brainiak_tpu.obs.metrics.MetricsRegistry`,
        optional
        Metrics source (default: the process default registry).
    control : callable, optional
        ``control(action, payload) -> dict`` handling POST control
        requests (``action`` is ``"submit"`` with npz body bytes, or
        ``"cancel"`` with a job-id string).  Raising ``ValueError``
        maps to a 400.  Without one, POSTs answer 405.
    """

    def __init__(self, port=0, host="127.0.0.1", readiness=None,
                 registry=None, control=None):
        self.requested_port = int(port)
        self.host = host
        self.readiness = readiness
        self.registry = registry
        self.control = control
        self._httpd = None   # guarded-by: _lock
        self._thread = None  # guarded-by: _lock
        self._lock = threading.Lock()

    @property
    def port(self):
        """The actually-bound port (None before :meth:`start`)."""
        with self._lock:
            return self._httpd.server_address[1] \
                if self._httpd is not None else None

    def start(self):
        """Bind and serve on a daemon thread (idempotent); returns
        self."""
        with self._lock:
            if self._httpd is not None:
                return self
            server = self

            class Handler(BaseHTTPRequestHandler):
                def do_GET(self):  # noqa: N802 (stdlib API name)
                    server._handle(self)

                def do_POST(self):  # noqa: N802 (stdlib API name)
                    server._handle_post(self)

                def log_message(self, fmt, *args):
                    logger.debug("obs http: " + fmt, *args)

            self._httpd = ThreadingHTTPServer(
                (self.host, self.requested_port), Handler)
            self._httpd.daemon_threads = True
            httpd = self._httpd
            self._thread = threading.Thread(
                # stdlib serve_forever polls its shutdown flag at
                # 0.5 s by default — a service shutdown would stall
                # on it (and a bench drive would charge it to
                # telemetry overhead); 20 ms keeps stop() prompt
                target=lambda: httpd.serve_forever(
                    poll_interval=0.02),
                name="obs-http", daemon=True)
            self._thread.start()
        logger.info("obs http exposition on port %s", self.port)
        return self

    def stop(self):
        """Shut the listener down (idempotent)."""
        with self._lock:
            httpd, thread = self._httpd, self._thread
            self._httpd = None
            self._thread = None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if thread is not None:
            thread.join(timeout=5.0)

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.stop()

    # -- request handling (http handler threads) ----------------------

    def _handle(self, handler):
        path = handler.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                registry = self.registry
                samples = registry.collect() if registry is not None \
                    else obs_metrics.collect()
                self._respond(
                    handler, 200, render_prometheus(samples),
                    "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                self._respond(handler, 200, "ok\n", "text/plain")
            elif path == "/readyz":
                self._ready(handler)
            elif path == "/jobs":
                from . import progress as obs_progress
                payload = {"fits": obs_progress.active_fits()}
                scheduler = _scheduler_state()
                if scheduler is not None:
                    payload["scheduler"] = scheduler
                body = json.dumps(payload, indent=2,
                                  sort_keys=True) + "\n"
                self._respond(handler, 200, body,
                              "application/json")
            else:
                self._respond(handler, 404,
                              f"unknown path {path!r}; endpoints: "
                              "/metrics /healthz /readyz /jobs\n",
                              "text/plain")
        except Exception:  # exposition must never kill the server
            logger.exception("obs http handler failed for %s", path)
            try:
                self._respond(handler, 500, "internal error\n",
                              "text/plain")
            except Exception:
                pass

    def _handle_post(self, handler):
        path, _, query = handler.path.partition("?")
        try:
            if self.control is None:
                self._respond(
                    handler, 405,
                    "no control plane attached; POST disabled\n",
                    "text/plain")
                return
            if path == "/jobs/submit":
                length = int(handler.headers.get(
                    "Content-Length", 0) or 0)
                body = handler.rfile.read(length) if length else b""
                verdict = self.control("submit", body)
            elif path == "/jobs/cancel":
                params = dict(
                    part.split("=", 1) for part in query.split("&")
                    if "=" in part)
                job_id = params.get("job_id", "")
                if not job_id:
                    raise ValueError(
                        "cancel requires ?job_id=<id>")
                verdict = self.control("cancel", job_id)
            else:
                self._respond(
                    handler, 404,
                    f"unknown control path {path!r}; endpoints: "
                    "/jobs/submit /jobs/cancel\n", "text/plain")
                return
            self._respond(
                handler, 200,
                json.dumps(verdict, indent=2, sort_keys=True) + "\n",
                "application/json")
        except ValueError as exc:
            try:
                self._respond(handler, 400, f"{exc}\n", "text/plain")
            except Exception:
                pass
        except Exception:  # control must never kill the server
            logger.exception("obs http control failed for %s", path)
            try:
                self._respond(handler, 500, "internal error\n",
                              "text/plain")
            except Exception:
                pass

    def _ready(self, handler):
        if self.readiness is None:
            self._respond(handler, 200,
                          json.dumps({"ready": True}) + "\n",
                          "application/json")
            return
        ok, detail = self.readiness()
        body = json.dumps(dict({"ready": bool(ok)}, **(detail or {})),
                          indent=2, sort_keys=True) + "\n"
        self._respond(handler, 200 if ok else 503, body,
                      "application/json")

    @staticmethod
    def _respond(handler, status, body, content_type):
        payload = body.encode("utf-8")
        handler.send_response(status)
        handler.send_header("Content-Type", content_type)
        handler.send_header("Content-Length", str(len(payload)))
        handler.end_headers()
        handler.wfile.write(payload)


def maybe_start_from_env(readiness=None):
    """Start a :class:`TelemetryServer` when
    ``BRAINIAK_TPU_OBS_HTTP_PORT`` names a port; returns the started
    server or None (unset/invalid = the default: no listener).
    ``BRAINIAK_TPU_OBS_HTTP_HOST`` overrides the bind address
    (default loopback; empty string = all interfaces)."""
    raw = os.environ.get(HTTP_PORT_ENV)
    if raw is None or raw == "":
        return None
    try:
        port = int(raw)
    except ValueError:
        logger.warning("ignoring non-numeric %s=%r", HTTP_PORT_ENV,
                       raw)
        return None
    if port < 0:
        return None
    host = os.environ.get(HTTP_HOST_ENV, "127.0.0.1")
    return TelemetryServer(port=port, host=host,
                           readiness=readiness).start()
