"""brainiak_tpu.obs: structured tracing, metrics, and telemetry.

The framework's observability layer (PR 3), closing the loop between
PR 1's resilience events and PR 2's retrace lint:

- :mod:`~brainiak_tpu.obs.spans` — hierarchical trace spans (context
  manager + decorator) with async-dispatch-aware stop;
- :mod:`~brainiak_tpu.obs.metrics` — typed counter/gauge/histogram
  registry with labels (``fit_steps_total{estimator=SRM}``,
  ``retrace_total{site=...}``, ``checkpoint_seconds``, ...);
- :mod:`~brainiak_tpu.obs.runtime` — JAX-level collectors
  (``counted_cache`` retrace hooks on the jitted-program builders,
  device memory snapshots, mesh/topology capture);
- :mod:`~brainiak_tpu.obs.sink` — schema-versioned record dispatch:
  per-host JSON-lines files (env ``BRAINIAK_TPU_OBS_DIR``) and an
  in-memory sink for tests;
- :mod:`~brainiak_tpu.obs.report` — ``python -m brainiak_tpu.obs
  report`` aggregates JSONL into per-stage/per-estimator summaries.

Disabled by default: with no sink configured every instrumentation
site is a no-op (no records, no ``block_until_ready`` host syncs).
See docs/observability.md.

The deprecated ``brainiak_tpu.utils.profiling`` names
(:func:`stage_timer` / :func:`stage_times` /
:func:`reset_stage_times` / :func:`device_trace`) are re-exported
here by their new home.
"""

from .metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    collect,
    counter,
    default_registry,
    gauge,
    histogram,
)
from .report import validate_bench_record  # noqa: F401
from .runtime import (  # noqa: F401
    counted_cache,
    device_memory_snapshot,
    device_trace,
    install_compile_listener,
    topology_event,
)
from .sink import (  # noqa: F401
    OBS_DIR_ENV,
    SCHEMA_VERSION,
    JsonlSink,
    MemorySink,
    add_sink,
    emit,
    enabled,
    event,
    make_record,
    remove_sink,
    validate_record,
)
from .spans import (  # noqa: F401
    current_span,
    reset_stage_times,
    span,
    stage_timer,
    stage_times,
    traced,
)

__all__ = [
    "OBS_DIR_ENV",
    "SCHEMA_VERSION",
    "Counter",
    "Gauge",
    "Histogram",
    "JsonlSink",
    "MemorySink",
    "MetricsRegistry",
    "add_sink",
    "collect",
    "counted_cache",
    "counter",
    "current_span",
    "default_registry",
    "device_memory_snapshot",
    "device_trace",
    "emit",
    "enabled",
    "event",
    "gauge",
    "histogram",
    "install_compile_listener",
    "make_record",
    "remove_sink",
    "reset_stage_times",
    "span",
    "stage_timer",
    "stage_times",
    "topology_event",
    "traced",
    "validate_bench_record",
    "validate_record",
]
