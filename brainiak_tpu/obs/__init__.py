"""brainiak_tpu.obs: structured tracing, metrics, and telemetry.

The framework's observability layer (PR 3), closing the loop between
PR 1's resilience events and PR 2's retrace lint:

- :mod:`~brainiak_tpu.obs.spans` — hierarchical trace spans (context
  manager + decorator) with async-dispatch-aware stop;
- :mod:`~brainiak_tpu.obs.metrics` — typed counter/gauge/histogram
  registry with labels (``fit_steps_total{estimator=SRM}``,
  ``retrace_total{site=...}``, ``checkpoint_seconds``, ...);
- :mod:`~brainiak_tpu.obs.runtime` — JAX-level collectors
  (``counted_cache`` retrace hooks on the jitted-program builders,
  device memory snapshots, mesh/topology capture);
- :mod:`~brainiak_tpu.obs.sink` — schema-versioned record dispatch:
  per-host JSON-lines files (env ``BRAINIAK_TPU_OBS_DIR``) and an
  in-memory sink for tests;
- :mod:`~brainiak_tpu.obs.report` — ``python -m brainiak_tpu.obs
  report`` aggregates JSONL into per-stage/per-estimator summaries
  (``--top N`` lists the slowest spans; cost rows carry roofline
  ratios);
- :mod:`~brainiak_tpu.obs.profile` (PR 4) — XLA cost attribution:
  ``profile_program`` captures FLOPs/bytes/compile-time ``cost``
  records (schema v2) for the framework's jitted programs, activated
  by ``BRAINIAK_TPU_OBS_PROFILE``; ``memory_watermark`` snapshots
  HBM/host peaks around fit chunks;
- :mod:`~brainiak_tpu.obs.export` (PR 4) — ``python -m
  brainiak_tpu.obs export`` renders per-rank JSONL sinks into one
  Chrome-trace/Perfetto timeline with topology-anchored clock-skew
  merge;
- :mod:`~brainiak_tpu.obs.regress` (PR 4) — ``python -m
  brainiak_tpu.obs regress`` gates fresh bench numbers against the
  tier-separated BENCH_* history;
- :mod:`~brainiak_tpu.obs.trace` (PR 12) — request-scoped tracing:
  one trace id per serve request, span chains with parentage across
  threads and processes (npz-codec propagation), rendered as Chrome
  flows by ``obs export``;
- :mod:`~brainiak_tpu.obs.sketch` (PR 12) — mergeable
  bounded-relative-error quantile sketches (DDSketch-style): O(1)
  observe/memory, exact ``merge()`` so replica percentiles pool;
- :mod:`~brainiak_tpu.obs.http` (PR 12) — opt-in live exposition
  (``/metrics`` Prometheus text, ``/healthz``, ``/readyz``) on a
  stdlib daemon thread (``BRAINIAK_TPU_OBS_HTTP_PORT`` / ``serve
  service --http-port``);
- :mod:`~brainiak_tpu.obs.slo` (PR 12) — declarative objectives
  with multi-window burn-rate tracking: ``slo_violation`` events,
  error-budget gauges on the exposition endpoint;
- :mod:`~brainiak_tpu.obs.progress` (PR 19) — fit-progress and
  convergence telemetry: every resilient fit owns a stable
  ``fit_id`` (checkpoint-persisted across resumes), emits schema-v4
  ``progress`` records per chunk (objective, delta, EWMA rate, ETA),
  detects plateaus and fires ``divergence_precursor`` events before
  the non-finite guard trips, and feeds the ``/jobs`` endpoint;
- :mod:`~brainiak_tpu.obs.flight` (PR 19) — always-on bounded
  flight-recorder ring of recent records; :func:`~flight.dump`
  writes incident snapshots (auto-triggered on divergence aborts,
  sanitizer trips, retry exhaustion, SLO violations, replica
  deaths), rendered by ``python -m brainiak_tpu.obs postmortem``;
- :mod:`~brainiak_tpu.obs.watch` (PR 19) — ``python -m
  brainiak_tpu.obs watch`` live terminal view of active fits
  (``--url`` scrapes ``/jobs``; ``--dir`` tails JSONL sinks).

Disabled by default: with no sink configured every instrumentation
site is a no-op (no records, no ``block_until_ready`` host syncs).
See docs/observability.md.

The deprecated ``brainiak_tpu.utils.profiling`` names
(:func:`stage_timer` / :func:`stage_times` /
:func:`reset_stage_times` / :func:`device_trace`) are re-exported
here by their new home.
"""

from .metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    collect,
    counter,
    default_registry,
    gauge,
    histogram,
)
from .profile import (  # noqa: F401
    PROFILE_ENV,
    memory_watermark,
    profile_level,
    profile_program,
    profiling,
)
from .http import (  # noqa: F401
    HTTP_PORT_ENV,
    TelemetryServer,
    parse_prometheus_text,
    render_prometheus,
)
from .flight import (  # noqa: F401
    FLIGHT_DIR_ENV,
    FLIGHT_RECORDS_ENV,
)
from .flight import dump as flight_dump  # noqa: F401
from .flight import records as flight_records  # noqa: F401
from .progress import (  # noqa: F401
    FitProgress,
    active_fits,
    new_fit_id,
)
from .report import validate_bench_record  # noqa: F401
from .sketch import QuantileSketch  # noqa: F401
from .slo import (  # noqa: F401
    BurnRule,
    Objective,
    SLOTracker,
)
from .runtime import (  # noqa: F401
    counted_cache,
    device_memory_snapshot,
    device_trace,
    install_compile_listener,
    topology_event,
)
from .sink import (  # noqa: F401
    OBS_DIR_ENV,
    OBS_MAX_MB_ENV,
    SCHEMA_VERSION,
    JsonlSink,
    MemorySink,
    add_sink,
    emit,
    enabled,
    event,
    make_record,
    remove_sink,
    suspended,
    validate_record,
)
from .trace import (  # noqa: F401
    new_span_id,
    new_trace_id,
    trace_chains,
    trace_is_connected,
)
from .spans import (  # noqa: F401
    current_span,
    reset_stage_times,
    span,
    stage_timer,
    stage_times,
    traced,
)

__all__ = [
    "FLIGHT_DIR_ENV",
    "FLIGHT_RECORDS_ENV",
    "HTTP_PORT_ENV",
    "OBS_DIR_ENV",
    "OBS_MAX_MB_ENV",
    "PROFILE_ENV",
    "SCHEMA_VERSION",
    "BurnRule",
    "Counter",
    "FitProgress",
    "Gauge",
    "Histogram",
    "JsonlSink",
    "MemorySink",
    "MetricsRegistry",
    "Objective",
    "QuantileSketch",
    "SLOTracker",
    "TelemetryServer",
    "active_fits",
    "add_sink",
    "collect",
    "counted_cache",
    "counter",
    "current_span",
    "default_registry",
    "device_memory_snapshot",
    "device_trace",
    "emit",
    "enabled",
    "event",
    "flight_dump",
    "flight_records",
    "gauge",
    "histogram",
    "install_compile_listener",
    "make_record",
    "memory_watermark",
    "new_fit_id",
    "new_span_id",
    "new_trace_id",
    "parse_prometheus_text",
    "profile_level",
    "profile_program",
    "profiling",
    "remove_sink",
    "render_prometheus",
    "reset_stage_times",
    "span",
    "stage_timer",
    "stage_times",
    "suspended",
    "topology_event",
    "trace_chains",
    "trace_is_connected",
    "traced",
    "validate_bench_record",
    "validate_record",
]
