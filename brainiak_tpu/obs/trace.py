"""Request-scoped tracing: one end-to-end trace per serve request.

The PR 3/4 span tree answers "where does *time* go"; serving needs
the orthogonal question — "where did *this request* go" — across
threads (the submit happens on a caller thread, dispatch on the
service loop) and across processes (a router process submits, a
replica process serves).  A **trace** is the unit of that question:

- a ``trace_id`` (16 hex chars) minted once per request at
  :meth:`~brainiak_tpu.serve.service.ServeService.submit` /
  ``submit_many`` — or pre-assigned by an upstream process and
  carried in through the npz request codec (:func:`inject_npz` /
  :func:`extract_npz`), so multi-process replicas join the
  *submitter's* trace instead of starting their own;
- a chain of spans, each carrying its own ``span_id`` (8 hex chars)
  and the ``parent_id`` of the causally-preceding span:
  ``serve.submit`` (service ingress) → ``serve.enqueue`` (bucket
  queue) → ``serve.dispatch`` (the batch that carried it, one span
  per member request) → ``serve.request`` (delivery, the record the
  engine already emitted — now parented).

The trace fields ride the existing span records (sink schema v3,
optional keys — v1/v2 traces still load), so every downstream tool
works unchanged and ``obs export --format=chrome-trace``
additionally renders each trace as a Chrome flow (arrows across
rank lanes, using the existing topology-anchored clock-skew merge).

Discipline: tracing is live exactly when obs is
(:func:`~brainiak_tpu.obs.sink.enabled`); disabled, no ids are
minted, no records emitted, and no host syncs added — the
instrumented serve loop keeps the PR 3 zero-overhead contract
(acceptance-tested in ``tests/obs/test_trace.py``).
"""

import os
import time

from . import sink

__all__ = [
    "NPZ_PARENT_KEY",
    "NPZ_TRACE_KEY",
    "extract_npz",
    "inject_npz",
    "new_span_id",
    "new_trace_id",
    "start_trace",
    "trace_chains",
    "trace_is_connected",
    "traced_span",
]

#: npz codec key patterns for per-request trace propagation
#: (``save_requests``/``load_requests`` in
#: :mod:`brainiak_tpu.serve.batching` read/write these).
NPZ_TRACE_KEY = "trace.{i}"
NPZ_PARENT_KEY = "trace_parent.{i}"


def new_trace_id():
    """A fresh 16-hex-char trace id (random, collision-safe across
    processes — no coordination needed between replicas)."""
    return os.urandom(8).hex()


def new_span_id():
    """A fresh 8-hex-char span id."""
    return os.urandom(4).hex()


def start_trace(request):
    """Ensure ``request`` carries a ``trace_id``; returns it.

    A pre-assigned id (an upstream submitter's, via the npz codec)
    is honored — that is what stitches multi-process replicas into
    one trace.  While obs is disabled no id is minted (zero
    overhead) and None is returned, but a pre-assigned id still
    travels on the request untouched."""
    if getattr(request, "trace_id", None):
        return request.trace_id
    if not sink.enabled():
        return None
    request.trace_id = new_trace_id()
    return request.trace_id


def traced_span(name, dur_s, request, path=None, attrs=None):
    """Emit one span record in ``request``'s trace and ADVANCE the
    chain: the new span's parent is the request's current
    ``parent_id`` and the request's ``parent_id`` becomes the new
    span's id, so the next stage parents correctly without knowing
    what came before.  No-op (returns None) while obs is disabled
    or the request is untraced."""
    if not sink.enabled():
        return None
    trace_id = getattr(request, "trace_id", None)
    if not trace_id:
        return None
    span_id = new_span_id()
    sink.emit(sink.make_record(
        "span", name, path=path or name, dur_s=float(dur_s),
        trace_id=trace_id, span_id=span_id,
        parent_id=getattr(request, "parent_id", None),
        attrs=attrs or None))
    request.parent_id = span_id
    return span_id


class stage_clock:
    """Tiny monotonic stopwatch for the traced serve stages (the
    stages are host-side bookkeeping — enqueue, batch assembly —
    so no device sync is involved; device-synced timing stays the
    job of :func:`brainiak_tpu.obs.spans.span`)."""

    __slots__ = ("t0",)

    def __init__(self):
        self.t0 = time.perf_counter()

    def elapsed(self):
        return time.perf_counter() - self.t0


# -- npz request-codec propagation ------------------------------------

def inject_npz(store, index, trace_id, parent_id=None):
    """Stamp one request's trace context into a request-npz dict
    (the ``save_requests`` store).  None ids are omitted — the codec
    stays byte-identical for untraced requests."""
    import numpy as np
    if trace_id:
        store[NPZ_TRACE_KEY.format(i=index)] = \
            np.asarray(str(trace_id))
    if parent_id:
        store[NPZ_PARENT_KEY.format(i=index)] = \
            np.asarray(str(parent_id))
    return store


def extract_npz(z, index):
    """``(trace_id, parent_id)`` for one request of a loaded npz
    (None, None when the request was saved untraced)."""
    import numpy as np
    tkey = NPZ_TRACE_KEY.format(i=index)
    pkey = NPZ_PARENT_KEY.format(i=index)
    trace_id = str(np.asarray(z[tkey])) if tkey in z.files else None
    parent_id = str(np.asarray(z[pkey])) if pkey in z.files else None
    return trace_id, parent_id


# -- trace reconstruction (export CLI + tests) ------------------------

def trace_chains(records):
    """Group span/event records by ``trace_id``, each group sorted
    by record timestamp: ``{trace_id: [record, ...]}``.  Records
    without a trace id are ignored."""
    chains = {}
    for rec in records:
        tid = rec.get("trace_id")
        if tid:
            chains.setdefault(tid, []).append(rec)
    for recs in chains.values():
        recs.sort(key=lambda r: float(r["ts"]))
    return chains


def trace_is_connected(records):
    """True when one trace's records form a single connected
    parent-chain: every span's ``parent_id`` is either another
    member's ``span_id`` or the (single) external root handed in by
    an upstream process.  The acceptance predicate for "one
    connected trace per request"."""
    ids = {rec.get("span_id") for rec in records
           if rec.get("span_id")}
    n_roots = 0
    for rec in records:
        parent = rec.get("parent_id")
        if parent is None or parent not in ids:
            n_roots += 1
    return n_roots == 1
