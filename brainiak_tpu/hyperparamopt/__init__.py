"""Bayesian hyperparameter optimization (TPE-style)."""

from .hpo import (
    fmin,
    get_next_sample,
    get_sigma,
    gmm_1d_distribution,
)
