"""TPE-style Bayesian hyperparameter optimization.

Re-design of /root/reference/src/brainiak/hyperparamopt/hpo.py (Bergstra et
al. 2011/2013): per-variable 1-D Gaussian-mixture models over the best 15%
and remaining trials; candidates sampled from the "good" mixture are scored
by the likelihood ratio (expected improvement) and the best not-too-close
candidate is evaluated next.

Host-side NumPy — the objective being tuned is typically a jitted
brainiak_tpu fit, and this driver is negligible next to it.  The
per-point Python loops of the reference's GMM pdf (hpo.py:89-218) are
vectorized.
"""

import logging

import numpy as np
from scipy.special import erf
import scipy.stats as st

logger = logging.getLogger(__name__)

__all__ = ["fmin", "get_next_sample", "get_sigma", "gmm_1d_distribution"]


def get_sigma(x, min_limit=-np.inf, max_limit=np.inf):
    """Per-point bandwidths: distance to the farthest of the two nearest
    neighbors (including the limits) (reference hpo.py:46-85)."""
    z = np.append(x, [min_limit, max_limit])
    sigma = np.ones(x.shape)
    for i in range(x.size):
        left_gaps = np.where(z < x[i], x[i] - z, np.inf)
        right_gaps = np.where(z > x[i], z - x[i], np.inf)
        xleft_gap = left_gaps.min()
        xright_gap = right_gaps.min()
        sigma[i] = max(xleft_gap, xright_gap)
        if sigma[i] == np.inf:
            sigma[i] = min(xleft_gap, xright_gap)
        if sigma[i] == -np.inf:  # should never happen
            sigma[i] = 1.0
    return sigma


class gmm_1d_distribution:
    """Truncated 1-D Gaussian mixture over a set of points
    (reference hpo.py:89-218).

    Parameters: points ``x``, truncation limits, optional per-point
    weights.  Callable returns the pdf at scalar or array inputs;
    ``get_samples`` draws truncated samples.
    """

    def __init__(self, x, min_limit=-np.inf, max_limit=np.inf,
                 weights=1.0):
        self.points = x
        self.N = x.size
        self.min_limit = min_limit
        self.max_limit = max_limit
        self.sigma = get_sigma(x, min_limit=min_limit,
                               max_limit=max_limit)
        self.weights = (
            2 / (erf((max_limit - x) / (np.sqrt(2.) * self.sigma))
                 - erf((min_limit - x) / (np.sqrt(2.) * self.sigma)))
            * weights)
        self.W_sum = np.sum(self.weights)

    def __call__(self, x):
        scalar = np.isscalar(x)
        xv = np.atleast_1d(np.asarray(x, dtype=float))
        z = (xv[:, None] - self.points[None, :]) / self.sigma[None, :]
        pdf = np.exp(-0.5 * z ** 2) / (np.sqrt(2 * np.pi)
                                       * self.sigma[None, :])
        y = (pdf * self.weights[None, :]).sum(axis=1) / self.W_sum
        y = np.where((xv < self.min_limit) | (xv > self.max_limit), 0.0, y)
        return float(y[0]) if scalar else y

    def get_gmm_pdf(self, x):
        return self.__call__(x)

    def get_samples(self, n):
        """Draw n truncated samples via rejection on the mixture."""
        normalized_w = self.weights / np.sum(self.weights)
        samples = np.zeros(n)
        k = 0
        while k < n:
            idx = st.rv_discrete(
                values=(range(self.N), normalized_w)).rvs(size=n - k)
            draws = np.random.normal(loc=self.points[idx],
                                     scale=self.sigma[idx])
            valid = draws[(draws >= self.min_limit)
                          & (draws <= self.max_limit)]
            take = min(len(valid), n - k)
            samples[k:k + take] = valid[:take]
            k += take
        return samples


def get_next_sample(x, y, min_limit=-np.inf, max_limit=np.inf):
    """Expected-improvement candidate from the good/rest GMM likelihood
    ratio (reference hpo.py:221-280)."""
    order = np.argsort(y)
    xs, ys = np.asarray(x)[order], np.asarray(y)[order]
    n = ys.shape[0]
    g = int(np.round(np.ceil(0.15 * n)))
    lx_pts, ly = xs[:g], ys[:g]
    gx_pts = xs[g:n]
    lymin, lymax = ly.min(), ly.max()
    weights = ((lymax - ly) / (lymax - lymin)) if lymax > lymin \
        else np.ones_like(ly)
    lx = gmm_1d_distribution(lx_pts, min_limit=min_limit,
                             max_limit=max_limit, weights=weights)
    gx = gmm_1d_distribution(gx_pts, min_limit=min_limit,
                             max_limit=max_limit)

    samples = lx.get_samples(n=1000)
    ei = lx(samples) / gx(samples)

    # avoid re-sampling points too close to previous trials
    h = (x.max() - x.min()) / (10 * x.size)
    s = 0
    while np.abs(x - samples[ei.argmax()]).min() < h:
        ei[ei.argmax()] = 0
        s += 1
        if s == samples.size:
            break
    return samples[ei.argmax()]


def fmin(loss_fn, space, max_evals, trials, init_random_evals=30,
         explore_prob=0.2):
    """Minimize ``loss_fn`` over the given 1-D-per-variable space
    (reference hpo.py:282-374).

    space : dict of {name: {'dist': scipy frozen dist, 'lo':, 'hi':}}
    trials : list accumulating {'<var>':…, 'loss':…} dicts (may be
        pre-seeded).
    Returns the best trial dict.
    """
    for s in space:
        if not hasattr(space[s]['dist'], 'rvs'):
            raise ValueError('Unknown distribution type for variable')
        space[s].setdefault('lo', -np.inf)
        space[s].setdefault('hi', np.inf)

    if len(trials) > init_random_evals:
        init_random_evals = 0

    for t in range(max_evals):
        sdict = {}
        use_random_sampling = (t < init_random_evals
                               or np.random.random() <= explore_prob)
        yarray = np.array([tr['loss'] for tr in trials])
        for s in space:
            if use_random_sampling:
                sdict[s] = space[s]['dist'].rvs()
            else:
                sarray = np.array([tr[s] for tr in trials])
                sdict[s] = get_next_sample(sarray, yarray,
                                           min_limit=space[s]['lo'],
                                           max_limit=space[s]['hi'])
        logger.debug('%s next point %d = %s',
                     'Explore' if use_random_sampling else 'Exploit',
                     t, sdict)
        y = loss_fn(sdict)
        sdict['loss'] = y
        trials.append(sdict)

    yarray = np.array([tr['loss'] for tr in trials])
    best = trials[int(yarray.argmin())]
    logger.info('Best point so far = %s', best)
    return best
