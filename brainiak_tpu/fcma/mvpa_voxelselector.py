"""Activity-based (MVPA) voxel selection via searchlight.

Re-design of /root/reference/src/brainiak/fcma/mvpa_voxelselector.py with
the same API, minus the MPI rank checks."""

import logging

import numpy as np
from sklearn import model_selection

logger = logging.getLogger(__name__)

__all__ = ["MVPAVoxelSelector"]


def _sfn(data, mask, myrad, bcast_var):
    """Searchlight voxel function: CV accuracy of the masked activity
    vectors (reference mvpa_voxelselector.py:34-49)."""
    labels, num_folds, clf = bcast_var[0], bcast_var[1], bcast_var[2]
    masked_data = data[0][mask, :].T
    skf = model_selection.StratifiedKFold(n_splits=num_folds,
                                          shuffle=False)
    return np.mean(model_selection.cross_val_score(
        clf, masked_data, y=labels, cv=skf, n_jobs=1))


class MVPAVoxelSelector:
    """Searchlight CV-accuracy voxel ranking (reference
    mvpa_voxelselector.py:52-136).

    Parameters
    ----------
    data : 4D array [x, y, z, epoch] (from prepare_searchlight_mvpa_data)
    mask : 3D boolean array
    labels : per-epoch condition labels
    num_folds : CV folds
    sl : a brainiak_tpu.searchlight.Searchlight instance
    """

    def __init__(self, data, mask, labels, num_folds, sl):
        self.data = data
        self.mask = mask.astype(bool)
        self.labels = labels
        self.num_folds = num_folds
        self.sl = sl
        if np.sum(self.mask) == 0:
            raise ValueError('Zero processed voxels')

    def run(self, clf):
        """Returns (result_volume, [(voxel_id, accuracy)] sorted desc)."""
        logger.info('running activity-based voxel selection via '
                    'Searchlight')
        self.sl.distribute([self.data], self.mask)
        self.sl.broadcast((self.labels, self.num_folds, clf))
        result_volume = self.sl.run_searchlight(_sfn)
        result_list = result_volume[self.mask]
        results = []
        for idx, value in enumerate(result_list):
            if value is None:
                value = 0
            results.append((idx, value))
        results.sort(key=lambda tup: tup[1], reverse=True)
        return result_volume, results
